"""Quantized serving planes (ROADMAP item 4, device residency leg a).

BENCH_r08 pins the fused serving graph's remaining boundary cost: 14 B/row
still crosses host→device in full f32 per batch. For a FITTED model that
payload is overdescribed — every numeric column has a fit-time value range
(the vectorizers' monoid min/max), and a tree predictor immediately
re-bins the plane into at most ``max_bins`` codes anyway. This module
compresses each numeric value column to ONE uint8 code per row with a
per-column decode table traced into the fused program:

* **bin-aligned** (tree predictors): the host encodes each value to its
  EXACT bin under the predictor's thresholds (``bin_data_host``
  semantics: count of thresholds strictly below, f32 compare), and the
  decode table holds one representative value per bin chosen (and
  self-verified at build) to re-bin to the same code in-graph — tree
  predictions stay **bit-identical** to the f32 plane;
* **affine** (GLMs and any column without thresholds): code =
  ``rint((v - lo) / scale)`` over the fit range ``[lo, hi]``, decode =
  ``lo + code·scale`` — a dequant epilogue traced in-graph ahead of the
  predictor core, with the max reconstruction error ``scale/2`` surfaced
  on the per-column ``quantError`` ledger (serve-time values outside the
  fit range clamp; ±Inf clamps to the range edge, NaN encodes as ``lo``
  and is masked by the imputation ``where`` anyway);
* **constant / all-null** columns (no usable range) decode exactly to
  ``lo`` with zero error.

Both modes share ONE in-graph decode: a ``[F, 256]`` f32 reps-table
gather (:func:`dequantize`), uploaded once with the model params at
program bring-up — the per-batch upload is the uint8 codes alone. The
plan is deterministic from the persisted fit ranges + model arrays
(``to_json``/``from_json`` round-trips it for the manifest), so a
reloaded model rebuilds the identical plan.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

__all__ = ["ColumnQuant", "QuantPlan", "N_CODES", "dequantize"]

#: uint8 code space — one byte per value per row on the wire
N_CODES = 256


@dataclasses.dataclass
class ColumnQuant:
    """One column's code↔value contract: ``mode`` ∈ {affine, bins,
    constant}, a 256-entry f32 decode table ``reps``, and the encode
    parameters for its mode. ``quant_error`` bounds the absolute
    reconstruction error for in-range values (0.0 when predictions are
    provably unaffected: bins / constant)."""

    mode: str
    lo: float
    hi: float
    scale: float
    reps: np.ndarray
    quant_error: float
    thresholds: np.ndarray | None = None  # sorted f32, bins mode only

    @classmethod
    def affine(cls, lo: float, hi: float) -> "ColumnQuant":
        """Uniform uint8 grid over the fit range [lo, hi]. Non-finite
        range edges clamp to a finite span; a degenerate range becomes a
        constant column (codes all 0, decode exact)."""
        lo = float(np.float32(lo))
        hi = float(np.float32(hi))
        if not np.isfinite(lo):
            lo = 0.0
        if not np.isfinite(hi):
            hi = lo
        if hi <= lo:
            reps = np.full(N_CODES, np.float32(lo))
            return cls("constant", lo, lo, 0.0, reps, 0.0)
        scale = (hi - lo) / (N_CODES - 1)
        reps = (
            np.float32(lo)
            + np.float32(scale) * np.arange(N_CODES, dtype=np.float32)
        ).astype(np.float32)
        # the grid is f32; the realized half-step bounds the error
        err = float(np.max(np.diff(reps))) / 2.0
        return cls("affine", lo, hi, float(scale), reps, err)

    @classmethod
    def bins(cls, thresholds: np.ndarray) -> "ColumnQuant | None":
        """Bin-aligned codes for one predictor column: code = number of
        thresholds strictly below the value (``bin_data`` semantics, f32
        compare), decode = a representative that re-bins to the same
        code. Returns None when the column cannot be represented (more
        than 256 bins, or the self-verification fails) — the caller
        falls back to affine."""
        thr = np.asarray(thresholds, dtype=np.float32).ravel()
        finite = np.sort(thr[np.isfinite(thr)])
        n_bins = int(thr.shape[0]) + 1
        if n_bins > N_CODES:
            return None
        reps = np.zeros(N_CODES, dtype=np.float32)
        if finite.size == 0:
            # every value bins to 0 (x > NaN is False on device)
            return cls("bins", 0.0, 0.0, 0.0, reps, 0.0, finite)
        # bin 0: any value ≤ the smallest threshold (strictly-below count
        # is 0 at the threshold itself)
        reps[0] = finite[0]
        achievable = {0}
        last = reps[0]
        uniq = np.unique(finite)
        for b in range(1, n_bins):
            # bin b is reachable iff some distinct edge d has exactly b
            # thresholds ≤ d; the next representable f32 above d then has
            # exactly b thresholds strictly below it
            cand = None
            for d in uniq:
                if int((finite <= d).sum()) == b:
                    cand = np.nextafter(np.float32(d), np.float32(np.inf))
                    break
            if cand is not None:
                achievable.add(b)
                last = np.float32(cand)
            reps[b] = last
        reps[n_bins:] = last
        # self-verify: every achievable code's rep re-bins to itself
        # under the exact device semantics
        rebinned = (reps[:n_bins, None] > finite[None, :]).sum(axis=1)
        for b in achievable:
            if int(rebinned[b]) != b:
                return None
        return cls("bins", float(finite[0]), float(finite[-1]), 0.0,
                   reps, 0.0, finite)

    def encode(self, vals: np.ndarray) -> np.ndarray:
        """Host codec: f32 values → uint8 codes (the only per-batch
        upload for this column)."""
        v = np.asarray(vals, dtype=np.float32)
        if self.mode == "constant":
            return np.zeros(v.shape, dtype=np.uint8)
        if self.mode == "bins":
            thr = self.thresholds
            if thr is None or thr.size == 0:
                return np.zeros(v.shape, dtype=np.uint8)
            # count of thresholds strictly below = searchsorted-left over
            # the sorted edges; NaN routes to bin 0 like bin_data_host
            x = np.where(np.isnan(v), np.float32(-np.inf), v)
            return np.searchsorted(thr, x, side="left").astype(np.uint8)
        # affine: NaN → lo (masked by imputation anyway); ±Inf rides the
        # clip to the range edges
        x = np.where(np.isnan(v), np.float32(self.lo), v)
        with np.errstate(invalid="ignore"):
            q = np.rint(
                (x - np.float32(self.lo)) / np.float32(self.scale)
            )
        return np.clip(q, 0, N_CODES - 1).astype(np.uint8)

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "mode": self.mode,
            "lo": self.lo,
            "hi": self.hi,
            "scale": self.scale,
            "quantError": self.quant_error,
        }
        if self.thresholds is not None:
            out["thresholds"] = [float(t) for t in self.thresholds]
        return out

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "ColumnQuant":
        if d["mode"] == "bins":
            got = cls.bins(np.asarray(d.get("thresholds", []), np.float32))
            if got is not None:
                return got
        if d["mode"] == "constant":
            return cls.affine(d["lo"], d["lo"])
        return cls.affine(d["lo"], d["hi"])


class QuantPlan:
    """Per-column quantization of one member's value columns. The encode
    side runs in the member's host ingest; the reps table is a model
    param the traced :func:`dequantize` gathers from in-graph."""

    def __init__(self, columns: list[ColumnQuant]):
        self.columns = list(columns)

    def reps_table(self) -> np.ndarray:
        """[F, 256] f32 decode table (uploaded once with model params)."""
        return np.stack([c.reps for c in self.columns]).astype(np.float32)

    def encode(self, vals: np.ndarray) -> np.ndarray:
        """[N, F] f32 → [N, F] uint8 (4× fewer bytes on the wire)."""
        out = np.empty(vals.shape, dtype=np.uint8)
        for j, c in enumerate(self.columns):
            out[:, j] = c.encode(vals[:, j])
        return out

    def errors(self) -> list[float]:
        """Per-column max reconstruction error (the quantError ledger)."""
        return [float(c.quant_error) for c in self.columns]

    def descriptor(self) -> str:
        """Structural fingerprint contribution: per-column modes only —
        the reps table is a traced param, so same-shaped plans share
        executables like every other model array."""
        tags = {"affine": "a", "bins": "b", "constant": "c"}
        return "q8" + "".join(tags[c.mode] for c in self.columns)

    def to_json(self) -> dict[str, Any]:
        return {"columns": [c.to_json() for c in self.columns]}

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "QuantPlan":
        return cls([ColumnQuant.from_json(c) for c in d["columns"]])


def dequantize(codes, reps):
    """In-graph decode (the dequant epilogue): codes [N, F] uint8 +
    reps [F, 256] f32 → values [N, F] f32 via one per-column table
    gather. Traced inside the member kernels of ``compiler/fused.py``."""
    import jax.numpy as jnp

    f = reps.shape[0]
    return reps[jnp.arange(f)[None, :], codes.astype(jnp.int32)]
