"""Dynamic schedule reconciler — instrumented locks + the runtime
lock-order graph, squared against the static analyzer.

The static side (:mod:`~transmogrifai_tpu.analysis.concurrency`) derives
a lock-order graph from the AST; this module derives the SAME graph from
what the process actually did. The seam is :func:`make_lock`: the
thread-crossed subsystems create their locks through it, naming each lock
with the static analyzer's canonical key
(``"serving/service.py:ScoringService._lock"``). With tracing OFF (the
``TPTPU_LOCK_TRACE=0`` default) ``make_lock`` returns the raw
``threading`` primitive — zero wrappers, zero cost, nothing to misbehave
in production. With tracing ON it returns a :class:`TracedLock` that
records, per acquisition, an edge from every lock the acquiring thread
already holds to the new one.

:func:`reconcile_lock_orders` then asserts the dynamic graph is a
SUBGRAPH of the static one — the same static-vs-runtime reconciliation
idiom as the transfer census (``plan_audit`` TPX census vs the PR-10
runtime census). A dynamic edge the static analyzer cannot see (TPC006)
means a lock acquisition flowed through a path the AST pass cannot
resolve — exactly the blind spot where the next ABBA deadlock hides.

Cross-process capture: the hammer/chaos suites run in a subprocess with
``TPTPU_LOCK_TRACE=1`` and ``TPTPU_LOCK_TRACE_OUT=<path>``; an atexit
hook dumps the dynamic graph as JSON for the parent to reconcile.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Any, Callable, Iterable

from .findings import Report, Severity

__all__ = [
    "TracedLock",
    "dump_dynamic",
    "dynamic_graph",
    "load_dynamic",
    "make_lock",
    "reconcile_lock_orders",
    "reset_dynamic",
    "trace_enabled",
]

TRACE_ENV = "TPTPU_LOCK_TRACE"
TRACE_OUT_ENV = "TPTPU_LOCK_TRACE_OUT"

#: edge -> acquisition count; writes hold _GRAPH_LOCK (TPL001)
_GRAPH: dict[tuple[str, str], int] = {}
_GRAPH_LOCK = threading.Lock()
_TLS = threading.local()
_DUMP_REGISTERED = False
#: bumped by reset_dynamic so every thread's seen-edge cache invalidates
#: lazily on its next acquisition (a live worker thread must re-record
#: edges into the NEW graph, not keep suppressing them)
_GENERATION = 0


def trace_enabled() -> bool:
    """True when ``TPTPU_LOCK_TRACE`` asks for instrumented locks.
    Consulted at LOCK CREATION time: module-level locks decide at import,
    so the env var must be set before the process starts (the hammer
    suites run in a subprocess for exactly this reason)."""
    return os.environ.get(TRACE_ENV, "0").strip().lower() not in (
        "", "0", "false", "off",
    )


class TracedLock:
    """A lock wrapper recording the acquisition ORDER, not timings.

    Supports the full lock protocol (``with``, ``acquire``/``release``,
    ``locked``) so it can stand in for ``threading.Lock``/``RLock``
    anywhere the seam modules use one. Re-entrant acquisitions of the
    same name (RLocks, per-key lock FAMILIES sharing one name) do not
    record self-edges — a family is one node in both graphs.

    Per-thread bookkeeping is a name stack in a ``threading.local``; the
    global edge map is touched only for edges this thread has not seen
    before, so the steady-state cost of an acquisition is one list append
    and one set lookup.

    Known limitation: releasing a traced lock from a DIFFERENT thread
    than acquired it (legal for plain locks) cannot pop the acquiring
    thread's stack, so that thread would record phantom held edges
    afterwards. Every instrumented seam lock is ``with``-statement
    scoped (the queue Condition releases/reacquires on its own thread),
    so this cannot happen in-tree — and if a future lock does it, the
    phantom edge surfaces LOUDLY as a TPC006 reconciliation failure
    rather than hiding an ordering."""

    __slots__ = ("_lock", "name")

    def __init__(self, lock: Any, name: str):
        self._lock = lock
        self.name = name

    # ------------------------------------------------------------ recording
    def _held_stack(self) -> list[str]:
        stack = getattr(_TLS, "held", None)
        if stack is None:
            stack = _TLS.held = []
        return stack

    def _record_acquire(self) -> None:
        stack = self._held_stack()
        name = self.name
        if stack:
            seen = getattr(_TLS, "seen", None)
            if seen is None or getattr(_TLS, "gen", -1) != _GENERATION:
                seen = _TLS.seen = set()
                _TLS.gen = _GENERATION
            for held in stack:
                if held == name:  # RLock re-entry / family sibling
                    continue
                edge = (held, name)
                if edge in seen:
                    continue
                seen.add(edge)
                with _GRAPH_LOCK:
                    _GRAPH[edge] = _GRAPH.get(edge, 0) + 1
        stack.append(name)

    def _record_release(self) -> None:
        stack = self._held_stack()
        # release() from a different thread than acquire() is legal for
        # plain locks; tolerate an unbalanced stack instead of corrupting
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break

    # ---------------------------------------------------------- lock protocol
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._record_acquire()
        return got

    def release(self) -> None:
        self._lock.release()
        self._record_release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        self._lock.acquire()
        self._record_acquire()
        return True

    def __exit__(self, *exc: Any) -> None:
        self._lock.release()
        self._record_release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TracedLock({self.name!r})"


def make_lock(name: str, factory: Callable[[], Any] = threading.Lock):
    """The instrumented-lock seam. Tracing off (default): returns
    ``factory()`` unchanged — the raw primitive, zero overhead. Tracing
    on: wraps it in a :class:`TracedLock` carrying ``name``, which MUST
    be the static analyzer's canonical key for this lock so the two
    graphs share a vocabulary."""
    lock = factory()
    if not trace_enabled():
        return lock
    global _DUMP_REGISTERED
    if not _DUMP_REGISTERED:
        with _GRAPH_LOCK:
            if not _DUMP_REGISTERED:
                out = os.environ.get(TRACE_OUT_ENV)
                if out:
                    atexit.register(dump_dynamic, out)
                _DUMP_REGISTERED = True
    return TracedLock(lock, name)


# ------------------------------------------------------------------ the graph
def dynamic_graph() -> dict[str, Any]:
    """JSON-able snapshot of the dynamic lock-order graph."""
    with _GRAPH_LOCK:
        items = sorted(_GRAPH.items())
    nodes = sorted({n for (a, b), _ in items for n in (a, b)})
    return {
        "traced": trace_enabled(),
        "nodes": nodes,
        "edges": [
            {"from": a, "to": b, "count": c} for (a, b), c in items
        ],
    }


def reset_dynamic() -> None:
    """Drop every recorded edge (test isolation). The generation bump
    invalidates EVERY thread's seen-edge cache lazily (checked on its
    next acquisition), so a live worker thread re-records its edges into
    the new graph instead of silently suppressing them."""
    global _GENERATION
    with _GRAPH_LOCK:
        _GRAPH.clear()
        _GENERATION += 1


def dump_dynamic(path: str) -> None:
    """Write the dynamic graph as JSON (the atexit hook of a traced
    subprocess run)."""
    doc = dynamic_graph()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_dynamic(path: str) -> dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


# --------------------------------------------------------------- reconciler
def _edge_pairs(graph: dict[str, Any] | Iterable) -> list[tuple[str, str]]:
    """Normalize either graph shape (the static analyzer's
    ``lockGraph["edges"]`` or :func:`dynamic_graph`'s) to (from, to)
    pairs."""
    edges = graph.get("edges", graph) if isinstance(graph, dict) else graph
    out: list[tuple[str, str]] = []
    for e in edges:
        if isinstance(e, dict):
            out.append((e["from"], e["to"]))
        else:
            a, b = e[0], e[1]
            out.append((str(a), str(b)))
    return out


def reconcile_lock_orders(
    static: dict[str, Any],
    dynamic: dict[str, Any],
) -> Report:
    """Assert dynamic ⊆ static: every lock-order edge the process
    actually exercised must be visible to the static analyzer.

    ``static`` is the ``lockGraph`` attachment of a
    :func:`~transmogrifai_tpu.analysis.concurrency.analyze_paths` report
    (or any ``{"edges": [...]}``); ``dynamic`` is
    :func:`dynamic_graph`'s shape. Dynamic edges between locks the static
    graph has never HEARD of (neither endpoint is a static node) are
    reported too — an untracked lock is exactly as invisible as an
    untracked edge. Returns a Report with one TPC006 WARNING per
    statically-invisible edge and a ``reconciliation`` data attachment;
    ``report.ok`` stays True (warnings don't refuse) — CI gates on
    ``len(report)`` instead."""
    static_edges = set(_edge_pairs(static))
    static_nodes = set(static.get("nodes") or [])
    for a, b in static_edges:
        static_nodes.add(a)
        static_nodes.add(b)
    dynamic_edges = _edge_pairs(dynamic)
    report = Report()
    invisible: list[tuple[str, str]] = []
    for a, b in sorted(set(dynamic_edges)):
        if a == b:
            continue  # family/re-entrant self-edges are not an ordering
        if (a, b) in static_edges:
            continue
        invisible.append((a, b))
        report.add(
            "TPC006",
            f"runtime acquired {b!r} while holding {a!r}, but the static "
            "lock-order graph has no such edge — the acquisition flows "
            "through a call path the AST pass cannot resolve (add a "
            "'# tpc: lock(...)' annotation or an explicit type hint so "
            "the deadlock detector can see it)",
            subject=f"{a} -> {b}",
            severity=Severity.WARNING,
            path=a.split(":", 1)[0],
            line=0,
            context=f"{a} -> {b}",
        )
    report.data["reconciliation"] = {
        "staticEdges": len(static_edges),
        "staticNodes": len(static_nodes),
        "dynamicEdges": len(set(dynamic_edges)),
        "invisibleEdges": [list(e) for e in invisible],
        "subgraph": not invisible,
    }
    return report
