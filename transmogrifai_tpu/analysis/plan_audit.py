"""Serving-plan auditor — abstract interpretation of a fitted stage plan.

Walks the flat, ordered plan that ``local/scoring.py`` builds (the same
one the standing scorer of ROADMAP item 1 would pin) and propagates
symbolic ``[N, width]`` shapes/dtypes through the stage families without
executing anything:

* **widths** come from each vectorizer's fit-static metadata cache (or
  the :class:`~transmogrifai_tpu.featurize.engine.FusionPlanner`'s learned
  widths) — a stage whose width cannot be proven yet is reported (TPX004),
* **placement** classifies every stage host vs device in the steady-state
  batch regime, yielding the per-stage host↔device **transfer census**
  ROADMAP item 5 (single fused on-device scoring graph) needs: what
  crosses the PCIe/ICI boundary per row today, and therefore what a fused
  program would eliminate,
* **recompile hazards**: device dispatch keyed on a raw (unbucketed)
  batch dimension compiles one program per distinct batch size (TPX001);
  lane-bucketing opt-out is surfaced (TPX005),
* **donation misuse**: the modules behind the plan's device stages are
  AST-scanned for a donated buffer being read again after a
  ``donating()`` dispatch (TPX003) — the one bug class donation makes
  possible.

The census lands in ``report.data["transferCensus"]`` and is surfaced on
``score_fn.metadata()["analysis"]``.
"""
from __future__ import annotations

import ast
import functools
import os
from typing import Any, Iterable, Sequence

from .findings import Report, Severity

__all__ = ["audit_serving_plan", "donation_misuse"]

#: serving batches at or below this row count predict host-side in numpy
#: (local/scoring.py reads the same env knob)
_HOST_PREDICT_MAX = 16384


def _width_of(stage, fusion=None) -> int | None:
    """Fit-static output width of a vectorizer-ish stage, if provable:
    the vectorizer metadata cache, the combiner's flatten cache, a
    feature-removal stage's rewritten metadata, or the FusionPlanner's
    learned widths — all populated without running the stage here."""
    for attr in ("_meta_cache", "_flatten_cache"):
        cached = getattr(stage, attr, None)
        if cached is not None:
            try:
                return int(cached[1].size)
            except Exception:
                pass
    new_meta = getattr(stage, "new_metadata", None)
    if new_meta is not None:
        try:
            return int(new_meta.size)
        except Exception:
            pass
    if fusion is not None:
        w = getattr(fusion, "widths", {}).get(getattr(stage, "uid", None))
        if w is not None:
            return int(w)
    return None


def _meta_of(stage):
    """Fit-static :class:`VectorMetadata` of a vectorizer-ish stage, if
    recoverable (the provenance LOCO groups by)."""
    for attr in ("_meta_cache", "_flatten_cache"):
        cached = getattr(stage, attr, None)
        if cached is not None:
            try:
                if cached[1].columns is not None:
                    return cached[1]
            except Exception:
                pass
    new_meta = getattr(stage, "new_metadata", None)
    if new_meta is not None and getattr(new_meta, "columns", None) is not None:
        return new_meta
    return None


def _classify(stage) -> str:
    from ..models.base import PredictorModel
    from ..ops.base import _CachedMetaVectorizer
    from ..ops.combiner import VectorsCombiner

    if isinstance(stage, PredictorModel):
        return "predictor"
    if isinstance(stage, VectorsCombiner):
        return "combiner"
    if isinstance(stage, _CachedMetaVectorizer):
        return "vectorizer"
    return "host"


def audit_serving_plan(
    plan: Sequence,
    raw_features: Iterable,
    result_names: Sequence[str],
    fusion=None,
    bucketed: bool = True,
    host_predict_max: int | None = None,
    fused=None,
    fused_reason: str | None = None,
    fused_counters: dict | None = None,
) -> Report:
    """Audit an ordered fitted stage ``plan``. ``bucketed`` states whether
    the caller pads batches onto power-of-two buckets before dispatch
    (the serving closure does; raw ``WorkflowModel.score`` does not).
    ``fusion`` is the plan's FusionPlanner, source of learned widths.

    ``fused`` is the closure's compiled
    :class:`~transmogrifai_tpu.compiler.fused.FusedServingProgram` (or
    None): when present, its covered stages audit as device-placed, the
    census states the fused two-crossing contract (ONE ingest upload, ONE
    render download per batch), and the fused module joins the TPX003
    donation scan. ``fused_reason`` (why no program) and
    ``fused_counters`` (runtime dispatch/fallback counts) feed TPX008."""
    report = Report()
    cutoff = (
        int(os.environ.get("TPTPU_HOST_PREDICT_MAX", str(_HOST_PREDICT_MAX)))
        if host_predict_max is None
        else host_predict_max
    )

    fused_covered = frozenset() if fused is None else fused.covered
    fused_widths = {} if fused is None else fused.static_widths
    widths: dict[str, int | None] = {}
    placement: dict[str, str] = {}  # output name -> "host" | "device"
    census_stages: list[dict[str, Any]] = []
    h2d = d2h = 0
    up_bytes_per_row = down_bytes_per_row = 0.0
    unknown_widths: list[str] = []

    for f in raw_features:
        placement[f.name] = "host"  # row codecs build columns host-side

    for t in plan:
        family = _classify(t)
        out_name = t.output_name
        in_fused = out_name in fused_covered
        width: int | None = None
        if family == "predictor":
            width = 1
        else:
            width = _width_of(t, fusion)
            if width is None and in_fused:
                # the fused build proved widths statically from the
                # member specs — no first batch needed
                width = fused_widths.get(out_name)
            if width is None and family == "combiner":
                member_ws = [widths.get(nm) for nm in t.input_names]
                if all(w is not None for w in member_ws):
                    width = int(sum(member_ws))  # type: ignore[arg-type]
            if width is None and family in ("vectorizer", "combiner"):
                unknown_widths.append(out_name)
        widths[out_name] = width

        device = family == "predictor" or in_fused
        placement[out_name] = "device" if device else "host"
        entry: dict[str, Any] = {
            "stage": t.operation_name,
            "output": out_name,
            "family": family,
            "width": width,
            "placement": placement[out_name],
        }
        if in_fused:
            entry["fused"] = True
        if family == "predictor" and not in_fused:
            in_name = t.input_names[-1] if t.input_names else None
            in_w = widths.get(in_name)
            up = None if in_w is None else in_w * 4  # f32 feature plane
            # Prediction columns download as f64 (pred, prob, raw)
            down = 8 * 3
            entry.update(
                {
                    "input": in_name,
                    "upBytesPerRow": up,
                    "downBytesPerRow": down,
                    "deviceWhenRowsAbove": cutoff,
                }
            )
            h2d += 1
            d2h += 1
            up_bytes_per_row += up or 0.0
            down_bytes_per_row += down
        census_stages.append(entry)

    if fused is not None:
        # the fused program's whole-segment contract: ingest codecs cross
        # once, the predictor core crosses back once — per batch
        h2d += 1
        d2h += 1
        up_bytes_per_row += fused.up_bytes_per_row
        down_bytes_per_row += fused.down_bytes_per_row

    # ---- transfer census (report attachment, not a finding)
    report.data["transferCensus"] = {
        "resultFeatures": [str(nm) for nm in result_names],
        "stages": census_stages,
        "hostToDeviceTransfers": h2d,
        "deviceToHostTransfers": d2h,
        "upBytesPerRow": up_bytes_per_row,
        "downBytesPerRow": down_bytes_per_row,
        "hostPredictCutoffRows": cutoff,
        "batchBucketed": bool(bucketed),
        "fusedProgram": fused is not None,
    }
    if fused is not None:
        report.data["fusedProgram"] = fused.describe()

    # ---- TPX007: predictor feature plane without usable provenance —
    # LOCO explanations would silently degrade to anonymous per-column
    # groups (col_<j> instead of feature names). Only provable
    # degradations are reported: an unknown width before the first batch
    # is TPX004's business, not a metadata defect.
    by_output = {t.output_name: t for t in plan}
    for t in plan:
        if _classify(t) != "predictor" or not t.input_names:
            continue
        in_name = t.input_names[-1]
        producer = by_output.get(in_name)
        if producer is None:
            continue
        meta = _meta_of(producer)
        in_w = widths.get(in_name)
        degraded = (meta is None and in_w is not None) or (
            meta is not None and in_w is not None and meta.size != in_w
        )
        if degraded:
            report.add(
                "TPX007",
                f"feature vector '{in_name}' feeding predictor "
                f"{t.operation_name!r} has "
                + (
                    "no recoverable provenance metadata"
                    if meta is None
                    else f"metadata for {meta.size} column(s) but width "
                         f"{in_w}"
                )
                + " — explain=k / RecordInsightsLOCO will name anonymous "
                "col_<j> groups instead of features (counted as "
                "metaFallbacks on the attribution ledger)",
                subject=in_name,
                severity=Severity.WARNING,
            )

    # ---- TPX002: device -> host -> device bounce in plan order
    device_stage_names = {
        e["output"] for e in census_stages if e["placement"] == "device"
    }
    for t in plan:
        if placement.get(t.output_name) != "host":
            continue
        feeds_device = any(
            t.output_name in (u.input_names or ())
            for u in plan
            if placement.get(u.output_name) == "device"
        )
        from_device = any(
            nm in device_stage_names for nm in (t.input_names or ())
        )
        if feeds_device and from_device:
            report.add(
                "TPX002",
                f"host stage {t.operation_name!r} sits between two device "
                "dispatches — its inputs download from device and its "
                "output re-uploads every batch",
                subject=t.output_name,
                severity=Severity.WARNING,
            )

    # ---- TPX001: unbucketed batch-keyed device dispatch
    if device_stage_names and not bucketed:
        report.add(
            "TPX001",
            "device-dispatching stage(s) "
            f"{sorted(device_stage_names)} receive the RAW batch dimension "
            "— every distinct batch size compiles a fresh program; route "
            "batches through the serving closure's power-of-two buckets",
            subject=";".join(sorted(device_stage_names)),
            severity=Severity.WARNING,
        )

    # ---- TPX004: widths not provable yet (fusion/audit learn on batch 1)
    for nm in unknown_widths:
        report.add(
            "TPX004",
            f"output '{nm}' has no fit-static width yet — shape "
            "propagation resumes after the first scored batch",
            subject=nm,
            severity=Severity.INFO,
        )

    # ---- TPX005: lane bucketing opt-out (process-wide env)
    if os.environ.get("TPTPU_LANE_BUCKETS", "1") == "0":
        report.add(
            "TPX005",
            "TPTPU_LANE_BUCKETS=0: GLM sweep lane counts dispatch "
            "unpadded — every distinct candidate count compiles its own "
            "sweep program",
            subject="env",
            severity=Severity.INFO,
        )

    # ---- TPX006: fusion unavailable for this plane
    if fusion is not None and getattr(fusion, "disabled", False):
        report.add(
            "TPX006",
            "fused plane assembly is unavailable for this plan (no single "
            "VectorsCombiner over dense sequence vectorizers) — the final "
            "feature vector concatenates per-stage buffers each batch",
            subject="plan",
            severity=Severity.INFO,
        )

    # ---- TPX008: fused path unavailable / runtime degradations
    if fused is None and fused_reason is not None:
        report.add(
            "TPX008",
            "fused scoring graph unavailable — steady-state batches run "
            f"the staged loop ({fused_reason})",
            subject="plan",
            severity=Severity.INFO,
        )
    fallbacks = int((fused_counters or {}).get("fallbacks", 0))
    if fallbacks > 0:
        last = (fused_counters or {}).get("lastFallback")
        report.add(
            "TPX008",
            f"{fallbacks} batch(es) degraded from the fused graph to the "
            "staged loop at dispatch time"
            + (f" (last: {last})" if last else ""),
            subject="plan",
            severity=Severity.WARNING,
            fallbacks=fallbacks,
        )

    # ---- TPX003: donated-buffer reuse in the modules behind the plan
    modules = set()
    for t in plan:
        if _classify(t) == "predictor":
            mod = type(t).__module__
            if mod.startswith("transmogrifai_tpu"):
                modules.add(mod)
    if fused is not None:
        # the fused dispatch donates its ingest buffers — its module is
        # exactly the bug surface TPX003 exists for
        modules.add("transmogrifai_tpu.compiler.fused")
    for mod in sorted(modules):
        report.extend(donation_misuse_module(mod))
    return report


# --------------------------------------------------------------------------
# donation misuse (AST)
# --------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def donation_misuse_module(module_name: str) -> Report:
    """AST-scan one imported module for donated-buffer reuse. Cached for
    the process lifetime: module source is static, and ``metadata()``
    (the polled monitoring surface) re-audits on every call."""
    import importlib

    try:
        mod = importlib.import_module(module_name)
        path = mod.__file__
        with open(path) as f:
            src = f.read()
    except Exception:
        return Report()
    return donation_misuse(src, path or module_name)


def donation_misuse(source: str, path: str = "<string>") -> Report:
    """TPX003: inside one function, a variable passed at a donated
    position of a ``donating(...)``-built callable (directly or through
    ``aot_call``'s args tuple) must not be READ again unless re-bound at
    or after the dispatch — donated buffers are consumed by XLA and may
    alias the output."""
    report = Report()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return report

    for fn in [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]:
        _scan_function(fn, path, report)
    return report


def _donate_argnums(call: ast.Call) -> tuple[int, ...] | None:
    """The literal donate_argnums of a ``donating(...)`` call, if static."""
    candidates: list[ast.expr] = []
    if len(call.args) >= 3:
        candidates.append(call.args[2])
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            candidates.append(kw.value)
    for node in candidates:
        try:
            val = ast.literal_eval(node)
        except Exception:
            continue
        if isinstance(val, int):
            return (val,)
        if isinstance(val, (tuple, list)):
            return tuple(int(v) for v in val)
    return None


def _is_name_call(node: ast.expr, name: str) -> bool:
    return (isinstance(node, ast.Name) and node.id == name) or (
        isinstance(node, ast.Attribute) and node.attr == name
    )


def _scan_function(fn: ast.AST, path: str, report: Report) -> None:
    donated_fns: dict[str, tuple[int, ...]] = {}
    # events: (lineno, kind, name) — kind in {load, store}
    events: list[tuple[int, str, str]] = []
    # dispatches: (lineno, donated names, stored names at that statement)
    dispatches: list[tuple[int, set[str], set[str]]] = []

    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if _is_name_call(call.func, "donating"):
                nums = _donate_argnums(call)
                if nums is not None:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            donated_fns[tgt.id] = nums

    if not donated_fns:
        return

    class _V(ast.NodeVisitor):
        def visit_Name(self, node: ast.Name) -> None:
            kind = "store" if isinstance(node.ctx, ast.Store) else "load"
            events.append((node.lineno, kind, node.id))
            self.generic_visit(node)

        def visit_Call(self, node: ast.Call) -> None:
            donated: set[str] = set()
            fname = None
            if isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname in donated_fns:
                for i in donated_fns[fname]:
                    if i < len(node.args) and isinstance(
                        node.args[i], ast.Name
                    ):
                        donated.add(node.args[i].id)
            elif fname == "aot_call" and len(node.args) >= 3:
                jf = node.args[1]
                jf_name = jf.id if isinstance(jf, ast.Name) else None
                argtup = node.args[2]
                if jf_name in donated_fns and isinstance(
                    argtup, (ast.Tuple, ast.List)
                ):
                    for i in donated_fns[jf_name]:
                        if i < len(argtup.elts) and isinstance(
                            argtup.elts[i], ast.Name
                        ):
                            donated.add(argtup.elts[i].id)
            if donated:
                dispatches.append((node.lineno, donated, set()))
            self.generic_visit(node)

    _V().visit(fn)

    # a Store on the dispatch line (the `x, buf = f(buf, ...)` rebind)
    # re-defines the name from that statement on
    for lineno, donated, stored in dispatches:
        for ev_line, kind, name in events:
            if kind == "store" and name in donated and ev_line >= lineno:
                stored.add(name)

    for lineno, donated, stored in dispatches:
        for name in sorted(donated):
            later_store = [
                e for e in events
                if e[1] == "store" and e[2] == name and e[0] >= lineno
            ]
            later_loads = [
                e for e in events
                if e[1] == "load" and e[2] == name and e[0] > lineno
            ]
            for load_line, _, _ in later_loads:
                # a store at/before the load (and at/after the dispatch)
                # re-binds the name — the load sees the NEW buffer
                if any(lineno <= s[0] <= load_line for s in later_store):
                    continue
                report.add(
                    "TPX003",
                    f"'{name}' is read at line {load_line} after being "
                    f"donated to a dispatch at line {lineno} — donated "
                    "buffers are consumed and may alias the output",
                    subject=f"{path}:{load_line}",
                    severity=Severity.WARNING,
                )
                break  # one finding per donated name per dispatch
