"""Static-analysis plane: TP-coded findings over DAGs, plans and code.

Five analysers share one :class:`Finding`/:class:`Report` core
(``analysis/findings.py``):

* :mod:`~transmogrifai_tpu.analysis.preflight` — ``TPA0xx`` pre-flight
  DAG validation (``Workflow.validate()``; runs automatically at the top
  of ``train()``), restoring the reference's compile-time feature-type
  guarantees as an eager check.
* :mod:`~transmogrifai_tpu.analysis.plan_audit` — ``TPX0xx`` serving-plan
  audit: symbolic ``[N, width]`` shape propagation, the host↔device
  transfer census, recompile-hazard and donation checks
  (``score_fn.metadata()["analysis"]``).
* :mod:`~transmogrifai_tpu.analysis.lint` — ``TPL0xx`` AST lint of the
  package's own invariants (``python -m transmogrifai_tpu lint``, gated
  in CI against the committed ``lint_baseline.json``).
* :mod:`~transmogrifai_tpu.analysis.concurrency` — ``TPC0xx`` cross-module
  static concurrency analysis: the inferred lock registry, the whole-repo
  lock-order graph with cycle (potential-deadlock) detection,
  guarded-field discipline, foreign-callable-under-lock, and non-atomic
  publish checks (``python -m transmogrifai_tpu lint --concurrency``,
  gated against ``concurrency_baseline.json``).
* :mod:`~transmogrifai_tpu.analysis.schedule` — the dynamic side of the
  concurrency plane: injectable instrumented locks
  (``TPTPU_LOCK_TRACE=1``, off by default) recording the ACTUAL
  acquisition order into a dynamic lock-order graph, and
  ``reconcile_lock_orders`` asserting the dynamic graph is a subgraph of
  the static one — the same static-vs-runtime reconciliation idiom as
  the transfer census.

``schedule`` is deliberately stdlib-only (and ``findings``-only) so the
thread-crossed subsystems can import the lock seam at module-init time.

See ``docs/analysis.md`` for the full code catalogue.
"""
from .findings import CODES, Finding, PreflightError, Report, Severity  # noqa: F401
from .plan_audit import audit_serving_plan  # noqa: F401
from .preflight import preflight  # noqa: F401

__all__ = [
    "CODES",
    "Finding",
    "PreflightError",
    "Report",
    "Severity",
    "audit_serving_plan",
    "preflight",
]
