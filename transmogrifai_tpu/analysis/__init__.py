"""Static-analysis plane: TP-coded findings over DAGs, plans and code.

Seven analysers share one :class:`Finding`/:class:`Report` core
(``analysis/findings.py``):

* :mod:`~transmogrifai_tpu.analysis.preflight` — ``TPA0xx`` pre-flight
  DAG validation (``Workflow.validate()``; runs automatically at the top
  of ``train()``), restoring the reference's compile-time feature-type
  guarantees as an eager check.
* :mod:`~transmogrifai_tpu.analysis.plan_audit` — ``TPX0xx`` serving-plan
  audit: symbolic ``[N, width]`` shape propagation, the host↔device
  transfer census, recompile-hazard and donation checks
  (``score_fn.metadata()["analysis"]``).
* :mod:`~transmogrifai_tpu.analysis.lint` — ``TPL0xx`` AST lint of the
  package's own invariants (``python -m transmogrifai_tpu lint``, gated
  in CI against the committed ``lint_baseline.json``).
* :mod:`~transmogrifai_tpu.analysis.concurrency` — ``TPC0xx`` cross-module
  static concurrency analysis: the inferred lock registry, the whole-repo
  lock-order graph with cycle (potential-deadlock) detection,
  guarded-field discipline, foreign-callable-under-lock, and non-atomic
  publish checks (``python -m transmogrifai_tpu lint --concurrency``,
  gated against ``concurrency_baseline.json``).
* :mod:`~transmogrifai_tpu.analysis.schedule` — the dynamic side of the
  concurrency plane: injectable instrumented locks
  (``TPTPU_LOCK_TRACE=1``, off by default) recording the ACTUAL
  acquisition order into a dynamic lock-order graph, and
  ``reconcile_lock_orders`` asserting the dynamic graph is a subgraph of
  the static one — the same static-vs-runtime reconciliation idiom as
  the transfer census.
* :mod:`~transmogrifai_tpu.analysis.program` — ``TPJ0xx`` compiled-
  program contract audit: jaxpr-level IR lints over every registered
  XLA program plus the tracing-hazard AST lint and the three-way
  transfer-census reconciliation (``python -m transmogrifai_tpu lint
  --programs``, gated against ``program_baseline.json``).
* :mod:`~transmogrifai_tpu.analysis.spmd` — ``TPS0xx`` SPMD contract
  audit of the parallel plane: static collective-order divergence and
  PartitionSpec/axis-binding analysis, a jaxpr/HLO collective census of
  every registered shard_map kernel, and the per-host collective-tape
  reconciler riding the ``parallel/guarded.py`` seam
  (``TPTPU_COLLECTIVE_TRACE=1``; ``python -m transmogrifai_tpu lint
  --spmd``, gated against ``spmd_baseline.json``).

``schedule`` is deliberately stdlib-only (and ``findings``-only) so the
thread-crossed subsystems can import the lock seam at module-init time;
``parallel/guarded.py`` plays the same role for the collective tape.

See ``docs/analysis.md`` for the full code catalogue.
"""
from .findings import CODES, Finding, PreflightError, Report, Severity  # noqa: F401
from .plan_audit import audit_serving_plan  # noqa: F401
from .preflight import preflight  # noqa: F401

__all__ = [
    "CODES",
    "Finding",
    "PreflightError",
    "Report",
    "Severity",
    "audit_serving_plan",
    "preflight",
]
