"""TP-coded invariant linter (``TPL0xx``) — AST rules over this package.

The repo polices its own concurrency and determinism invariants the same
way the pre-flight pass polices user DAGs. Rules:

* **TPL001** — module-level mutable state written without holding a lock,
  in the thread-crossed subsystems (``featurize/``, ``compiler/``,
  ``utils/aot.py``, ``telemetry/``, ``serving/``, ``resilience/``): the
  chunk-pool workers, the async warmup thread, the telemetry span/event
  buffers, and the standing-service worker threads (which share the
  sentinel/breaker/quarantine state and the serving process flags) cross
  these modules with the main thread.
* **TPL002** — per-row Python loops inside ``ops/`` columnar hot paths
  (``transform_columns`` / ``blocks_for``): the PR-5 columnar engine
  killed these; new ones silently re-open the 10-100x serving gap.
* **TPL003** — ``jax.jit`` built inside a function that is not cache
  decorated: a fresh jit per call retraces/recompiles every invocation
  and bypasses the AOT executable bank (module-level jits are the
  sanctioned pattern — ``aot_call`` wraps those).
* **TPL004** — wall-clock calls (``time.time/monotonic/perf_counter/
  sleep``) inside ``resilience/``: every component there takes an
  injectable clock so the fault suite runs without sleeping; a literal
  clock call dodges the injection seam.
* **TPL005** — unseeded randomness anywhere (package and ``tools/``):
  legacy ``np.random.*`` global-state calls, ``np.random.default_rng()``
  with no seed, and the stdlib ``random`` module's global RNG.

Suppression: ``# tplint: ok`` or ``# tplint: disable=TPL003`` on the
offending line. Accepted legacy findings live in the committed
``lint_baseline.json`` — CI (``python -m transmogrifai_tpu lint``) fails
only on findings NOT in the baseline, so the bar ratchets.
"""
from __future__ import annotations

import ast
import json
import os
from collections import Counter
from typing import Any, Iterable

from .findings import Finding, Report, Severity

__all__ = [
    "lint_paths",
    "lint_source",
    "load_baseline",
    "new_findings",
    "baseline_entries",
]

#: subsystems whose module globals are crossed by worker/warmup threads
#: (telemetry/ buffers are written from scoring, pool, and warmup threads;
#: serving/ + resilience/ joined when the standing service put sentinel,
#: breaker, and shed state in front of concurrent service workers;
#: insights/ joined when the attribution ledger/drift monitor went in
#: front of concurrent explain sweeps; local/ joined when scoring closures
#: started carrying service-shared breaker/guard/quarantine state and the
#: fused-program holder in front of concurrent service workers; parallel/
#: joined when the guarded-collective seam grew the per-host tape — the
#: collective tracer records from whatever thread dispatches a reduction).
#: The concurrency analyzer (analysis/concurrency.py, TPC0xx) scopes its
#: whole-repo lock-order pass to this same list.
_LOCKED_SUBSYSTEMS = (
    "featurize/", "compiler/", "utils/aot.py", "telemetry/", "serving/",
    "resilience/", "insights/", "local/", "parallel/",
)

_MUTATORS = {
    "append", "add", "update", "pop", "popitem", "setdefault", "clear",
    "extend", "remove", "discard", "insert",
}

_WALLCLOCK = {"time", "monotonic", "perf_counter", "perf_counter_ns", "sleep"}

_NP_LEGACY = {
    "seed", "rand", "randn", "randint", "random", "random_sample", "ranf",
    "sample", "choice", "shuffle", "permutation", "uniform", "normal",
    "standard_normal", "beta", "binomial", "poisson", "exponential",
    "bytes",
}

_PY_RANDOM = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "uniform", "gauss", "sample", "betavariate", "expovariate",
    "getrandbits", "triangular", "vonmisesvariate",
}

_CACHE_DECORATORS = {"lru_cache", "cache", "cached_property"}


def _suppressed(line: str, code: str) -> bool:
    from .findings import suppressed

    return suppressed(line, code)


from .findings import attr_chain as _attr_chain  # shared AST helper


def _is_cached(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        chain = _attr_chain(target)
        if chain and chain[-1] in _CACHE_DECORATORS:
            return True
    return False


# --------------------------------------------------------------------------
# per-rule scanners
# --------------------------------------------------------------------------
def _module_mutable_globals(tree: ast.Module) -> set[str]:
    """Module-level names bound to mutable containers (dict/list/set
    literals or constructor calls)."""
    names: set[str] = set()
    for stmt in tree.body:
        targets: list[ast.expr] = []
        value: ast.expr | None = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                                     ast.DictComp, ast.SetComp))
        if isinstance(value, ast.Call):
            chain = _attr_chain(value.func)
            if chain and chain[-1] in {
                "dict", "list", "set", "defaultdict", "OrderedDict",
                "deque", "Counter",
            }:
                mutable = True
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


from .findings import lock_guarded_expr as _lock_guarded  # shared heuristic


class _SharedStateVisitor(ast.NodeVisitor):
    """TPL001 — subscript writes / mutator calls on module globals
    outside a ``with <lock>`` block."""

    def __init__(
        self,
        globals_: set[str],
        hits: list[tuple[int, str]],
        root: ast.AST,
    ):
        self.globals = globals_
        self.hits = hits
        self.lock_depth = 0
        self.root = root

    def _visit_function(self, node: ast.AST) -> None:
        # nested defs get their own pass from _scan_shared_state (and run
        # outside any enclosing `with lock:` anyway) — descending here
        # would report each of their hits twice
        if node is self.root:
            self.generic_visit(node)

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_With(self, node: ast.With) -> None:
        guarded = any(_lock_guarded(i.context_expr) for i in node.items)
        if guarded:
            self.lock_depth += 1
        self.generic_visit(node)
        if guarded:
            self.lock_depth -= 1

    def _check_target(self, target: ast.expr, lineno: int) -> None:
        if self.lock_depth:
            return
        if isinstance(target, ast.Subscript) and isinstance(
            target.value, ast.Name
        ) and target.value.id in self.globals:
            self.hits.append((lineno, target.value.id))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if (
            not self.lock_depth
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in self.globals
        ):
            self.hits.append((node.lineno, node.func.value.id))
        self.generic_visit(node)


def _scan_shared_state(tree: ast.Module, report_hits: list) -> None:
    globals_ = _module_mutable_globals(tree)
    if not globals_:
        return
    for fn in [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]:
        hits: list[tuple[int, str]] = []
        _SharedStateVisitor(globals_, hits, fn).visit(fn)
        for lineno, name in hits:
            report_hits.append((
                "TPL001", lineno,
                f"module global '{name}' mutated in {fn.name}() without "
                "holding a lock (thread-crossed subsystem)",
            ))


def _is_row_iter(it: ast.expr) -> bool:
    """range(num_rows) / X.to_list() / zip|enumerate over a .to_list()."""
    if isinstance(it, ast.Call):
        if isinstance(it.func, ast.Attribute) and it.func.attr == "to_list":
            return True
        chain = _attr_chain(it.func)
        if chain == ["range"] and any(
            isinstance(a, ast.Name) and a.id == "num_rows" for a in it.args
        ):
            return True
        if chain and chain[-1] in ("zip", "enumerate"):
            return any(_is_row_iter(a) for a in it.args)
    return False


def _scan_row_loops(tree: ast.Module, report_hits: list) -> None:
    for fn in [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name in ("transform_columns", "blocks_for")
    ]:
        for node in ast.walk(fn):
            if isinstance(node, ast.For) and _is_row_iter(node.iter):
                report_hits.append((
                    "TPL002", node.lineno,
                    f"per-row Python loop in {fn.name}() — hot-path "
                    "transforms must stay columnar (vectorize or use the "
                    "native kernels)",
                ))
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp,
                       ast.GeneratorExp)
            ) and any(_is_row_iter(g.iter) for g in node.generators):
                report_hits.append((
                    "TPL002", node.lineno,
                    f"per-row comprehension in {fn.name}() — hot-path "
                    "transforms must stay columnar (vectorize or use the "
                    "native kernels)",
                ))


def _function_body_minus_nested(fn: ast.AST):
    """Nodes of ``fn``'s body excluding nested function BODIES (their
    decorators still belong to ``fn``'s execution)."""
    out: list[ast.AST] = []
    stack: list[ast.AST] = list(getattr(fn, "body", ()))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _scan_naked_jit(tree: ast.Module, report_hits: list) -> None:
    for fn in [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]:
        if _is_cached(fn):
            continue
        for node in _function_body_minus_nested(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain[-2:] == ["jax", "jit"] or chain == ["jit"]:
                report_hits.append((
                    "TPL003", node.lineno,
                    f"jax.jit built inside uncached {fn.name}() — a fresh "
                    "jit per call retraces every invocation and bypasses "
                    "the AOT executable bank (hoist to module level or "
                    "lru_cache the factory)",
                ))


def _scan_wallclock(tree: ast.Module, report_hits: list) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if (
            len(chain) == 2
            and chain[1] in _WALLCLOCK
            and chain[0] in ("time", "_time", "_t")
        ):
            report_hits.append((
                "TPL004", node.lineno,
                f"wall-clock call {'.'.join(chain)}() in resilience/ — "
                "route through the component's injectable clock so the "
                "fault suite stays deterministic",
            ))


def _scan_unseeded_rng(tree: ast.Module, report_hits: list) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if len(chain) == 3 and chain[:2] in (["np", "random"],
                                             ["numpy", "random"]):
            if chain[2] == "default_rng":
                if not node.args and not node.keywords:
                    report_hits.append((
                        "TPL005", node.lineno,
                        "np.random.default_rng() without a seed — results "
                        "are irreproducible; pass an explicit seed",
                    ))
            elif chain[2] in _NP_LEGACY:
                report_hits.append((
                    "TPL005", node.lineno,
                    f"legacy np.random.{chain[2]}() uses hidden global "
                    "state — use np.random.default_rng(seed)",
                ))
        elif chain[:1] == ["random"] and len(chain) == 2:
            if chain[1] == "Random":
                if not node.args and not node.keywords:
                    report_hits.append((
                        "TPL005", node.lineno,
                        "random.Random() without a seed — pass an explicit "
                        "seed",
                    ))
            elif chain[1] in _PY_RANDOM:
                report_hits.append((
                    "TPL005", node.lineno,
                    f"stdlib random.{chain[1]}() uses the global RNG — "
                    "use a seeded random.Random(seed)",
                ))


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------
def lint_source(source: str, rel_path: str) -> Report:
    """Lint one file's source. ``rel_path`` (posix, repo-relative) selects
    which rules apply and keys the findings for the baseline."""
    report = Report()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        report.add(
            "TPL000",
            f"file does not parse: {e}",
            subject=f"{rel_path}:{e.lineno or 0}",
            severity=Severity.WARNING,
            path=rel_path, line=e.lineno or 0, context="",
        )
        return report
    lines = source.splitlines()
    hits: list[tuple[str, int, str]] = []

    rel = rel_path.replace(os.sep, "/")
    if any(seg in rel for seg in _LOCKED_SUBSYSTEMS):
        _scan_shared_state(tree, hits)
    if "/ops/" in rel or rel.startswith("ops/"):
        _scan_row_loops(tree, hits)
    if "/resilience/" in rel or rel.startswith("resilience/"):
        _scan_wallclock(tree, hits)
    _scan_naked_jit(tree, hits)
    _scan_unseeded_rng(tree, hits)

    for code, lineno, message in sorted(hits, key=lambda h: (h[1], h[0])):
        context = lines[lineno - 1].strip() if 0 < lineno <= len(lines) else ""
        if _suppressed(context, code):
            continue
        report.add(
            code, message,
            subject=f"{rel}:{lineno}",
            severity=Severity.WARNING,
            path=rel, line=lineno, context=context,
        )
    return report


def lint_paths(paths: Iterable[str], root: str = ".") -> Report:
    """Lint every ``.py`` file under ``paths`` (files or directories).
    Finding paths are stored relative to ``root`` so the committed
    baseline is location-independent."""
    report = Report()
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git", "node_modules")
            ]
            files.extend(
                os.path.join(dirpath, f)
                for f in filenames
                if f.endswith(".py")
            )
    for path in sorted(files):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        report.extend(lint_source(source, rel))
    return report


# --------------------------------------------------------------------------
# baseline
# --------------------------------------------------------------------------
def _finding_key(f: Finding) -> tuple[str, str, str]:
    """Line-number-independent identity: (code, path, source context) —
    renumbering a file does not invalidate the baseline, editing the
    offending line does."""
    d = f.detail
    return (f.code, d.get("path", ""), d.get("context", ""))


def baseline_entries(report: Report) -> dict[str, Any]:
    """JSON-able baseline from a report (``--write-baseline``)."""
    return {
        "version": 1,
        "findings": [
            {
                "code": f.code,
                "path": f.detail.get("path", ""),
                "context": f.detail.get("context", ""),
            }
            for f in report.findings
        ],
    }


def load_baseline(path: str) -> Counter:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return Counter(
        (e["code"], e["path"], e["context"]) for e in data.get("findings", [])
    )


def new_findings(report: Report, baseline: Counter | None) -> list[Finding]:
    """Findings not covered by the baseline multiset: the CI gate."""
    if not baseline:
        return list(report.findings)
    budget = Counter(baseline)
    out: list[Finding] = []
    for f in report.findings:
        key = _finding_key(f)
        if budget[key] > 0:
            budget[key] -= 1
        else:
            out.append(f)
    return out
