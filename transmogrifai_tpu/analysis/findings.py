"""Finding/Report core of the static-analysis plane.

The reference's headline guarantee is *compile-time typed* feature
pipelines: Scala's type system rejects an invalid DAG before Spark ever
runs (SURVEY §1). A Python rebuild cannot lean on a compiler, so this
package makes the same class of defect machine-checkable as an eager
static pass: every rule emits a TP-coded :class:`Finding` through one
shared :class:`Report`, whether it came from the pre-flight DAG validator
(``TPA0xx``), the serving-plan auditor (``TPX0xx``) or the package linter
(``TPL0xx``). One vocabulary, three analysers, one rendering.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Iterable, Iterator


class Severity(enum.Enum):
    ERROR = "error"      # refuses train()/CI — the DAG/plan/code is wrong
    WARNING = "warning"  # suspicious but runnable; CI fails only on NEW ones
    INFO = "info"        # census/ledger data riding the report

    def __str__(self) -> str:  # noqa: D105
        return self.value


#: registry of every analyser code — docs/analysis.md catalogues these and
#: the tests assert emitted findings use registered codes only.
CODES: dict[str, str] = {
    # ---- TPA: pre-flight DAG validation (analysis/preflight.py)
    "TPA001": "stage input feature type incompatible with declared input_types",
    "TPA002": "stage wired with the wrong number of input features",
    "TPA003": "response lineage leaks into a predictor's feature input",
    "TPA004": "duplicate output feature name across distinct stages",
    "TPA005": "two distinct raw features share one name",
    "TPA006": "orphan feature: no origin stage and not a declared raw leaf",
    "TPA007": "stage has no input features wired",
    "TPA008": "stateful stage used before fit (estimator in a serving plan)",
    "TPA009": "cycle in the stage graph",
    "TPA010": "layer inconsistency: stage scheduled before an ancestor",
    "TPA011": "duplicate stage uid across distinct stage objects",
    "TPA012": "stage is neither Estimator nor Transformer",
    "TPA013": "more than one ModelSelector in the workflow",
    # ---- TPX: serving-plan audit (analysis/plan_audit.py)
    "TPX001": "device dispatch keyed on raw batch size (recompile hazard)",
    "TPX002": "host stage sandwiched between device stages (transfer bounce)",
    "TPX003": "donated buffer read again after a donating() dispatch",
    "TPX004": "stage width unknown until the first batch (shapes unprovable)",
    "TPX005": "lane bucketing disabled (TPTPU_LANE_BUCKETS=0)",
    "TPX006": "fused plane assembly unavailable for this plan",
    "TPX007": "predictor feature vector carries no usable provenance "
              "metadata — LOCO attributions degrade to anonymous "
              "per-column groups",
    "TPX008": "fused scoring graph unavailable or degraded — steady-state "
              "batches fall back to the staged loop",
    # ---- TPR: cross-run regression sentinel (telemetry/runlog.py)
    "TPR001": "training phase slowed beyond tolerance between runs",
    "TPR002": "compiled-program count blew up between runs",
    "TPR003": "host<->device transfer volume grew beyond tolerance "
              "between runs",
    "TPR004": "quality metric dropped beyond tolerance between runs",
    # ---- TPL: package invariant lint (analysis/lint.py)
    "TPL000": "file does not parse — the linter cannot scan it",
    "TPL001": "shared module-level state written without holding a lock",
    "TPL002": "per-row Python loop in an ops/ columnar hot path",
    "TPL003": "jax.jit built inside an uncached function (retrace hazard)",
    "TPL004": "wall-clock call in resilience/ (inject the clock instead)",
    "TPL005": "unseeded random source",
    # ---- TPJ: compiled-program contract audit (analysis/program.py)
    "TPJ000": "program could not be traced — the auditor cannot inspect it",
    "TPJ001": "giant constant folded into the compiled program instead of "
              "arriving as a traced argument",
    "TPJ002": "f64/x64 value or weak-type promotion inside a device program",
    "TPJ003": "declared donated argument is never aliased into the "
              "compiled output (donation is a no-op)",
    "TPJ004": "host callback / pure_callback / debug print inside a "
              "device program",
    "TPJ005": "jaxpr structure drifts across lane/shape buckets "
              "(recompile-hazard fork)",
    "TPJ006": "program-level transfer counts disagree with the "
              "static-plan / runtime transfer census",
    "TPJ007": "Python control flow branches on a traced value inside a "
              "jitted body",
    "TPJ008": "host-sync coercion (.item()/float()/np.asarray) inside a "
              "jitted body",
    "TPJ009": "jitted function closes over an ndarray value (baked as a "
              "program constant)",
    "TPJ010": "warmup family map and the traceable-program registry "
              "disagree (silent cold start or dead map entry)",
    # ---- TPS: SPMD contract audit (analysis/spmd.py + parallel/guarded.py)
    "TPS000": "file/program could not be analyzed — the SPMD auditor "
              "cannot inspect it",
    "TPS001": "collective issue order may diverge across hosts: python "
              "control flow on a host-varying value guards a collective",
    "TPS002": "shard_map body uses an axis name the wrapping mesh/in_specs "
              "never bind",
    "TPS003": "PartitionSpec rank/axis mismatch against the array or mesh "
              "it shards",
    "TPS004": "non-commutative or dtype-unstable op inside a guarded "
              "reduction (breaks the bit-identical merge contract)",
    "TPS005": "collective issued while holding a lock (cross-host "
              "deadlock bridge into the TPC lock graph)",
    "TPS006": "lowered HLO contains a collective kind the jaxpr census "
              "never declared (hidden resharding)",
    "TPS007": "host-dependent shape feeds a collective (one compiled "
              "program per host — recompile storm)",
    "TPS008": "per-host collective tapes diverge or are unexplained by "
              "the static census",
    # ---- TPC: concurrency analysis (analysis/concurrency.py + schedule.py)
    "TPC000": "file does not parse — the concurrency analyzer cannot scan it",
    "TPC001": "potential deadlock: cycle in the static lock-order graph",
    "TPC002": "field written under a lock at some sites but bare at others",
    "TPC003": "field guarded by different locks at different write sites",
    "TPC004": "foreign callable (user callback / exposition source) invoked "
              "while holding a lock",
    "TPC005": "non-atomic publish: shared attribute built up across "
              "multiple statements instead of build-then-single-assign",
    "TPC006": "dynamic lock-order edge observed at runtime is invisible to "
              "the static lock-order graph",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One TP-coded diagnostic.

    ``subject`` names what the finding is about (a stage uid, feature name
    or ``path:line``); ``detail`` carries structured context for JSON
    surfaces (never required for rendering)."""

    code: str
    message: str
    subject: str = ""
    severity: Severity = Severity.ERROR
    detail: dict[str, Any] = dataclasses.field(default_factory=dict, hash=False)

    def __post_init__(self) -> None:
        if self.code not in CODES:
            raise ValueError(f"unregistered analyser code {self.code!r}")

    def render(self) -> str:
        where = f" [{self.subject}]" if self.subject else ""
        return f"{self.code} {self.severity}: {self.message}{where}"

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "code": self.code,
            "severity": str(self.severity),
            "message": self.message,
            "subject": self.subject,
        }
        if self.detail:
            out["detail"] = self.detail
        return out


class PreflightError(ValueError):
    """A pre-flight pass found errors. Subclasses ``ValueError``, matching
    the historical ``validate_stages`` behaviour for wiring/uid errors
    (the old stage-kind ``TypeError`` is subsumed: every finding class now
    raises this one type); carries the full :class:`Report` for
    programmatic access."""

    def __init__(self, report: "Report"):
        self.report = report
        errors = report.errors()
        lines = [f.render() for f in errors]
        super().__init__(
            f"static analysis found {len(errors)} error(s):\n  "
            + "\n  ".join(lines)
        )


class Report:
    """An ordered collection of findings plus analyser attachments
    (``data`` — e.g. the plan auditor's transfer census)."""

    def __init__(self, findings: Iterable[Finding] = ()) -> None:
        self.findings: list[Finding] = list(findings)
        self.data: dict[str, Any] = {}

    # ------------------------------------------------------------ building
    def add(
        self,
        code: str,
        message: str,
        subject: str = "",
        severity: Severity = Severity.ERROR,
        **detail: Any,
    ) -> Finding:
        f = Finding(code, message, subject, severity, detail)
        self.findings.append(f)
        return f

    def extend(self, other: "Report") -> "Report":
        self.findings.extend(other.findings)
        self.data.update(other.data)
        return self

    # ------------------------------------------------------------- queries
    def __iter__(self) -> Iterator[Finding]:
        return iter(self.findings)

    def __len__(self) -> int:
        return len(self.findings)

    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity is Severity.ERROR]

    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity is Severity.WARNING]

    def by_code(self, code: str) -> list[Finding]:
        return [f for f in self.findings if f.code == code]

    @property
    def ok(self) -> bool:
        """True when no ERROR findings (warnings/info don't refuse)."""
        return not self.errors()

    def raise_if_errors(self) -> "Report":
        if not self.ok:
            raise PreflightError(self)
        return self

    # ----------------------------------------------------------- rendering
    def pretty(self) -> str:
        if not self.findings:
            return "no findings"
        return "\n".join(f.render() for f in self.findings)

    def summary_line(self) -> str:
        """One line for ``summary_pretty()``: counts + distinct codes."""
        codes: dict[str, int] = {}
        for f in self.findings:
            codes[f.code] = codes.get(f.code, 0) + 1
        code_s = ", ".join(
            f"{c}×{n}" if n > 1 else c for c, n in sorted(codes.items())
        )
        return (
            f"Static analysis: {len(self.errors())} error(s), "
            f"{len(self.warnings())} warning(s)"
            + (f" ({code_s})" if code_s else "")
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "findings": [f.to_json() for f in self.findings],
            "errors": len(self.errors()),
            "warnings": len(self.warnings()),
            **self.data,
        }


# --------------------------------------------------------------------------
# shared comment-directive parser (one grammar for every analyser)
# --------------------------------------------------------------------------
# Historically each analyser grew its own dialect (``# tplint: disable=``,
# ``# tpc: lock(key)``) with copy-paste-divergent parsing. The canonical
# spelling is now the unified ``# tp: <verb>`` prefix, understood by every
# analyser; the per-analyser prefixes keep working — ``tpj:`` as a plain
# alias, ``tplint:``/``tpc:`` as DEPRECATED legacy spellings (one release,
# warned once per dialect per process).
#
# Grammar (one or more directives per comment, whitespace-tolerant):
#   # tp: ok                      suppress every finding on this line
#   # tp: disable=TPL003          suppress one code (comma-list accepted)
#   # tp: lock(key)               concurrency lock-alias annotation
#   # tp: guarded(key)            caller-holds-the-lock annotation
#   # tp: type(Cls)               attribute-type hint for call resolution
import logging as _logging
import re as _re

_DIRECTIVE_PREFIXES = ("tp", "tplint", "tpc", "tpj", "tps")
_LEGACY_PREFIXES = ("tplint", "tpc")
_DIR_RE = _re.compile(
    # disable codes are exact TPx-code tokens (comma-separated) so a
    # trailing uppercase rationale ("# tp: disable=TPL003 SEE DOCS")
    # can never corrupt the code being suppressed
    r"#\s*(tp|tplint|tpc|tpj|tps):\s*"
    r"(ok|disable=[A-Z]{3}\d+(?:\s*,\s*[A-Z]{3}\d+)*"
    r"|(?:lock|guarded|type)\(\s*[^)]+?\s*\))"
)
_log = _logging.getLogger(__name__)
_warned_legacy: set = set()


def _warn_legacy(prefix: str) -> None:
    if prefix in _LEGACY_PREFIXES and prefix not in _warned_legacy:
        _warned_legacy.add(prefix)
        _log.warning(
            "'# %s:' directives are deprecated — use the unified '# tp:' "
            "prefix (the old spelling keeps working for one release)",
            prefix,
        )


def parse_directives(line: str) -> list[tuple[str, str, str]]:
    """Every directive on ``line`` as ``(prefix, verb, arg)`` tuples:
    ``("tp", "disable", "TPL003")``, ``("tpc", "lock", "key")``,
    ``("tp", "ok", "")``. Legacy prefixes warn once per process."""
    out: list[tuple[str, str, str]] = []
    for m in _DIR_RE.finditer(line):
        prefix, body = m.group(1), m.group(2)
        _warn_legacy(prefix)
        if body == "ok":
            out.append((prefix, "ok", ""))
        elif body.startswith("disable="):
            for code in body[len("disable="):].split(","):
                code = code.strip()
                if code:
                    out.append((prefix, "disable", code))
        else:
            verb, _, arg = body.partition("(")
            out.append((prefix, verb.strip(), arg.rstrip(")").strip()))
    return out


#: analyser code family -> the legacy per-analyser prefix it honours
_FAMILY_PREFIX = {"TPL": "tplint", "TPC": "tpc", "TPJ": "tpj", "TPS": "tps"}


def suppressed(line: str, code: str) -> bool:
    """True when ``line`` carries a directive suppressing ``code`` — in
    the unified ``tp`` dialect or the code family's own prefix. An ``ok``
    under a DIFFERENT analyser's prefix does not leak across families
    (``# tpc: ok`` must not silence a TPL finding on the same line)."""
    family = _FAMILY_PREFIX.get(code[:3])
    for prefix, verb, arg in parse_directives(line):
        if prefix not in ("tp", family):
            continue
        if verb == "ok":
            return True
        if verb == "disable" and arg == code:
            return True
    return False


def annotations(line: str, verb: str, family: str | None = None) -> list[str]:
    """Arguments of every ``verb(...)`` annotation on ``line`` (``lock``,
    ``guarded``, ``type``) in the unified dialect or ``family``'s prefix."""
    out = []
    for prefix, v, arg in parse_directives(line):
        if v != verb:
            continue
        if prefix == "tp" or family is None or prefix == family:
            out.append(arg)
    return out


def attr_chain(node) -> list[str]:
    """``['np', 'random', 'choice']`` for ``np.random.choice`` — ``[]``
    when the expression is not a plain name/attribute chain. The one AST
    helper every analyser shares (lint, concurrency, program, spmd)."""
    import ast as _ast

    parts: list[str] = []
    while isinstance(node, _ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, _ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def lock_guarded_expr(expr) -> bool:
    """True when a ``with``-item context expression looks like a lock
    acquisition (any chain part mentions "lock"). ONE heuristic shared by
    TPL001 (unlocked shared state) and TPS005 (collective under lock) so
    the two families can never silently diverge on what counts as a
    lock."""
    import ast as _ast

    chain = attr_chain(expr)
    if isinstance(expr, _ast.Call):
        chain = attr_chain(expr.func)
    return any("lock" in part.lower() for part in chain)
