"""Finding/Report core of the static-analysis plane.

The reference's headline guarantee is *compile-time typed* feature
pipelines: Scala's type system rejects an invalid DAG before Spark ever
runs (SURVEY §1). A Python rebuild cannot lean on a compiler, so this
package makes the same class of defect machine-checkable as an eager
static pass: every rule emits a TP-coded :class:`Finding` through one
shared :class:`Report`, whether it came from the pre-flight DAG validator
(``TPA0xx``), the serving-plan auditor (``TPX0xx``) or the package linter
(``TPL0xx``). One vocabulary, three analysers, one rendering.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Iterable, Iterator


class Severity(enum.Enum):
    ERROR = "error"      # refuses train()/CI — the DAG/plan/code is wrong
    WARNING = "warning"  # suspicious but runnable; CI fails only on NEW ones
    INFO = "info"        # census/ledger data riding the report

    def __str__(self) -> str:  # noqa: D105
        return self.value


#: registry of every analyser code — docs/analysis.md catalogues these and
#: the tests assert emitted findings use registered codes only.
CODES: dict[str, str] = {
    # ---- TPA: pre-flight DAG validation (analysis/preflight.py)
    "TPA001": "stage input feature type incompatible with declared input_types",
    "TPA002": "stage wired with the wrong number of input features",
    "TPA003": "response lineage leaks into a predictor's feature input",
    "TPA004": "duplicate output feature name across distinct stages",
    "TPA005": "two distinct raw features share one name",
    "TPA006": "orphan feature: no origin stage and not a declared raw leaf",
    "TPA007": "stage has no input features wired",
    "TPA008": "stateful stage used before fit (estimator in a serving plan)",
    "TPA009": "cycle in the stage graph",
    "TPA010": "layer inconsistency: stage scheduled before an ancestor",
    "TPA011": "duplicate stage uid across distinct stage objects",
    "TPA012": "stage is neither Estimator nor Transformer",
    "TPA013": "more than one ModelSelector in the workflow",
    # ---- TPX: serving-plan audit (analysis/plan_audit.py)
    "TPX001": "device dispatch keyed on raw batch size (recompile hazard)",
    "TPX002": "host stage sandwiched between device stages (transfer bounce)",
    "TPX003": "donated buffer read again after a donating() dispatch",
    "TPX004": "stage width unknown until the first batch (shapes unprovable)",
    "TPX005": "lane bucketing disabled (TPTPU_LANE_BUCKETS=0)",
    "TPX006": "fused plane assembly unavailable for this plan",
    "TPX007": "predictor feature vector carries no usable provenance "
              "metadata — LOCO attributions degrade to anonymous "
              "per-column groups",
    "TPX008": "fused scoring graph unavailable or degraded — steady-state "
              "batches fall back to the staged loop",
    # ---- TPR: cross-run regression sentinel (telemetry/runlog.py)
    "TPR001": "training phase slowed beyond tolerance between runs",
    "TPR002": "compiled-program count blew up between runs",
    "TPR003": "host<->device transfer volume grew beyond tolerance "
              "between runs",
    "TPR004": "quality metric dropped beyond tolerance between runs",
    # ---- TPL: package invariant lint (analysis/lint.py)
    "TPL000": "file does not parse — the linter cannot scan it",
    "TPL001": "shared module-level state written without holding a lock",
    "TPL002": "per-row Python loop in an ops/ columnar hot path",
    "TPL003": "jax.jit built inside an uncached function (retrace hazard)",
    "TPL004": "wall-clock call in resilience/ (inject the clock instead)",
    "TPL005": "unseeded random source",
    # ---- TPC: concurrency analysis (analysis/concurrency.py + schedule.py)
    "TPC000": "file does not parse — the concurrency analyzer cannot scan it",
    "TPC001": "potential deadlock: cycle in the static lock-order graph",
    "TPC002": "field written under a lock at some sites but bare at others",
    "TPC003": "field guarded by different locks at different write sites",
    "TPC004": "foreign callable (user callback / exposition source) invoked "
              "while holding a lock",
    "TPC005": "non-atomic publish: shared attribute built up across "
              "multiple statements instead of build-then-single-assign",
    "TPC006": "dynamic lock-order edge observed at runtime is invisible to "
              "the static lock-order graph",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One TP-coded diagnostic.

    ``subject`` names what the finding is about (a stage uid, feature name
    or ``path:line``); ``detail`` carries structured context for JSON
    surfaces (never required for rendering)."""

    code: str
    message: str
    subject: str = ""
    severity: Severity = Severity.ERROR
    detail: dict[str, Any] = dataclasses.field(default_factory=dict, hash=False)

    def __post_init__(self) -> None:
        if self.code not in CODES:
            raise ValueError(f"unregistered analyser code {self.code!r}")

    def render(self) -> str:
        where = f" [{self.subject}]" if self.subject else ""
        return f"{self.code} {self.severity}: {self.message}{where}"

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "code": self.code,
            "severity": str(self.severity),
            "message": self.message,
            "subject": self.subject,
        }
        if self.detail:
            out["detail"] = self.detail
        return out


class PreflightError(ValueError):
    """A pre-flight pass found errors. Subclasses ``ValueError``, matching
    the historical ``validate_stages`` behaviour for wiring/uid errors
    (the old stage-kind ``TypeError`` is subsumed: every finding class now
    raises this one type); carries the full :class:`Report` for
    programmatic access."""

    def __init__(self, report: "Report"):
        self.report = report
        errors = report.errors()
        lines = [f.render() for f in errors]
        super().__init__(
            f"static analysis found {len(errors)} error(s):\n  "
            + "\n  ".join(lines)
        )


class Report:
    """An ordered collection of findings plus analyser attachments
    (``data`` — e.g. the plan auditor's transfer census)."""

    def __init__(self, findings: Iterable[Finding] = ()) -> None:
        self.findings: list[Finding] = list(findings)
        self.data: dict[str, Any] = {}

    # ------------------------------------------------------------ building
    def add(
        self,
        code: str,
        message: str,
        subject: str = "",
        severity: Severity = Severity.ERROR,
        **detail: Any,
    ) -> Finding:
        f = Finding(code, message, subject, severity, detail)
        self.findings.append(f)
        return f

    def extend(self, other: "Report") -> "Report":
        self.findings.extend(other.findings)
        self.data.update(other.data)
        return self

    # ------------------------------------------------------------- queries
    def __iter__(self) -> Iterator[Finding]:
        return iter(self.findings)

    def __len__(self) -> int:
        return len(self.findings)

    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity is Severity.ERROR]

    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity is Severity.WARNING]

    def by_code(self, code: str) -> list[Finding]:
        return [f for f in self.findings if f.code == code]

    @property
    def ok(self) -> bool:
        """True when no ERROR findings (warnings/info don't refuse)."""
        return not self.errors()

    def raise_if_errors(self) -> "Report":
        if not self.ok:
            raise PreflightError(self)
        return self

    # ----------------------------------------------------------- rendering
    def pretty(self) -> str:
        if not self.findings:
            return "no findings"
        return "\n".join(f.render() for f in self.findings)

    def summary_line(self) -> str:
        """One line for ``summary_pretty()``: counts + distinct codes."""
        codes: dict[str, int] = {}
        for f in self.findings:
            codes[f.code] = codes.get(f.code, 0) + 1
        code_s = ", ".join(
            f"{c}×{n}" if n > 1 else c for c, n in sorted(codes.items())
        )
        return (
            f"Static analysis: {len(self.errors())} error(s), "
            f"{len(self.warnings())} warning(s)"
            + (f" ({code_s})" if code_s else "")
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "findings": [f.to_json() for f in self.findings],
            "errors": len(self.errors()),
            "warnings": len(self.warnings()),
            **self.data,
        }
