"""Pre-flight DAG validation — the compile-time feature-type check, eagerly.

Reference guarantee being restored: TransmogrifAI's 45 typed feature
wrappers make an invalid stage wiring *unrepresentable* — the Scala
compiler rejects it (SURVEY §1). Here the same rules run as a static pass
over the lineage-traced feature DAG, BEFORE any data is read:

* per-edge feature-type compatibility against each stage's declared
  ``input_types`` (TPA001/TPA002),
* response-lineage leakage — a predictor whose feature input can reach a
  raw response through anything but a sanctioned label slot (TPA003),
* duplicate/orphan outputs, duplicate raw names and stage uids
  (TPA004/TPA005/TPA006/TPA011),
* stateful-stage-before-fit contract for serving plans (TPA008),
* cycle and layer-consistency checks over ``compute_dag`` (TPA009/TPA010),
  subsuming the thin historical ``validate_stages``.

Entry points: :func:`preflight` (used by ``Workflow.validate()`` and run
automatically at the top of ``Workflow.train()``) and
:func:`structural_findings` (the layer-shaped subset behind
``workflow.dag.validate_stages``). The pass is pure graph walking — on the
flagship titanic flow it costs well under a millisecond, irrelevant next
to ``train()``.
"""
from __future__ import annotations

from typing import Iterable, Sequence

from ..features.feature import Feature, FeatureGeneratorStage
from ..stages.base import Estimator, PipelineStage, Transformer
from .findings import Report, Severity

__all__ = ["preflight", "structural_findings"]


# --------------------------------------------------------------------------
# cycle-safe graph collection
# --------------------------------------------------------------------------
def _live_inputs(stage: PipelineStage) -> tuple[Feature, ...]:
    return tuple(getattr(stage, "input_features", ()) or ())


def _collect(
    result_features: Iterable[Feature],
) -> tuple[list[PipelineStage], list[Feature], list[list[PipelineStage]]]:
    """(stages, leaf features, cycles) reachable from the result features.

    Unlike ``Feature.parent_stages`` this walk is cycle-SAFE: a hand-wired
    loop is reported as a finding instead of blowing the recursion limit
    deep inside ``train()``. Leaves are features with a generator origin or
    no origin at all."""
    stages: list[PipelineStage] = []
    seen_stages: set[int] = set()
    leaves: list[Feature] = []
    seen_leaves: set[int] = set()
    cycles: list[list[PipelineStage]] = []

    # iterative DFS over stages with colouring for cycle detection
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict[int, int] = {}

    def visit_feature(f: Feature, path: list[PipelineStage]) -> None:
        stage = f.origin_stage
        if stage is None or isinstance(stage, FeatureGeneratorStage):
            if id(f) not in seen_leaves:
                seen_leaves.add(id(f))
                leaves.append(f)
            return
        visit_stage(stage, path)

    def visit_stage(s: PipelineStage, path: list[PipelineStage]) -> None:
        c = color.get(id(s), 0)
        if c == BLACK:
            return
        if c == GRAY:
            # cycle: slice the current path from the first occurrence of s
            try:
                i = next(j for j, p in enumerate(path) if p is s)
            except StopIteration:
                i = 0
            cycles.append(path[i:] + [s])
            return
        color[id(s)] = GRAY
        path.append(s)
        for f in _live_inputs(s):
            visit_feature(f, path)
        path.pop()
        color[id(s)] = BLACK
        if id(s) not in seen_stages:
            seen_stages.add(id(s))
            stages.append(s)

    for rf in result_features:
        visit_feature(rf, [])
    return stages, leaves, cycles


# --------------------------------------------------------------------------
# individual checks
# --------------------------------------------------------------------------
def _check_wiring(stages: Sequence[PipelineStage], report: Report) -> None:
    """TPA001/TPA002/TPA007/TPA012 — the per-stage edge checks."""
    from ..types import is_subtype

    for s in stages:
        if not isinstance(s, (Estimator, Transformer)):
            report.add(
                "TPA012",
                f"stage {s!r} is neither Estimator nor Transformer",
                subject=getattr(s, "uid", repr(s)),
            )
            continue
        inputs = _live_inputs(s)
        if not inputs:
            report.add(
                "TPA007",
                f"stage {s!r} has no input features wired",
                subject=s.uid,
            )
            continue
        declared = s.input_types
        if declared is None:
            continue
        if len(inputs) != len(declared):
            report.add(
                "TPA002",
                f"stage {s!r} expects {len(declared)} input(s) "
                f"{tuple(t.__name__ for t in declared)}, got {len(inputs)} "
                f"({', '.join(f.name for f in inputs)})",
                subject=s.uid,
                expected=len(declared),
                got=len(inputs),
            )
            continue
        for i, (f, expected) in enumerate(zip(inputs, declared)):
            if not is_subtype(f.ftype, expected):
                report.add(
                    "TPA001",
                    f"stage {s!r} input {i} ('{f.name}') has type "
                    f"{f.ftype.__name__}, expected {expected.__name__}",
                    subject=s.uid,
                    feature=f.name,
                    position=i,
                    actual=f.ftype.__name__,
                    expected=expected.__name__,
                )


def _check_uids_and_outputs(
    stages: Sequence[PipelineStage],
    leaves: Sequence[Feature],
    report: Report,
) -> None:
    """TPA011 (uid collisions), TPA004 (output-name collisions incl. raw
    names — with_column would silently overwrite), TPA005 (raw-name
    collisions), TPA006 (origin-less features)."""
    by_uid: dict[str, PipelineStage] = {}
    for s in stages:
        prior = by_uid.get(s.uid)
        if prior is not None and prior is not s:
            report.add(
                "TPA011",
                f"duplicate stage uid '{s.uid}' on distinct stages "
                f"{type(prior).__name__} and {type(s).__name__}",
                subject=s.uid,
            )
        by_uid[s.uid] = s

    raw_by_name: dict[str, Feature] = {}
    for f in leaves:
        prior = raw_by_name.get(f.name)
        if prior is not None and prior.uid != f.uid:
            report.add(
                "TPA005",
                f"two distinct raw features named '{f.name}' in one DAG — "
                "they would silently read each other's data",
                subject=f.name,
            )
        raw_by_name.setdefault(f.name, f)
        if f.origin_stage is None:
            report.add(
                "TPA006",
                f"feature '{f.name}' has no origin stage; it will be read "
                "by name from the input data — declare it via "
                "FeatureBuilder so its extraction is part of the DAG",
                subject=f.name,
                severity=Severity.WARNING,
            )

    out_by_name: dict[str, PipelineStage] = {}
    for s in stages:
        name = _output_name(s)
        if name is None:
            continue
        prior = out_by_name.get(name)
        if prior is not None and prior is not s:
            report.add(
                "TPA004",
                f"stages {prior!r} and {s!r} both produce output feature "
                f"'{name}' — the later one silently overwrites the column",
                subject=name,
            )
        out_by_name.setdefault(name, s)
        if name in raw_by_name:
            report.add(
                "TPA004",
                f"stage {s!r} output '{name}' collides with a raw feature "
                "of the same name — the transform overwrites the raw column",
                subject=name,
            )


def _output_name(s: PipelineStage) -> str | None:
    try:
        return s.output_name
    except Exception:
        return None  # unwired stage; TPA007 already covers it


def _label_positions(stage: PipelineStage) -> frozenset[int]:
    return frozenset(getattr(stage, "label_inputs", ()) or ())


def _check_leakage(stages: Sequence[PipelineStage], report: Report) -> None:
    """TPA003 — response lineage reaching a predictor's FEATURE input.

    The sanctioned crossings are exactly the label slots declared by
    label-aware stages (``label_inputs`` on PredictorEstimator/
    PredictorModel, SanityChecker, DecisionTreeNumericBucketizer): walking
    a predictor's non-label inputs backwards must never reach a raw
    response except through such a slot. This is the eager equivalent of
    the reference's response/predictor type discipline — data-dependent
    leakage (suspiciously-predictive engineered features) stays with the
    SanityChecker at fit time."""
    from ..models.base import PredictorEstimator, PredictorModel

    for sink in stages:
        if not isinstance(sink, (PredictorEstimator, PredictorModel)):
            continue
        label_slots = _label_positions(sink)
        for pos, feat in enumerate(_live_inputs(sink)):
            if pos in label_slots:
                continue
            path = _response_path(feat)
            if path is not None:
                report.add(
                    "TPA003",
                    f"predictor {sink!r} input {pos} ('{feat.name}') has "
                    f"the response '{path[-1]}' in its lineage "
                    f"(path: {' <- '.join(path)}) — the model would train "
                    "on its own answer",
                    subject=sink.uid,
                    feature=feat.name,
                    path=path,
                )


def _response_path(feature: Feature) -> list[str] | None:
    """Names from ``feature`` back to a reachable raw response, honouring
    label slots (not traversed) — None when no response is reachable."""
    seen: set[int] = set()
    # stack of (feature, path-so-far); bounded by graph size via ``seen``
    stack: list[tuple[Feature, tuple[str, ...]]] = [(feature, (feature.name,))]
    while stack:
        f, path = stack.pop()
        if id(f) in seen:
            continue
        seen.add(id(f))
        stage = f.origin_stage
        if stage is None or isinstance(stage, FeatureGeneratorStage):
            if f.is_response:
                return list(path)
            continue
        label_slots = _label_positions(stage)
        for pos, parent in enumerate(_live_inputs(stage)):
            if pos in label_slots:
                continue
            stack.append((parent, path + (parent.name,)))
    return None


def _check_fit_state(
    stages: Sequence[PipelineStage],
    fitted: dict[str, PipelineStage] | None,
    mode: str,
    report: Report,
) -> None:
    """TPA008 — the stateful-stage-before-fit contract: a serving plan may
    only contain transformers; an estimator whose fitted model is absent
    from ``fitted`` would have to fit at score time."""
    if mode != "serve":
        return
    fitted = fitted or {}
    for s in stages:
        resolved = fitted.get(s.uid, s)
        if isinstance(resolved, Estimator):
            report.add(
                "TPA008",
                f"stateful stage {s!r} appears in a serving plan without a "
                "fitted model — estimators must be fitted by train() first",
                subject=s.uid,
            )


def _check_selectors(stages: Sequence[PipelineStage], report: Report) -> None:
    from ..selector.model_selector import ModelSelector

    selectors = [s for s in stages if isinstance(s, ModelSelector)]
    if len(selectors) > 1:
        report.add(
            "TPA013",
            "Only one ModelSelector is allowed per workflow "
            f"(found {len(selectors)}: "
            f"{', '.join(s.uid for s in selectors)})",
            subject=selectors[1].uid,
        )


def _check_layers(
    result_features: Sequence[Feature],
    stages: Sequence[PipelineStage],
    report: Report,
) -> None:
    """TPA010 — compute_dag layer consistency: every stage must be
    scheduled strictly AFTER all its ancestor stages (a violation means a
    stage would transform before an input column exists)."""
    from ..workflow.dag import compute_dag

    layers = compute_dag(result_features)
    layer_of: dict[int, int] = {}
    for i, layer in enumerate(layers):
        for s in layer:
            layer_of[id(s)] = i
    for s in stages:
        li = layer_of.get(id(s))
        if li is None:
            # stage reachable from lineage but missing from the schedule
            report.add(
                "TPA010",
                f"stage {s!r} is reachable from the result features but "
                "missing from the computed DAG layers",
                subject=s.uid,
            )
            continue
        for f in _live_inputs(s):
            p = f.origin_stage
            if p is None or isinstance(p, FeatureGeneratorStage):
                continue
            pi = layer_of.get(id(p))
            if pi is not None and pi >= li:
                report.add(
                    "TPA010",
                    f"stage {s!r} (layer {li}) is scheduled no later than "
                    f"its ancestor {p!r} (layer {pi}) — '{f.name}' would "
                    "be read before it is produced",
                    subject=s.uid,
                )


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------
def preflight(
    result_features: Iterable[Feature],
    mode: str = "train",
    fitted: dict[str, PipelineStage] | None = None,
) -> Report:
    """Validate the feature DAG rooted at ``result_features``.

    ``mode="train"`` allows unfitted estimators (train will fit them);
    ``mode="serve"`` additionally enforces the before-fit contract
    (TPA008) against the ``fitted`` stage dict. Returns a :class:`Report`
    — call ``.raise_if_errors()`` for the refusing behaviour ``train()``
    uses."""
    if mode not in ("train", "serve"):
        raise ValueError(f"unknown preflight mode {mode!r}")
    report = Report()
    rfs = list(result_features)
    if not rfs:
        report.add("TPA007", "no result features declared", subject="workflow")
        return report
    stages, leaves, cycles = _collect(rfs)
    for cyc in cycles:
        report.add(
            "TPA009",
            "cycle in the stage graph: "
            + " -> ".join(type(s).__name__ for s in cyc),
            subject=cyc[0].uid if cyc else "",
            stages=[s.uid for s in cyc],
        )
    _check_wiring(stages, report)
    _check_uids_and_outputs(stages, leaves, report)
    _check_leakage(stages, report)
    _check_fit_state(stages, fitted, mode, report)
    _check_selectors(stages, report)
    if not cycles:
        # compute_dag recurses through lineage — only safe on acyclic DAGs
        _check_layers(rfs, stages, report)
    return report


def structural_findings(layers: list[list[PipelineStage]]) -> Report:
    """The layer-shaped structural subset behind
    ``workflow.dag.validate_stages``: uid collisions, stage-kind and
    wiring checks, and duplicate output feature names — every finding
    names the offending stage and feature."""
    report = Report()
    stages = [s for layer in layers for s in layer]
    _check_wiring(stages, report)
    by_uid: dict[str, PipelineStage] = {}
    out_by_name: dict[str, PipelineStage] = {}
    for s in stages:
        prior = by_uid.get(s.uid)
        if prior is not None and prior is not s:
            report.add(
                "TPA011",
                f"duplicate stage uid '{s.uid}' on distinct stages "
                f"{type(prior).__name__} and {type(s).__name__}",
                subject=s.uid,
            )
        by_uid[s.uid] = s
        name = _output_name(s)
        if name is None:
            continue
        prior_out = out_by_name.get(name)
        if prior_out is not None and prior_out is not s:
            report.add(
                "TPA004",
                f"stages {prior_out!r} and {s!r} both produce output "
                f"feature '{name}' — the later one silently overwrites "
                "the column",
                subject=name,
            )
        out_by_name.setdefault(name, s)
    return report
