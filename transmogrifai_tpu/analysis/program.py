"""Compiled-program contract auditor (``TPJ0xx``) — the sixth analyser.

Every other analysis family stops at the AST or the plan: TPA walks the
DAG, TPX abstract-interprets the serving plan, TPL/TPC lint source. None
of them ever looks at what XLA actually received. This module does: it
traces every REGISTERED program (the ``SCORE_PROGRAMS``/family maps of
``compiler/warmup.py``, the fused serving builders of ``compiler/fused.py``
and the GLM/tree sweep entry points in ``models/``) to its jaxpr over
representative bucketed abstract shapes, then lints the IR:

* **TPJ001** — a giant (> ``TPTPU_PROGRAM_CONST_MAX``, default 64 KiB)
  constant folded into the jaxpr instead of arriving as a traced
  argument. This is the exact hazard the fused graph's
  structural-fingerprint keying exists to prevent (a model array baked as
  a constant forks one executable per model and bloats every blob);
* **TPJ002** — a 64-bit (x64) value anywhere in the program, or weak-type
  promotion reaching a program OUTPUT: on TPU an f64 op silently falls to
  f32-with-different-rounding or refuses to lower;
* **TPJ003** — declared ``donate_argnums`` whose buffers are never
  aliased into the compiled output: donation is silently a no-op and the
  pipelined-dispatch memory story is fiction;
* **TPJ004** — host callbacks (``pure_callback`` / ``io_callback`` /
  debug prints) inside a device program: every dispatch round-trips the
  host, defeating the one-dispatch contract;
* **TPJ005** — per-bucket jaxpr-structure fingerprints that must be
  identical across lane/batch buckets modulo shapes: a fork means the
  bucketing plane compiles one program per bucket FAMILY instead of one
  program per bucket (recompile-hazard drift);
* **TPJ006** — the jaxpr-level transfer count (each dispatched program =
  ONE argument upload + ONE result download per batch) reconciled as the
  third leg against the static plan census (PR 6) and the runtime census
  (PR 10) via ``telemetry.runlog.reconcile_transfer_census(
  program_counts=...)``;
* **TPJ007-009** — AST tracing-hazard lints over ``models/``,
  ``compiler/`` and ``insights/loco.py``: Python ``if``/``while`` on a
  traced value, ``.item()``/``float()``/``np.asarray`` host-sync inside a
  jitted body, and closure capture of ndarray values by jitted functions;
* **TPJ010** — the warmup family map cross-checked against the
  traceable-program registry: a mapped name no module registers is a
  silent cold start, a registered scoring program absent from every
  family never warms.

Entry points: ``python -m transmogrifai_tpu lint --programs`` (gated on
the committed ``program_baseline.json`` — same (code, path, line-text)
keying and exit-3-on-missing contract as the TPL/TPC gates),
``score_fn.audit(programs=True)`` (audits the FITTED fused program and
the serving programs its plan dispatches), and the compile bank
(``utils/aot.py`` audits at bank-admission time under
``TPTPU_PROGRAM_AUDIT=1`` so a contract-violating program never gets a
persisted blob).

Programs register by exposing ``program_trace_specs()`` in their defining
module (``models/gbdt.py``, ``models/trees.py``, ``models/solvers.py``,
``ops/embeddings.py``, ``compiler/fused.py``) — the spec owns the
representative shapes, so they live next to the code they describe.
"""
from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Any, Callable, Iterable, Sequence

from .findings import Report, Severity, suppressed

__all__ = [
    "ProgramSpec",
    "collect_specs",
    "audit_programs",
    "audit_spec",
    "audit_jit_call",
    "audit_fused_program",
    "program_transfer_counts",
    "reconcile_program_census",
    "warmup_map_findings",
    "tracing_hazards_paths",
    "tracing_hazard_source",
    "jaxpr_fingerprint",
    "DEFAULT_AST_PATHS",
    "SPEC_MODULES",
]

#: modules that register traceable programs (each exposes
#: ``program_trace_specs()``)
SPEC_MODULES = (
    "transmogrifai_tpu.models.gbdt",
    "transmogrifai_tpu.models.trees",
    "transmogrifai_tpu.models.serve_pallas",
    "transmogrifai_tpu.models.solvers",
    "transmogrifai_tpu.ops.embeddings",
    "transmogrifai_tpu.compiler.fused",
    # the SPMD plane's shard_map kernels (PR 15): traced over device-free
    # AbstractMeshes so the TPJ IR lints and the TPS collective census
    # (analysis/spmd.py) inspect the exact collective programs
    "transmogrifai_tpu.parallel.reductions",
    "transmogrifai_tpu.parallel.multihost",
    "transmogrifai_tpu.parallel.ring",
    "transmogrifai_tpu.parallel.segments",
    # the sharded CV candidate sweep (explicit SweepLayout PartitionSpecs
    # + fold-level donation): registered so the TPJ bank gate audits the
    # pjit'd sweep programs and the TPS census proves no hidden reshard
    "transmogrifai_tpu.parallel.sweep",
)

#: source trees the tracing-hazard AST lint (TPJ007-009) covers
DEFAULT_AST_PATHS = (
    "transmogrifai_tpu/models",
    "transmogrifai_tpu/compiler",
    "transmogrifai_tpu/insights/loco.py",
)

#: constants above this many bytes must arrive as traced args (TPJ001)
_CONST_MAX_DEFAULT = 1 << 16


def _const_max() -> int:
    return int(
        os.environ.get("TPTPU_PROGRAM_CONST_MAX", str(_CONST_MAX_DEFAULT))
    )


@dataclasses.dataclass
class ProgramSpec:
    """One registered program and how to trace it representatively.

    ``build(bucket)`` returns ``(args, statics)`` for one bucket of the
    bucketed axis (lane count for sweep programs, padded batch rows for
    serving programs). ``fn`` is the dispatched callable (jit-wrapped or
    plain — plain ones are jitted here with ``static_argnames`` =
    statics' keys, matching the ``aot_call`` convention). ``base_fn`` is
    the UNjitted python function, required when ``donate_argnums`` is
    non-empty (the donation twin is rebuilt for the lowering check)."""

    name: str
    fn: Any
    build: Callable[[int], tuple[tuple, dict]]
    buckets: tuple[int, ...] = (8,)
    bucket_axis: str = "batch"  # "batch" | "lanes" (reporting only)
    donate_argnums: tuple[int, ...] = ()
    base_fn: Any = None
    static_argnames: tuple[str, ...] = ()
    scoring: bool = False
    module: str = ""


def _as_spec(obj: Any, module: str) -> ProgramSpec:
    if isinstance(obj, ProgramSpec):
        if not obj.module:
            obj.module = module
        return obj
    spec = ProgramSpec(**obj)
    if not spec.module:
        spec.module = module
    return spec


def collect_specs(
    names: Iterable[str] | None = None,
    errors: list | None = None,
) -> list[ProgramSpec]:
    """Every registered :class:`ProgramSpec` (optionally filtered by
    program name). A module whose import or ``program_trace_specs()``
    raises is recorded on ``errors`` as ``(module, exception)`` —
    :func:`audit_programs` surfaces each as a TPJ000 finding so a broken
    registration can never silently shrink the audited set."""
    import importlib

    specs: list[ProgramSpec] = []
    for mod_name in SPEC_MODULES:
        try:
            mod = importlib.import_module(mod_name)
            fn = getattr(mod, "program_trace_specs", None)
            if fn is None:
                continue
            for obj in fn():
                specs.append(_as_spec(obj, mod_name))
        except Exception as e:
            if errors is not None:
                errors.append((mod_name, e))
            continue
    if names is not None:
        wanted = set(names)
        specs = [s for s in specs if s.name in wanted]
    return specs


# --------------------------------------------------------------------------
# jaxpr plumbing
# --------------------------------------------------------------------------
def _trace_closed(spec_fn, args: tuple, statics: dict):
    """ClosedJaxpr of ``fn(*args, **statics)``; jit-wraps plain callables
    with the statics' keys as static_argnames (the aot_call contract)."""
    import jax

    fn = spec_fn
    if not hasattr(fn, "trace"):
        # trace-only jit: never dispatched or banked
        fn = jax.jit(fn, static_argnames=tuple(statics))  # tp: disable=TPL003
    return fn.trace(*args, **statics).jaxpr


def _sub_jaxprs(params: dict):
    """Nested (closed or raw) jaxprs inside an eqn's params — scan/while
    bodies, cond branches, pjit call_jaxprs. Duck-typed so it holds
    across jax's core/extend module moves."""
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            if hasattr(item, "jaxpr") and hasattr(item, "consts"):
                yield item  # a ClosedJaxpr
            elif hasattr(item, "eqns") and hasattr(item, "invars"):
                yield item  # a raw Jaxpr


def _walk(closed, seen=None):
    """Yield (jaxpr, consts) for the closed jaxpr and every nested one."""
    if seen is None:
        seen = set()
    jaxpr = getattr(closed, "jaxpr", closed)
    consts = list(getattr(closed, "consts", ()) or ())
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    yield jaxpr, consts
    for eqn in jaxpr.eqns:
        for sub in _sub_jaxprs(eqn.params):
            yield from _walk(sub, seen)


def _norm_param(v: Any) -> Any:
    """Shape-free view of an eqn param for the structural fingerprint:
    ints and int-tuples (shapes, axes, lengths that scale with the
    bucket) collapse to a placeholder; dtypes/strings/bools/callables
    keep their identity; nested jaxprs fingerprint recursively."""
    if isinstance(v, bool):
        return v
    if isinstance(v, int):
        return "#"
    if isinstance(v, (str, bytes, float, type(None))):
        return v
    if isinstance(v, (list, tuple)):
        return tuple(_norm_param(x) for x in v)
    if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
        return ("jaxpr", jaxpr_fingerprint(v))
    if callable(v):
        return getattr(v, "__name__", type(v).__name__)
    if hasattr(v, "dtype") and hasattr(v, "shape"):
        return ("array", str(v.dtype))
    return type(v).__name__


def jaxpr_fingerprint(closed) -> str:
    """Structure fingerprint of a (closed) jaxpr, stable modulo shapes:
    the ordered primitive sequence with shape-free params, recursed
    through scan/cond/pjit bodies. Two lane buckets of one program family
    MUST fingerprint identically (TPJ005)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    parts: list[str] = []
    for eqn in jaxpr.eqns:
        norm = tuple(
            (k, _norm_param(v)) for k, v in sorted(eqn.params.items())
        )
        parts.append(f"{eqn.primitive.name}{norm}")
    return hashlib.sha1("\n".join(parts).encode()).hexdigest()[:16]


_CALLBACK_PRIMS = (
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "host_callback", "callback",
)

_X64_DTYPES = ("float64", "int64", "uint64", "complex128")


def _aval_of(var):
    return getattr(var, "aval", None)


def _check_jaxpr(
    report: Report,
    closed,
    name: str,
    bucket: int,
    const_max: int | None = None,
) -> None:
    """TPJ001 (giant consts), TPJ002 (x64/weak), TPJ004 (callbacks) over
    one traced program."""
    import numpy as np

    limit = _const_max() if const_max is None else const_max
    flagged_consts: set[int] = set()
    seen_cb: set[str] = set()
    x64_hit = False
    for jaxpr, consts in _walk(closed):
        for c in consts:
            nbytes = int(getattr(c, "nbytes", 0) or 0)
            if nbytes > limit and id(c) not in flagged_consts:
                flagged_consts.add(id(c))
                shape = tuple(getattr(c, "shape", ()) or ())
                report.add(
                    "TPJ001",
                    f"program '{name}' folds a {nbytes}-byte constant "
                    f"(shape {shape}) into the compiled graph — pass it "
                    "as a traced argument so same-shaped models share one "
                    "executable",
                    subject=f"program:{name}",
                    severity=Severity.ERROR,
                    path=f"program:{name}", line=0,
                    context=f"{name} const{shape}", nbytes=nbytes,
                )
        for eqn in jaxpr.eqns:
            pname = eqn.primitive.name
            if any(cb in pname for cb in _CALLBACK_PRIMS) and \
                    pname not in seen_cb:
                seen_cb.add(pname)
                report.add(
                    "TPJ004",
                    f"program '{name}' embeds host callback primitive "
                    f"'{pname}' — every dispatch round-trips the host",
                    subject=f"program:{name}",
                    severity=Severity.ERROR,
                    path=f"program:{name}", line=0,
                    context=f"{name} callback:{pname}",
                )
            if not x64_hit:
                for var in list(eqn.invars) + list(eqn.outvars):
                    aval = _aval_of(var)
                    if aval is None:
                        continue
                    if str(getattr(aval, "dtype", "")) in _X64_DTYPES:
                        x64_hit = True
                        report.add(
                            "TPJ002",
                            f"program '{name}' carries a "
                            f"{aval.dtype} value through op '{pname}' — "
                            "64-bit math must not reach a TPU kernel",
                            subject=f"program:{name}",
                            severity=Severity.ERROR,
                            path=f"program:{name}", line=0,
                            context=f"{name} x64:{aval.dtype}",
                        )
                        break
    # weak-type promotion escaping through an OUTPUT (weak intermediates
    # from python literals are normal; a weak output means the program's
    # result dtype is decided by the CALLER's promotion rules)
    top = getattr(closed, "jaxpr", closed)
    for i, var in enumerate(top.outvars):
        aval = _aval_of(var)
        if aval is not None and getattr(aval, "weak_type", False):
            report.add(
                "TPJ002",
                f"program '{name}' output {i} is weak-typed — its dtype "
                "floats with caller promotion instead of being pinned by "
                "the program",
                subject=f"program:{name}",
                severity=Severity.WARNING,
                path=f"program:{name}", line=0,
                context=f"{name} weak-out:{i}",
            )
            break


def _check_donation(report: Report, spec: ProgramSpec, args, statics) -> None:
    """TPJ003: lower the donating twin and require at least one argument
    buffer aliased into the output (``tf.aliasing_output`` /
    ``jax.buffer_donor`` in the StableHLO)."""
    import jax

    if not spec.donate_argnums:
        return
    base = spec.base_fn
    if base is None:
        return
    static_names = spec.static_argnames or tuple(statics)
    try:
        import warnings

        twin = jax.jit(  # tp: disable=TPL003 — lower-only, never dispatched
            base, static_argnames=static_names,
            donate_argnums=spec.donate_argnums,
        )
        with warnings.catch_warnings():
            # "Some donated buffers were not usable" is exactly the
            # signal this check converts into a TPJ003 finding
            warnings.filterwarnings(
                "ignore", message=".*donated buffers.*"
            )
            text = twin.lower(*args, **statics).as_text()
    except Exception as e:
        report.add(
            "TPJ000",
            f"donation twin of '{spec.name}' failed to lower: {e}",
            subject=f"program:{spec.name}",
            severity=Severity.WARNING,
            path=f"program:{spec.name}", line=0,
            context=f"{spec.name} donation-lower",
        )
        return
    if "tf.aliasing_output" not in text and "jax.buffer_donor" not in text:
        report.add(
            "TPJ003",
            f"program '{spec.name}' declares donate_argnums="
            f"{spec.donate_argnums} but NO argument buffer is aliased "
            "into the compiled output — donation is a no-op and the "
            "chunk-to-chunk buffer reuse never happens",
            subject=f"program:{spec.name}",
            severity=Severity.WARNING,
            path=f"program:{spec.name}", line=0,
            context=f"{spec.name} donation",
        )


def audit_jit_call(
    name: str,
    jit_fn: Any,
    args: tuple,
    statics: dict,
    const_max: int | None = None,
) -> Report:
    """Audit ONE concrete dispatch (the bank-admission seam in
    ``utils/aot.py``): trace over the call's own avals, run the IR checks.
    Never raises — an untraceable program is a TPJ000 warning."""
    report = Report()
    try:
        import jax

        avals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") and hasattr(a, "dtype") else a,
            args,
        )
        closed = _trace_closed(jit_fn, avals, statics)
    except Exception as e:
        report.add(
            "TPJ000",
            f"program '{name}' could not be traced for audit: {e}",
            subject=f"program:{name}",
            severity=Severity.WARNING,
            path=f"program:{name}", line=0, context=f"{name} trace",
        )
        return report
    _check_jaxpr(report, closed, name, bucket=-1, const_max=const_max)
    return report


def audit_spec(spec: ProgramSpec, buckets: Sequence[int] | None = None) -> Report:
    """Trace one registered program over its buckets and run every IR
    check, including the cross-bucket TPJ005 fingerprint comparison."""
    report = Report()
    buckets = tuple(buckets) if buckets is not None else spec.buckets
    fingerprints: dict[int, str] = {}
    first_inputs = None
    for b in buckets:
        try:
            args, statics = spec.build(b)
            closed = _trace_closed(spec.fn, args, statics)
        except Exception as e:
            report.add(
                "TPJ000",
                f"program '{spec.name}' failed to trace at bucket {b}: "
                f"{e}",
                subject=f"program:{spec.name}",
                severity=Severity.WARNING,
                path=f"program:{spec.name}", line=0,
                context=f"{spec.name} trace",
            )
            continue
        if first_inputs is None:
            first_inputs = (args, statics)
        _check_jaxpr(report, closed, spec.name, bucket=b)
        fingerprints[b] = jaxpr_fingerprint(closed)
    if len(set(fingerprints.values())) > 1:
        by_fp: dict[str, list[int]] = {}
        for b, fp in fingerprints.items():
            by_fp.setdefault(fp, []).append(b)
        report.add(
            "TPJ005",
            f"program '{spec.name}' jaxpr structure FORKS across "
            f"{spec.bucket_axis} buckets {sorted(fingerprints)} — "
            f"distinct structures {sorted(by_fp.values())} compile "
            "distinct program families instead of one program per bucket",
            subject=f"program:{spec.name}",
            severity=Severity.WARNING,
            path=f"program:{spec.name}", line=0,
            context=f"{spec.name} bucket-fork",
            fingerprints={str(b): fp for b, fp in fingerprints.items()},
        )
    if first_inputs is not None:
        _check_donation(report, spec, *first_inputs)
    report.data.setdefault("programs", {})[spec.name] = {
        "buckets": list(fingerprints),
        "fingerprints": fingerprints and sorted(set(fingerprints.values())),
        "bucketAxis": spec.bucket_axis,
        "donateArgnums": list(spec.donate_argnums),
    }
    return report


# --------------------------------------------------------------------------
# warmup-map reconciliation (TPJ010)
# --------------------------------------------------------------------------
def warmup_map_findings(
    specs: Sequence[ProgramSpec] | None = None,
    score_programs: frozenset | None = None,
    family_programs: dict | None = None,
) -> Report:
    """Cross-check the warmup family maps against the traceable-program
    registry. A mapped name no module registers warms nothing (silent
    cold start on every fresh process); a registered SCORING program
    absent from every family never prewarms."""
    from ..compiler import warmup as _w

    report = Report()
    if specs is None:
        specs = collect_specs()
    score = _w.SCORE_PROGRAMS if score_programs is None else score_programs
    families = (
        _w._FAMILY_PROGRAMS if family_programs is None else family_programs
    )
    mapped: set[str] = set(score)
    for fam in families.values():
        mapped.update(fam)
    registered = {s.name for s in specs}
    for name in sorted(mapped - registered):
        report.add(
            "TPJ010",
            f"warmup map lists program '{name}' but no module registers "
            "a traceable spec for it — the name warms nothing and the "
            "auditor cannot inspect it (silent cold start)",
            subject=f"program:{name}",
            severity=Severity.WARNING,
            path=f"program:{name}", line=0, context=f"{name} unmapped",
        )
    scoring_registered = {s.name for s in specs if s.scoring}
    for name in sorted(scoring_registered - mapped):
        report.add(
            "TPJ010",
            f"scoring program '{name}' is registered with the bank but "
            "absent from SCORE_PROGRAMS and every family map — serving "
            "never warms it",
            subject=f"program:{name}",
            severity=Severity.WARNING,
            path=f"program:{name}", line=0, context=f"{name} unwarmed",
        )
    return report


# --------------------------------------------------------------------------
# transfer-census third leg (TPJ006)
# --------------------------------------------------------------------------
def program_transfer_counts(plan=None, fused=None) -> dict[str, Any]:
    """Per-batch boundary crossings derived from the COMPILED programs a
    serving plan dispatches: every dispatched program is exactly one
    argument upload and one result download (the aot_call contract — its
    args device_put as one pytree, its outputs render once). The fused
    graph is one program; the staged path dispatches one predict program
    per predictor stage."""
    programs: list[str] = []
    if fused is not None:
        programs.append("fused_serve")
    elif plan is not None:
        from ..models.base import PredictorModel

        for t in plan:
            if isinstance(t, PredictorModel):
                programs.append(f"predict:{t.operation_name}")
    return {
        "programs": programs,
        "hostToDevicePerBatch": len(programs),
        "deviceToHostPerBatch": len(programs),
        "source": "jaxpr",
    }


def reconcile_program_census(
    static_census: dict[str, Any], program_counts: dict[str, Any]
) -> Report:
    """TPJ006 when the program-derived per-batch crossing counts disagree
    with the static plan census — the third reconciliation leg (the
    runtime leg rides ``telemetry.runlog.reconcile_transfer_census``'s
    ``program_counts=`` argument)."""
    report = Report()
    st_h2d = int(static_census.get("hostToDeviceTransfers", 0))
    st_d2h = int(static_census.get("deviceToHostTransfers", 0))
    pg_h2d = int(program_counts.get("hostToDevicePerBatch", 0))
    pg_d2h = int(program_counts.get("deviceToHostPerBatch", 0))
    report.data["programTransferCounts"] = dict(program_counts)
    if (st_h2d, st_d2h) != (pg_h2d, pg_d2h):
        report.add(
            "TPJ006",
            "program-level transfer counts disagree with the static plan "
            f"census: programs say {pg_h2d} h2d / {pg_d2h} d2h per batch, "
            f"the plan census says {st_h2d} / {st_d2h} — one of the three "
            "census legs is lying",
            subject="census",
            severity=Severity.WARNING,
            path="program:census", line=0, context="census three-way",
            programH2d=pg_h2d, programD2h=pg_d2h,
            staticH2d=st_h2d, staticD2h=st_d2h,
        )
    return report


# --------------------------------------------------------------------------
# fitted fused-program audit (score_fn.audit(programs=True))
# --------------------------------------------------------------------------
def audit_fused_program(fused, rows: Sequence[int] = (8, 16)) -> Report:
    """Audit the FITTED fused serving program: trace ``_fused_eval`` over
    the program's own member specs + real fit-static params at two batch
    buckets. Model arrays arrive through ``params`` — anything that shows
    up as a giant jaxpr constant instead violates the PR-11
    traced-args-not-constants contract (TPJ001) by construction."""
    from ..compiler import fused as _fused

    spec = ProgramSpec(
        name="fused_serve",
        fn=_fused._fused_eval,
        base_fn=_fused._fused_eval,
        build=lambda n: (
            (
                tuple(m.dummy(n) for m in fused.members),
                fused._params_host,
            ),
            {"spec": fused._spec},
        ),
        buckets=tuple(rows),
        bucket_axis="batch",
        donate_argnums=(0,),
        static_argnames=("spec",),
        scoring=True,
        module="compiler.fused",
    )
    return audit_spec(spec)


# --------------------------------------------------------------------------
# AST tracing-hazard lint (TPJ007-009)
# --------------------------------------------------------------------------
import ast  # noqa: E402

_NP_CTORS = {
    "array", "asarray", "zeros", "ones", "empty", "full", "arange",
    "linspace", "eye", "load",
}
_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
_SYNC_CASTS = {"float", "int", "bool", "complex"}


from .findings import attr_chain as _attr_chain  # noqa: E402 — shared helper


def _jit_call_statics(call: ast.Call) -> tuple[bool, set[str]]:
    """(is_jax_jit, static names) for a Call node — handles ``jax.jit``,
    ``jit`` and ``partial(jax.jit, ...)``."""
    chain = _attr_chain(call.func)
    statics: set[str] = set()
    is_jit = chain[-2:] == ["jax", "jit"] or chain == ["jit"]
    if not is_jit and chain and chain[-1] == "partial" and call.args:
        inner = _attr_chain(call.args[0])
        if inner[-2:] == ["jax", "jit"] or inner == ["jit"]:
            is_jit = True
    if not is_jit:
        return False, statics
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            try:
                val = ast.literal_eval(kw.value)
            except Exception:
                continue
            if isinstance(val, str):
                statics.add(val)
            else:
                statics.update(str(v) for v in val)
    return True, statics


class _JitIndex:
    """Which function defs in a module are jitted, and their static
    param names. Detects decorator jits (``@jax.jit``,
    ``@partial(jax.jit, ...)``), wrap-by-name (``Y = jax.jit(X)``,
    ``Y = partial(jax.jit, ...)(X)``) and pass-by-name
    (``jax.jit(fn_name, ...)`` anywhere)."""

    def __init__(self, tree: ast.Module):
        self.jitted: dict[int, set[str]] = {}  # id(funcdef) -> statics
        defs: dict[str, list[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        is_jit, statics = _jit_call_statics(dec)
                        if is_jit:
                            self.jitted[id(node)] = statics
                    else:
                        chain = _attr_chain(dec)
                        if chain[-2:] == ["jax", "jit"] or chain == ["jit"]:
                            self.jitted[id(node)] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            is_jit, statics = _jit_call_statics(node)
            # partial(jax.jit, ...)(fn) — the jit partial called on fn
            wrapped: ast.expr | None = None
            if is_jit:
                chain = _attr_chain(node.func)
                if chain and chain[-1] == "partial":
                    continue  # the partial itself; the outer call wraps
                if node.args:
                    wrapped = node.args[0]
            elif isinstance(node.func, ast.Call):
                inner_jit, statics = _jit_call_statics(node.func)
                if inner_jit and node.args:
                    wrapped = node.args[0]
            if wrapped is not None and isinstance(wrapped, ast.Name):
                for d in defs.get(wrapped.id, ()):
                    self.jitted.setdefault(id(d), set()).update(statics)

    def statics_of(self, fn: ast.AST) -> set[str] | None:
        return self.jitted.get(id(fn))


def _param_names(fn: ast.AST) -> list[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _traced_names_in(expr: ast.expr, traced: set[str]) -> list[str]:
    """Traced param names whose VALUE the expression actually consumes —
    shape/dtype metadata reads, ``is None`` tests and ``isinstance``
    checks don't count (they are static at trace time)."""
    hits: list[str] = []
    skip: set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _SHAPE_ATTRS:
            for inner in ast.walk(node.value):
                skip.add(id(inner))
        elif isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            for inner in ast.walk(node):
                skip.add(id(inner))
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] in ("isinstance", "len", "getattr",
                                       "hasattr", "callable"):
                for inner in ast.walk(node):
                    skip.add(id(inner))
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in traced
            and id(node) not in skip
        ):
            hits.append(node.id)
    return hits


def _ndarray_bindings(scope_body: Iterable[ast.stmt]) -> set[str]:
    """Names bound (at this scope's statement level) to an ndarray-building
    call — the closure-capture bait of TPJ009."""
    out: set[str] = set()
    for stmt in scope_body:
        targets: list[ast.expr] = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if not isinstance(value, ast.Call):
            continue
        chain = _attr_chain(value.func)
        if len(chain) >= 2 and chain[0] in ("np", "numpy") and \
                chain[-1] in _NP_CTORS:
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _local_bindings(fn: ast.AST) -> set[str]:
    """Every name the function binds (params, assignments, loops, withs,
    imports, comprehension targets, nested defs)."""
    bound: set[str] = set(_param_names(fn))
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node is not fn:
            bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
    return bound


def tracing_hazard_source(source: str, rel_path: str) -> Report:
    """TPJ007-009 over one file. Approximation contract: "traced value"
    means a direct parameter of a jitted function that is not in its
    static_argnames — first-order dataflow only, suppressible with
    ``# tpj: ok`` / ``# tp: disable=TPJ00x``."""
    report = Report()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        report.add(
            "TPJ000",
            f"file does not parse: {e}",
            subject=f"{rel_path}:{e.lineno or 0}",
            severity=Severity.WARNING,
            path=rel_path, line=e.lineno or 0, context="",
        )
        return report
    lines = source.splitlines()
    index = _JitIndex(tree)
    hits: list[tuple[str, int, str]] = []

    # map each function def to its enclosing function (for TPJ009)
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in ast.walk(node):
                if child is not node and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and id(child) not in parents:
                    parents[id(child)] = node

    module_ndarrays = _ndarray_bindings(tree.body)

    for fn in [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]:
        statics = index.statics_of(fn)
        if statics is None:
            continue
        traced = set(_param_names(fn)) - statics
        nested_ids = {
            id(n) for child in ast.iter_child_nodes(fn)
            for n in ast.walk(child)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

        for node in ast.walk(fn):
            if id(node) in nested_ids and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            # ---- TPJ007: python control flow on a traced value
            if isinstance(node, (ast.If, ast.While)):
                names = _traced_names_in(node.test, traced)
                if names:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    hits.append((
                        "TPJ007", node.lineno,
                        f"python `{kind}` on traced value(s) "
                        f"{sorted(set(names))} inside jitted {fn.name}() — "
                        "trace-time branching forks one program per value "
                        "(use lax.cond/select or make it static)",
                    ))
            # ---- TPJ008: host-sync coercions
            elif isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and _traced_names_in(node.func.value, traced)
                ):
                    hits.append((
                        "TPJ008", node.lineno,
                        f".item() on a traced value inside jitted "
                        f"{fn.name}() — forces a device sync per call",
                    ))
                elif (
                    len(chain) == 1 and chain[0] in _SYNC_CASTS
                    and node.args
                    and _traced_names_in(node.args[0], traced)
                ):
                    hits.append((
                        "TPJ008", node.lineno,
                        f"{chain[0]}() coerces a traced value inside "
                        f"jitted {fn.name}() — host sync / trace error",
                    ))
                elif (
                    len(chain) == 2 and chain[0] in ("np", "numpy")
                    and chain[1] in ("asarray", "array")
                    and node.args
                    and _traced_names_in(node.args[0], traced)
                ):
                    hits.append((
                        "TPJ008", node.lineno,
                        f"np.{chain[1]}() materializes a traced value "
                        f"inside jitted {fn.name}() — forces a device "
                        "download mid-program",
                    ))

        # ---- TPJ009: closure capture of ndarray values
        enclosing = parents.get(id(fn))
        bait = set(module_ndarrays)
        if enclosing is not None:
            bait |= _ndarray_bindings(
                s for s in ast.walk(enclosing) if isinstance(s, ast.stmt)
            )
        if bait:
            bound = _local_bindings(fn)
            captured = sorted({
                n.id for n in ast.walk(fn)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and n.id in bait and n.id not in bound
            })
            if captured:
                hits.append((
                    "TPJ009", fn.lineno,
                    f"jitted {fn.name}() closes over ndarray value(s) "
                    f"{captured} — they bake into the program as "
                    "constants (one executable per array, bloated "
                    "blobs); pass them as traced arguments",
                ))

    rel = rel_path.replace(os.sep, "/")
    for code, lineno, message in sorted(hits, key=lambda h: (h[1], h[0])):
        context = lines[lineno - 1].strip() if 0 < lineno <= len(lines) else ""
        if suppressed(context, code):
            continue
        report.add(
            code, message,
            subject=f"{rel}:{lineno}",
            severity=Severity.WARNING,
            path=rel, line=lineno, context=context,
        )
    return report


def tracing_hazards_paths(
    paths: Iterable[str] | None = None, root: str = "."
) -> Report:
    """TPJ007-009 over every ``.py`` file under ``paths`` (defaults to
    the tracing-hazard surface: models/, compiler/, insights/loco.py)."""
    report = Report()
    if paths is None:
        paths = [os.path.join(root, p) for p in DEFAULT_AST_PATHS]
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git", "node_modules")
            ]
            files.extend(
                os.path.join(dirpath, f)
                for f in filenames if f.endswith(".py")
            )
    for path in sorted(files):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        report.extend(tracing_hazard_source(source, rel))
    return report


# --------------------------------------------------------------------------
# whole-registry driver (the CLI `lint --programs` pass)
# --------------------------------------------------------------------------
def audit_programs(
    names: Iterable[str] | None = None,
    include_ir: bool = True,
    include_ast: bool = True,
    ast_paths: Iterable[str] | None = None,
    root: str = ".",
    buckets: Sequence[int] | None = None,
) -> Report:
    """The full TPJ pass: trace + IR-lint every registered program
    (TPJ001-005), cross-check the warmup maps (TPJ010), and run the
    tracing-hazard AST lint (TPJ007-009). Programs that fail to import or
    trace degrade to TPJ000 findings, never exceptions."""
    report = Report()
    if include_ir:
        spec_errors: list = []
        specs = collect_specs(names, errors=spec_errors)
        for mod_name, err in spec_errors:
            report.add(
                "TPJ000",
                f"program registration in '{mod_name}' failed — its "
                f"programs are MISSING from this audit: {err}",
                subject=f"module:{mod_name}",
                severity=Severity.WARNING,
                path=f"module:{mod_name}", line=0,
                context=f"{mod_name} collect",
            )
        programs: dict[str, Any] = {}
        for spec in specs:
            sub = audit_spec(spec, buckets=buckets)
            programs.update(sub.data.pop("programs", {}))
            report.extend(sub)
        report.data["programs"] = programs
        if names is None:
            report.extend(warmup_map_findings(specs))
    if include_ast:
        report.extend(tracing_hazards_paths(ast_paths, root=root))
    return report
