"""SPMD contract auditor (``TPS0xx``) — the seventh analyser.

The ``parallel/`` plane is the repo's only subsystem whose correctness
depends on N processes executing the SAME program in the SAME order: a
``psum`` is a rendezvous, and a host that reaches it late, never, or out
of order deadlocks the mesh (or silently merges the wrong statistics).
None of the six existing analysers can see that contract — TPA walks the
user DAG, TPX/TPJ audit the serving plan and its programs, TPL/TPC lint
single-process invariants. This module audits the parallel plane in
three legs, mirroring the TPC/TPJ architecture:

* **Static AST pass** (:func:`analyze_paths`) over the SPMD surface
  (``parallel/``, ``models/trees.py``, ``resilience/distributed.py``):

  - **TPS001** — python control flow conditioned on a *host-varying*
    value (``process_index()``, host row slices, wall-clock readings,
    retry/failover state) guarding a collective: hosts may issue
    collectives in different orders or different counts — the classic
    SPMD deadlock (the PR-3 ``FailoverController`` re-entry shape). A
    branch predicate that is itself the result of a collective is
    host-invariant by construction (all hosts agreed on it), so the
    barrier-fixed twin of a divergent branch scans clean.
  - **TPS002** — a ``shard_map`` body using an axis name its wrapping
    ``mesh``/``in_specs``/``out_specs`` never bind (the compat-shim
    break class: the kernel traces, then dies on the first real mesh).
  - **TPS003** — a ``PartitionSpec`` whose axis names are not in the
    sharded mesh's vocabulary, or whose entry count disagrees with the
    statically-known rank of the array it shards.
  - **TPS004** — a non-commutative or dtype-unstable op inside a
    shard_map reduction kernel: subtraction of two collective-reduced
    values (the raw-moment variance shape — catastrophic f32
    cancellation) or a 64-bit dtype in the kernel body. Both break the
    ``_guarded`` contract of commutative bit-identical merges.
  - **TPS005** — a collective issued while holding a lock: host A waits
    in the collective holding the lock, host B needs the lock to reach
    its collective — a cross-host ABBA that bridges into the TPC lock
    graph.
  - **TPS007** — a host-dependent shape (unpadded host row block)
    feeding a placement/dispatch primitive: every host compiles its own
    program (recompile storm), and shape-divergent collectives hang.

* **IR leg** (:func:`static_collective_census`): every shard_map kernel
  registered through ``program_trace_specs()`` (``parallel/reductions``,
  ``multihost``, ``ring``, ``segments`` — the PR-6 registry, extended)
  traces to its jaxpr over a device-free ``AbstractMesh`` and yields a
  **static collective census**: count + primitive + axes of every
  collective in the program. The lowered StableHLO is then reconciled
  against it — **TPS006** flags an HLO collective kind the jaxpr census
  never declared (hidden resharding: exactly what ROADMAP item 3's
  explicit-PartitionSpec acceptance needs to refuse).

* **Dynamic reconciler** (:func:`reconcile_collective_orders`): under
  ``TPTPU_COLLECTIVE_TRACE=1`` the canonical seam
  (``parallel/guarded.py`` — every collective already funnels through
  it) records each simulated host's ``(sequence#, name)`` collective
  tape, through failovers (a lost host's tape freezes). The reconciler
  asserts every survivor's tape is IDENTICAL, every lost host's tape is
  a prefix of it, and every issued name is explained by the static seam
  census — **TPS008** otherwise. The third static-vs-runtime reconciler
  after the transfer census and the lock-order graph.

Entry points: ``python -m transmogrifai_tpu lint --spmd`` (gated on the
committed ``spmd_baseline.json`` — same (code, path, line-text) keying
and exit-3 contract as TPL/TPC/TPJ), ``--all`` includes the family, and
``summary_json()["analysis"]["spmd"]`` carries the compact package
summary. ``bench.py multichip`` stamps the ``collectiveAudit`` verdict
into the MULTICHIP artifact.
"""
from __future__ import annotations

import ast
import functools
import os
from typing import Any, Iterable, Sequence

from .findings import Report, Severity, attr_chain, suppressed

__all__ = [
    "DEFAULT_SPMD_PATHS",
    "analyze_paths",
    "analyze_source",
    "audit_spmd",
    "default_spmd_paths",
    "hlo_collective_kinds",
    "package_summary",
    "reconcile_collective_orders",
    "reconcile_hlo_census",
    "seam_collective_census",
    "static_collective_census",
]

#: the SPMD surface: every module that builds or drives shard_map
#: kernels / cross-host collectives
DEFAULT_SPMD_PATHS = (
    "transmogrifai_tpu/parallel",
    "transmogrifai_tpu/models/trees.py",
    "transmogrifai_tpu/resilience/distributed.py",
    # the sharded-sweep driver: workflow CV routes GLM lanes through the
    # SweepLayout pjit path (parallel/sweep.py registers the programs;
    # this entry keeps the DRIVING code on the static TPS surface too)
    "transmogrifai_tpu/workflow/cv.py",
)

# ---- vocabularies ---------------------------------------------------------
#: call names that ISSUE a collective (directly or through the guarded
#: seam) — reaching one is a cross-host rendezvous
_LAX_COLLECTIVES = {
    "psum", "pmin", "pmax", "pmean", "ppermute", "all_gather",
    "all_to_all", "pshuffle", "pbroadcast", "psum_scatter",
}
_REDUCTION_ENTRIES = {
    "pcolumn_stats", "pcentered_gram", "pxtx", "phistogram",
    "pcontingency", "global_column_stats", "ring_gram", "ring_corr",
    "psegment_reduce", "aggregate_events_on_device",
}
_SEAM_ENTRIES = {"guarded_collective", "_guarded"}
#: cross-host sync points that every host must reach (global-array
#: assembly blocks until all processes call it)
_SYNC_ENTRIES = {"make_global_array", "make_array_from_process_local_data",
                 "ingest_global_array", "sync_global_devices"}
COLLECTIVE_CALLS = (
    _LAX_COLLECTIVES | _REDUCTION_ENTRIES | _SEAM_ENTRIES | _SYNC_ENTRIES
)

#: calls whose RESULT varies per host (taint seeds for TPS001/TPS007)
_HOST_VARYING_CALLS = {
    "process_index", "host_row_slice", "read_host_block",
    "dead_hosts", "live_hosts",
    # wall-clock readings: per-host timing is the retry/failover
    # divergence channel (the CollectiveGuard re-entry shape)
    "time", "monotonic", "perf_counter", "perf_counter_ns", "clock",
}
#: parameter / attribute terminal names treated as host-varying state
_HOST_VARYING_NAMES = {
    "host", "host_id", "host_index", "process_id", "lost", "lost_hosts",
}

#: jaxpr primitives that are collectives (the census vocabulary).
#: ``psum2`` is shard_map's replication-checked rewrite of ``psum``
#: (check_rep=True re-expresses psum as pbroadcast + psum2).
COLLECTIVE_PRIMITIVES = {
    "psum", "psum2", "pmin", "pmax", "ppermute", "all_gather",
    "all_to_all", "reduce_scatter", "pbroadcast", "psum_scatter",
    "pgather",
}

#: lowered-HLO collective kinds -> the jaxpr primitives that declare them
HLO_KIND_SOURCES = {
    "all_reduce": ("psum", "psum2", "pmin", "pmax", "psum_scatter"),
    "collective_permute": ("ppermute",),
    "all_gather": ("all_gather",),
    "all_to_all": ("all_to_all", "pgather"),
    "reduce_scatter": ("reduce_scatter", "psum_scatter"),
    "collective_broadcast": ("pbroadcast",),
}

#: axis-name constants of the parallel plane (module-qualified names are
#: resolved per-file too; these cover cross-module imports)
_AXIS_CONSTANTS = {"DATA_AXIS": "data", "MODEL_AXIS": "model",
                   "DCN_AXIS": "dcn"}

#: known mesh constructors -> the axis vocabulary they bind
_MESH_CTOR_AXES = {
    "make_mesh": {"data", "model"},
    "auto_mesh": {"data", "model"},
    "default_execution_mesh": {"data", "model"},
    "make_multihost_mesh": {"dcn", "data", "model"},
}

#: spec-helper functions -> the axis names their PartitionSpec binds
_SPEC_HELPER_AXES = {"_data_spec": {"data"}, "dcn_data_spec": {"dcn", "data"}}


def _call_name(node: ast.AST) -> str:
    chain = attr_chain(node.func) if isinstance(node, ast.Call) else []
    return chain[-1] if chain else ""


def _expr_names(expr: ast.AST) -> set[str]:
    return {
        n.id for n in ast.walk(expr)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


# ==========================================================================
# axis / PartitionSpec resolution (TPS002 / TPS003)
# ==========================================================================
class _AxisEnv:
    """Resolves expressions to axis-name sets: string constants, module
    axis constants, local assignments of strings/tuples, P(...) specs and
    the per-module spec helpers. Unresolvable -> None (never guess)."""

    def __init__(self, module_consts: dict[str, Any], helpers: dict[str, set]):
        self.consts = dict(module_consts)
        self.helpers = dict(helpers)
        self.local: dict[str, Any] = {}

    def bind_local(self, name: str, value: Any) -> None:
        self.local[name] = value

    def axis_of(self, expr: ast.AST) -> set[str] | None:
        """Axis names an axis-argument expression denotes, or None."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return {expr.value}
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: set[str] = set()
            for el in expr.elts:
                sub = self.axis_of(el)
                if sub is None:
                    return None
                out |= sub
            return out
        if isinstance(expr, ast.Name):
            val = self.local.get(expr.id, self.consts.get(expr.id))
            if isinstance(val, str):
                return {val}
            if isinstance(val, (set, frozenset)):
                return set(val)
            return None
        chain = attr_chain(expr)
        if chain and chain[-1] in _AXIS_CONSTANTS:
            return {_AXIS_CONSTANTS[chain[-1]]}
        return None

    def spec_axes(self, expr: ast.AST) -> tuple[set[str], int] | None:
        """(axis names, entry count) of a PartitionSpec-building
        expression: ``P(...)`` literals, spec-helper calls, or names
        bound to one. None when unresolvable."""
        if isinstance(expr, ast.Call):
            name = _call_name(expr)
            if name in ("P", "PartitionSpec"):
                axes: set[str] = set()
                for a in expr.args:
                    if isinstance(a, ast.Constant) and a.value is None:
                        continue
                    sub = self.axis_of(a)
                    if sub is None:
                        return None
                    axes |= sub
                if any(isinstance(a, ast.Starred) for a in expr.args):
                    return None
                return axes, len(expr.args)
            if name in self.helpers:
                # helper(*trailing): 1 leading sharded entry + trailing
                return set(self.helpers[name]), 1 + len(expr.args)
        if isinstance(expr, ast.Name):
            val = self.local.get(expr.id)
            if isinstance(val, tuple) and len(val) == 2 and \
                    isinstance(val[0], set):
                return val
        return None


def _module_axis_consts(tree: ast.Module) -> dict[str, str]:
    """Module-level ``NAME = "axis"`` string constants (plus the shared
    cross-module axis names)."""
    out = dict(_AXIS_CONSTANTS)
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt.value.value
    return out


def _module_spec_helpers(tree: ast.Module, consts: dict) -> dict[str, set]:
    """Functions whose body returns a single ``P(...)`` — the local spec
    helpers (``_data_spec``); their bound axis names by helper name."""
    helpers = dict(_SPEC_HELPER_AXES)
    env = _AxisEnv(consts, {})
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Return) and isinstance(
                stmt.value, ast.Call
            ) and _call_name(stmt.value) in ("P", "PartitionSpec"):
                axes: set[str] = set()
                ok = True
                for a in stmt.value.args:
                    if isinstance(a, ast.Starred):
                        continue
                    if isinstance(a, ast.Constant) and a.value is None:
                        continue
                    sub = env.axis_of(a)
                    if sub is None:
                        ok = False
                        break
                    axes |= sub
                if ok and axes:
                    helpers[node.name] = axes
    return helpers


def _is_shard_map_decorated(fn: ast.FunctionDef) -> ast.Call | None:
    """The ``partial(shard_map, ...)`` / ``shard_map(...)`` decorator
    Call of a kernel def, else None."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        name = _call_name(dec)
        if name == "shard_map":
            return dec
        if name == "partial" and dec.args and \
                attr_chain(dec.args[0])[-1:] == ["shard_map"]:
            return dec
    return None


def _collect_local_axis_bindings(fn: ast.AST, env: _AxisEnv) -> None:
    """Resolve simple local assigns (``axes = (DCN_AXIS, DATA_AXIS)``,
    ``spec = P("data", None)``) so axis args and specs passed by name
    resolve. Encountered in source order; last bind wins."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not isinstance(t, ast.Name):
            continue
        axes = env.axis_of(node.value)
        if axes is not None:
            env.bind_local(t.id, axes if len(axes) > 1 else next(iter(axes)))
            continue
        spec = env.spec_axes(node.value)
        if spec is not None:
            env.bind_local(t.id, spec)


#: axis-consuming calls -> which positional arg names the axis
_AXIS_ARG_POS = {
    "psum": 1, "pmin": 1, "pmax": 1, "pmean": 1, "ppermute": 1,
    "all_gather": 1, "all_to_all": 1, "pbroadcast": 1, "pshuffle": 1,
    "psum_scatter": 1, "axis_index": 0,
}


def _kernel_used_axes(fn: ast.FunctionDef, env: _AxisEnv):
    """(axis name, call name, lineno) for every resolvable axis-consuming
    call in a shard_map body; unresolvable axis args are skipped."""
    out: list[tuple[set, str, int]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        pos = _AXIS_ARG_POS.get(name)
        if pos is None:
            continue
        axis_expr = None
        if len(node.args) > pos:
            axis_expr = node.args[pos]
        else:
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis"):
                    axis_expr = kw.value
        if axis_expr is None:
            continue
        axes = env.axis_of(axis_expr)
        if axes:
            out.append((axes, name, node.lineno))
    return out


def _shard_map_bound_axes(dec: ast.Call, env: _AxisEnv) -> tuple[set, bool]:
    """(bound axis names, resolved?) from the decorator's mesh/in_specs/
    out_specs kwargs. resolved=False when NOTHING resolved (judging used
    axes against an empty guess would be noise, not analysis)."""
    bound: set[str] = set()
    resolved = False
    for kw in dec.keywords:
        if kw.arg == "mesh":
            if isinstance(kw.value, ast.Call):
                ctor = _call_name(kw.value)
                if ctor in _MESH_CTOR_AXES:
                    bound |= _MESH_CTOR_AXES[ctor]
                    resolved = True
        elif kw.arg in ("in_specs", "out_specs"):
            exprs = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            for e in exprs:
                spec = env.spec_axes(e)
                if spec is not None:
                    bound |= spec[0]
                    resolved = True
    return bound, resolved


# ==========================================================================
# host-varying taint (TPS001) + host-shaped taint (TPS007)
# ==========================================================================
def _is_host_varying_expr(expr: ast.AST, tainted: set[str]) -> list[str]:
    """The host-varying sources an expression consumes: tainted local
    names, host-varying calls, host-state attribute reads. A value that
    came out of a collective is host-INVARIANT (all hosts agreed), so
    collective-call results never taint — that is the barrier-fixed twin."""
    hits: list[str] = []
    skip: set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in COLLECTIVE_CALLS:
                for inner in ast.walk(node):
                    skip.add(id(inner))
    for node in ast.walk(expr):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in _HOST_VARYING_CALLS:
                hits.append(f"{name}()")
        elif isinstance(node, ast.Attribute) and \
                node.attr in _HOST_VARYING_NAMES:
            hits.append(node.attr)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in tainted:
            hits.append(node.id)
    return hits


def _collective_calls_in(body: Iterable[ast.stmt]) -> list[tuple[str, int]]:
    out: list[tuple[str, int]] = []
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name in COLLECTIVE_CALLS:
                    out.append((name, node.lineno))
    return out


def _scan_order_divergence(fn: ast.AST, hits: list) -> None:
    """TPS001 over one function: in-order taint of host-varying values,
    then (a) a tainted branch/loop guarding a collective, (b) a loop
    containing both a collective and a tainted early exit — different
    iteration counts issue different collective counts per host."""
    tainted: set[str] = {
        p.arg for p in (
            list(fn.args.posonlyargs) + list(fn.args.args)
            + list(fn.args.kwonlyargs)
        )
        if p.arg in _HOST_VARYING_NAMES
    }

    def visit(stmts: Iterable[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs get their own pass
            if isinstance(stmt, ast.Assign):
                sources = _is_host_varying_expr(stmt.value, tainted)
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        if sources:
                            tainted.add(t.id)
                        else:
                            tainted.discard(t.id)
            elif isinstance(stmt, ast.AugAssign) and isinstance(
                stmt.target, ast.Name
            ):
                if _is_host_varying_expr(stmt.value, tainted):
                    tainted.add(stmt.target.id)
            elif isinstance(stmt, (ast.If, ast.While)):
                sources = _is_host_varying_expr(stmt.test, tainted)
                if sources:
                    for name, lineno in _collective_calls_in(
                        stmt.body + stmt.orelse
                    ):
                        kind = "if" if isinstance(stmt, ast.If) else "while"
                        hits.append((
                            "TPS001", lineno,
                            f"collective {name}() guarded by a python "
                            f"`{kind}` on host-varying value(s) "
                            f"{sorted(set(sources))} — hosts may issue "
                            "collectives in different orders/counts "
                            "(derive the predicate from an agreeing "
                            "collective, or hoist the collective out of "
                            "the branch)",
                        ))
            elif isinstance(stmt, ast.For):
                sources = _is_host_varying_expr(stmt.iter, tainted)
                if sources:
                    for name, lineno in _collective_calls_in(stmt.body):
                        hits.append((
                            "TPS001", lineno,
                            f"collective {name}() inside a loop over "
                            f"host-varying {sorted(set(sources))} — hosts "
                            "iterate different counts and issue different "
                            "collective sequences",
                        ))
            # loops whose EXIT depends on host-varying state while the
            # body issues collectives: the retry/failover re-entry shape
            if isinstance(stmt, (ast.While, ast.For)):
                colls = _collective_calls_in(stmt.body)
                if colls:
                    # pre-taint the loop body's own assignments (in
                    # source order): the exit predicate usually consumes
                    # a value the SAME iteration computed (`took =
                    # clock() - start`)
                    body_taint = set(tainted)
                    for node in sorted(
                        (n for inner in stmt.body
                         for n in ast.walk(inner)
                         if isinstance(n, (ast.Assign, ast.AugAssign))),
                        key=lambda n: n.lineno,
                    ):
                        value = node.value
                        targets = (
                            node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                        varying = _is_host_varying_expr(value, body_taint)
                        for t in targets:
                            if isinstance(t, ast.Name):
                                if varying:
                                    body_taint.add(t.id)
                                elif isinstance(node, ast.Assign):
                                    body_taint.discard(t.id)
                    for inner in stmt.body:
                        for node in ast.walk(inner):
                            if isinstance(node, ast.If) and any(
                                isinstance(x, (ast.Break, ast.Continue,
                                               ast.Return))
                                for b in (node.body, node.orelse)
                                for x in b
                            ):
                                sources = _is_host_varying_expr(
                                    node.test, body_taint
                                )
                                if sources:
                                    name, lineno = colls[0]
                                    hits.append((
                                        "TPS001", node.lineno,
                                        "loop re-issues collective "
                                        f"{name}() (line {lineno}) but "
                                        "exits on host-varying "
                                        f"{sorted(set(sources))} — hosts "
                                        "retry different numbers of times "
                                        "(the failover re-entry shape); "
                                        "agree on the retry decision "
                                        "collectively first",
                                    ))
            # recurse into nested bodies in source order
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    visit(sub)
            for handler in getattr(stmt, "handlers", ()):
                visit(handler.body)

    visit(list(getattr(fn, "body", ())))


#: shape-fixing producers that clear the host-shaped taint (TPS007)
_SHAPE_FIXERS = {"pad_rows", "pad_cols", "zeros", "ones", "full", "empty",
                 "concatenate"}
#: placement/dispatch sinks a host-shaped value must not reach
_PLACEMENT_SINKS = {"make_global_array", "shard_rows", "shard_cols",
                    "device_put", "shard_rows_if_active"}


def _scan_host_shapes(fn: ast.AST, hits: list) -> None:
    """TPS007 over one function: values whose SHAPE derives from this
    host's real-row block (``read_host_block``, ``x[host_row_slice(...)]``)
    must be padded to the host-invariant block before they reach a
    placement primitive — otherwise every host compiles its own program
    and shape-divergent collectives hang."""
    shaped: set[str] = set()   # names carrying a host-dependent shape
    slices: set[str] = set()   # names bound to a host_row_slice result

    def value_shaped(expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name in ("read_host_block",):
                    return True
                if name == "host_row_slice":
                    return False  # the slice itself; subscripting taints
            elif isinstance(node, ast.Subscript):
                sl = node.slice
                if isinstance(sl, ast.Name) and sl.id in slices:
                    return True
                if isinstance(sl, ast.Call) and \
                        _call_name(sl) == "host_row_slice":
                    return True
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and node.id in shaped:
                return True
        return False

    def visit(stmts: Iterable[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.Assign):
                val = stmt.value
                fixer = isinstance(val, ast.Call) and \
                    _call_name(val) in _SHAPE_FIXERS
                is_slice = isinstance(val, ast.Call) and \
                    _call_name(val) == "host_row_slice"
                tainted_val = not fixer and value_shaped(val)
                for t in stmt.targets:
                    names = [t] if isinstance(t, ast.Name) else [
                        e for e in getattr(t, "elts", ()) if
                        isinstance(e, ast.Name)
                    ]
                    for n in names:
                        if is_slice:
                            slices.add(n.id)
                            shaped.discard(n.id)
                        elif tainted_val:
                            shaped.add(n.id)
                        else:
                            shaped.discard(n.id)
                            slices.discard(n.id)
            for node in ast.walk(stmt) if not isinstance(
                stmt, (ast.If, ast.While, ast.For, ast.With, ast.Try)
            ) else ():
                if isinstance(node, ast.Call) and \
                        _call_name(node) in _PLACEMENT_SINKS and node.args:
                    # the array argument: device_put/shard_* take it at
                    # position 0 or 1 (mesh-first helpers)
                    name = _call_name(node)
                    idx = 1 if name in ("shard_rows", "shard_cols") and \
                        len(node.args) > 1 else 0
                    arg = node.args[idx]
                    if value_shaped(arg):
                        hits.append((
                            "TPS007", node.lineno,
                            f"host-dependent shape feeds {name}() — this "
                            "host's real-row block has a different shape "
                            "on every host, so each compiles its own "
                            "program (recompile storm) and shape-"
                            "divergent collectives hang; pad to the "
                            "host-invariant block first (pad_rows / "
                            "zeros-block copy)",
                        ))
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    visit(sub)
            for handler in getattr(stmt, "handlers", ()):
                visit(handler.body)

    visit(list(getattr(fn, "body", ())))


# ==========================================================================
# locks (TPS005) and kernel-body stability (TPS004)
# ==========================================================================
from .findings import lock_guarded_expr as _lock_guarded  # noqa: E402 — shared


def _scan_collective_under_lock(tree: ast.Module, hits: list) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        if not any(_lock_guarded(i.context_expr) for i in node.items):
            continue
        for name, lineno in _collective_calls_in(node.body):
            hits.append((
                "TPS005", lineno,
                f"collective {name}() issued while holding a lock — if "
                "any other host needs this lock to reach its own "
                f"{name}(), the mesh deadlocks (snapshot under the lock, "
                "issue the collective outside it); this edge bridges "
                "into the TPC lock-order graph",
            ))


def _scan_kernel_stability(fn: ast.FunctionDef, hits: list) -> None:
    """TPS004 inside one shard_map kernel body: (a) subtraction whose
    operands BOTH derive from collective reductions — the raw-moment
    variance shape, catastrophic f32 cancellation under reordering;
    (b) 64-bit dtypes — f64 math silently degrades (or refuses to
    lower) on TPU, so merges stop being bit-identical."""
    reduced: set[str] = set()

    def from_reduce(expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and \
                    _call_name(node) in _LAX_COLLECTIVES:
                return True
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ) and node.id in reduced:
                return True
        return False

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and from_reduce(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    reduced.add(t.id)
    for node in ast.walk(fn):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
            if from_reduce(node.left) and from_reduce(node.right):
                hits.append((
                    "TPS004", node.lineno,
                    f"subtraction of two collective-reduced values in "
                    f"kernel {fn.name}() — the raw-moment shape "
                    "catastrophically cancels in f32 and its rounding is "
                    "reduction-order-sensitive, breaking the guarded "
                    "seam's bit-identical commutative-merge contract "
                    "(center first, then reduce — see pcolumn_stats)",
                ))
        chain = attr_chain(node) if isinstance(node, ast.Attribute) else []
        if chain and chain[-1] in ("float64", "int64", "uint64",
                                   "complex128"):
            hits.append((
                "TPS004", node.lineno,
                f"64-bit dtype in shard_map kernel {fn.name}() — TPU has "
                "no f64 ALU, so the op silently falls to different "
                "rounding (or refuses to lower) and merges stop being "
                "bit-identical across mesh shapes",
            ))
        if isinstance(node, ast.Call) and _call_name(node) == "astype":
            for a in node.args:
                if isinstance(a, ast.Constant) and \
                        str(a.value).endswith("64"):
                    hits.append((
                        "TPS004", node.lineno,
                        f"64-bit cast in shard_map kernel {fn.name}()",
                    ))


# ==========================================================================
# per-file driver
# ==========================================================================
def analyze_source(source: str, rel_path: str) -> Report:
    """The static TPS pass over one file. ``rel_path`` (posix,
    repo-relative) keys findings for the baseline."""
    report = Report()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        report.add(
            "TPS000",
            f"file does not parse: {e}",
            subject=f"{rel_path}:{e.lineno or 0}",
            severity=Severity.WARNING,
            path=rel_path, line=e.lineno or 0, context="",
        )
        return report
    lines = source.splitlines()
    hits: list[tuple[str, int, str]] = []

    consts = _module_axis_consts(tree)
    helpers = _module_spec_helpers(tree, consts)
    seams: dict[str, list[int]] = {}
    kernels = 0

    funcs = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in funcs:
        _scan_order_divergence(fn, hits)
        _scan_host_shapes(fn, hits)
        dec = _is_shard_map_decorated(fn) if isinstance(
            fn, ast.FunctionDef
        ) else None
        if dec is None:
            continue
        kernels += 1
        env = _AxisEnv(consts, helpers)
        _collect_local_axis_bindings(fn, env)
        bound, resolved = _shard_map_bound_axes(dec, env)
        if resolved:
            for axes, call, lineno in _kernel_used_axes(fn, env):
                missing = axes - bound
                if missing:
                    hits.append((
                        "TPS002", lineno,
                        f"shard_map kernel {fn.name}() issues {call}() "
                        f"over axis {sorted(missing)} but the wrapping "
                        f"mesh/in_specs bind only {sorted(bound)} — the "
                        "kernel traces, then dies with an unbound-axis "
                        "error on the first real mesh (the compat-shim "
                        "break class)",
                    ))
        _scan_kernel_stability(fn, hits)
        # ---- TPS003(a): spec axes vs a resolvable mesh vocabulary
        mesh_axes: set[str] | None = None
        for kw in dec.keywords:
            if kw.arg == "mesh" and isinstance(kw.value, ast.Call):
                ctor = _call_name(kw.value)
                mesh_axes = _MESH_CTOR_AXES.get(ctor)
        if mesh_axes is not None:
            env2 = _AxisEnv(consts, helpers)
            _collect_local_axis_bindings(fn, env2)
            for kw in dec.keywords:
                if kw.arg not in ("in_specs", "out_specs"):
                    continue
                exprs = (
                    kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value]
                )
                for e in exprs:
                    spec = env2.spec_axes(e)
                    if spec and spec[0] - mesh_axes:
                        hits.append((
                            "TPS003", e.lineno,
                            f"PartitionSpec axes "
                            f"{sorted(spec[0] - mesh_axes)} are not in "
                            f"the mesh's vocabulary {sorted(mesh_axes)}",
                        ))

    # ---- TPS003(b): literal-spec placement with statically-known ranks
    _scan_spec_ranks(tree, consts, helpers, hits)
    _scan_collective_under_lock(tree, hits)

    # ---- seam census: names issued through the guarded seam
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _call_name(node) in _SEAM_ENTRIES:
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                seams.setdefault(node.args[0].value, []).append(node.lineno)

    rel = rel_path.replace(os.sep, "/")
    for code, lineno, message in sorted(hits, key=lambda h: (h[1], h[0])):
        context = lines[lineno - 1].strip() if 0 < lineno <= len(lines) else ""
        if suppressed(context, code):
            continue
        report.add(
            code, message,
            subject=f"{rel}:{lineno}",
            severity=Severity.WARNING,
            path=rel, line=lineno, context=context,
        )
    if seams:
        report.data["spmdSeams"] = {
            rel: {name: lns for name, lns in sorted(seams.items())}
        }
    if kernels:
        report.data["shardMapKernels"] = {rel: kernels}
    return report


def _literal_rank(expr: ast.AST) -> int | None:
    """Rank of an array-building call with a literal shape tuple
    (``np.zeros((a, b))``, ``rng.normal(size=(a, b))``, ``x.reshape``)."""
    if not isinstance(expr, ast.Call):
        return None
    name = _call_name(expr)
    shape_expr = None
    if name in ("zeros", "ones", "full", "empty", "reshape"):
        if expr.args:
            shape_expr = expr.args[0]
    elif name in ("normal", "uniform", "integers", "standard_normal"):
        for kw in expr.keywords:
            if kw.arg == "size":
                shape_expr = kw.value
    if shape_expr is None:
        return None
    if isinstance(shape_expr, (ast.Tuple, ast.List)):
        return len(shape_expr.elts)
    if isinstance(shape_expr, ast.Constant) and isinstance(
        shape_expr.value, int
    ):
        return 1
    return None


def _scan_spec_ranks(tree, consts, helpers, hits: list) -> None:
    """TPS003(b): ``device_put(x, NamedSharding(mesh, SPEC))`` where both
    the spec's entry count and x's rank are statically known and
    disagree — a mis-ranked PartitionSpec either errors at placement or
    silently shards the wrong axis."""
    env = _AxisEnv(consts, helpers)
    ranks: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            r = _literal_rank(node.value)
            if r is not None:
                ranks[node.targets[0].id] = r
            else:
                ranks.pop(node.targets[0].id, None)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and
                _call_name(node) == "device_put" and len(node.args) >= 2):
            continue
        arr, shd = node.args[0], node.args[1]
        rank = None
        if isinstance(arr, ast.Name):
            rank = ranks.get(arr.id)
        else:
            rank = _literal_rank(arr)
        if rank is None:
            continue
        spec_expr = None
        if isinstance(shd, ast.Call) and _call_name(shd) == "NamedSharding" \
                and len(shd.args) >= 2:
            spec_expr = shd.args[1]
        if spec_expr is None:
            continue
        spec = env.spec_axes(spec_expr)
        if spec is not None and spec[1] > rank:
            hits.append((
                "TPS003", node.lineno,
                f"PartitionSpec has {spec[1]} entries but the array it "
                f"shards has rank {rank} — the spec names more axes than "
                "the array has dimensions",
            ))


def analyze_paths(
    paths: Iterable[str] | None = None,
    root: str = ".",
    restrict: bool = True,
) -> Report:
    """The static TPS pass over every ``.py`` under ``paths``; with
    ``restrict`` (the default) only files on the SPMD surface are read —
    single-device code has no collective order to get wrong."""
    if paths is None:
        paths, root = default_spmd_paths()
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git", "node_modules")
            ]
            files.extend(
                os.path.join(dirpath, f)
                for f in filenames if f.endswith(".py")
            )
    report = Report()
    seams: dict[str, Any] = {}
    kernels: dict[str, int] = {}
    for path in sorted(set(files)):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        if restrict and not _in_scope(rel):
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        sub = analyze_source(source, rel)
        seams.update(sub.data.pop("spmdSeams", {}))
        kernels.update(sub.data.pop("shardMapKernels", {}))
        report.extend(sub)
    report.data["spmdSeams"] = seams
    report.data["shardMapKernels"] = kernels
    return report


def _in_scope(rel: str) -> bool:
    rel = rel.replace(os.sep, "/")
    return (
        "/parallel/" in rel or rel.startswith("parallel/")
        or rel.endswith("models/trees.py")
        or rel.endswith("resilience/distributed.py")
    )


def default_spmd_paths() -> tuple[list[str], str]:
    """(paths, root) mirroring ``concurrency.default_concurrency_paths``:
    a repo checkout analyzes the SPMD surface with repo-relative keys; an
    installed package analyzes itself."""
    if os.path.isdir("transmogrifai_tpu"):
        return [p for p in DEFAULT_SPMD_PATHS if os.path.exists(p)], "."
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg)
    return (
        [
            os.path.join(pkg, "parallel"),
            os.path.join(pkg, "models", "trees.py"),
            os.path.join(pkg, "resilience", "distributed.py"),
        ],
        root,
    )


def seam_collective_census(
    paths: Iterable[str] | None = None, root: str = "."
) -> dict[str, Any]:
    """{collective name -> [site, ...]} of every name issued through the
    guarded seam (the vocabulary the dynamic tapes must be explained by)."""
    report = analyze_paths(paths, root=root)
    out: dict[str, list[str]] = {}
    for rel, names in (report.data.get("spmdSeams") or {}).items():
        for name, linenos in names.items():
            out.setdefault(name, []).extend(
                f"{rel}:{ln}" for ln in linenos
            )
    return out


# ==========================================================================
# IR leg: the static collective census (TPS006)
# ==========================================================================
def hlo_collective_kinds(text: str) -> set[str]:
    """Collective kinds present in a lowered StableHLO/HLO text dump
    (underscore and hyphen spellings both occur across jax versions)."""
    kinds: set[str] = set()
    for kind in HLO_KIND_SOURCES:
        if kind in text or kind.replace("_", "-") in text:
            kinds.add(kind)
    return kinds


def reconcile_hlo_census(
    name: str, declared_prims: set[str], hlo_kinds: set[str]
) -> Report:
    """TPS006 for every lowered collective kind none of the program's
    jaxpr-census primitives declare — lowering inserted a collective the
    trace never showed (hidden resharding)."""
    report = Report()
    for kind in sorted(hlo_kinds):
        if not set(HLO_KIND_SOURCES[kind]) & declared_prims:
            report.add(
                "TPS006",
                f"program '{name}' lowers to HLO containing "
                f"'{kind}' but its jaxpr collective census declares "
                f"{sorted(declared_prims) or 'no collectives'} — XLA "
                "inserted a collective the trace never showed (hidden "
                "resharding); make the resharding explicit in the "
                "program or fix the specs",
                subject=f"program:{name}",
                severity=Severity.WARNING,
                path=f"program:{name}", line=0,
                context=f"{name} hlo:{kind}",
            )
    return report


def jaxpr_collectives(closed) -> list[dict[str, Any]]:
    """The collective census of one (closed) jaxpr: count + primitive +
    axes of every collective primitive, recursed through scan/cond/pjit
    bodies. The unit both census legs and the compat-shim parity tests
    share."""
    from . import program as PJ

    counts: dict[tuple[str, str], int] = {}
    for jaxpr, _consts in PJ._walk(closed):
        for eqn in jaxpr.eqns:
            pname = eqn.primitive.name
            if pname not in COLLECTIVE_PRIMITIVES:
                continue
            axes = eqn.params.get("axes") or eqn.params.get(
                "axis_name"
            ) or ()
            if isinstance(axes, (str, int)):
                axes = (axes,)
            key = (pname, ",".join(str(a) for a in axes))
            counts[key] = counts.get(key, 0) + 1
    return [
        {"primitive": p, "axes": a, "count": c}
        for (p, a), c in sorted(counts.items())
    ]


def _parallel_specs(errors: list | None = None):
    from . import program as PJ

    specs = PJ.collect_specs(errors=errors)
    return [
        s for s in specs
        if s.module.startswith("transmogrifai_tpu.parallel")
    ]


def static_collective_census(specs=None) -> Report:
    """Trace every registered parallel-plane shard_map kernel and derive
    its collective census (count + primitive + axes per collective in
    the jaxpr), then reconcile the lowered HLO against it (TPS006).
    Programs that fail to trace degrade to TPS000 findings. The census
    rides ``report.data["collectiveCensus"]``."""
    from . import program as PJ

    report = Report()
    errors: list = []
    if specs is None:
        specs = _parallel_specs(errors=errors)
    for mod_name, err in errors:
        report.add(
            "TPS000",
            f"program registration in '{mod_name}' failed — its kernels "
            f"are MISSING from the collective census: {err}",
            subject=f"module:{mod_name}",
            severity=Severity.WARNING,
            path=f"module:{mod_name}", line=0, context=f"{mod_name} collect",
        )
    census: dict[str, Any] = {}
    for spec in specs:
        bucket = spec.buckets[0]
        try:
            args, statics = spec.build(bucket)
            closed = PJ._trace_closed(spec.fn, args, statics)
        except Exception as e:
            report.add(
                "TPS000",
                f"program '{spec.name}' failed to trace for the "
                f"collective census: {e}",
                subject=f"program:{spec.name}",
                severity=Severity.WARNING,
                path=f"program:{spec.name}", line=0,
                context=f"{spec.name} trace",
            )
            continue
        collectives = jaxpr_collectives(closed)
        prims = {c["primitive"] for c in collectives}
        hlo_kinds: set[str] = set()
        try:
            fn = spec.fn
            if not hasattr(fn, "lower"):
                import jax

                fn = jax.jit(  # tp: disable=TPL003 — lower-only
                    fn, static_argnames=tuple(statics)
                )
            import warnings

            with warnings.catch_warnings():
                # donating programs (the sharded sweep) warn per-lower
                # about buffers whose shapes can't alias an output —
                # expected, and TPJ003 audits the aliasing separately
                warnings.filterwarnings(
                    "ignore", message=".*donated buffers.*"
                )
                text = fn.lower(*args, **statics).as_text()
            hlo_kinds = hlo_collective_kinds(text)
            report.extend(reconcile_hlo_census(spec.name, prims, hlo_kinds))
        except Exception as e:
            report.add(
                "TPS000",
                f"program '{spec.name}' failed to lower for the HLO "
                f"reconciliation: {e}",
                subject=f"program:{spec.name}",
                severity=Severity.WARNING,
                path=f"program:{spec.name}", line=0,
                context=f"{spec.name} lower",
            )
        census[spec.name] = {
            "collectives": collectives,
            "hloKinds": sorted(hlo_kinds),
        }
    report.data["collectiveCensus"] = census
    return report


def audit_spmd(
    paths: Iterable[str] | None = None,
    root: str = ".",
    include_ir: bool = True,
) -> Report:
    """The full TPS pass: static AST analysis over the SPMD surface plus
    (by default) the jaxpr/HLO collective census of every registered
    parallel kernel — the CLI ``lint --spmd`` entry."""
    report = analyze_paths(paths, root=root)
    if include_ir:
        report.extend(static_collective_census())
    return report


# ==========================================================================
# dynamic leg: the per-host collective-tape reconciler (TPS008)
# ==========================================================================
def reconcile_collective_orders(
    tapes: dict[str, Any],
    census: dict[str, Any] | None = None,
) -> Report:
    """Assert the per-host collective tapes agree and are explained.

    ``tapes`` is ``parallel.guarded.collective_tapes()``'s shape (live or
    loaded from a ``TPTPU_COLLECTIVE_TRACE_OUT`` dump). Invariants:

    * every SURVIVOR host's tape is identical — same names, same order,
      same sequence numbers (the commutative-reduce contract only holds
      when every host joins every collective);
    * a LOST host's tape is a strict prefix of the survivors' (it
      stopped at the failover point, it never diverged);
    * with ``census`` (:func:`seam_collective_census`'s shape, or any
      ``{name: ...}``), every issued name is statically declared.

    One TPS008 WARNING per violation plus a ``reconciliation`` data
    attachment; CI gates on ``len(report)``."""
    report = Report()
    hosts = {
        int(h): [(int(s), str(n)) for s, n in tape]
        for h, tape in (tapes.get("hosts") or {}).items()
    }
    lost = {int(h) for h in tapes.get("lost") or ()}
    n_hosts = int(tapes.get("nHosts") or (max(hosts) + 1 if hosts else 0))
    survivors = sorted(h for h in range(n_hosts) if h not in lost)
    divergent: list[int] = []

    reference: list[tuple[int, str]] | None = None
    ref_host = None
    for h in survivors:
        tape = hosts.get(h, [])
        if reference is None:
            reference, ref_host = tape, h
            continue
        if tape != reference:
            divergent.append(h)
            where = next(
                (i for i, (a, b) in enumerate(zip(reference, tape))
                 if a != b),
                min(len(reference), len(tape)),
            )
            report.add(
                "TPS008",
                f"host {h}'s collective tape diverges from host "
                f"{ref_host}'s at sequence {where}: "
                f"{tape[where] if where < len(tape) else '<ended>'} vs "
                f"{reference[where] if where < len(reference) else '<ended>'}"
                " — hosts issued collectives in different orders/counts "
                "(the deadlock precursor TPS001 exists to catch "
                "statically)",
                subject=f"host:{h}",
                severity=Severity.WARNING,
                path="tape:reconcile", line=0,
                context=f"host {h} diverges",
            )
    if reference is None and hosts:
        # every host was lost (a long multi-failover suite can exhaust
        # the host set): the LONGEST frozen tape is the reference and
        # every other tape must be a prefix of it — tapes only ever
        # freeze, so lockstep ordering still proves out
        ref_host = max(hosts, key=lambda h: len(hosts[h]))
        reference = hosts[ref_host]
    for h in sorted(lost):
        if h == ref_host:
            continue
        tape = hosts.get(h, [])
        if reference is not None and tape != reference[: len(tape)]:
            divergent.append(h)
            report.add(
                "TPS008",
                f"lost host {h}'s tape is not a prefix of the survivors' "
                "— it diverged BEFORE the failover, not because of it",
                subject=f"host:{h}",
                severity=Severity.WARNING,
                path="tape:reconcile", line=0,
                context=f"lost host {h} not a prefix",
            )
    issued = {n for tape in hosts.values() for _s, n in tape}
    unexplained = sorted(
        issued - set(census)
    ) if census is not None else []
    for name in unexplained:
        report.add(
            "TPS008",
            f"collective '{name}' was issued at runtime but the static "
            "seam census never declared it — a collective flows outside "
            "the guarded seam's vocabulary (route it through "
            "parallel.guarded.guarded_collective)",
            subject=f"collective:{name}",
            severity=Severity.WARNING,
            path="tape:census", line=0,
            context=f"{name} unexplained",
        )
    report.data["reconciliation"] = {
        "hosts": n_hosts,
        "lostHosts": sorted(lost),
        # reference length, or the longest frozen tape when every host
        # was lost (a long multi-failover suite can exhaust the host set)
        "tapeLength": len(reference or ()) or max(
            (len(t) for t in hosts.values()), default=0
        ),
        "divergentHosts": sorted(set(divergent)),
        "issuedNames": sorted(issued),
        "unexplainedNames": unexplained,
        "tapesAgree": not divergent,
        "explained": not unexplained,
    }
    return report


# ==========================================================================
# summary surface
# ==========================================================================
@functools.lru_cache(maxsize=1)
def package_summary() -> dict[str, Any]:
    """Compact cached summary for ``summary_json()["analysis"]["spmd"]``
    — the TPS family riding beside the TPA/TPX/TPC reports. Cached per
    process: the package's source does not change under a running
    train. Static AST leg only (the IR census traces jax programs —
    too heavy for a summary side-channel)."""
    paths, root = default_spmd_paths()
    report = analyze_paths(paths, root=root)
    codes: dict[str, int] = {}
    for f in report.findings:
        codes[f.code] = codes.get(f.code, 0) + 1
    seams = report.data.get("spmdSeams") or {}
    seam_names = sorted({n for names in seams.values() for n in names})
    return {
        "findings": len(report.findings),
        "codes": codes,
        "seamCollectives": seam_names,
        "shardMapKernels": sum(
            (report.data.get("shardMapKernels") or {}).values()
        ),
    }
