"""Static concurrency analyzer — the ``TPC0xx`` finding family.

The codebase is deeply multi-threaded (featurize pools, standing-service
workers, warmup threads, telemetry exposition, drift-window locks) and
the review trail proves the bug class recurs: PR 8 shipped — and review
caught — a live ABBA deadlock (``render_prometheus`` invoking exposition
sources inside the registry lock while ``submit()`` held the service
lock), PR 9 a half-built shared cache published non-atomically. This
module makes those shapes machine-checkable, the same way the TPA pass
polices user DAGs and the transfer census polices device crossings:

* a **lock registry** inferred from ``threading.Lock/RLock/Condition``
  assignments, :func:`analysis.schedule.make_lock` seams (whose string
  literal IS the canonical key, so the static and dynamic graphs share a
  vocabulary), and lightweight ``# tpc: lock(name)`` annotations for
  aliased locks the AST cannot connect (e.g. every ledger sharing the
  registry lock);
* a **whole-repo lock-order graph** from ``with``-statement nesting per
  function. Calls are inlined through the resolved call graph: the
  analyzer resolves same-module calls, ``self.method()``, attributes
  typed by their constructor assignment
  (``self.queue = AdmissionQueue(...)``), module-level singletons, and a
  tiny return-type oracle for the metrics factories
  (``REGISTRY.gauge(...).set`` acquires the registry lock) — and
  propagates each function's acquisition set transitively, so an edge
  exists whenever lock B can be acquired anywhere downstream of holding
  lock A. The static graph deliberately OVERAPPROXIMATES the dynamic
  one (``analysis/schedule.py``), which is what makes the
  dynamic-subgraph reconciliation meaningful. Cycles are **TPC001**
  potential deadlocks;
* **guarded-field discipline**: an instance field ever written under
  lock L must be written under a common lock at every site — bare
  writes are **TPC002**, disagreeing guards **TPC003**
  (``# tpc: guarded(key)`` documents caller-holds-the-lock helpers);
* **foreign-callable-under-lock** (**TPC004** — the exact PR-8 bug
  shape): invoking a data-derived callable (an exposition source pulled
  out of a dict, a user callback parameter) while holding any lock;
* **non-atomic publish** (**TPC005** — the exact PR-9 bug shape):
  assigning a fresh mutable container to a shared attribute and then
  filling it in across subsequent statements, instead of
  build-locally-then-single-assign.

Scope is the TPL001 thread-crossed subsystem list
(:data:`THREAD_CROSSED_SUBSYSTEMS`, shared with the linter).
Suppression mirrors tplint: ``# tpc: ok`` or ``# tpc: disable=TPC004``
on the offending line. Accepted findings live in the committed
``concurrency_baseline.json`` (same line-move-invariant key as
``lint_baseline.json``: code, path, source line text), so CI fails only
on NEW findings.

Annotation vocabulary (all line comments):

* ``# tpc: lock(key)`` — on a lock (or lock-alias) assignment or a
  ``with`` line: canonical key override, used to tie aliased locks
  (``self._lock = reg.lock``) to one graph node;
* ``# tpc: guarded(key)`` — on a write or ``def`` line: this code runs
  with ``key`` held by contract (caller-holds-the-lock helpers);
* ``# tpc: type(Class)`` — on an attribute assignment: the attribute's
  class when the constructor form cannot show it;
* ``# tpc: ok`` / ``# tpc: disable=TPCnnn`` — suppress on this line.

All of these parse through the SHARED directive parser in
``analysis/findings.py``: the unified ``# tp:`` prefix is the canonical
spelling for every verb above, and the ``# tpc:`` dialect keeps working
as a (deprecated, one release) legacy alias.

Static keys are PACKAGE-relative (``serving/service.py:ScoringService.
_lock``); finding paths stay repo-relative like every other analyser so
one baseline format serves both linters.
"""
from __future__ import annotations

import ast
import builtins as _builtins
import functools
import os
from typing import Any, Iterable

from .findings import Report, Severity

__all__ = [
    "THREAD_CROSSED_SUBSYSTEMS",
    "analyze_paths",
    "analyze_sources",
    "default_concurrency_paths",
    "package_summary",
]

# shared with the linter so both passes police one subsystem list
from .lint import _LOCKED_SUBSYSTEMS as THREAD_CROSSED_SUBSYSTEMS  # noqa: E402

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_RLOCK_KINDS = {"RLock"}
_MUTATORS = {
    "append", "add", "update", "pop", "popitem", "setdefault", "clear",
    "extend", "remove", "discard", "insert",
}
_FRESH_MUTABLE_CTORS = {
    "dict", "list", "set", "defaultdict", "OrderedDict", "deque", "Counter",
}
#: attribute names that mean "someone else's code" when called under a lock
_CALLBACK_ATTRS = {"callback", "cb", "fn", "hook"}
#: metrics-registry factory methods whose RETURN value carries the shared
#: registry lock — the one place attribute types flow through a factory
_FACTORY_RETURNS = {"counter": "Counter", "gauge": "Gauge",
                    "histogram": "Histogram"}
#: fields whose writes are lock/thread bookkeeping, not shared state
_EXEMPT_FIELD_SUFFIXES = ("_lock", "_locks", "_event", "_tls", "_cond")
_CTOR_NAMES = ("__init__", "__new__", "__post_init__")

# annotation verbs, parsed by the shared directive parser in findings.py
# (the unified '# tp:' prefix and the legacy '# tpc:' dialect both work)
_ANN_LOCK = "lock"
_ANN_GUARDED = "guarded"
_ANN_TYPE = "type"

_BUILTINS = set(dir(_builtins))
_UNSET = object()


def _suppressed(line: str, code: str) -> bool:
    from .findings import suppressed

    return suppressed(line, code)


from .findings import attr_chain as _attr_chain  # shared AST helper


def _pkg_rel(rel: str) -> str:
    """Lock keys are package-relative: strip everything up to and
    including the package directory so keys read the same whether the
    analyzer runs over a repo checkout or an installed package."""
    rel = rel.replace(os.sep, "/")
    marker = "transmogrifai_tpu/"
    i = rel.rfind(marker)
    return rel[i + len(marker):] if i >= 0 else rel


class _LockDef:
    __slots__ = ("key", "kind", "repo_rel", "line")

    def __init__(self, key: str, kind: str, repo_rel: str, line: int):
        self.key = key
        self.kind = kind  # "lock" | "rlock" | "condition" | "family"
        self.repo_rel = repo_rel
        self.line = line


class _CallSite:
    __slots__ = ("node", "held", "line", "target")

    def __init__(self, node: ast.Call, held: tuple[str, ...], line: int):
        self.node = node
        self.held = held
        self.line = line
        self.target: Any = _UNSET  # memoized resolution


class _Write:
    __slots__ = ("field", "line", "held", "value", "subscript")

    def __init__(self, field, line, held, value, subscript):
        self.field = field
        self.line = line
        self.held = held
        self.value = value
        self.subscript = subscript


class _FuncInfo:
    __slots__ = (
        "pkg_rel", "qual", "cls", "node", "acquires", "order_edges",
        "calls", "writes", "safe_names", "publishes", "acq_star",
        "lock_return",
    )

    def __init__(self, pkg_rel: str, qual: str, cls: str | None, node):
        self.pkg_rel = pkg_rel
        self.qual = qual
        self.cls = cls
        self.node = node
        self.lock_return: str | None = None
        self.reset()

    def reset(self) -> None:
        self.acquires: set[str] = set()
        #: (held_key, acquired_key, lineno)
        self.order_edges: list[tuple[str, str, int]] = []
        self.calls: list[_CallSite] = []
        self.writes: list[_Write] = []
        self.safe_names: set[str] = set()
        #: TPC005 candidates: field -> {"line", "held", "mutations"}
        self.publishes: dict[str, dict[str, Any]] = {}
        self.acq_star: set[str] | None = None


class _Module:
    __slots__ = (
        "repo_rel", "pkg_rel", "tree", "lines", "funcs", "classes",
        "mod_aliases", "from_names", "global_locks", "global_instances",
        "scope_locks",
    )

    def __init__(self, repo_rel: str, tree: ast.Module, lines: list[str]):
        self.repo_rel = repo_rel
        self.pkg_rel = _pkg_rel(repo_rel)
        self.tree = tree
        self.lines = lines
        self.funcs: set[str] = set()
        self.classes: dict[str, ast.ClassDef] = {}
        self.mod_aliases: dict[str, list[str]] = {}   # alias -> module parts
        self.from_names: dict[str, tuple[list[str], str]] = {}
        self.global_locks: dict[str, _LockDef] = {}
        self.global_instances: dict[str, str] = {}    # NAME -> class name
        self.scope_locks: dict[str, dict[str, _LockDef]] = {}  # qual -> env


class _Analyzer:
    """Whole-repo analysis: pass 0 collects module surfaces, walk A
    registers every lock/type definition (its analysis output is thrown
    away), the in-between passes resolve aliases and lock-returning
    functions, walk B re-analyzes every function with complete
    knowledge, and the rule passes run over walk B's records."""

    def __init__(self) -> None:
        self.modules: dict[str, _Module] = {}          # pkg_rel -> module
        self.class_index: dict[str, list[tuple[str, ast.ClassDef]]] = {}
        self.attr_locks: dict[tuple[str, str, str], _LockDef] = {}
        #: (pkg_rel, cls, attr) -> class-name string to resolve later
        self.attr_type_names: dict[tuple[str, str, str], str] = {}
        self.attr_types: dict[tuple[str, str, str], tuple[str, str]] = {}
        self.functions: dict[tuple[str, str], _FuncInfo] = {}
        #: per-module nested-def index: pkg_rel -> name -> [qual]
        self.nested_defs: dict[str, dict[str, list[str]]] = {}
        self.report = Report()
        #: (from_key, to_key) -> list of (repo_rel, line)
        self.edges: dict[tuple[str, str], list[tuple[str, int]]] = {}
        self._pending_cond_aliases: list = []

    # ---------------------------------------------------------------- helpers
    def _line(self, mod: _Module, lineno: int) -> str:
        if 0 < lineno <= len(mod.lines):
            return mod.lines[lineno - 1]
        return ""

    def _ann(self, mod: _Module, lineno: int, verb: str) -> str | None:
        from .findings import annotations

        got = annotations(self._line(mod, lineno), verb, family="tpc")
        return got[0] if got else None

    def _add_finding(
        self, code: str, message: str, mod: _Module, lineno: int,
        subject: str = "",
    ) -> None:
        context = self._line(mod, lineno).strip()
        if _suppressed(context, code):
            return
        self.report.add(
            code, message,
            subject=subject or f"{mod.repo_rel}:{lineno}",
            severity=Severity.WARNING,
            path=mod.repo_rel, line=lineno, context=context,
        )

    # ------------------------------------------------------------- pass 0
    def add_source(self, repo_rel: str, source: str) -> None:
        repo_rel = repo_rel.replace(os.sep, "/")
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            mod = _Module(repo_rel, ast.Module(body=[], type_ignores=[]),
                          source.splitlines())
            self.modules[mod.pkg_rel] = mod
            self.report.add(
                "TPC000", f"file does not parse: {e}",
                subject=f"{repo_rel}:{e.lineno or 0}",
                severity=Severity.WARNING,
                path=repo_rel, line=e.lineno or 0, context="",
            )
            return
        mod = _Module(repo_rel, tree, source.splitlines())
        self.modules[mod.pkg_rel] = mod
        # imports are collected from the WHOLE tree: function-level
        # imports are pervasive (lazy imports breaking cycles) and their
        # names are just as resolvable/safe as module-level ones
        for node in ast.walk(tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._collect_import(mod, node)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.funcs.add(stmt.name)
            elif isinstance(stmt, ast.ClassDef):
                mod.classes[stmt.name] = stmt
                self.class_index.setdefault(stmt.name, []).append(
                    (mod.pkg_rel, stmt)
                )
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._collect_global_assign(mod, stmt)

    def _collect_import(self, mod: _Module, stmt) -> None:
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                mod.mod_aliases[a.asname or a.name.split(".")[0]] = (
                    a.name.split(".")
                )
            return
        base = [p for p in (stmt.module or "").split(".") if p]
        for a in stmt.names:
            name = a.asname or a.name
            # `from ..telemetry import metrics as _tm` — a MODULE alias;
            # `from .queue import AdmissionQueue` — a class/function name.
            # Record both readings; resolution checks the scanned set.
            mod.mod_aliases.setdefault(name, base + [a.name])
            mod.from_names[name] = (base, a.name)

    def _lock_call(self, value: ast.expr) -> tuple[str | None, str] | None:
        """(explicit_key_or_None, kind) when ``value`` constructs a lock."""
        if not isinstance(value, ast.Call):
            return None
        chain = _attr_chain(value.func)
        if not chain:
            return None
        last = chain[-1]
        if last == "make_lock":
            key = None
            if value.args and isinstance(value.args[0], ast.Constant) and \
                    isinstance(value.args[0].value, str):
                key = value.args[0].value
            kind = "lock"
            if len(value.args) > 1:
                fchain = _attr_chain(value.args[1])
                if fchain and fchain[-1] in _RLOCK_KINDS:
                    kind = "rlock"
            return key, kind
        if last in _LOCK_FACTORIES:
            if last == "Condition":
                return None, "condition"
            return None, "rlock" if last in _RLOCK_KINDS else "lock"
        return None

    def _collect_global_assign(self, mod: _Module, stmt) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        value = stmt.value
        if value is None:
            return
        lock = self._lock_call(value)
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if lock is not None:
                explicit, kind = lock
                key = explicit or self._ann(mod, stmt.lineno, _ANN_LOCK) or \
                    f"{mod.pkg_rel}:{t.id}"
                mod.global_locks[t.id] = _LockDef(
                    key, kind, mod.repo_rel, stmt.lineno
                )
                if kind == "condition" and isinstance(value, ast.Call) \
                        and value.args:
                    self._pending_cond_aliases.append(
                        (mod, None, t.id, value.args[0])
                    )
            elif isinstance(value, ast.Call) and \
                    isinstance(value.func, ast.Name) and \
                    value.func.id in mod.classes:
                mod.global_instances[t.id] = value.func.id
            elif self._ann(mod, stmt.lineno, _ANN_LOCK):
                # annotated alias of a lock defined elsewhere; aliases are
                # usually the shared re-entrant registry lock, so rlock
                mod.global_locks[t.id] = _LockDef(
                    self._ann(mod, stmt.lineno, _ANN_LOCK), "rlock",
                    mod.repo_rel, stmt.lineno,
                )

    # ----------------------------------------------------------- walks A / B
    def scan_all(self) -> None:
        for mod in self.modules.values():
            for stmt in mod.tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._scan_function(mod, stmt, None, stmt.name, {})
                elif isinstance(stmt, ast.ClassDef):
                    for sub in stmt.body:
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            self._scan_function(
                                mod, sub, stmt.name,
                                f"{stmt.name}.{sub.name}", {},
                            )

    def apply_cond_aliases(self) -> None:
        """``threading.Condition(existing_lock)`` shares the wrapped
        lock: alias the Condition's node to the wrapped lock's key."""
        pending, self._pending_cond_aliases = self._pending_cond_aliases, []
        for mod, cls, name, arg in pending:
            target = self._resolve_lock_expr(mod, cls, arg, {})
            if target is None:
                continue
            ld = (
                mod.global_locks.get(name) if cls is None
                else self.attr_locks.get((mod.pkg_rel, cls, name))
            )
            if ld is not None:
                ld.key = target

    def compute_lock_returns(self) -> None:
        """A trivial lock-returning function (``return REGISTRY.lock``)
        lets ``with snapshot_lock():`` resolve without annotations."""
        for (pkg_rel, qual), info in self.functions.items():
            mod = self.modules[pkg_rel]
            scope = mod.scope_locks.get(qual, {})
            for stmt in info.node.body:
                if isinstance(stmt, ast.Return) and stmt.value is not None:
                    key = self._resolve_lock_expr(
                        mod, info.cls, stmt.value, scope
                    )
                    if key is not None:
                        info.lock_return = key

    def resolve_types(self) -> None:
        for akey, tname in self.attr_type_names.items():
            resolved = self._resolve_class_name(akey[0], tname)
            if resolved is not None:
                self.attr_types[akey] = resolved

    def index_nested(self) -> None:
        for (pkg_rel, qual), info in self.functions.items():
            if "." in qual and info.cls is None:
                self.nested_defs.setdefault(pkg_rel, {}).setdefault(
                    qual.rsplit(".", 1)[-1], []
                ).append(qual)

    def rescan(self) -> None:
        for info in self.functions.values():
            info.reset()
        self.scan_all()

    def _collect_safe_names(self, mod: _Module, fn) -> set[str]:
        """Names that resolve to code the author wrote (defs, lambdas,
        aliases of module-level callables like
        ``exc = TransientError if flag else FatalError``) — NOT
        data-derived callables — anywhere inside ``fn``."""
        module_safe = (
            mod.funcs | set(mod.classes) | set(mod.from_names)
            | set(mod.mod_aliases) | _BUILTINS
        )

        def _all_names_safe(expr: ast.expr, safe: set[str]) -> bool:
            if isinstance(expr, ast.Name):
                return expr.id in module_safe or expr.id in safe
            if isinstance(expr, ast.IfExp):
                return _all_names_safe(expr.body, safe) and _all_names_safe(
                    expr.orelse, safe
                )
            if isinstance(expr, ast.BoolOp):
                return all(_all_names_safe(v, safe) for v in expr.values)
            return False

        safe: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                safe.add(node.name)
            elif isinstance(node, ast.Assign):
                if isinstance(node.value, ast.Lambda) or _all_names_safe(
                    node.value, safe
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            safe.add(t.id)
        return safe

    def _scan_function(
        self,
        mod: _Module,
        fn,
        cls: str | None,
        qual: str,
        enclosing_locks: dict[str, _LockDef],
    ) -> None:
        fid = (mod.pkg_rel, qual)
        info = self.functions.get(fid)
        if info is None:
            info = self.functions[fid] = _FuncInfo(mod.pkg_rel, qual, cls, fn)
        info.safe_names = self._collect_safe_names(mod, fn)
        scope_locks = dict(enclosing_locks)
        mod.scope_locks[qual] = scope_locks
        guard_ann = self._ann(mod, fn.lineno, _ANN_GUARDED)
        base_held: tuple[str, ...] = (guard_ann,) if guard_ann else ()
        self._walk_stmts(mod, info, cls, qual, fn.body, base_held, scope_locks)

    # -------------------------------------------------- lock-expr resolution
    def _resolve_lock_expr(
        self,
        mod: _Module,
        cls: str | None,
        expr: ast.expr,
        scope_locks: dict[str, _LockDef],
    ) -> str | None:
        """Canonical lock key for an expression, or None when it is not
        (recognizably) a lock."""
        if isinstance(expr, ast.Call):
            lock = self._lock_call(expr)
            if lock is not None and lock[0] is not None:
                return lock[0]  # inline make_lock("key")
            target = self._resolve_call_target(mod, cls, expr)
            if target is not None:
                callee = self.functions.get(target)
                if callee is not None and callee.lock_return:
                    return callee.lock_return
            return None
        if isinstance(expr, ast.Subscript):
            return self._resolve_lock_expr(mod, cls, expr.value, scope_locks)
        if isinstance(expr, ast.Name):
            ld = scope_locks.get(expr.id) or mod.global_locks.get(expr.id)
            return ld.key if ld is not None else None
        if isinstance(expr, ast.Attribute):
            chain = _attr_chain(expr)
            if not chain:
                return None
            if chain[0] == "self" and cls is not None and len(chain) == 2:
                ld = self._attr_lock(mod.pkg_rel, cls, chain[1])
                return ld.key if ld is not None else None
            # NAME.attr where NAME is a module-level singleton instance
            if len(chain) == 2 and chain[0] in mod.global_instances:
                ld = self._attr_lock(
                    mod.pkg_rel, mod.global_instances[chain[0]], chain[1]
                )
                return ld.key if ld is not None else None
            # alias._LOCK on an imported (scanned) module
            if len(chain) == 2 and chain[0] in mod.mod_aliases:
                other = self._module_for(mod, mod.mod_aliases[chain[0]])
                if other is not None:
                    ld = other.global_locks.get(chain[1])
                    if ld is not None:
                        return ld.key
            # lock-ish but unresolvable: still a node, keyed by spelling,
            # so ordering through it is tracked rather than dropped
            if any("lock" in part.lower() for part in chain):
                return f"{mod.pkg_rel}:?{'.'.join(chain)}"
        return None

    def _lock_kind(self, key: str) -> str:
        for mod in self.modules.values():
            for ld in mod.global_locks.values():
                if ld.key == key:
                    return ld.kind
            for env in mod.scope_locks.values():
                for ld in env.values():
                    if ld.key == key:
                        return ld.kind
        for ld in self.attr_locks.values():
            if ld.key == key:
                return ld.kind
        return "lock"

    def _attr_lock(
        self, pkg_rel: str, cls: str, attr: str, _depth: int = 0
    ) -> _LockDef | None:
        got = self.attr_locks.get((pkg_rel, cls, attr))
        if got is not None or _depth > 5:
            return got
        entry = self._class_entry(pkg_rel, cls)
        if entry is None:
            return None
        crel, cdef = entry
        got = self.attr_locks.get((crel, cls, attr))
        if got is not None:
            return got
        for base in cdef.bases:
            chain = _attr_chain(base)
            if not chain:
                continue
            resolved = self._resolve_class_name(crel, chain[-1])
            if resolved is None:
                continue
            got = self._attr_lock(resolved[0], resolved[1], attr, _depth + 1)
            if got is not None:
                return got
        return None

    def _class_entry(
        self, pkg_rel: str, cls: str
    ) -> tuple[str, ast.ClassDef] | None:
        mod = self.modules.get(pkg_rel)
        if mod is not None and cls in mod.classes:
            return pkg_rel, mod.classes[cls]
        entries = self.class_index.get(cls) or []
        if len(entries) == 1:
            return entries[0]
        return None

    def _resolve_class_name(
        self, pkg_rel: str, name: str
    ) -> tuple[str, str] | None:
        mod = self.modules.get(pkg_rel)
        if mod is not None and name in mod.classes:
            return pkg_rel, name
        entries = self.class_index.get(name) or []
        if len(entries) == 1:
            return entries[0][0], name
        return None

    def _module_for(
        self, mod: _Module, parts: list[str]
    ) -> _Module | None:
        """Scanned module for an import spec (best-effort suffix match)."""
        if not parts:
            return None
        suffix = "/".join(parts) + ".py"
        candidates = [
            m for rel, m in self.modules.items()
            if rel == suffix or rel.endswith("/" + suffix)
        ]
        if len(candidates) == 1:
            return candidates[0]
        cur_dir = os.path.dirname(mod.pkg_rel)
        sibling = f"{cur_dir}/{parts[-1]}.py" if cur_dir else f"{parts[-1]}.py"
        return self.modules.get(sibling)

    # ------------------------------------------------------ statement walker
    def _walk_stmts(self, mod, info, cls, qual, stmts, held, scope_locks):
        for stmt in stmts:
            self._walk_stmt(mod, info, cls, qual, stmt, held, scope_locks)

    def _walk_stmt(self, mod, info, cls, qual, stmt, held, scope_locks):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._scan_function(
                mod, stmt, cls, f"{qual}.{stmt.name}", scope_locks
            )
            return
        if isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._scan_function(
                        mod, sub, stmt.name,
                        f"{qual}.{stmt.name}.{sub.name}", scope_locks,
                    )
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: list[str] = []
            # a '# tpc: lock(key)' on the with line aliases THE lock —
            # only meaningful for a single-item with; on a multi-item
            # with it would alias every item to one key, dropping locks
            # and fabricating self-edges
            ann = (
                self._ann(mod, stmt.lineno, _ANN_LOCK)
                if len(stmt.items) == 1 else None
            )
            for item in stmt.items:
                self._scan_exprs(mod, info, [item.context_expr], held)
                key = ann or self._resolve_lock_expr(
                    mod, cls, item.context_expr, scope_locks
                )
                if key is None:
                    continue
                # self-edges are recorded too: re-acquiring a PLAIN lock
                # is a self-deadlock (check_cycles filters rlock/family)
                for h in held + tuple(acquired):
                    info.order_edges.append((h, key, stmt.lineno))
                info.acquires.add(key)
                acquired.append(key)
            self._walk_stmts(
                mod, info, cls, qual, stmt.body, held + tuple(acquired),
                scope_locks,
            )
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._handle_assign(mod, info, cls, qual, stmt, held, scope_locks)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_exprs(mod, info, [stmt.iter], held)
            self._walk_stmts(mod, info, cls, qual, stmt.body, held, scope_locks)
            self._walk_stmts(
                mod, info, cls, qual, stmt.orelse, held, scope_locks
            )
            return
        if isinstance(stmt, ast.While):
            self._scan_exprs(mod, info, [stmt.test], held)
            self._walk_stmts(mod, info, cls, qual, stmt.body, held, scope_locks)
            self._walk_stmts(
                mod, info, cls, qual, stmt.orelse, held, scope_locks
            )
            return
        if isinstance(stmt, ast.If):
            self._scan_exprs(mod, info, [stmt.test], held)
            self._walk_stmts(mod, info, cls, qual, stmt.body, held, scope_locks)
            self._walk_stmts(
                mod, info, cls, qual, stmt.orelse, held, scope_locks
            )
            return
        if isinstance(stmt, ast.Try):
            self._walk_stmts(mod, info, cls, qual, stmt.body, held, scope_locks)
            for h in stmt.handlers:
                self._walk_stmts(
                    mod, info, cls, qual, h.body, held, scope_locks
                )
            self._walk_stmts(
                mod, info, cls, qual, stmt.orelse, held, scope_locks
            )
            self._walk_stmts(
                mod, info, cls, qual, stmt.finalbody, held, scope_locks
            )
            return
        # leaf / uncommon statements: scan expressions, recurse any nested
        # statement lists (match cases etc.) generically
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_exprs(mod, info, [child], held)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(mod, info, cls, qual, child, held, scope_locks)
            else:
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.stmt):
                        self._walk_stmt(
                            mod, info, cls, qual, sub, held, scope_locks
                        )
                    elif isinstance(sub, ast.expr):
                        self._scan_exprs(mod, info, [sub], held)

    def _handle_assign(self, mod, info, cls, qual, stmt, held, scope_locks):
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        value = getattr(stmt, "value", None)
        is_plain_assign = isinstance(stmt, ast.Assign)
        guard_ann = self._ann(mod, stmt.lineno, _ANN_GUARDED)
        eff_held = held + ((guard_ann,) if guard_ann else ())
        lock = (
            self._lock_call(value)
            if value is not None and is_plain_assign else None
        )
        family = (
            self._lock_family(value)
            if value is not None and is_plain_assign else None
        )
        for t in targets:
            if isinstance(t, ast.Name):
                if lock is not None:
                    explicit, kind = lock
                    key = explicit or self._ann(
                        mod, stmt.lineno, _ANN_LOCK
                    ) or f"{mod.pkg_rel}:{qual}.{t.id}"
                    scope_locks.setdefault(t.id, _LockDef(
                        key, kind, mod.repo_rel, stmt.lineno
                    ))
                elif family:
                    key = family[0] or self._ann(
                        mod, stmt.lineno, _ANN_LOCK
                    ) or f"{mod.pkg_rel}:{qual}.{t.id}[]"
                    scope_locks.setdefault(t.id, _LockDef(
                        key, "family", mod.repo_rel, stmt.lineno
                    ))
            elif isinstance(t, ast.Attribute) and isinstance(
                t.value, ast.Name
            ) and t.value.id == "self" and cls is not None:
                attr = t.attr
                akey = (mod.pkg_rel, cls, attr)
                if lock is not None:
                    explicit, kind = lock
                    key = explicit or self._ann(
                        mod, stmt.lineno, _ANN_LOCK
                    ) or f"{mod.pkg_rel}:{cls}.{attr}"
                    if akey not in self.attr_locks:
                        self.attr_locks[akey] = _LockDef(
                            key, kind, mod.repo_rel, stmt.lineno
                        )
                    if kind == "condition" and isinstance(
                        value, ast.Call
                    ) and value.args:
                        self._pending_cond_aliases.append(
                            (mod, cls, attr, value.args[0])
                        )
                elif family:
                    key = family[0] or self._ann(
                        mod, stmt.lineno, _ANN_LOCK
                    ) or f"{mod.pkg_rel}:{cls}.{attr}[]"
                    if akey not in self.attr_locks:
                        self.attr_locks[akey] = _LockDef(
                            key, "family", mod.repo_rel, stmt.lineno
                        )
                elif is_plain_assign and self._ann(
                    mod, stmt.lineno, _ANN_LOCK
                ):
                    if akey not in self.attr_locks:
                        self.attr_locks[akey] = _LockDef(
                            self._ann(mod, stmt.lineno, _ANN_LOCK), "rlock",
                            mod.repo_rel, stmt.lineno,
                        )
                else:
                    tname = self._ann(mod, stmt.lineno, _ANN_TYPE)
                    if tname is None and isinstance(value, ast.Call):
                        chain = _attr_chain(value.func)
                        if chain and chain[-1] in _FACTORY_RETURNS:
                            tname = _FACTORY_RETURNS[chain[-1]]
                        elif chain and chain[-1][:1].isupper():
                            tname = chain[-1]
                    if tname and akey not in self.attr_type_names:
                        self.attr_type_names[akey] = tname
                self._record_write(
                    info, qual, attr, stmt.lineno, eff_held, value,
                    subscript=False,
                )
            elif isinstance(t, ast.Subscript):
                base = t.value
                if isinstance(base, ast.Attribute) and isinstance(
                    base.value, ast.Name
                ) and base.value.id == "self":
                    self._record_write(
                        info, qual, base.attr, stmt.lineno, eff_held,
                        value, subscript=True,
                    )
                self._scan_exprs(mod, info, [t.slice, t.value], held)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    if isinstance(el, ast.Attribute) and isinstance(
                        el.value, ast.Name
                    ) and el.value.id == "self" and cls is not None:
                        self._record_write(
                            info, qual, el.attr, stmt.lineno, eff_held,
                            None, subscript=False,
                        )
        if value is not None:
            self._scan_exprs(mod, info, [value], held)

    def _lock_family(self, value: ast.expr) -> tuple[str | None, ...] | None:
        """``(explicit_key_or_None,)`` when ``value`` builds a dict whose
        values are locks; the member ``make_lock("…")`` literal — the
        canonical-key contract — wins over the derived attribute name."""
        if isinstance(value, ast.DictComp):
            lock = self._lock_call(value.value)
            return (lock[0],) if lock is not None else None
        if isinstance(value, ast.Dict):
            members = [self._lock_call(v) for v in value.values]
            if members and all(m is not None for m in members):
                keys = {m[0] for m in members}
                return (keys.pop(),) if len(keys) == 1 else (None,)
        return None

    def _fresh_mutable(self, value: ast.expr | None) -> bool:
        if value is None:
            return False
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            chain = _attr_chain(value.func)
            return bool(chain) and chain[-1] in _FRESH_MUTABLE_CTORS
        return False

    def _record_write(
        self, info, qual, field, lineno, held, value, subscript,
    ) -> None:
        if qual.rsplit(".", 1)[-1] in _CTOR_NAMES:
            return
        if field.endswith(_EXEMPT_FIELD_SUFFIXES):
            return
        if value is not None and (
            self._lock_call(value) or self._lock_family(value)
        ):
            return
        info.writes.append(
            _Write(field, lineno, frozenset(held), value, subscript)
        )
        # ---- TPC005 bookkeeping (statement order is walk order)
        pub = info.publishes.get(field)
        if not subscript and self._fresh_mutable(value):
            info.publishes[field] = {
                "line": lineno, "held": frozenset(held), "mutations": [],
            }
        elif subscript and pub is not None:
            pub["mutations"].append((lineno, frozenset(held)))

    # --------------------------------------------------------- expr scanning
    def _scan_exprs(self, mod, info, exprs, held) -> None:
        stack = [e for e in exprs if e is not None]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue  # lambda bodies run later, under unknown locks
            if isinstance(node, ast.Call):
                info.calls.append(_CallSite(node, held, node.lineno))
                # TPC005: mutator-method calls on a published field
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS and \
                        isinstance(f.value, ast.Attribute) and isinstance(
                            f.value.value, ast.Name
                        ) and f.value.value.id == "self":
                    pub = info.publishes.get(f.value.attr)
                    if pub is not None:
                        pub["mutations"].append(
                            (node.lineno, frozenset(held))
                        )
            stack.extend(
                c for c in ast.iter_child_nodes(node)
                if isinstance(c, ast.AST)
            )

    # ------------------------------------------------------- call resolution
    def _resolve_call_target(
        self, mod: _Module, cls: str | None, call: ast.Call,
        memo: _CallSite | None = None,
    ) -> tuple[str, str] | None:
        """(pkg_rel, qual) of the callee, when statically resolvable."""
        if memo is not None and memo.target is not _UNSET:
            return memo.target
        target = self._resolve_call_target_uncached(mod, cls, call)
        if memo is not None:
            memo.target = target
        return target

    def _resolve_call_target_uncached(
        self, mod: _Module, cls: str | None, call: ast.Call,
    ) -> tuple[str, str] | None:
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in mod.funcs:
                return (mod.pkg_rel, name)
            if name in mod.classes:
                return self._method_target(mod.pkg_rel, name, "__init__")
            nested = self.nested_defs.get(mod.pkg_rel, {}).get(name)
            if nested and len(nested) == 1:
                return (mod.pkg_rel, nested[0])
            if name in mod.from_names:
                base, orig = mod.from_names[name]
                other = self._module_for(mod, base)
                if other is not None and orig in other.funcs:
                    return (other.pkg_rel, orig)
                resolved = self._resolve_class_name(mod.pkg_rel, name)
                if resolved is not None:
                    return self._method_target(
                        resolved[0], resolved[1], "__init__"
                    )
            return None
        if isinstance(func, ast.Attribute):
            meth = func.attr
            base = func.value
            # REGISTRY.counter("x").inc() — the factory oracle
            if isinstance(base, ast.Call):
                bchain = _attr_chain(base.func)
                if bchain and bchain[-1] in _FACTORY_RETURNS:
                    resolved = self._resolve_class_name(
                        mod.pkg_rel, _FACTORY_RETURNS[bchain[-1]]
                    )
                    if resolved is not None:
                        return self._method_target(
                            resolved[0], resolved[1], meth
                        )
                return None
            chain = _attr_chain(base)
            if not chain:
                return None
            if chain[0] == "self" and cls is not None:
                if len(chain) == 1:
                    return self._method_target(mod.pkg_rel, cls, meth)
                if len(chain) == 2:
                    atype = self.attr_types.get((mod.pkg_rel, cls, chain[1]))
                    if atype is not None:
                        return self._method_target(atype[0], atype[1], meth)
                return None
            if len(chain) == 1:
                name = chain[0]
                if name in mod.global_instances:
                    return self._method_target(
                        mod.pkg_rel, mod.global_instances[name], meth
                    )
                if name in mod.mod_aliases:
                    other = self._module_for(mod, mod.mod_aliases[name])
                    if other is not None:
                        if meth in other.funcs:
                            return (other.pkg_rel, meth)
                        if meth in other.classes:
                            return self._method_target(
                                other.pkg_rel, meth, "__init__"
                            )
                return None
            if len(chain) == 2 and chain[0] in mod.mod_aliases:
                other = self._module_for(mod, mod.mod_aliases[chain[0]])
                if other is not None and chain[1] in other.global_instances:
                    return self._method_target(
                        other.pkg_rel, other.global_instances[chain[1]], meth
                    )
        return None

    def _method_target(
        self, pkg_rel: str, cls: str, meth: str, _depth: int = 0
    ) -> tuple[str, str] | None:
        if _depth > 5:
            return None
        entry = self._class_entry(pkg_rel, cls)
        if entry is None:
            return None
        crel, cdef = entry
        if (crel, f"{cls}.{meth}") in self.functions:
            return (crel, f"{cls}.{meth}")
        for b in cdef.bases:
            chain = _attr_chain(b)
            if not chain:
                continue
            resolved = self._resolve_class_name(crel, chain[-1])
            if resolved is not None:
                got = self._method_target(
                    resolved[0], resolved[1], meth, _depth + 1
                )
                if got is not None:
                    return got
        return None

    # ------------------------------------------- ACQ* + edges + rule passes
    def compute_acq_star(self) -> None:
        """Exact transitive-acquisition fixpoint over the resolved call
        graph: Tarjan emits SCCs children-first, so every member of an
        SCC gets the union of the component's direct acquisitions plus
        every already-computed callee closure — recursion (direct or
        mutual) loses nothing."""
        callees: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for fid, info in self.functions.items():
            mod = self.modules[fid[0]]
            out: set[tuple[str, str]] = set()
            for site in info.calls:
                target = self._resolve_call_target(
                    mod, info.cls, site.node, memo=site
                )
                if target is not None and target in self.functions:
                    out.add(target)
            callees[fid] = out
        for scc in _sccs(callees):
            closure: set[str] = set()
            for fid in scc:
                closure |= self.functions[fid].acquires
            for fid in scc:
                for callee in callees[fid]:
                    if callee not in scc:
                        # children-first SCC order: already computed
                        closure |= self.functions[callee].acq_star or set()
            for fid in scc:
                self.functions[fid].acq_star = closure

    def _acq_star(self, fid: tuple[str, str]) -> set[str]:
        info = self.functions.get(fid)
        if info is None or info.acq_star is None:
            return set()
        return info.acq_star

    def build_edges(self) -> None:
        for fid, info in sorted(self.functions.items()):
            mod = self.modules[fid[0]]
            for h, key, lineno in info.order_edges:
                self._add_edge(h, key, mod.repo_rel, lineno)
            for site in info.calls:
                if not site.held:
                    continue
                target = self._resolve_call_target(
                    mod, info.cls, site.node, memo=site
                )
                if target is None or target == fid:
                    continue
                for key in sorted(self._acq_star(target)):
                    for h in site.held:
                        self._add_edge(h, key, mod.repo_rel, site.line)

    def _add_edge(self, a: str, b: str, repo_rel: str, lineno: int) -> None:
        self.edges.setdefault((a, b), []).append((repo_rel, lineno))

    def check_cycles(self) -> None:
        graph: dict[str, set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for scc in _sccs(graph):
            nodes = sorted(scc)
            if len(scc) < 2:
                node = nodes[0]
                if node not in graph.get(node, ()):
                    continue  # no self loop
                if self._lock_kind(node) in ("rlock", "family"):
                    continue  # re-entrant / per-key siblings
                cyc_edges = [(node, node)]
            else:
                cyc_edges = sorted(
                    (a, b) for (a, b) in self.edges
                    if a in scc and b in scc and a != b
                )
            sites = [
                (e, *sorted(self.edges[e])[0]) for e in cyc_edges
                if e in self.edges
            ]
            if not sites:
                continue
            first = sites[0]
            mod = self._module_by_repo(first[1])
            detail = "; ".join(
                f"{a} -> {b} at {r}:{ln}" for (a, b), r, ln in sites
            )
            self._add_finding(
                "TPC001",
                "potential deadlock: lock-order cycle "
                f"{' -> '.join(nodes + [nodes[0]])} ({detail}) — two "
                "threads taking these locks in opposite orders will "
                "deadlock; impose one global order or move the inner "
                "acquisition outside the lock",
                mod, first[2],
            )

    def _module_by_repo(self, repo_rel: str) -> _Module:
        for m in self.modules.values():
            if m.repo_rel == repo_rel:
                return m
        return next(iter(self.modules.values()))

    def check_field_discipline(self) -> None:
        by_field: dict[tuple[str, str, str], list[tuple[_FuncInfo, _Write]]]
        by_field = {}
        for fid, info in self.functions.items():
            if info.cls is None:
                continue
            for w in info.writes:
                by_field.setdefault(
                    (fid[0], info.cls, w.field), []
                ).append((info, w))
        for (pkg_rel, cls, field), writes in sorted(by_field.items()):
            mod = self.modules[pkg_rel]
            locked = [(i, w) for i, w in writes if w.held]
            bare = [(i, w) for i, w in writes if not w.held]
            if not locked:
                continue  # no discipline established: TPL001 territory
            if bare:
                guards = sorted({k for _, w in locked for k in w.held})
                for info, w in sorted(bare, key=lambda p: p[1].line):
                    self._add_finding(
                        "TPC002",
                        f"{cls}.{field} is written under "
                        f"{'/'.join(guards)} elsewhere but bare here — a "
                        "concurrent reader/writer can observe a torn or "
                        "lost update; guard every write site (or mark a "
                        "caller-holds-the-lock helper with "
                        "'# tpc: guarded(<lock>)')",
                        mod, w.line, subject=f"{mod.repo_rel}:{w.line}",
                    )
                continue
            common: set[str] | None = None
            for _, w in locked:
                common = set(w.held) if common is None else (common & w.held)
            if common is not None and not common:
                guards = sorted({k for _, w in locked for k in w.held})
                _, w = min(locked, key=lambda p: p[1].line)
                self._add_finding(
                    "TPC003",
                    f"{cls}.{field} is written under DIFFERENT locks "
                    f"({', '.join(guards)}) at different sites — no "
                    "single lock serializes the field, so neither guard "
                    "guards; pick one lock for the field",
                    mod, w.line, subject=f"{mod.repo_rel}:{w.line}",
                )

    def check_foreign_calls(self) -> None:
        for fid, info in sorted(self.functions.items()):
            mod = self.modules[fid[0]]
            safe = (
                info.safe_names | mod.funcs | set(mod.classes)
                | set(mod.mod_aliases) | set(mod.from_names)
                | set(mod.global_locks) | set(mod.global_instances)
                | _BUILTINS
            )
            # enclosing-scope nested defs (closure helper siblings)
            parts = info.qual.split(".")
            for i in range(1, len(parts)):
                anc = self.functions.get((fid[0], ".".join(parts[:i])))
                if anc is not None:
                    safe |= anc.safe_names
            for site in info.calls:
                if not site.held:
                    continue
                func = site.node.func
                flagged = None
                if isinstance(func, ast.Name) and func.id not in safe:
                    flagged = f"{func.id}()"
                elif isinstance(func, ast.Attribute) and (
                    func.attr in _CALLBACK_ATTRS
                    or func.attr.startswith("on_")
                ):
                    chain = _attr_chain(func)
                    flagged = ".".join(chain or ["<expr>", func.attr]) + "()"
                if flagged is None:
                    continue
                self._add_finding(
                    "TPC004",
                    f"foreign callable {flagged} invoked while holding "
                    f"{'/'.join(sorted(site.held))} — user callbacks and "
                    "exposition sources can take arbitrary locks of their "
                    "own (the PR-8 render_prometheus ABBA); snapshot "
                    "under the lock, call outside it",
                    mod, site.line,
                )

    def check_publishes(self) -> None:
        for fid, info in sorted(self.functions.items()):
            if info.qual.rsplit(".", 1)[-1] in _CTOR_NAMES:
                continue
            mod = self.modules[fid[0]]
            for field, pub in sorted(info.publishes.items()):
                if not pub["mutations"]:
                    continue
                guards: frozenset = pub["held"]
                for _, mheld in pub["mutations"]:
                    guards = guards & mheld
                if guards:
                    continue  # publish + fill all under one common lock
                self._add_finding(
                    "TPC005",
                    f"non-atomic publish of self.{field}: a fresh "
                    "container is assigned to the shared attribute and "
                    "then filled in across later statements — a "
                    "concurrent reader sees it half-built (the PR-9 "
                    "cache bug); build a local, then publish with one "
                    "assignment",
                    mod, pub["line"],
                )

    # ---------------------------------------------------------------- output
    def finish(self) -> Report:
        locks: dict[str, dict[str, Any]] = {}
        for mod in self.modules.values():
            for ld in mod.global_locks.values():
                locks.setdefault(ld.key, {
                    "kind": ld.kind, "path": ld.repo_rel, "line": ld.line,
                })
            for env in mod.scope_locks.values():
                for ld in env.values():
                    locks.setdefault(ld.key, {
                        "kind": ld.kind, "path": ld.repo_rel,
                        "line": ld.line,
                    })
        for ld in self.attr_locks.values():
            locks.setdefault(ld.key, {
                "kind": ld.kind, "path": ld.repo_rel, "line": ld.line,
            })
        nodes = sorted(set(locks) | {n for e in self.edges for n in e})
        self.report.findings.sort(
            key=lambda f: (
                f.detail.get("path", ""), f.detail.get("line", 0), f.code,
            )
        )
        self.report.data["lockGraph"] = {
            "locks": {k: locks[k] for k in sorted(locks)},
            "nodes": nodes,
            "edges": [
                {
                    "from": a, "to": b,
                    "sites": [
                        f"{r}:{ln}" for r, ln in sorted(set(sites))[:4]
                    ],
                }
                for (a, b), sites in sorted(self.edges.items())
            ],
        }
        return self.report


def _sccs(graph: dict) -> list[set]:
    """Iterative Tarjan strongly-connected components, emitted
    children-first (reverse topological order). Nodes are any sortable
    hashables — lock keys for the order graph, function ids for the
    call graph."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    out: list[set] = []
    counter = [0]

    for root in sorted(graph):
        if root in index:
            continue
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        work = [(root, iter(sorted(graph.get(root, ()))))]
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc: set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                out.append(scc)
    return out


# ------------------------------------------------------------------ drivers
def analyze_sources(files: Iterable[tuple[str, str]]) -> Report:
    """Run the whole-repo analysis over ``(repo_rel_path, source)`` pairs
    (cross-module resolution needs every file at once, unlike the
    per-file linter)."""
    an = _Analyzer()
    for rel, source in files:
        an.add_source(rel, source)
    an.scan_all()            # walk A: register every lock/type definition
    an.apply_cond_aliases()
    an.index_nested()
    an.compute_lock_returns()
    an.resolve_types()
    an.rescan()              # walk B: authoritative, fully-resolved
    an.apply_cond_aliases()  # walk B may re-discover; idempotent
    an.compute_acq_star()
    an.build_edges()
    an.check_cycles()
    an.check_field_discipline()
    an.check_foreign_calls()
    an.check_publishes()
    return an.finish()


def _in_scope(rel: str) -> bool:
    rel = rel.replace(os.sep, "/")
    return any(seg in rel for seg in THREAD_CROSSED_SUBSYSTEMS)


def analyze_paths(
    paths: Iterable[str], root: str = ".", restrict: bool = True,
) -> Report:
    """Analyze every ``.py`` under ``paths``; with ``restrict`` (the
    default) only files on the thread-crossed subsystem list are read —
    single-threaded code has no lock order to get wrong."""
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git", "node_modules")
            ]
            files.extend(
                os.path.join(dirpath, f)
                for f in filenames if f.endswith(".py")
            )
    pairs: list[tuple[str, str]] = []
    for path in sorted(files):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        if restrict and not _in_scope(rel):
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                pairs.append((rel, fh.read()))
        except OSError:
            continue
    return analyze_sources(pairs)


def default_concurrency_paths() -> tuple[list[str], str]:
    """(paths, root) mirroring ``cli.default_lint_paths``: a repo
    checkout analyzes ``transmogrifai_tpu/``, an installed package
    analyzes itself with repo-style relative paths."""
    if os.path.isdir("transmogrifai_tpu"):
        return ["transmogrifai_tpu"], "."
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [pkg], os.path.dirname(pkg)


@functools.lru_cache(maxsize=1)
def package_summary() -> dict[str, Any]:
    """Compact cached summary for ``summary_json()["analysis"]`` — the
    TPC family riding beside the TPA/TPX reports. Cached per process:
    the package's source does not change under a running train."""
    paths, root = default_concurrency_paths()
    report = analyze_paths(paths, root=root)
    codes: dict[str, int] = {}
    for f in report.findings:
        codes[f.code] = codes.get(f.code, 0) + 1
    graph = report.data.get("lockGraph", {})
    return {
        "findings": len(report.findings),
        "codes": codes,
        "locks": len(graph.get("locks", {})),
        "edges": len(graph.get("edges", [])),
    }
