"""transmogrifai_tpu — a TPU-native AutoML framework for structured data.

A ground-up JAX/XLA rebuild of the capabilities of TransmogrifAI (Scala/Spark
reference at /root/reference): typed features, a lineage-traced feature DAG,
type-directed automated feature engineering, automated feature validation and
model selection with cross-validation, evaluators, insights, persistence, and
local scoring — with the numeric plane compiled to XLA and sharded over TPU
meshes instead of Spark executors.
"""
from . import types  # noqa: F401
from .dataset import Dataset  # noqa: F401
from .features import Feature, FeatureBuilder, from_dataset  # noqa: F401
from . import dsl  # noqa: F401  — attaches the rich-feature vocabulary

__version__ = "0.1.0"
