"""ctypes bindings for the C++ host kernels (native/tptpu_native.cpp).

The library is built on demand with ``make`` (g++ is in the image) and
cached next to the sources. Every entry point has a pure-Python/numpy
fallback, so the package works without a toolchain — `available()` reports
which path is active.

Covers the reference's host hot loops (SURVEY.md §2.5): MurmurHash3 feature
hashing (OPCollectionHashingVectorizer) and CSV field→double parsing
(readers module).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libtptpu.so")


def _load() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("TPTPU_DISABLE_NATIVE"):
            return None
        try:
            src = os.path.join(_NATIVE_DIR, "tptpu_native.cpp")
            stale = (
                os.path.exists(_SO_PATH)
                and os.path.exists(src)
                and os.path.getmtime(_SO_PATH) < os.path.getmtime(src)
            )
            if not os.path.exists(_SO_PATH) or stale:
                if not os.path.isdir(_NATIVE_DIR):
                    return None
                # rebuild on stale too: loading an older .so against newer
                # bindings is an in-place ABI mismatch (silently wrong
                # columns, not an error)
                subprocess.run(
                    ["make", "-s", "-B"] if stale else ["make", "-s"],
                    cwd=_NATIVE_DIR, check=True,
                    capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(_SO_PATH)
        except Exception as e:  # toolchain or load failure -> fallback
            log.info("native library unavailable (%s); using numpy fallbacks", e)
            return None
        lib.tp_murmur3_batch.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C"),
            ctypes.c_int64, ctypes.c_uint32,
            np.ctypeslib.ndpointer(np.uint32, flags="C"),
        ]
        lib.tp_murmur3_scatter.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C"),
            np.ctypeslib.ndpointer(np.int64, flags="C"),
            ctypes.c_int64, ctypes.c_uint32, ctypes.c_int64, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float32, flags="C"),
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.tp_parse_doubles.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C"),
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.float64, flags="C"),
            np.ctypeslib.ndpointer(np.uint8, flags="C"),
        ]
        if hasattr(lib, "tp_clean_tokenstats"):
            lib.tp_clean_tokenstats.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.uint8, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64,
            ]
        if hasattr(lib, "tp_count_tokens"):
            lib.tp_count_tokens.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64, ctypes.c_int64,
            ]
            lib.tp_count_tokens.restype = ctypes.c_int64
        if hasattr(lib, "tp_tokenize_hash_coo"):
            lib.tp_tokenize_hash_coo.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64, ctypes.c_uint32, ctypes.c_int64,
                ctypes.c_int, ctypes.c_int, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                ctypes.c_int64,
            ]
            lib.tp_tokenize_hash_coo.restype = ctypes.c_int64
        if hasattr(lib, "tp_tokenize_hash_scatter"):
            lib.tp_tokenize_hash_scatter.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64, ctypes.c_uint32, ctypes.c_int64,
                ctypes.c_int, ctypes.c_int, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.float32, flags="C"),
                ctypes.c_int64, ctypes.c_int64,
            ]
        if hasattr(lib, "tp_abi_version"):
            lib.tp_abi_version.restype = ctypes.c_int64
        if hasattr(lib, "tp_intern_tokens"):
            lib.tp_intern_tokens.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                np.ctypeslib.ndpointer(np.uint8, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64,
            ]
            lib.tp_intern_tokens.restype = ctypes.c_int64
        if hasattr(lib, "tp_intern_values"):
            lib.tp_intern_values.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
            ]
            lib.tp_intern_values.restype = ctypes.c_int64
        if hasattr(lib, "tp_text_valuestats"):
            lib.tp_text_valuestats.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.uint8, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
            ]
            lib.tp_text_valuestats.restype = ctypes.c_int64
        if hasattr(lib, "tp_code_bincount"):
            lib.tp_code_bincount.argtypes = [
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                np.ctypeslib.ndpointer(np.int64, flags="C"),
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                ctypes.c_int,
                np.ctypeslib.ndpointer(np.float32, flags="C"),
                ctypes.c_int64, ctypes.c_int64,
            ]
        if hasattr(lib, "tp_tree_predict_sum"):
            lib.tp_tree_predict_sum.argtypes = [
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                ctypes.c_int64, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                np.ctypeslib.ndpointer(np.float32, flags="C"),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.float32, flags="C"),
            ]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


#: ABI stamp the bindings below were written against (tp_abi_version in
#: native/tptpu_native.cpp). A loaded library reporting less predates some
#: kernel — affected entry points fail SOFT (numpy fallback + one warning +
#: a featurizeStats counter) instead of AttributeError at transform time.
ABI_VERSION = 3

_STALE_WARNED: set[str] = set()


def abi_version() -> int:
    """ABI stamp of the loaded library (0 = missing/unstamped)."""
    lib = _load()
    if lib is None or not hasattr(lib, "tp_abi_version"):
        return 0
    return int(lib.tp_abi_version())


def _require(symbol: str):
    """The loaded library, or None when it lacks ``symbol`` (stale cached
    build) — recorded once per symbol in the featurize ledger so operators
    can see a degraded kernel set instead of silently slow transforms."""
    lib = _load()
    if lib is None:
        return None
    if hasattr(lib, symbol):
        return lib
    if symbol not in _STALE_WARNED:
        _STALE_WARNED.add(symbol)
        log.warning(
            "libtptpu.so predates kernel %s (abi %d < %d): numpy fallback "
            "active — rebuild with `make -B` in native/",
            symbol, abi_version(), ABI_VERSION,
        )
        from .featurize import stats as _fstats

        _fstats.stats().count_stale_library(symbol)
    return None


def _concat(values: list) -> tuple[bytes, np.ndarray]:
    """Concatenate strings into one UTF-8 buffer + offsets[n+1].

    ASCII fast path: one join + one bulk isascii + one encode, with byte
    offsets from character lengths (== byte lengths for ASCII) — the
    per-item encode loop only runs for non-ASCII/mixed input."""
    n = len(values)
    try:
        joined = "".join(values)
    except TypeError:
        joined = None  # None/non-str present — per-item loop below
    if joined is not None and joined.isascii():
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(np.fromiter(map(len, values), np.int64, n), out=offsets[1:])
        return joined.encode("ascii"), offsets
    encoded = [v.encode("utf-8") if isinstance(v, str) else b"" for v in values]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    return b"".join(encoded), offsets


def _concat_tokens(values: list) -> tuple[bytes, np.ndarray] | None:
    """ASCII fast concat for the TOKENIZING consumers (tp_tokenize_* /
    tp_clean_*): join once with a '\\x00' separator and compute offsets
    from lengths — one C-level join + one encode instead of a per-row
    encode/append loop. Item slices then carry a trailing separator byte,
    which those consumers treat as an ordinary delimiter (non-alnum), so
    tokenization is unchanged. NOT valid for whole-string hashing
    (tp_murmur3_*), which hashes slices verbatim.

    Returns None when any item is non-ASCII (one bulk check) — the C
    tokenizers are byte-exact for ASCII only, so the caller must fall back
    to the Unicode-exact Python path for those rows."""
    n = len(values)
    if n == 0:
        return b"", np.zeros(1, dtype=np.int64)
    joined = "\x00".join(values)
    if not joined.isascii():
        return None
    lens = np.fromiter(map(len, values), np.int64, n)
    offsets = np.empty(n + 1, dtype=np.int64)
    offsets[0] = 0
    np.cumsum(lens + 1, out=offsets[1:])
    offsets[n] -= 1  # no trailing separator after the last item
    return joined.encode("ascii"), offsets


def murmur3_batch(values: list, seed: int = 42) -> np.ndarray:
    """uint32 murmur3 of each string (None → hash of empty)."""
    lib = _load()
    n = len(values)
    if lib is not None:
        buf, offsets = _concat(values)
        out = np.empty(n, dtype=np.uint32)
        lib.tp_murmur3_batch(buf, offsets, n, seed & 0xFFFFFFFF, out)
        return out
    from .utils.text import murmur3_32

    return np.array(
        [murmur3_32(v if isinstance(v, str) else "", seed) for v in values],
        dtype=np.uint32,
    )


def murmur3_scatter(
    tokens: list,
    rows: np.ndarray,
    num_rows: int,
    num_buckets: int,
    seed: int = 42,
    binary: bool = False,
    out: np.ndarray | None = None,
    col_offset: int = 0,
) -> np.ndarray:
    """Hash tokens → bucket counts in one pass: out[rows[i], h(tokens[i])] += 1.
    ``out`` may be a wider matrix; ``col_offset`` places the bucket block."""
    if out is None:
        out = np.zeros((num_rows, num_buckets), dtype=np.float32)
    lib = _load()
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    if (
        lib is not None
        # ABI guard: tp_murmur3_scatter gained col_offset in the same
        # commit as tp_tokenize_hash_coo — a stale cached .so without that
        # symbol has the old 9-arg scatter, which would silently ignore
        # the offset and corrupt the first block of a shared buffer
        and hasattr(lib, "tp_tokenize_hash_coo")
        and out.flags["C_CONTIGUOUS"]
        and out.dtype == np.float32
    ):
        buf, offsets = _concat(tokens)
        lib.tp_murmur3_scatter(
            buf, offsets, rows, len(tokens), seed & 0xFFFFFFFF,
            num_buckets, 1 if binary else 0, out, out.shape[1],
            col_offset,
        )
        return out
    _scatter_py(tokens, rows, num_buckets, seed, binary, out, col_offset)
    return out


def tokenize_hash_scatter(
    texts: list,
    rows: np.ndarray,
    num_buckets: int,
    out: np.ndarray,
    seed: int = 42,
    binary: bool = False,
    to_lowercase: bool = True,
    min_token_length: int = 1,
    prefix: str = "",
    col_offset: int = 0,
) -> bool:
    """Fused tokenize+hash+scatter for ASCII row strings (the
    SmartTextVectorizer hot loop in one native pass). Returns False when the
    native path can't take it (library missing, non-float32/C output) — the
    caller must then run the Python tokenize fallback. Callers route rows
    with non-ASCII content to the fallback themselves: the C tokenizer is
    exact only for ASCII (utils/text.py _TOKEN_RE semantics)."""
    lib = _load()
    if (
        lib is None
        or not hasattr(lib, "tp_tokenize_hash_scatter")
        or not out.flags["C_CONTIGUOUS"]
        or out.dtype != np.float32
    ):
        return False
    ct = _concat_tokens(texts)
    if ct is None:  # non-ASCII rows present — caller partitions
        return False
    buf, offsets = ct
    pref = prefix.encode("ascii")
    lib.tp_tokenize_hash_scatter(
        buf, offsets, np.ascontiguousarray(rows, dtype=np.int64),
        len(texts), seed & 0xFFFFFFFF, num_buckets,
        1 if binary else 0, 1 if to_lowercase else 0, min_token_length,
        pref, len(pref), out, out.shape[1], col_offset,
    )
    return True


def tokenize_hash_coo(
    texts: list,
    rows: np.ndarray,
    num_buckets: int,
    seed: int = 42,
    binary: bool = False,
    to_lowercase: bool = True,
    min_token_length: int = 1,
    prefix: str = "",
) -> tuple[np.ndarray, np.ndarray] | None:
    """Fused tokenize+hash emitting COO (row, bucket) pairs — the sparse
    SmartText hot path. Dense hash planes are ~99.8% zeros at 512 buckets,
    and on low-memory-bandwidth hosts the dense output's page faults
    dominate the whole text plane; pairs are ~50× smaller. Returns
    (rows int32[nnz], cols int32[nnz]) with implicit value 1.0 per pair
    (duplicates accumulate under add-combine; binary mode pre-dedupes), or
    None when the native path can't take it (library missing or non-ASCII
    rows present — caller falls back)."""
    lib = _load()
    if (
        lib is None
        or not hasattr(lib, "tp_tokenize_hash_coo")
        or not hasattr(lib, "tp_count_tokens")
    ):
        return None
    ct = _concat_tokens(texts)
    if ct is None:
        return None
    buf, offsets = ct
    # worst-case token count instead of a counting prepass: every token
    # needs at least one word char plus a delimiter, so the fill pass can
    # never emit more than (bytes + strings) / 2 + 1 pairs — sizing the
    # output this way saves a full scan of the buffer
    cap = (len(buf) + len(texts)) // 2 + 1
    out_rows = np.empty(max(cap, 1), dtype=np.int32)
    out_cols = np.empty(max(cap, 1), dtype=np.int32)
    pref = prefix.encode("ascii")
    n = int(
        lib.tp_tokenize_hash_coo(
            buf, offsets, np.ascontiguousarray(rows, dtype=np.int64),
            len(texts), seed & 0xFFFFFFFF, num_buckets,
            1 if binary else 0, 1 if to_lowercase else 0, min_token_length,
            pref, len(pref), out_rows, out_cols, cap,
        )
    )
    # copy out of the worst-case-sized scratch: a view would pin the
    # whole allocation for the lifetime of the sparse block
    return out_rows[:n].copy(), out_cols[:n].copy()


def clean_tokenstats(texts: list) -> tuple[list, np.ndarray] | None:
    """Batch TextUtils.cleanString + token-length histogram over ASCII
    strings in one native pass. Returns (cleaned_strings, length_hist) or
    None when the native path is unavailable (caller falls back to the
    per-row Python clean/tokenize)."""
    lib = _load()
    if lib is None or not hasattr(lib, "tp_clean_tokenstats"):
        return None
    ct = _concat_tokens(texts)
    if ct is None:  # non-ASCII rows present — caller partitions
        return None
    buf, offsets = ct
    out_buf = np.zeros(max(len(buf), 1), dtype=np.uint8)
    out_offsets = np.zeros(len(texts) + 1, dtype=np.int64)
    hist = np.zeros(256, dtype=np.int64)
    lib.tp_clean_tokenstats(
        buf, offsets, len(texts), out_buf, out_offsets, hist, hist.shape[0]
    )
    # decode the cleaned buffer ONCE; per-row values are slices of it
    raw = out_buf[: out_offsets[-1]].tobytes().decode("ascii")
    cleaned = [
        raw[out_offsets[i]:out_offsets[i + 1]]
        for i in range(len(texts))
    ]
    return cleaned, hist


def text_stats_pass(
    texts: list, cap: int, clean_text: bool
) -> tuple[np.ndarray, list[str], np.ndarray] | None:
    """The SmartText fit hot loop in ONE native pass
    (``tp_text_valuestats``): clean + token-length histogram + capped
    value counts without ever materializing a per-row Python string.
    Returns ``(length_hist, uniques, counts)`` where ``uniques`` holds
    only the FIRST ``cap + 1`` distinct (cleaned) values in row order
    with their FULL counts (the capped-Counter monoid of TextStats), or
    None when the native path can't take the column (library
    missing/stale or non-ASCII rows)."""
    lib = _require("tp_text_valuestats")
    if lib is None:
        return None
    ct = _concat_tokens(texts)
    if ct is None:  # non-ASCII rows present — caller partitions
        return None
    buf, offsets = ct
    n = len(texts)
    hist = np.zeros(256, dtype=np.int64)
    uniq_buf = np.empty(max(len(buf), 1), dtype=np.uint8)
    uniq_offsets = np.zeros(n + 1, dtype=np.int64)
    counts = np.empty(n, dtype=np.int64)
    n_uniq = int(
        lib.tp_text_valuestats(
            buf, offsets, n, hist, hist.shape[0],
            0 if clean_text else 1, 1,
            uniq_buf, uniq_offsets, counts,
        )
    )
    k = min(n_uniq, cap + 1)
    raw = uniq_buf[: uniq_offsets[k]].tobytes().decode("ascii")
    uniques = [
        raw[uniq_offsets[u]:uniq_offsets[u + 1]] for u in range(k)
    ]
    return hist, uniques, counts[:k]


def _scatter_py(tokens, rows, num_buckets, seed, binary, out, col_offset):
    h = murmur3_batch(tokens, seed)
    j = (h % np.uint32(num_buckets)).astype(np.int64) + col_offset
    if binary:
        out[rows, j] = 1.0
    else:
        np.add.at(out, (rows, j), 1.0)


def validate_tree_stack(sf: np.ndarray, lv: np.ndarray, num_f: int) -> None:
    """Bounds-check a host tree stack against a binned plane width BEFORE
    any pointer reaches C: the kernel gathers binned[i, sf[...]] and
    lv[t, node << (depth - eff)] unchecked, so a malformed stack (corrupt
    manifest, truncated arrays) would read out of bounds instead of
    raising like the numpy traversal does. Raises IndexError."""
    depth = sf.shape[1]
    if sf.size and int(sf.max()) >= num_f:
        raise IndexError(
            f"tree_predict_sum: split feature index {int(sf.max())} out of "
            f"bounds for {num_f} binned feature(s)"
        )
    if lv.ndim != 2 or lv.shape[1] != (1 << depth):
        raise IndexError(
            f"tree_predict_sum: leaf table width {lv.shape[1:]} does not "
            f"match depth {depth} (expected {1 << depth})"
        )


def tree_predict_sum(
    binned: np.ndarray, sf: np.ndarray, sb: np.ndarray, lv: np.ndarray,
    prevalidated: bool = False,
) -> np.ndarray | None:
    """Per-row sum of leaf values across R stacked trees (serving predict
    hot loop — see trees._traverse_host for the layout and semantics).
    Returns float32 [n], or None when the library is unavailable (caller
    falls back to the numpy traversal).

    ``prevalidated=True`` skips the per-call stack bounds check: the
    serving path validates ONCE at model-load time (_PreparedStack) and
    keeps only an O(1) plane-width guard in the hot loop. Set env
    ``TPTPU_NATIVE_VALIDATE=1`` to force the full check back on every
    call (belt-and-braces when debugging a suspect manifest)."""
    lib = _load()
    if lib is None or not hasattr(lib, "tp_tree_predict_sum"):
        return None
    binned = np.ascontiguousarray(binned, dtype=np.int32)
    sf = np.ascontiguousarray(sf, dtype=np.int32)
    sb = np.ascontiguousarray(sb, dtype=np.int32)
    lv = np.ascontiguousarray(lv, dtype=np.float32)
    n, num_f = binned.shape
    r, depth, width = sf.shape
    if (
        not prevalidated
        or os.environ.get("TPTPU_NATIVE_VALIDATE", "0") == "1"
    ):
        validate_tree_stack(sf, lv, num_f)
    out = np.empty(n, dtype=np.float32)
    lib.tp_tree_predict_sum(
        binned, n, num_f, sf, sb, lv, r, depth, width, lv.shape[1], out,
    )
    return out


def intern_tokens(
    texts: list,
    to_lowercase: bool = True,
    min_token_length: int = 1,
) -> tuple[np.ndarray, np.ndarray, list[str]] | None:
    """Tokenize + intern ASCII row strings in ONE native pass: returns
    ``(codes int32[T], row_offsets int64[len(texts)+1], vocab)`` where
    ``vocab`` holds the unique (lowercased) tokens in first-occurrence
    order — the only per-token Python strings ever built. None when the
    native path can't take it (library missing/stale or non-ASCII rows) —
    the caller partitions or falls back to the dict interner."""
    lib = _require("tp_intern_tokens")
    if lib is None:
        return None
    ct = _concat_tokens(texts)
    if ct is None:  # non-ASCII rows present — caller partitions
        return None
    buf, offsets = ct
    if not hasattr(lib, "tp_count_tokens"):
        return None
    cap = int(lib.tp_count_tokens(buf, offsets, len(texts), min_token_length))
    codes = np.empty(max(cap, 1), dtype=np.int32)
    row_offsets = np.zeros(len(texts) + 1, dtype=np.int64)
    uniq_buf = np.empty(max(len(buf), 1), dtype=np.uint8)
    uniq_offsets = np.zeros(max(cap, 1) + 1, dtype=np.int64)
    n_uniq = int(
        lib.tp_intern_tokens(
            buf, offsets, len(texts), 1 if to_lowercase else 0,
            min_token_length, codes, row_offsets, uniq_buf, uniq_offsets,
            cap,
        )
    )
    raw = uniq_buf[: uniq_offsets[n_uniq]].tobytes().decode("ascii")
    vocab = [
        raw[uniq_offsets[u]:uniq_offsets[u + 1]] for u in range(n_uniq)
    ]
    return codes[: row_offsets[-1]], row_offsets, vocab


def intern_values(
    values: list,
) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Intern whole strings (byte-exact, any unicode): returns
    ``(codes int32[n], first_rows int64[U], counts int64[U])`` — unique
    value u IS ``values[first_rows[u]]``, so no string is ever rebuilt.
    None when the native library is missing/stale OR any value is not a
    str (interning is byte-keyed; a str() coercion would collapse e.g. 7
    with "7") — callers fall back to the raw-keyed dict interner, which
    has the exact historical per-value semantics. None entries are the
    caller's to map out first."""
    lib = _require("tp_intern_values")
    if lib is None:
        return None
    n = len(values)
    if n == 0:
        z64 = np.zeros(0, dtype=np.int64)
        return np.zeros(0, dtype=np.int32), z64, z64
    try:
        joined = "".join(values)
    except TypeError:
        return None  # non-str values present — dict fallback keys raw
    offsets = np.zeros(n + 1, dtype=np.int64)
    if joined.isascii():
        np.cumsum(np.fromiter(map(len, values), np.int64, n), out=offsets[1:])
        buf = joined.encode("ascii")
    else:
        encoded = [v.encode("utf-8") for v in values]
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        buf = b"".join(encoded)
    codes = np.empty(n, dtype=np.int32)
    first_rows = np.empty(n, dtype=np.int64)
    counts = np.empty(n, dtype=np.int64)
    n_uniq = int(lib.tp_intern_values(buf, offsets, n, codes, first_rows, counts))
    return codes, first_rows[:n_uniq], counts[:n_uniq]


def code_bincount(
    codes: np.ndarray,
    row_offsets: np.ndarray,
    code_to_col: np.ndarray,
    out: np.ndarray,
    binary: bool = False,
    col_offset: int = 0,
) -> np.ndarray:
    """Scatter interned token codes into per-row bucket counts:
    ``out[r, col_offset + code_to_col[codes[t]]] (+)= 1`` for row r's
    tokens, skipping negative columns. ``out`` may be a wider float32
    matrix (strided block write). Numpy fallback is exact."""
    codes = np.ascontiguousarray(codes, dtype=np.int32)
    row_offsets = np.ascontiguousarray(row_offsets, dtype=np.int64)
    code_to_col = np.ascontiguousarray(code_to_col, dtype=np.int32)
    n_rows = len(row_offsets) - 1
    lib = _require("tp_code_bincount")
    if (
        lib is not None
        and out.flags["C_CONTIGUOUS"]
        and out.dtype == np.float32
    ):
        lib.tp_code_bincount(
            codes, row_offsets, n_rows, code_to_col, 1 if binary else 0,
            out, out.shape[1], col_offset,
        )
        return out
    from .featurize import stats as _fstats

    _fstats.stats().count_fallback("code_bincount")
    cols = code_to_col[codes].astype(np.int64)
    rows = np.repeat(
        np.arange(n_rows, dtype=np.int64), np.diff(row_offsets)
    )
    keep = cols >= 0
    rows, cols = rows[keep], cols[keep] + col_offset
    if binary:
        out[rows, cols] = 1.0
    else:
        np.add.at(out, (rows, cols), 1.0)
    return out


def parse_doubles(values: list) -> tuple[np.ndarray, np.ndarray]:
    """Batch str→double: (values float64[n], mask bool[n])."""
    lib = _load()
    n = len(values)
    if lib is not None:
        buf, offsets = _concat(values)
        out = np.empty(n, dtype=np.float64)
        mask = np.empty(n, dtype=np.uint8)
        lib.tp_parse_doubles(buf, offsets, n, out, mask)
        return out, mask.astype(bool)
    out = np.zeros(n, dtype=np.float64)
    mask = np.zeros(n, dtype=bool)
    for i, v in enumerate(values):
        if v is None:
            continue
        s = v.strip() if isinstance(v, str) else v
        if s == "" or s is None:
            continue
        try:
            out[i] = float(s)
            mask[i] = True
        except (TypeError, ValueError):
            pass
    return out, mask
