"""CSV reading with schema auto-inference.

Reference: readers/.../CSVAutoReaders.scala (header+schema inference) and
utils/.../io/csv/CSVToAvro.scala. Inference rules: a column whose non-empty
values all parse as integers becomes Integral, as floats becomes Real, as
booleans becomes Binary; otherwise Text. Empty strings are missing.
"""
from __future__ import annotations

import csv as _csv
from typing import Any, Iterable, Sequence

from .. import types as T
from ..dataset import Dataset
from ..types.columns import column_from_values
from .core import DataReader

_BOOL_TOKENS = {"true", "false", "t", "f", "yes", "no"}


def _infer_type(values: Iterable[str | None]) -> type:
    saw_any = False
    is_bool = is_int = is_float = True
    for v in values:
        if v is None or v == "":
            continue
        saw_any = True
        s = v.strip()
        if is_bool and s.lower() not in _BOOL_TOKENS:
            is_bool = False
        if is_int:
            try:
                int(s)
            except ValueError:
                is_int = False
        if not is_int and is_float:
            try:
                float(s)
            except ValueError:
                is_float = False
        if not (is_bool or is_int or is_float):
            return T.Text
    if not saw_any:
        return T.Text
    if is_bool:
        return T.Binary
    if is_int:
        return T.Integral
    if is_float:
        return T.Real
    return T.Text


def _read_table(
    path: str,
    headers: Sequence[str] | None,
    has_header: bool | None,
) -> tuple[list[str], list[list[str]]]:
    """Shared CSV parse: (column names, body rows). Missing trailing cells in
    short rows are treated as empty."""
    with open(path, newline="", encoding="utf-8") as fh:
        # physically blank lines are ignored (Spark CSV semantics; a
        # trailing newline must not surface as an all-missing row) — but
        # ',,,' all-empty RECORDS are kept
        rows = [r for r in _csv.reader(fh) if r]
    if not rows:
        return [], []
    if has_header is None:
        has_header = headers is None
    if has_header:
        return rows[0], rows[1:]
    if headers is None:
        raise ValueError("headers required when the file has no header row")
    return list(headers), rows


def _cell(row: list[str], j: int) -> str | None:
    return (row[j] if j < len(row) else "") or None


def infer_csv_dataset(
    path: str,
    headers: Sequence[str] | None = None,
    has_header: bool | None = None,
    type_overrides: dict[str, type] | None = None,
) -> Dataset:
    """Read a CSV into a typed columnar Dataset with inferred feature types."""
    names, body = _read_table(path, headers, has_header)
    if not names:
        return Dataset({}, 0)
    columns = {}
    overrides = type_overrides or {}
    for j, name in enumerate(names):
        vals = [_cell(r, j) for r in body]
        ftype = overrides.get(name)
        if ftype is None:
            ftype = _infer_type(vals)
            if ftype is T.Real:
                # hot path: batch field->double parse in native code. Only
                # for INFERRED Real columns (inference guarantees
                # parseability); user overrides keep the strict raising path.
                from ..native import parse_doubles
                from ..types.columns import NumericColumn

                values, mask = parse_doubles(vals)
                # strtod rejects a few strings Python float() accepts
                # (unicode digits, exotic whitespace): re-parse only the
                # (typically zero) fields the native path marked missing
                import numpy as _np

                for i in _np.nonzero(~_np.asarray(mask))[0]:
                    v = vals[i]
                    if v is not None and v.strip():
                        try:
                            values[i] = float(v)
                            mask[i] = True
                        except ValueError:
                            pass
                columns[name] = NumericColumn(T.Real, values, mask)
                continue
        columns[name] = column_from_values(ftype, vals)
    return Dataset.of(columns)


def read_csv_auto(path: str, **kwargs: Any) -> Dataset:
    return infer_csv_dataset(path, **kwargs)


class CsvReader(DataReader):
    """Record reader yielding dict rows (DataReaders.Simple.csv,
    DataReaders.scala:49)."""

    def __init__(
        self,
        path: str,
        headers: Sequence[str] | None = None,
        has_header: bool | None = None,
        key_fn: Any = None,
    ):
        super().__init__(key_fn)
        self.path = path
        self.headers = headers
        self.has_header = has_header

    def read_records(self) -> Iterable[dict[str, str | None]]:
        names, body = _read_table(self.path, self.headers, self.has_header)
        return [{n: _cell(r, j) for j, n in enumerate(names)} for r in body]
