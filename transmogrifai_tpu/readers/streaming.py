"""Streaming reader — micro-batch scoring source.

Reference: readers/.../StreamingReaders.scala:50-70 (`StreamingReaders.Simple
.avro`) feeding OpWorkflowRunner.streamingScore (OpWorkflowRunner.scala:232).
The Spark Streaming DStream becomes a plain iterator of record batches; the
runner scores each batch with the already-jitted score function (the TPU
path: host loop feeding a compiled program, SURVEY.md §2.6 "async scoring").
"""
from __future__ import annotations

import logging
import os
from typing import Any, Callable, Iterable, Iterator, Sequence

from ..dataset import Dataset
from ..features.feature import Feature
from ..resilience.retry import TransientError
from ..telemetry import metrics as _tmetrics
from .core import SimpleReader

log = logging.getLogger(__name__)


class StreamExhausted(TransientError):
    """A chunk fetch burned its whole retry budget on transient errors:
    names the chunk, the attempts spent, and the last underlying error
    (docs/faq.md). Subclasses ``TransientError`` on purpose — the
    file-stream defer/drop path and the out-of-core ingest quarantine
    both treat it as the bounded transient failure it is, while typed
    callers can read ``chunk``/``attempts``/``last_error`` instead of
    parsing a log line. Fatal errors (bad format, permissions) still
    raise as themselves: retries never ran, so nothing was exhausted."""

    def __init__(self, chunk: str, attempts: int, last_error: BaseException):
        super().__init__(
            f"stream chunk {chunk!r} exhausted after {attempts} "
            f"attempts: {type(last_error).__name__}: {last_error}"
        )
        self.chunk = chunk
        self.attempts = int(attempts)
        self.last_error = last_error


class _ChunkFetchStats(_tmetrics.LedgerCore):
    """Process-wide chunk-fetch ledger: every ``_fetch_chunk`` attempt
    count lands here (the RetryPolicy returns how many attempts one fetch
    took, but until now that number only reached a log line). Snapshotted
    into the ``resilience`` ledger source (resilience/distributed.py), so
    the counters reach ``score_fn.metadata()`` and the Prometheus
    exposition like every other resilience counter."""

    KEYS = (
        "streamChunkFetches",     # successful fetches (post-retry)
        "streamChunkRetries",     # fetches that needed more than 1 attempt
        "streamChunkAttempts",    # total attempts across all fetches
        "streamChunkExhausted",   # fetches whose retry budget ran out
    )

    def __init__(self) -> None:
        super().__init__(self.KEYS)

    def record_fetch(self, attempts: int) -> None:
        with self._lock:
            self._counts["streamChunkFetches"] += 1
            self._counts["streamChunkAttempts"] += int(attempts)
            if attempts > 1:
                self._counts["streamChunkRetries"] += 1

    def record_exhausted(self, attempts: int = 0) -> None:
        with self._lock:
            self._counts["streamChunkExhausted"] += 1
            self._counts["streamChunkAttempts"] += int(attempts)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset_for_tests(self) -> None:
        with self._lock:
            self._reset_counts()


CHUNK_STATS = _ChunkFetchStats()


class StreamingReader:
    """An iterator of micro-batches, each a list of records.

    ``fetch_fn`` (optional) materializes each raw batch — a remote read,
    a decode, a validation pass — behind the chunk ``RetryPolicy``: a
    transient failure backs off and retries inside the fetch, and a
    budget that runs dry raises the typed :class:`StreamExhausted`
    (``stream_batches`` quarantines such a chunk — counted on
    ``CHUNK_STATS`` — instead of killing the stream). Without
    ``fetch_fn`` batches pass through untouched, exactly as before."""

    #: chunk retry policy — None picks resilience.retry.default_io_policy
    retry_policy = None

    def __init__(
        self,
        batches: Iterable[Sequence[Any]],
        key_fn: Callable[[Any], str] | None = None,
        fetch_fn: Callable[[Sequence[Any]], Sequence[Any]] | None = None,
    ):
        self._batches = batches
        self.key_fn = key_fn
        self.fetch_fn = fetch_fn

    def is_unbounded(self) -> bool:
        """Streaming sources declare no known size — ``Workflow.train``
        auto-routes them through the out-of-core chunked fit
        (workflow/stream.py) instead of materializing."""
        return True

    def _fetch_batch(self, index: int, batch: Sequence[Any]) -> Sequence[Any]:
        """One chunk fetch behind the RetryPolicy + fault hooks; raises
        ``StreamExhausted`` when transient retries run dry."""
        from ..resilience import faults
        from ..resilience.retry import default_io_policy, is_transient

        chunk_name = f"chunk-{index}"

        def fetch() -> Sequence[Any]:
            plan = faults.active()
            if plan is not None:
                plan.on_stream_chunk(chunk_name)
            return self.fetch_fn(batch) if self.fetch_fn else batch

        policy = self.retry_policy or default_io_policy()
        try:
            records, attempts = policy.call(fetch)
        except Exception as e:
            attempts = getattr(e, "_retry_attempts", 1)
            CHUNK_STATS.record_exhausted(attempts)
            if is_transient(e):
                raise StreamExhausted(chunk_name, attempts, e) from e
            raise
        CHUNK_STATS.record_fetch(attempts)
        return records

    def stream_batches(self) -> Iterator[Sequence[Any]]:
        """Yield record batches in arrival order. With a ``fetch_fn``,
        each batch rides the retry policy; an exhausted budget quarantines
        that chunk (``streamChunkExhausted`` on the resilience ledger)
        and the stream continues — bounded badness, never a dead train."""
        for i, batch in enumerate(self._batches):
            if not batch:
                continue
            if self.fetch_fn is None:
                yield batch
                continue
            try:
                records = self._fetch_batch(i, batch)
            except StreamExhausted as e:
                log.error(
                    "stream chunk %s quarantined after %d attempts: %s",
                    e.chunk, e.attempts, e.last_error,
                )
                continue
            if records:
                yield records

    def stream_datasets(
        self, raw_features: Sequence[Feature]
    ) -> Iterator[Dataset]:
        """Yield one columnar Dataset per micro-batch."""
        for batch in self.stream_batches():
            yield SimpleReader(batch, self.key_fn).generate_dataset(raw_features)


class FileStreamingReader(StreamingReader):
    """Directory-monitoring micro-batch source — the file-stream analog of
    ``StreamingReaders.Simple.avro`` (StreamingReaders.scala:50-70), where
    Spark Streaming's file source turns each newly arrived file into one
    micro-batch.

    Each matching file (csv/avro/parquet by extension; anything else
    raises) becomes one batch of records, in arrival (mtime, then name)
    order. ``poll`` mode keeps watching the directory for files appearing
    after the stream started — ``max_polls``/``poll_interval_s`` bound the
    watch so scoring loops terminate deterministically in tests and batch
    jobs.

    Producers must move files INTO the directory atomically (write
    elsewhere or to a non-matching name, then rename) — the Spark
    file-source contract. For producers that write in place, set
    ``settle_s`` > 0: files whose mtime is younger than that are left for
    a later poll instead of being read mid-write. Transiently unreadable
    files are retried on the next poll (and logged), not silently dropped.
    """

    #: retry policy for chunk fetches — None picks the module default
    #: (resilience.retry.default_io_policy). A transient error mid-fetch
    #: (flaky NFS, object-store hiccup) backs off and retries INSIDE one
    #: poll before the defer-to-next-poll path even engages; fatal errors
    #: (bad format, permissions) fail immediately as before.
    retry_policy = None

    def __init__(
        self,
        directory: str,
        pattern: str = "*.csv",
        key_fn: Callable[[Any], str] | None = None,
        poll: bool = False,
        poll_interval_s: float = 0.5,
        max_polls: int = 10,
        headers: Sequence[str] | None = None,
        has_header: bool | None = None,
        settle_s: float = 0.0,
    ):
        super().__init__((), key_fn)
        self.directory = directory
        self.pattern = pattern
        self.poll = poll
        self.poll_interval_s = poll_interval_s
        self.max_polls = max_polls
        #: csv schema passthrough — Spark-style part files have no header
        #: row (CsvReader would otherwise consume row 1 as column names)
        self.headers = list(headers) if headers is not None else None
        self.has_header = has_header
        self.settle_s = settle_s

    def _fetch_chunk(self, path: str) -> list:
        """One chunk fetch behind the RetryPolicy: transient errors (and
        injected ``fail_chunk_read`` faults) back off and retry before the
        caller's defer/drop handling sees anything."""
        from ..resilience import faults
        from ..resilience.retry import default_io_policy

        def fetch() -> list:
            plan = faults.active()
            if plan is not None:
                plan.on_stream_chunk(path)
            return self._read_file(path)

        from ..resilience.retry import is_transient

        policy = self.retry_policy or default_io_policy()
        try:
            records, attempts = policy.call(fetch)
        except Exception as e:
            # the policy attaches the burned attempt count to the final
            # exception — land it in the ledger before re-raising so an
            # exhausted retry budget is visible, not just a log line
            attempts = getattr(e, "_retry_attempts", 1)
            CHUNK_STATS.record_exhausted(attempts)
            if is_transient(e):
                # retries genuinely ran dry: surface the typed exception
                # naming chunk + attempts + last error (still a
                # TransientError, so the defer/drop path below is intact)
                raise StreamExhausted(path, attempts, e) from e
            raise
        CHUNK_STATS.record_fetch(attempts)
        if attempts > 1:
            log.warning(
                "stream chunk %s fetched after %d attempts", path, attempts
            )
        return records

    def _read_file(self, path: str) -> list:
        if path.endswith(".avro"):
            from ..utils.avro import read_avro

            return list(read_avro(path))
        if path.endswith(".parquet"):
            from .parquet import read_parquet

            return read_parquet(path).rows()
        if path.endswith((".csv", ".tsv", ".txt")):
            from .csv import CsvReader

            return list(
                CsvReader(
                    path, headers=self.headers, has_header=self.has_header
                ).read_records()
            )
        raise ValueError(
            f"unsupported stream file format: {os.path.basename(path)} "
            "(csv/tsv/txt, avro, parquet)"
        )

    def _batches_iter(self) -> Iterator[list]:
        import fnmatch
        import time

        seen: set[str] = set()
        polls = 0
        while True:
            try:
                entries = [
                    os.path.join(self.directory, n)
                    for n in os.listdir(self.directory)
                    if fnmatch.fnmatch(n, self.pattern)
                ]
            except FileNotFoundError:
                entries = []

            def arrival(p):
                # a file can vanish between listdir and stat (concurrent
                # archiver) — sort the gone ones first, they're skipped on
                # read below
                try:
                    return (os.path.getmtime(p), p)
                except OSError:
                    return (-1.0, p)

            fresh = sorted((p for p in entries if p not in seen), key=arrival)

            def try_read(p: str, final: bool):
                """(records | None, ok). Not-ok files are deferred (poll /
                first pass) or dropped LOUDLY (final retry): a file inside
                the settle window may still be mid-write — reading it would
                yield a TRUNCATED batch."""
                if self.settle_s > 0:
                    try:
                        settling = (
                            time.time() - os.path.getmtime(p) < self.settle_s
                        )
                    except OSError as e:
                        settling = True
                        if final:
                            log.error(
                                "stream file %s dropped after retry (%s)",
                                p, e,
                            )
                            return None, False
                    if settling:
                        if final:
                            log.error(
                                "stream file %s still being written after "
                                "settle retry; dropped", p,
                            )
                        return None, False
                try:
                    records = self._fetch_chunk(p)
                except (OSError, TimeoutError, TransientError) as e:
                    # the RetryPolicy exhausted its attempts on a transient
                    # error (or the error was fatal): defer to the next
                    # poll / final retry exactly as before
                    if final:
                        log.error(
                            "stream file %s dropped after retry (%s)", p, e
                        )
                    else:
                        log.warning(
                            "stream file %s unreadable (%s); will retry",
                            p, e,
                        )
                    return None, False
                seen.add(p)
                return records, True

            deferred: list[str] = []
            for p in fresh:
                records, ok = try_read(p, final=False)
                if not ok:
                    deferred.append(p)
                elif records:
                    yield records
            if not self.poll:
                # single pass has no next poll: wait out the settle window
                # once and retry the deferred files
                if deferred:
                    time.sleep(self.settle_s if self.settle_s > 0 else 0.05)
                    for p in deferred:
                        records, ok = try_read(p, final=True)
                        if ok and records:
                            yield records
                return
            polls += 1
            if polls >= self.max_polls:
                return
            time.sleep(self.poll_interval_s)

    def stream_batches(self) -> Iterator[Sequence[Any]]:
        return self._batches_iter()

    def stream_datasets(
        self, raw_features: Sequence[Feature]
    ) -> Iterator[Dataset]:
        for batch in self._batches_iter():
            yield SimpleReader(batch, self.key_fn).generate_dataset(raw_features)
