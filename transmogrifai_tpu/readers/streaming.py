"""Streaming reader — micro-batch scoring source.

Reference: readers/.../StreamingReaders.scala:50-70 (`StreamingReaders.Simple
.avro`) feeding OpWorkflowRunner.streamingScore (OpWorkflowRunner.scala:232).
The Spark Streaming DStream becomes a plain iterator of record batches; the
runner scores each batch with the already-jitted score function (the TPU
path: host loop feeding a compiled program, SURVEY.md §2.6 "async scoring").
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Sequence

from ..dataset import Dataset
from ..features.feature import Feature
from .core import SimpleReader


class StreamingReader:
    """An iterator of micro-batches, each a list of records."""

    def __init__(
        self,
        batches: Iterable[Sequence[Any]],
        key_fn: Callable[[Any], str] | None = None,
    ):
        self._batches = batches
        self.key_fn = key_fn

    def stream_datasets(
        self, raw_features: Sequence[Feature]
    ) -> Iterator[Dataset]:
        """Yield one columnar Dataset per micro-batch."""
        for batch in self._batches:
            if not batch:
                continue
            yield SimpleReader(batch, self.key_fn).generate_dataset(raw_features)
