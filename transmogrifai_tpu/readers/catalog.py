"""Reader catalog — the ``DataReaders.Simple/Aggregate/Conditional`` factory
surface (readers/.../DataReaders.scala:44-198), so reference users find the
same entry points by name.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable

from .aggregate import (
    AggregateParams,
    AggregateReader,
    ConditionalParams,
    ConditionalReader,
)
from .core import DatasetReader, SimpleReader
from .csv import CsvReader
from .parquet import AvroReader, ParquetReader


class Simple:
    """One record per row (DataReaders.scala:49-116)."""

    @staticmethod
    def csv(path: str, key_fn: Callable[[Any], str] | None = None, **kw: Any) -> CsvReader:
        return CsvReader(path, key_fn=key_fn, **kw)

    @staticmethod
    def parquet(path: str, key_fn: Callable[[Any], str] | None = None) -> ParquetReader:
        return ParquetReader(path, key_fn=key_fn)

    @staticmethod
    def avro(path: str, key_fn: Callable[[Any], str] | None = None) -> AvroReader:
        return AvroReader(path, key_fn=key_fn)

    @staticmethod
    def records(records: Iterable[Any], key_fn: Callable[[Any], str] | None = None) -> SimpleReader:
        """csvCase/parquetCase analog: pre-parsed records (dicts/dataclasses)."""
        return SimpleReader(records, key_fn=key_fn)

    @staticmethod
    def dataset(ds: Any) -> DatasetReader:
        return DatasetReader(ds)


class Aggregate:
    """Group events by key and monoid-aggregate them with a CutOffTime
    (DataReaders.scala:116-160; AggregateParams DataReader.scala:279)."""

    @staticmethod
    def records(
        records: Iterable[Any],
        key_fn: Callable[[Any], str],
        params: AggregateParams,
    ) -> AggregateReader:
        return AggregateReader(records, key_fn=key_fn, aggregate_params=params)

    @staticmethod
    def csv(
        path: str, key_fn: Callable[[Any], str], params: AggregateParams, **kw: Any
    ) -> AggregateReader:
        return AggregateReader(
            CsvReader(path, **kw).read_records(), key_fn=key_fn, aggregate_params=params
        )

    @staticmethod
    def parquet(
        path: str, key_fn: Callable[[Any], str], params: AggregateParams
    ) -> AggregateReader:
        return AggregateReader(
            ParquetReader(path).read_records(), key_fn=key_fn, aggregate_params=params
        )


class Conditional:
    """Aggregate relative to a per-key target event time — temporally
    leakage-free labels (DataReaders.scala:160-198; ConditionalParams
    DataReader.scala:351)."""

    @staticmethod
    def records(
        records: Iterable[Any],
        key_fn: Callable[[Any], str],
        params: ConditionalParams,
    ) -> ConditionalReader:
        return ConditionalReader(records, key_fn=key_fn, conditional_params=params)

    @staticmethod
    def csv(
        path: str, key_fn: Callable[[Any], str], params: ConditionalParams, **kw: Any
    ) -> ConditionalReader:
        return ConditionalReader(
            CsvReader(path, **kw).read_records(), key_fn=key_fn, conditional_params=params
        )

    @staticmethod
    def parquet(
        path: str, key_fn: Callable[[Any], str], params: ConditionalParams
    ) -> ConditionalReader:
        return ConditionalReader(
            ParquetReader(path).read_records(), key_fn=key_fn, conditional_params=params
        )


class DataReaders:
    Simple = Simple
    Aggregate = Aggregate
    Conditional = Conditional
