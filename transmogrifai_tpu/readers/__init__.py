"""Data plane (reference: readers module)."""
from .csv import CsvReader, infer_csv_dataset, read_csv_auto  # noqa: F401
from .core import DataReader, SimpleReader  # noqa: F401
