"""Data plane (reference: readers module)."""
from .csv import CsvReader, infer_csv_dataset, read_csv_auto  # noqa: F401
from .core import DataReader, DatasetReader, SimpleReader  # noqa: F401
from .aggregate import (  # noqa: F401
    AggregateParams,
    AggregateReader,
    ConditionalParams,
    ConditionalReader,
    CutOffTime,
    StreamingAggregateReader,
    StreamingConditionalReader,
    TimeStampToKeep,
    event_parity_oracle,
)
from .joins import (  # noqa: F401
    JoinedAggregateReader,
    JoinedReader,
    JoinKeys,
    JoinType,
    TimeBasedFilter,
    TimeColumn,
    join_datasets,
)
from .streaming import (  # noqa: F401
    FileStreamingReader,
    StreamExhausted,
    StreamingReader,
)
from .parquet import (  # noqa: F401
    AvroReader,
    ParquetReader,
    dataset_from_arrow,
    infer_avro_dataset,
    infer_parquet_dataset,
    read_parquet,
    write_parquet,
)
from .catalog import DataReaders  # noqa: F401
