"""Aggregate / Conditional readers — event data → one row per entity.

Reference: readers/.../DataReader.scala:206-360 (AggregatedReader,
AggregateDataReader, ConditionalDataReader), aggregators/CutOffTime.scala,
readers/TimeStampToKeep.scala, DataReaders.scala:116-198.

Semantics (DataReader.scala:259-331, FeatureAggregator.scala:110-124):
  * records are grouped by ``key_fn``;
  * each raw feature's values are extracted per event, filtered by the
    cutoff window, and folded with the feature's monoid aggregator;
  * predictors aggregate events with ``ts <  cutoff`` (within
    ``predictor_window`` before it, when set);
  * responses aggregate events with ``ts >= cutoff`` (within
    ``response_window`` after it, when set);
  * Conditional readers derive the cutoff per key from the first/min/max/
    random event satisfying ``target_condition`` and can drop keys where
    the condition never fires.

Grouping runs host-side (the reference's groupBy shuffle); the folds are
commutative monoids so per-key results are event-order-invariant, matching
the Spark implementation's shard-independence.
"""
from __future__ import annotations

import dataclasses
import enum
import random
import time
from typing import Any, Callable, Iterable, Sequence

from ..dataset import Dataset
from ..features.aggregators import LastAggregator, aggregator_of
from ..features.feature import Feature, FeatureGeneratorStage
from ..types.columns import column_from_values
from .core import DataReader


class CutOffTimeType(enum.Enum):
    """CutOffTimeTypes.scala."""

    UNIX_EPOCH = "UnixEpoch"
    DAYS_AGO = "DaysAgo"
    WEEKS_AGO = "WeeksAgo"
    DDMMYYYY = "DDMMYYYY"
    NO_CUTOFF = "NoCutoff"


@dataclasses.dataclass(frozen=True)
class CutOffTime:
    """CutOffTime.scala:43 — a cutoff in epoch millis (None = no cutoff)."""

    ctype: CutOffTimeType
    time_ms: int | None

    @staticmethod
    def unix_epoch(since_epoch_ms: int) -> "CutOffTime":
        return CutOffTime(CutOffTimeType.UNIX_EPOCH, max(int(since_epoch_ms), 0))

    @staticmethod
    def days_ago(days: int, now_ms: int | None = None) -> "CutOffTime":
        now = _start_of_day(now_ms)
        return CutOffTime(CutOffTimeType.DAYS_AGO, now - days * 86_400_000)

    @staticmethod
    def weeks_ago(weeks: int, now_ms: int | None = None) -> "CutOffTime":
        now = _start_of_day(now_ms)
        return CutOffTime(CutOffTimeType.WEEKS_AGO, now - weeks * 7 * 86_400_000)

    @staticmethod
    def ddmmyyyy(s: str) -> "CutOffTime":
        ts = time.mktime(time.strptime(s, "%d%m%Y"))
        return CutOffTime(CutOffTimeType.DDMMYYYY, int(ts * 1000))

    @staticmethod
    def no_cutoff() -> "CutOffTime":
        return CutOffTime(CutOffTimeType.NO_CUTOFF, None)


def _start_of_day(now_ms: int | None) -> int:
    now = time.time() if now_ms is None else now_ms / 1000.0
    lt = time.localtime(now)
    return int(time.mktime((lt.tm_year, lt.tm_mon, lt.tm_mday, 0, 0, 0,
                            lt.tm_wday, lt.tm_yday, -1)) * 1000)


class TimeStampToKeep(enum.Enum):
    """TimeStampToKeep.scala — which target-event time becomes the cutoff."""

    MIN = "min"
    MAX = "max"
    RANDOM = "random"


def _in_window(
    ts: int,
    cutoff_ms: int | None,
    is_response: bool,
    window_ms: int | None,
) -> bool:
    """GenericFeatureAggregator.filterByDateWithCutoff
    (FeatureAggregator.scala:110-124)."""
    if cutoff_ms is None:
        return True
    if window_ms is None:
        return ts >= cutoff_ms if is_response else ts < cutoff_ms
    if is_response:
        return cutoff_ms <= ts <= cutoff_ms + window_ms
    return cutoff_ms - window_ms <= ts < cutoff_ms


def _aggregate_feature(
    feature: Feature,
    events: Sequence[tuple[int, Any]],  # (ts, record)
    cutoff_ms: int | None,
    is_response: bool,
    window_ms: int | None,
) -> Any:
    stage = feature.origin_stage
    assert isinstance(stage, FeatureGeneratorStage)
    agg = stage.aggregate_fn or aggregator_of(feature.ftype)
    if not hasattr(agg, "plus"):
        # plain callable (user aggregate_fn): fold the filtered values directly
        vals = [
            stage.extract_fn(r) if stage.extract_fn else r
            for ts, r in events
            if _in_window(ts, cutoff_ms, is_response, window_ms)
        ]
        return agg(vals)
    acc = agg.zero
    for ts, record in events:
        if not _in_window(ts, cutoff_ms, is_response, window_ms):
            continue
        value = stage.extract_fn(record) if stage.extract_fn else record
        if isinstance(agg, LastAggregator):
            prepared = agg.prepare_event(value, ts)
        else:
            prepared = agg.prepare(value)
        acc = agg.plus(acc, prepared)
    return agg.present(acc)


def _column_for(feature: Feature, vals: list) -> Any:
    """Build the output column; vector aggregates (CombineVector concatenates
    per-event vectors) are zero-padded to the longest row so the columnar
    [N, D] layout stays rectangular."""
    from ..types import Storage

    if feature.ftype.storage is Storage.VECTOR:
        import numpy as np

        rows = [np.asarray(v, dtype=np.float32).ravel() for v in vals]
        width = max((len(r) for r in rows), default=0)
        out = np.zeros((len(rows), width), dtype=np.float32)
        for i, r in enumerate(rows):
            out[i, : len(r)] = r
        return column_from_values(feature.ftype, out)
    return column_from_values(feature.ftype, vals)


@dataclasses.dataclass
class AggregateParams:
    """AggregateParams (DataReader.scala:279)."""

    timestamp_fn: Callable[[Any], int] | None
    cutoff_time: CutOffTime
    response_window_ms: int | None = None
    predictor_window_ms: int | None = None


class AggregateReader(DataReader):
    """DataReaders.Aggregate.* (DataReaders.scala:116): group events by key,
    monoid-aggregate each raw feature around the cutoff."""

    def __init__(
        self,
        records: Iterable[Any],
        key_fn: Callable[[Any], str],
        aggregate_params: AggregateParams,
    ):
        super().__init__(key_fn)
        self._records = records
        self.params = aggregate_params

    def read_records(self) -> Iterable[Any]:
        return self._records

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        ts_fn = self.params.timestamp_fn
        groups: dict[str, list[tuple[int, Any]]] = {}
        for r in self._read_records_with_retry():
            groups.setdefault(self.key_fn(r), []).append(
                (ts_fn(r) if ts_fn else 0, r)
            )
        keys = sorted(groups)
        cutoff = self.params.cutoff_time.time_ms
        cols: dict[str, Any] = {
            _KEY_COLUMN: column_from_values(_key_type(), keys)
        }
        for f in raw_features:
            window = (
                self.params.response_window_ms
                if f.is_response
                else self.params.predictor_window_ms
            )
            vals = [
                _aggregate_feature(f, groups[k], cutoff, f.is_response, window)
                for k in keys
            ]
            cols[f.name] = _column_for(f, vals)
        return Dataset.of(cols)


@dataclasses.dataclass
class ConditionalParams:
    """ConditionalParams (DataReader.scala:351-358)."""

    timestamp_fn: Callable[[Any], int]
    target_condition: Callable[[Any], bool]
    response_window_ms: int | None = 7 * 86_400_000  # one week
    predictor_window_ms: int | None = 7 * 86_400_000
    timestamp_to_keep: TimeStampToKeep = TimeStampToKeep.RANDOM
    cutoff_time_fn: Callable[[str, Sequence[Any]], CutOffTime] | None = None
    drop_if_target_condition_not_met: bool = False
    seed: int | None = None  # the reference's Random is unseeded; we seed
    #: injectable "now" for the unmet-condition fallback cutoff
    #: (DataReader.scala:325 calls now()); pinning it makes streamed and
    #: materialized twins bit-comparable and keeps tests clock-free
    now_ms: int | None = None


class ConditionalReader(DataReader):
    """DataReaders.Conditional.* (DataReaders.scala:198): cutoff per key at
    the target event, predictors before / responses after
    (DataReader.scala:295-331)."""

    def __init__(
        self,
        records: Iterable[Any],
        key_fn: Callable[[Any], str],
        conditional_params: ConditionalParams,
    ):
        super().__init__(key_fn)
        self._records = records
        self.params = conditional_params

    def read_records(self) -> Iterable[Any]:
        return self._records

    def _cutoff_for(
        self, key: str, events: list[tuple[int, Any]], rng: random.Random
    ) -> int | None:
        p = self.params
        if p.cutoff_time_fn is not None:
            return p.cutoff_time_fn(key, [r for _, r in events]).time_ms
        target_times = [ts for ts, r in events if p.target_condition(r)]
        if not target_times:
            return None  # caller drops or uses now()
        if p.timestamp_to_keep is TimeStampToKeep.MIN:
            return min(target_times)
        if p.timestamp_to_keep is TimeStampToKeep.MAX:
            return max(target_times)
        return target_times[rng.randrange(len(target_times))]

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        p = self.params
        rng = random.Random(p.seed)
        groups: dict[str, list[tuple[int, Any]]] = {}
        for r in self._read_records_with_retry():
            groups.setdefault(self.key_fn(r), []).append((p.timestamp_fn(r), r))
        keys, cutoffs = [], []
        now_ms = (
            p.now_ms if p.now_ms is not None else int(time.time() * 1000)
        )
        for k in sorted(groups):
            cutoff = self._cutoff_for(k, groups[k], rng)
            if cutoff is None:
                if p.drop_if_target_condition_not_met:
                    continue
                cutoff = now_ms  # DataReader.scala:325: now() when unmet
            keys.append(k)
            cutoffs.append(cutoff)
        cols: dict[str, Any] = {
            _KEY_COLUMN: column_from_values(_key_type(), keys)
        }
        for f in raw_features:
            window = (
                p.response_window_ms if f.is_response else p.predictor_window_ms
            )
            vals = [
                _aggregate_feature(f, groups[k], c, f.is_response, window)
                for k, c in zip(keys, cutoffs)
            ]
            cols[f.name] = _column_for(f, vals)
        return Dataset.of(cols)


_KEY_COLUMN = "key"


def _key_type() -> type:
    from .. import types as T

    return T.ID


# ----------------------------------------------------- streamed event-time
class _FeatureFold:
    """Incremental per-feature event fold — ``_aggregate_feature`` turned
    into monoid state so the streamed readers never hold a key's event
    list. Monoid aggregators fold ``plus`` per event; a plain-callable
    ``aggregate_fn`` has no incremental form, so only its FILTERED
    extracted values buffer (bounded by in-window events, not the
    stream)."""

    def __init__(self, feature: Feature):
        stage = feature.origin_stage
        assert isinstance(stage, FeatureGeneratorStage)
        self.stage = stage
        self.agg = stage.aggregate_fn or aggregator_of(feature.ftype)
        self.monoid = hasattr(self.agg, "plus")

    def zero(self) -> Any:
        return self.agg.zero if self.monoid else []

    def fold(self, acc: Any, ts: int, record: Any) -> Any:
        value = (
            self.stage.extract_fn(record)
            if self.stage.extract_fn else record
        )
        if not self.monoid:
            acc.append(value)
            return acc
        if isinstance(self.agg, LastAggregator):
            prepared = self.agg.prepare_event(value, ts)
        else:
            prepared = self.agg.prepare(value)
        return self.agg.plus(acc, prepared)

    def present(self, acc: Any) -> Any:
        return self.agg.present(acc) if self.monoid else self.agg(acc)


class _StreamedEventReader(DataReader):
    """Shared chunk plumbing for the streamed event-time readers. The
    source is an iterable of record chunks OR a zero-arg callable
    producing one (a callable is REQUIRED wherever two passes are needed
    — a plain generator would be empty on the second)."""

    def __init__(
        self,
        chunks: Iterable[Sequence[Any]] | Callable[[], Iterable[Sequence[Any]]],
        key_fn: Callable[[Any], str],
    ):
        super().__init__(key_fn)
        self._chunks = chunks

    def _chunk_iter(self) -> Iterable[Sequence[Any]]:
        return self._chunks() if callable(self._chunks) else self._chunks

    def read_records(self) -> Iterable[Any]:
        for chunk in self._chunk_iter():
            yield from chunk


class StreamingAggregateReader(_StreamedEventReader):
    """Point-in-time-correct aggregate reader over a chunked event
    stream: one pass, per-entity monoid accumulators — memory is bounded
    by ENTITIES, never events. Semantically identical to
    :class:`AggregateReader` over the concatenated chunks (the parity
    oracle in tests/bench pins column-exact equality): predictors fold
    events strictly before the cutoff, responses at/after it, each within
    its window — no future leakage regardless of how the stream is
    chunked."""

    def __init__(
        self,
        chunks: Iterable[Sequence[Any]] | Callable[[], Iterable[Sequence[Any]]],
        key_fn: Callable[[Any], str],
        aggregate_params: AggregateParams,
    ):
        super().__init__(chunks, key_fn)
        self.params = aggregate_params

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        p = self.params
        ts_fn = p.timestamp_fn
        cutoff = p.cutoff_time.time_ms
        folds = [_FeatureFold(f) for f in raw_features]
        windows = [
            p.response_window_ms if f.is_response else p.predictor_window_ms
            for f in raw_features
        ]
        state: dict[str, list[Any]] = {}
        for chunk in self._chunk_iter():
            for r in chunk:
                k = self.key_fn(r)
                ts = ts_fn(r) if ts_fn else 0
                accs = state.get(k)
                if accs is None:
                    accs = [fold.zero() for fold in folds]
                    state[k] = accs
                for i, f in enumerate(raw_features):
                    if _in_window(ts, cutoff, f.is_response, windows[i]):
                        accs[i] = folds[i].fold(accs[i], ts, r)
        keys = sorted(state)
        cols: dict[str, Any] = {
            _KEY_COLUMN: column_from_values(_key_type(), keys)
        }
        for i, f in enumerate(raw_features):
            vals = [folds[i].present(state[k][i]) for k in keys]
            cols[f.name] = _column_for(f, vals)
        return Dataset.of(cols)


class StreamingConditionalReader(_StreamedEventReader):
    """Per-entity cutoff-time semantics over a chunked event stream, in
    two streamed passes: pass 1 folds each key's target-event times
    (min/max incremental; RANDOM keeps the target times only), pass 2
    folds the windowed aggregates against the per-key cutoffs. Chunks
    must therefore come from a re-iterable source (sequence or callable)
    that replays the SAME records in the SAME order. Bit-identical to
    :class:`ConditionalReader` over the concatenated chunks given the
    same ``seed`` (pin ``now_ms`` when keys can miss the target
    condition). ``cutoff_time_fn`` needs a key's full event list and is
    not supported streamed."""

    def __init__(
        self,
        chunks: Iterable[Sequence[Any]] | Callable[[], Iterable[Sequence[Any]]],
        key_fn: Callable[[Any], str],
        conditional_params: ConditionalParams,
    ):
        super().__init__(chunks, key_fn)
        if conditional_params.cutoff_time_fn is not None:
            raise ValueError(
                "cutoff_time_fn requires each key's full event list and "
                "cannot stream; use ConditionalReader or precompute "
                "cutoffs"
            )
        self.params = conditional_params

    def _cutoffs(self) -> dict[str, int | None]:
        """Pass 1 → per-key cutoff. Consumes the rng over sorted keys
        exactly like ``ConditionalReader._cutoff_for`` so the streamed
        and materialized twins draw identical RANDOM cutoffs."""
        p = self.params
        keep = p.timestamp_to_keep
        # MIN/MAX fold to one int; RANDOM needs the times (arrival order,
        # matching the materialized group lists) for index selection
        times: dict[str, Any] = {}
        seen: set[str] = set()
        for chunk in self._chunk_iter():
            for r in chunk:
                k = self.key_fn(r)
                seen.add(k)
                if not p.target_condition(r):
                    continue
                ts = p.timestamp_fn(r)
                if keep is TimeStampToKeep.RANDOM:
                    times.setdefault(k, []).append(ts)
                elif keep is TimeStampToKeep.MIN:
                    times[k] = min(times.get(k, ts), ts)
                else:
                    times[k] = max(times.get(k, ts), ts)
        rng = random.Random(p.seed)
        out: dict[str, int | None] = {}
        for k in sorted(seen):
            t = times.get(k)
            if t is None:
                out[k] = None
            elif keep is TimeStampToKeep.RANDOM:
                out[k] = t[rng.randrange(len(t))]
            else:
                out[k] = t
        return out

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        p = self.params
        cutoffs = self._cutoffs()
        now_ms = (
            p.now_ms if p.now_ms is not None else int(time.time() * 1000)
        )
        keys = []
        for k in sorted(cutoffs):
            if cutoffs[k] is None:
                if p.drop_if_target_condition_not_met:
                    continue
                cutoffs[k] = now_ms  # DataReader.scala:325: now() when unmet
            keys.append(k)
        kept = set(keys)
        folds = [_FeatureFold(f) for f in raw_features]
        windows = [
            p.response_window_ms if f.is_response else p.predictor_window_ms
            for f in raw_features
        ]
        state: dict[str, list[Any]] = {
            k: [fold.zero() for fold in folds] for k in keys
        }
        for chunk in self._chunk_iter():
            for r in chunk:
                k = self.key_fn(r)
                if k not in kept:
                    continue
                ts = p.timestamp_fn(r)
                cutoff = cutoffs[k]
                accs = state[k]
                for i, f in enumerate(raw_features):
                    if _in_window(ts, cutoff, f.is_response, windows[i]):
                        accs[i] = folds[i].fold(accs[i], ts, r)
        cols: dict[str, Any] = {
            _KEY_COLUMN: column_from_values(_key_type(), keys)
        }
        for i, f in enumerate(raw_features):
            vals = [folds[i].present(state[k][i]) for k in keys]
            cols[f.name] = _column_for(f, vals)
        return Dataset.of(cols)


def event_parity_oracle(streamed: Dataset, materialized: Dataset) -> dict:
    """Column-exact comparison of a streamed event-time frame against its
    materialized twin — the acceptance oracle for the streamed readers
    (and ``bench.py fit-stream``). Returns ``{"identical": bool,
    "mismatches": [...]}`` naming every differing column (or a shape/
    schema difference) instead of a bare boolean, so a parity break is
    diagnosable from the report."""
    mismatches: list[str] = []
    a, b = streamed.columns, materialized.columns
    if sorted(a) != sorted(b):
        mismatches.append(
            f"columns differ: {sorted(a)} vs {sorted(b)}"
        )
        return {"identical": False, "mismatches": mismatches}
    for name in sorted(a):
        va, vb = a[name].to_list(), b[name].to_list()
        if len(va) != len(vb):
            mismatches.append(
                f"{name}: {len(va)} rows vs {len(vb)}"
            )
        elif va != vb:
            bad = next(
                i for i, (x, y) in enumerate(zip(va, vb)) if x != y
            )
            mismatches.append(
                f"{name}: row {bad}: {va[bad]!r} != {vb[bad]!r}"
            )
    return {"identical": not mismatches, "mismatches": mismatches}
