"""Parquet / Arrow ingestion with schema-directed typing, plus columnar
dataset save/load.

Reference surface covered here:
  - ``DataReaders.Simple.parquetCase`` (readers/.../DataReaders.scala:116) —
    typed parquet reading;
  - ``DataReaders.Simple.avro`` — covered by the gated avro entry points at
    the bottom (the image has no avro library; parquet is the native
    columnar interchange for this build and arrow covers in-memory);
  - ``RichDataset.saveAvro``/``loadAvro`` (features/.../utils/spark/
    RichDataset.scala:201-330) — ``write_parquet``/``read_parquet`` round-trip
    a typed Dataset, preserving feature types in file metadata.

Arrow is the right interchange for a TPU host pipeline: column buffers come
out of the file contiguous and typed, so numeric features go straight into
(values, mask) ndarray pairs without a per-row boxing pass, and from there
to ``jax.device_put``.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from .. import types as T
from ..dataset import Dataset
from ..types.columns import (
    ListColumn,
    MapColumn,
    NumericColumn,
    TextColumn,
    column_from_values,
)
from .core import DataReader

_META_KEY = b"transmogrifai_tpu.feature_types"


def _require_pyarrow():
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet  # noqa: F401
    except ImportError as e:  # pragma: no cover - env-dependent
        raise ImportError(
            "pyarrow is required for parquet/arrow ingestion; install it or "
            "use the CSV reader (transmogrifai_tpu.readers.csv)"
        ) from e
    import pyarrow as pa

    return pa


def _arrow_to_ftype(pa: Any, typ: Any) -> type:
    """Arrow type -> feature type (FeatureBuilder.fromDataFrame's
    schema-directed inference, features/FeatureBuilder.scala:232)."""
    if pa.types.is_boolean(typ):
        return T.Binary
    if pa.types.is_integer(typ):
        return T.Integral
    if pa.types.is_floating(typ) or pa.types.is_decimal(typ):
        return T.Real
    if pa.types.is_timestamp(typ):
        return T.DateTime
    if pa.types.is_date(typ):
        return T.Date
    if pa.types.is_string(typ) or pa.types.is_large_string(typ):
        return T.Text
    if pa.types.is_list(typ) or pa.types.is_large_list(typ):
        inner = typ.value_type
        if pa.types.is_string(inner) or pa.types.is_large_string(inner):
            return T.TextList
        if pa.types.is_timestamp(inner):
            return T.DateTimeList
        if pa.types.is_integer(inner):
            # FeatureSparkTypes.scala:216 maps ArrayType(LongType) to
            # DateList; DateTimeList is reserved for timestamp elements
            return T.DateList
        if pa.types.is_floating(inner):
            return T.Geolocation
        return T.TextList
    if pa.types.is_map(typ):
        val = typ.item_type
        if pa.types.is_floating(val) or pa.types.is_decimal(val):
            return T.RealMap
        if pa.types.is_integer(val):
            return T.IntegralMap
        if pa.types.is_boolean(val):
            return T.BinaryMap
        return T.TextMap
    if pa.types.is_struct(typ):
        return T.TextMap
    return T.Text


def _numeric_from_chunked(ftype: type, arr: Any, dtype: Any) -> NumericColumn:
    """Zero-boxing path: arrow buffer -> (values, mask) ndarrays."""
    np_arr = arr.to_numpy(zero_copy_only=False)
    if np_arr.dtype == object:  # nullable ints surface as object
        mask = np.array([v is not None for v in np_arr], dtype=bool)
        vals = np.array(
            [v if v is not None else 0 for v in np_arr], dtype=dtype
        )
        return NumericColumn(ftype, vals, mask)
    mask = ~np.isnan(np_arr) if np_arr.dtype.kind == "f" else np.ones(
        len(np_arr), dtype=bool
    )
    null_mask = arr.is_null().to_numpy(zero_copy_only=False)
    mask &= ~null_mask
    # only NaN means missing; +/-inf are real values and must survive
    vals = np.nan_to_num(np_arr, nan=0.0, posinf=np.inf, neginf=-np.inf)
    return NumericColumn(ftype, vals.astype(dtype, copy=False), mask)


def dataset_from_arrow(
    table: Any, type_overrides: dict[str, type] | None = None
) -> Dataset:
    """Typed columnar Dataset from a pyarrow Table."""
    pa = _require_pyarrow()
    overrides = dict(type_overrides or {})
    # honor feature types a previous write_parquet stamped into the schema
    meta = table.schema.metadata or {}
    if _META_KEY in meta:
        by_name = T.FEATURE_TYPES_BY_NAME
        stamped = json.loads(meta[_META_KEY].decode())
        for name, tname in stamped.items():
            if name not in overrides and tname in by_name:
                overrides[name] = by_name[tname]

    columns: dict[str, Any] = {}
    for field in table.schema:
        name = field.name
        arr = table.column(name).combine_chunks()
        ftype = overrides.get(name) or _arrow_to_ftype(pa, field.type)
        storage = ftype.storage
        if storage in (T.Storage.REAL,):
            columns[name] = _numeric_from_chunked(ftype, arr, np.float64)
        elif storage in (T.Storage.INTEGRAL, T.Storage.DATE):
            if pa.types.is_timestamp(field.type):
                # normalize to epoch millis (the reference's Date unit)
                arr = arr.cast(pa.timestamp("ms")).cast(pa.int64())
            elif pa.types.is_date(field.type):
                import pyarrow.compute as pc

                if pa.types.is_date32(field.type):  # days -> epoch millis
                    arr = arr.cast(pa.int32()).cast(pa.int64())
                    arr = pc.multiply(arr, pa.scalar(86_400_000, type=pa.int64()))
                else:  # date64 is already millis
                    arr = arr.cast(pa.int64())
            columns[name] = _numeric_from_chunked(ftype, arr, np.int64)
        elif storage is T.Storage.BINARY:
            columns[name] = _numeric_from_chunked(ftype, arr, bool)
        elif storage is T.Storage.TEXT:
            vals = arr.to_pylist()
            columns[name] = TextColumn.from_values(
                ftype, [None if v is None else str(v) for v in vals]
            )
        else:
            columns[name] = column_from_values(ftype, arr.to_pylist())
    return Dataset.of(columns)


def infer_parquet_dataset(
    path: str, type_overrides: dict[str, type] | None = None
) -> Dataset:
    """Read a parquet file into a typed Dataset (DataReaders.Simple.parquetCase)."""
    _require_pyarrow()
    import pyarrow.parquet as pq

    return dataset_from_arrow(pq.read_table(path), type_overrides)


def read_parquet(path: str, **kwargs: Any) -> Dataset:
    return infer_parquet_dataset(path, **kwargs)


def write_parquet(dataset: Dataset, path: str) -> None:
    """Persist a typed Dataset, stamping feature types into file metadata so
    ``read_parquet`` round-trips exactly (RichDataset.saveAvro analog)."""
    pa = _require_pyarrow()
    import pyarrow.parquet as pq

    arrays, names, stamped = [], [], {}
    for name, col in dataset.columns.items():
        stamped[name] = col.feature_type.__name__
        if isinstance(col, NumericColumn):
            vals = col.values.astype(object)
            vals[~col.mask] = None
            arrays.append(pa.array(vals.tolist()))
        elif isinstance(col, (TextColumn, ListColumn, MapColumn)):
            vals = col.to_list()
            if isinstance(col, MapColumn):
                # empty map ≠ missing: only None becomes null
                arrays.append(
                    pa.array(
                        [
                            list(v.items()) if v is not None else None
                            for v in vals
                        ],
                        type=_map_arrow_type(pa, vals),
                    )
                )
            elif isinstance(col, ListColumn):
                arrays.append(
                    pa.array([list(v) if v is not None else None for v in vals])
                )
            else:
                arrays.append(pa.array(vals))
        else:
            # vector/prediction/set columns: store as list<double>/list<string>
            vals = col.to_list()
            arrays.append(
                pa.array([
                    None if v is None
                    else sorted(v) if isinstance(v, frozenset)
                    else list(np.asarray(v, dtype=float))
                    for v in vals
                ])
            )
        names.append(name)
    table = pa.table(dict(zip(names, arrays)))
    table = table.replace_schema_metadata(
        {**(table.schema.metadata or {}), _META_KEY: json.dumps(stamped).encode()}
    )
    pq.write_table(table, path)


def _map_arrow_type(pa: Any, vals: list) -> Any:
    for v in vals:
        if v:
            sample = next(iter(v.values()))
            if isinstance(sample, bool):
                return pa.map_(pa.string(), pa.bool_())
            if isinstance(sample, (int, np.integer)):
                return pa.map_(pa.string(), pa.int64())
            if isinstance(sample, (float, np.floating)):
                return pa.map_(pa.string(), pa.float64())
            break
    return pa.map_(pa.string(), pa.string())


class ParquetReader(DataReader):
    """Record reader over parquet rows (DataReaders.Simple.parquetCase)."""

    def __init__(self, path: str, key_fn: Callable[[Any], str] | None = None):
        super().__init__(key_fn)
        self.path = path

    def read_records(self) -> Iterable[dict[str, Any]]:
        _require_pyarrow()
        import pyarrow.parquet as pq

        return pq.read_table(self.path).to_pylist()


# --- avro --------------------------------------------------------------------

def _read_avro_records(path: str) -> list[dict[str, Any]]:
    """fastavro when available, else the vendored pure-Python container
    reader (utils/avro.py) — the reader catalog has no gated hole."""
    try:
        import fastavro
    except ImportError:
        from ..utils.avro import read_avro

        return read_avro(path)
    with open(path, "rb") as fh:  # pragma: no cover - fastavro not in image
        return list(fastavro.reader(fh))


def _avro_value_type(values: list[Any]) -> type:
    """Feature type from decoded Avro values (CSVAutoReaders.scala infers
    from the Avro schema; here the schema already decoded to Python)."""
    present = [v for v in values if v is not None]
    if not present:
        return T.Text
    if all(isinstance(v, bool) for v in present):
        return T.Binary
    if all(isinstance(v, int) and not isinstance(v, bool) for v in present):
        return T.Integral
    if all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in present):
        return T.Real
    if all(isinstance(v, list) for v in present):
        return T.TextList
    if all(isinstance(v, dict) for v in present):
        inner = [x for v in present for x in v.values() if x is not None]
        if inner and all(
            isinstance(x, (int, float)) and not isinstance(x, bool)
            for x in inner
        ):
            return T.RealMap
        return T.TextMap
    return T.Text


def infer_avro_dataset(path: str, **kwargs: Any) -> Dataset:
    """DataReaders.Simple.avro equivalent (CSVAutoReaders.scala)."""
    records = _read_avro_records(path)
    names: list[str] = []
    for r in records:
        for k in r:
            if k not in names:
                names.append(k)
    overrides = kwargs.get("type_overrides", {})
    cols = {}
    for n in names:
        values = [r.get(n) for r in records]
        cols[n] = column_from_values(
            overrides.get(n, _avro_value_type(values)), values
        )
    return Dataset.of(cols)


class AvroReader(DataReader):
    def __init__(self, path: str, key_fn: Callable[[Any], str] | None = None):
        super().__init__(key_fn)
        self.path = path

    def read_records(self) -> Iterable[dict[str, Any]]:
        return _read_avro_records(self.path)
