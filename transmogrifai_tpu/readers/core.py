"""DataReader core: records -> raw-feature columns.

Reference: readers/.../DataReader.scala:57-203 — ``generateDataFrame`` reads
source records, keys them, applies each raw feature's ``extract_fn`` (+
aggregator for event data), and produces one row per entity. The columnar
equivalent produces one Column per raw feature.

Simple readers: one record per row. Aggregate/Conditional readers (event
grouping with cutoff-time semantics, DataReader.scala:206-360) live in
transmogrifai_tpu.readers.aggregate.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Sequence

from ..dataset import Dataset
from ..features.feature import Feature, FeatureGeneratorStage


class DataReader:
    """Base reader (DataReader.scala:57)."""

    #: retry policy for record I/O — None picks the module default
    #: (resilience.retry.default_io_policy): transient errors (flaky
    #: network/disk) back off and retry, real errors fail immediately
    retry_policy = None

    def __init__(self, key_fn: Callable[[Any], str] | None = None):
        self.key_fn = key_fn

    def is_unbounded(self) -> bool:
        """Whether this source declares no known size. Materializing
        readers are bounded; streaming sources (readers/streaming.py)
        return True and ``Workflow.train`` auto-routes them through the
        out-of-core chunked fit (workflow/stream.py)."""
        return False

    def read_records(self) -> Iterable[Any]:  # pragma: no cover - abstract
        raise NotImplementedError

    def _read_records_with_retry(self) -> list[Any]:
        from ..resilience.retry import default_io_policy

        policy = self.retry_policy or default_io_policy()
        records, attempts = policy.call(lambda: list(self.read_records()))
        if attempts > 1:
            import logging

            logging.getLogger(__name__).warning(
                "reader %s succeeded after %d attempts",
                type(self).__name__, attempts,
            )
        return records

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        """readDataset + generateRow (DataReader.scala:106,190)."""
        records = self._read_records_with_retry()
        cols = {}
        for f in raw_features:
            stage = f.origin_stage
            assert isinstance(stage, FeatureGeneratorStage), (
                f"Raw feature {f.name} must originate from a FeatureGeneratorStage"
            )
            cols[f.name] = stage.extract_column(records)
        if self.key_fn is not None and "key" not in cols:
            # keyed readers always carry KeyFieldName in the generated frame
            # (DataFrameFieldNames.scala) — the join plane depends on it
            from .. import types as T
            from ..types.columns import column_from_values

            cols = {
                "key": column_from_values(
                    T.ID, [self.key_fn(r) for r in records]
                ),
                **cols,
            }
        return Dataset.of(cols)


class SimpleReader(DataReader):
    """One record per row (DataReaders.Simple, DataReaders.scala:44)."""

    def __init__(self, records: Iterable[Any], key_fn: Callable[[Any], str] | None = None):
        super().__init__(key_fn)
        self._records = records

    def read_records(self) -> Iterable[Any]:
        return self._records


class DatasetReader(DataReader):
    """Pass-through reader over an already-columnar Dataset (the
    ``setInputDataset`` path, core/.../OpWorkflowCore.scala)."""

    def __init__(self, dataset: Dataset):
        super().__init__(None)
        self.dataset = dataset

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        cols = {}
        rows = None  # row-wise view materialized at most once
        for f in raw_features:
            stage = f.origin_stage
            if (
                isinstance(stage, FeatureGeneratorStage)
                and stage.extract_fn is not None
            ):
                # extract_fn always wins: passing a column through by name
                # here would silently skip the user's extraction logic (to
                # score already-aggregated event data, use score(reader=...))
                if rows is None:
                    rows = self.dataset.rows()
                cols[f.name] = stage.extract_column(rows)
            elif f.name in self.dataset:
                cols[f.name] = self.dataset[f.name]
            else:
                raise KeyError(
                    f"Raw feature '{f.name}' missing from input dataset"
                )
        return Dataset.of(cols)
