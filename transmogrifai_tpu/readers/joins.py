"""Joined readers — typed joins of two readers' outputs on key columns.

Reference: readers/.../JoinedDataReader.scala:83-390 and JoinTypes.scala.
The reference joins the two generated DataFrames on `JoinKeys` (default both
sides' "key" column) with inner/left-outer/outer semantics, then optionally
re-aggregates. Columnar equivalent: hash-join the two Datasets; missing side
rows become all-missing columns (the reference's nulls).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Sequence

import numpy as np

from ..dataset import Dataset
from ..features.feature import Feature
from ..types.columns import empty_like
from .core import DataReader


class JoinType(enum.Enum):
    """JoinTypes.scala."""

    INNER = "inner"
    LEFT_OUTER = "leftOuter"
    OUTER = "outer"


@dataclasses.dataclass(frozen=True)
class JoinKeys:
    """JoinedDataReader.scala: key column names on each side (default the
    reader key column)."""

    left_key: str = "key"
    right_key: str = "key"
    result_key: str = "key"


@dataclasses.dataclass(frozen=True)
class TimeColumn:
    """Time column for post-join aggregation (JoinedDataReader.scala:54-60):
    ``keep`` controls whether it survives into the aggregated result."""

    name: str
    keep: bool = True


@dataclasses.dataclass(frozen=True)
class TimeBasedFilter:
    """Time-based filter for post-join conditional aggregation
    (JoinedDataReader.scala:66-75): per result row, the right (child) side's
    events merge only when their ``primary`` timestamp falls in the window
    anchored at that row's ``condition`` timestamp."""

    condition: TimeColumn
    primary: TimeColumn
    time_window_ms: int


class JoinedReader(DataReader):
    """Join the outputs of two readers (JoinedDataReader.scala:83).

    Each raw feature must be resolvable by exactly one side; the split is by
    feature name against each side's generated columns. The join is
    MANY-TO-MANY (Spark DataFrame.join semantics): every left row pairs with
    every matching right row.
    """

    def __init__(
        self,
        left: DataReader,
        right: DataReader,
        join_type: JoinType = JoinType.LEFT_OUTER,
        join_keys: JoinKeys = JoinKeys(),
        left_features: Sequence[Feature] = (),
        right_features: Sequence[Feature] = (),
    ):
        super().__init__(None)
        self.left = left
        self.right = right
        self.join_type = join_type
        self.join_keys = join_keys
        self.left_features = tuple(left_features)
        self.right_features = tuple(right_features)

    def inner_join(self, other: "DataReader", **kw) -> "JoinedReader":
        return JoinedReader(self, other, JoinType.INNER, **kw)

    def with_secondary_aggregation(
        self, time_filter: TimeBasedFilter
    ) -> "JoinedAggregateReader":
        """Aggregate after joining (JoinedDataReader.withSecondaryAggregation
        :228-236): group the joined rows by the result key; parent-side
        features keep one copy per key, child-side features monoid-merge
        under the time filter."""
        return JoinedAggregateReader(
            self.left, self.right, self.join_type, self.join_keys,
            self.left_features, self.right_features, time_filter,
        )

    def _split_features(self, raw_features: Sequence[Feature]):
        left_names = {f.name for f in self.left_features}
        right_names = {f.name for f in self.right_features}
        lf = [f for f in raw_features if f.name in left_names]
        rf = [f for f in raw_features if f.name in right_names]
        unclaimed = [
            f.name for f in raw_features
            if f.name not in left_names and f.name not in right_names
        ]
        if unclaimed:
            raise ValueError(
                f"Raw features {unclaimed} not declared on either join side"
            )
        return lf, rf

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        lf, rf = self._split_features(raw_features)
        lds = self.left.generate_dataset(lf)
        rds = self.right.generate_dataset(rf)
        return join_datasets(
            lds, rds, self.join_type, self.join_keys
        )


class JoinedAggregateReader(JoinedReader):
    """Join + group-by-key secondary aggregation
    (JoinedAggregateDataReader, JoinedDataReader.scala:240-305):

      * parent (left) features take the LAST joined value per key — the
        reference's DummyJoinedAggregator (convertTypesMerge = v2);
      * child (right) features monoid-merge only the rows whose primary
        timestamp passes the window test against that row's condition
        timestamp (JoinedConditionalAggregator.update:429-438 — predictors:
        cutoff-window < t < cutoff; responses: cutoff <= t < cutoff+window);
      * time columns with keep=False are dropped from the result.
    """

    def __init__(
        self, left, right, join_type, join_keys,
        left_features, right_features, time_filter: TimeBasedFilter,
    ):
        super().__init__(
            left, right, join_type, join_keys, left_features, right_features
        )
        self.time_filter = time_filter

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        lf, rf = self._split_features(raw_features)
        joined = super().generate_dataset(raw_features)
        # reference isCombinedJoin (JoinedDataReader.scala:103): only a join
        # producing the COMBINED key aggregates the left side conditionally;
        # any other key combination treats left as the parent table (one
        # copy per key — DummyJoinedAggregator)
        combined = self.join_keys.result_key == "combinedKey"
        return post_join_aggregate(
            joined, lf, rf, self.join_keys, self.time_filter,
            combined=combined,
        )


def post_join_aggregate(
    joined: Dataset,
    left_features: Sequence[Feature],
    right_features: Sequence[Feature],
    keys: JoinKeys,
    time_filter: TimeBasedFilter,
    combined: bool = False,
) -> Dataset:
    """Group the joined rows by the result key and aggregate each feature
    (JoinedAggregateDataReader.postJoinAggregate:275-305)."""
    from ..features.aggregators import aggregator_of
    from .aggregate import _column_for

    key_vals = joined[keys.result_key].to_list()
    n = len(key_vals)

    def ms_list(name: str) -> list[int]:
        if name not in joined:
            # zero-filling here would silently zero every windowed
            # aggregate; the filter's time columns MUST be raw features
            raise ValueError(
                f"TimeBasedFilter column '{name}' is not in the joined "
                "data — declare it among the join's raw features (keep="
                "False only drops it from the aggregated RESULT)"
            )
        return [
            0 if v is None else int(v) for v in joined[name].to_list()
        ]

    primary_ms = ms_list(time_filter.primary.name)
    condition_ms = ms_list(time_filter.condition.name)

    order: list[str] = []
    groups: dict[str, list[int]] = {}
    for i, k in enumerate(key_vals):
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(i)

    def in_window(i: int, is_response: bool) -> bool:
        ts, cutoff = primary_ms[i], condition_ms[i]
        w = time_filter.time_window_ms
        if is_response:
            return cutoff <= ts < cutoff + w
        return cutoff - w < ts < cutoff

    cols = {}
    from ..types.columns import column_from_values

    from .. import types as T

    cols[keys.result_key] = column_from_values(T.ID, order)
    time_drop = {
        t.name for t in (time_filter.condition, time_filter.primary)
        if not t.keep
    }
    right_names = {f.name for f in right_features}
    for f in list(left_features) + list(right_features):
        if f.name not in joined or f.name == keys.result_key:
            continue
        values = joined[f.name].to_list()
        conditional = f.name in right_names or combined
        out_vals = []
        for k in order:
            idxs = groups[k]
            if not conditional:
                out_vals.append(values[idxs[-1]])  # dummy: keep last copy
                continue
            agg = aggregator_of(f.ftype)
            acc = agg.zero
            for i in idxs:
                if values[i] is None or not in_window(i, f.is_response):
                    continue
                acc = agg.plus(acc, agg.prepare(values[i]))
            out_vals.append(agg.present(acc))
        if f.name not in time_drop:
            cols[f.name] = _column_for(f, out_vals)
    return Dataset.of(cols)


def join_datasets(
    left: Dataset,
    right: Dataset,
    join_type: JoinType = JoinType.LEFT_OUTER,
    keys: JoinKeys = JoinKeys(),
) -> Dataset:
    """Hash-join two columnar Datasets on their key columns — MANY-TO-MANY
    (Spark DataFrame.join semantics, JoinedDataReader.scala:168-175): every
    left row pairs with every matching right row; unmatched sides become
    all-missing columns per the join type."""
    lkeys = [_key_str(v) for v in left[keys.left_key].to_list()]
    rkeys = [_key_str(v) for v in right[keys.right_key].to_list()]
    rindex: dict[str, list[int]] = {}
    for i, k in enumerate(rkeys):
        rindex.setdefault(k, []).append(i)

    # left rows are addressed positionally so duplicate left keys each keep
    # their own data; the right side is looked up through its key index
    out_keys: list[str] = []
    li_list: list[int] = []
    ri_list: list[int] = []
    for i, k in enumerate(lkeys):
        matches = rindex.get(k)
        if not matches:
            if join_type is not JoinType.INNER:
                out_keys.append(k)
                li_list.append(i)
                ri_list.append(-1)
            continue
        for r in matches:
            out_keys.append(k)
            li_list.append(i)
            ri_list.append(r)
    if join_type is JoinType.OUTER:
        seen = set(lkeys)
        for i, k in enumerate(rkeys):
            if k not in seen:
                out_keys.append(k)
                li_list.append(-1)
                ri_list.append(i)
    li = np.array(li_list, dtype=np.int64)
    ri = np.array(ri_list, dtype=np.int64)

    cols = {}
    for name, col in left.columns.items():
        if name == keys.left_key:
            continue
        cols[name] = _gather(col, li, left.num_rows)
    for name, col in right.columns.items():
        if name == keys.right_key:
            continue
        if name in cols:
            raise ValueError(f"Join column collision: '{name}' on both sides")
        cols[name] = _gather(col, ri, right.num_rows)
    from ..types.columns import column_from_values

    from .. import types as T

    cols = {keys.result_key: column_from_values(T.ID, out_keys), **cols}
    return Dataset.of(cols)


def _key_str(v) -> str:
    return "" if v is None else str(v)


def _gather(col, idx: np.ndarray, n_src: int):
    """Take rows by index; -1 produces a missing row."""
    from ..types.columns import VectorColumn, column_from_values

    missing = idx < 0
    if not missing.any():
        return col.take(idx)
    if isinstance(col, VectorColumn):
        # rectangular: unmatched rows become zero vectors, metadata kept
        src = np.asarray(col.values)
        out = np.zeros((len(idx), src.shape[1]), dtype=src.dtype)
        valid = ~missing
        if valid.any() and n_src:
            out[valid] = src[idx[valid]]
        return VectorColumn(col.feature_type, out, col.metadata)
    if missing.all() or n_src == 0:
        return empty_like(col.feature_type, len(idx))
    # take valid rows then splice in missing rows
    safe = np.where(missing, 0, idx)
    taken = col.take(safe)
    vals = taken.to_list()
    evals = empty_like(col.feature_type, int(missing.sum())).to_list()
    j = 0
    for i, m in enumerate(missing):
        if m:
            vals[i] = evals[j]
            j += 1
    return column_from_values(col.feature_type, vals)
