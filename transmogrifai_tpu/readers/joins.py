"""Joined readers — typed joins of two readers' outputs on key columns.

Reference: readers/.../JoinedDataReader.scala:83-390 and JoinTypes.scala.
The reference joins the two generated DataFrames on `JoinKeys` (default both
sides' "key" column) with inner/left-outer/outer semantics, then optionally
re-aggregates. Columnar equivalent: hash-join the two Datasets; missing side
rows become all-missing columns (the reference's nulls).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Sequence

import numpy as np

from ..dataset import Dataset
from ..features.feature import Feature
from ..types.columns import empty_like
from .core import DataReader


class JoinType(enum.Enum):
    """JoinTypes.scala."""

    INNER = "inner"
    LEFT_OUTER = "leftOuter"
    OUTER = "outer"


@dataclasses.dataclass(frozen=True)
class JoinKeys:
    """JoinedDataReader.scala: key column names on each side (default the
    reader key column)."""

    left_key: str = "key"
    right_key: str = "key"
    result_key: str = "key"


class JoinedReader(DataReader):
    """Join the outputs of two readers (JoinedDataReader.scala:83).

    Each raw feature must be resolvable by exactly one side; the split is by
    feature name against each side's generated columns.
    """

    def __init__(
        self,
        left: DataReader,
        right: DataReader,
        join_type: JoinType = JoinType.LEFT_OUTER,
        join_keys: JoinKeys = JoinKeys(),
        left_features: Sequence[Feature] = (),
        right_features: Sequence[Feature] = (),
    ):
        super().__init__(None)
        self.left = left
        self.right = right
        self.join_type = join_type
        self.join_keys = join_keys
        self.left_features = tuple(left_features)
        self.right_features = tuple(right_features)

    def inner_join(self, other: "DataReader", **kw) -> "JoinedReader":
        return JoinedReader(self, other, JoinType.INNER, **kw)

    def generate_dataset(self, raw_features: Sequence[Feature]) -> Dataset:
        left_names = {f.name for f in self.left_features}
        right_names = {f.name for f in self.right_features}
        lf = [f for f in raw_features if f.name in left_names]
        rf = [f for f in raw_features if f.name in right_names]
        unclaimed = [
            f.name for f in raw_features
            if f.name not in left_names and f.name not in right_names
        ]
        if unclaimed:
            raise ValueError(
                f"Raw features {unclaimed} not declared on either join side"
            )
        lds = self.left.generate_dataset(lf)
        rds = self.right.generate_dataset(rf)
        return join_datasets(
            lds, rds, self.join_type, self.join_keys
        )


def join_datasets(
    left: Dataset,
    right: Dataset,
    join_type: JoinType = JoinType.LEFT_OUTER,
    keys: JoinKeys = JoinKeys(),
) -> Dataset:
    """Hash-join two columnar Datasets on their key columns."""
    lkeys = [_key_str(v) for v in left[keys.left_key].to_list()]
    rkeys = [_key_str(v) for v in right[keys.right_key].to_list()]
    rindex: dict[str, int] = {}
    for i, k in enumerate(rkeys):
        rindex.setdefault(k, i)  # first match wins (1:1 join)

    # left rows are addressed positionally so duplicate left keys each keep
    # their own data; only the right side is looked up through its key index
    out_keys: list[str] = []
    li_list: list[int] = []
    ri_list: list[int] = []
    for i, k in enumerate(lkeys):
        r = rindex.get(k, -1)
        if join_type is JoinType.INNER and r < 0:
            continue
        out_keys.append(k)
        li_list.append(i)
        ri_list.append(r)
    if join_type is JoinType.OUTER:
        seen = set(lkeys)
        for i, k in enumerate(rkeys):
            if k not in seen and rindex[k] == i:
                out_keys.append(k)
                li_list.append(-1)
                ri_list.append(i)
    li = np.array(li_list, dtype=np.int64)
    ri = np.array(ri_list, dtype=np.int64)

    cols = {}
    for name, col in left.columns.items():
        if name == keys.left_key:
            continue
        cols[name] = _gather(col, li, left.num_rows)
    for name, col in right.columns.items():
        if name == keys.right_key:
            continue
        if name in cols:
            raise ValueError(f"Join column collision: '{name}' on both sides")
        cols[name] = _gather(col, ri, right.num_rows)
    from ..types.columns import column_from_values

    from .. import types as T

    cols = {keys.result_key: column_from_values(T.ID, out_keys), **cols}
    return Dataset.of(cols)


def _key_str(v) -> str:
    return "" if v is None else str(v)


def _gather(col, idx: np.ndarray, n_src: int):
    """Take rows by index; -1 produces a missing row."""
    from ..types.columns import VectorColumn, column_from_values

    missing = idx < 0
    if not missing.any():
        return col.take(idx)
    if isinstance(col, VectorColumn):
        # rectangular: unmatched rows become zero vectors, metadata kept
        src = np.asarray(col.values)
        out = np.zeros((len(idx), src.shape[1]), dtype=src.dtype)
        valid = ~missing
        if valid.any() and n_src:
            out[valid] = src[idx[valid]]
        return VectorColumn(col.feature_type, out, col.metadata)
    if missing.all() or n_src == 0:
        return empty_like(col.feature_type, len(idx))
    # take valid rows then splice in missing rows
    safe = np.where(missing, 0, idx)
    taken = col.take(safe)
    vals = taken.to_list()
    evals = empty_like(col.feature_type, int(missing.sum())).to_list()
    j = 0
    for i, m in enumerate(missing):
        if m:
            vals[i] = evals[j]
            j += 1
    return column_from_values(col.feature_type, vals)
