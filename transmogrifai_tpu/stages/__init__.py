"""Stage system (reference: features/.../stages/OpPipelineStages.scala)."""
from .base import (  # noqa: F401
    Estimator,
    Model,
    PipelineStage,
    Transformer,
)
