"""Vector column provenance metadata — the ledger that makes SanityChecker,
ModelInsights, and LOCO possible.

Reference: features/.../utils/spark/OpVectorColumnMetadata.scala:67 and
OpVectorMetadata.scala:51. Every column of every feature vector records which
raw feature(s) it came from, the parent feature type, an optional grouping
(e.g. the pivot group or map key), an optional indicator value (the pivoted
categorical value, OTHER, or the null-indicator marker), and an optional
descriptor (e.g. circular-date component). In the reference this rides Spark
column Metadata; here it is a static structure attached to VectorColumn and
computed at trace/fit time.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Sequence

#: OpVectorColumnMetadata.NullString — marks null-indicator columns
NULL_STRING = "NullIndicatorValue"
#: OpVectorColumnMetadata.OtherString — marks the other/rest pivot bucket
OTHER_STRING = "OTHER"


@dataclasses.dataclass(frozen=True)
class ColumnMeta:
    """One vector column's provenance (OpVectorColumnMetadata.scala:67)."""

    parent_names: tuple[str, ...]
    parent_type: str
    grouping: str | None = None
    indicator_value: str | None = None
    descriptor_value: str | None = None
    index: int = 0

    @property
    def is_null_indicator(self) -> bool:
        return self.indicator_value == NULL_STRING

    @property
    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER_STRING

    def make_name(self) -> str:
        """Human-readable column name (OpVectorColumnMetadata.makeColName)."""
        parts = ["_".join(self.parent_names)]
        if self.grouping:
            parts.append(self.grouping)
        if self.descriptor_value:
            parts.append(self.descriptor_value)
        if self.indicator_value:
            parts.append(self.indicator_value)
        return "_".join(parts) + f"_{self.index}"

    def grouped_key(self) -> tuple:
        """Key identifying the pivot group this column belongs to — columns
        sharing a group are dropped together by the SanityChecker
        (OpVectorColumnMetadata.grouping semantics)."""
        return (self.parent_names, self.grouping)

    def to_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict[str, Any]) -> "ColumnMeta":
        d = dict(d)
        d["parent_names"] = tuple(d["parent_names"])
        return ColumnMeta(**d)


@dataclasses.dataclass
class VectorMetadata:
    """Provenance for a whole feature vector (OpVectorMetadata.scala:51)."""

    name: str
    columns: tuple[ColumnMeta, ...] = ()

    @property
    def size(self) -> int:
        return len(self.columns)

    def column_names(self) -> list[str]:
        return [c.make_name() for c in self.columns]

    @staticmethod
    def flatten(name: str, parts: Sequence["VectorMetadata"]) -> "VectorMetadata":
        """Concatenate per-vectorizer metadata, reindexing columns
        (OpVectorMetadata.flatten — used by VectorsCombiner)."""
        cols: list[ColumnMeta] = []
        for part in parts:
            for c in part.columns:
                cols.append(dataclasses.replace(c, index=len(cols)))
        return VectorMetadata(name, tuple(cols))

    def select(self, indices: Iterable[int]) -> "VectorMetadata":
        """Keep a subset of columns, reindexed (SanityChecker drop mask)."""
        cols = [
            dataclasses.replace(self.columns[i], index=j)
            for j, i in enumerate(indices)
        ]
        return VectorMetadata(self.name, tuple(cols))

    def index_of_group(self) -> dict[tuple, list[int]]:
        """Map pivot-group key -> column indices (group-wise removal)."""
        groups: dict[tuple, list[int]] = {}
        for i, c in enumerate(self.columns):
            groups.setdefault(c.grouped_key(), []).append(i)
        return groups

    def to_json(self) -> dict[str, Any]:
        return {"name": self.name, "columns": [c.to_json() for c in self.columns]}

    @staticmethod
    def from_json(d: dict[str, Any]) -> "VectorMetadata":
        return VectorMetadata(
            d["name"], tuple(ColumnMeta.from_json(c) for c in d["columns"])
        )


def indicator_columns(
    parent_name: str,
    parent_type: str,
    values: Sequence[str],
    grouping: str | None = None,
) -> list[ColumnMeta]:
    """Pivot columns for categorical values (one per value)."""
    return [
        ColumnMeta(
            parent_names=(parent_name,),
            parent_type=parent_type,
            grouping=grouping if grouping is not None else parent_name,
            indicator_value=v,
        )
        for v in values
    ]
