"""Stage ABI: PipelineStage / Transformer / Estimator / Model.

Reference design (features/.../stages/OpPipelineStages.scala:55,169,218-524):
stages declare typed input features and produce output feature(s); estimators
``fit`` data into models; transformers are pure functions of their inputs.
Arity is explicit there (OpPipelineStage1..2N); here arity is simply
``len(input_features)`` with input/output types validated dynamically.

TPU-native contract (SURVEY.md §7 step 3):
  * ``Transformer.transform_columns(*cols, num_rows)`` is columnar — it maps
    whole columns (numpy host-side for text, jax/XLA for the numeric/vector
    plane), not rows. Local per-row scoring reuses it with length-1 columns.
  * ``Estimator.fit(dataset)`` computes a (small) summary — implemented as
    map/monoid-reduce so it is shard-order-invariant — returns a ``Model``
    and records a JSON-able summary into ``self.metadata`` (the
    stage-metadata-as-ledger pattern, SURVEY.md §5.5).
"""
from __future__ import annotations

from typing import Any, Sequence

from ..types import FeatureType, is_subtype
from ..types.columns import Column
from ..utils import uid as uid_util
from ..dataset import Dataset


class PipelineStage:
    """Base of every stage (OpPipelineStageBase, OpPipelineStages.scala:55)."""

    #: (input feature types, output feature type(s)) — overridden by subclasses
    input_types: tuple[type, ...] | None = None
    output_type: type = FeatureType
    #: input positions that legitimately consume the RESPONSE (the label
    #: slot of predictors / SanityChecker / supervised bucketizers). The
    #: pre-flight leakage check (analysis/preflight.py TPA003) treats these
    #: as the only sanctioned response crossings — response lineage
    #: reaching any other input of a predictor is flagged.
    label_inputs: tuple[int, ...] = ()

    def __init__(self, operation_name: str, uid: str | None = None):
        self.operation_name = operation_name
        self.uid = uid or uid_util.make_uid(type(self))
        self.input_features: tuple[Any, ...] = ()  # tuple[Feature, ...]
        #: fitted-stage summary ledger — JSON-able dict, written at fit time
        self.metadata: dict[str, Any] = {}

    # ---------------------------------------------------------------- wiring
    def set_input(self, *features: Any) -> "PipelineStage":
        """Declare input features; validates arity/types (transformSchema).

        Rewiring an already-wired stage to different features is an error —
        it would corrupt the first output feature's lineage (the reference
        enforces this via immutable stage/feature construction)."""
        if self.input_features and tuple(features) != self.input_features:
            raise ValueError(
                f"{self} is already wired to {self.input_names}; create a new "
                "stage instance instead of rewiring"
            )
        self._validate_inputs(features)
        self.input_features = tuple(features)
        return self

    def _validate_inputs(self, features: Sequence[Any]) -> None:
        if self.input_types is not None:
            if len(features) != len(self.input_types):
                raise ValueError(
                    f"{self}: expected {len(self.input_types)} inputs, "
                    f"got {len(features)}"
                )
            for f, expected in zip(features, self.input_types):
                if not is_subtype(f.ftype, expected):
                    raise TypeError(
                        f"{self}: input '{f.name}' has type {f.ftype.__name__}, "
                        f"expected {expected.__name__}"
                    )

    @property
    def input_names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.input_features)

    # --------------------------------------------------------------- outputs
    @property
    def output_name(self) -> str:
        """Derived output column name (OpPipelineStages makeOutputName).
        A fixed name (set by Estimator.fit on models, or by the loader)
        takes precedence."""
        fixed = getattr(self, "_fixed_output_name", None)
        if fixed is not None:
            return fixed
        _, suffix = uid_util.from_string(self.uid)
        base = "-".join(self.input_names) if self.input_features else "out"
        if len(base) > 80:
            base = base[:80]
        return f"{base}_{self.operation_name}_{suffix}"

    def get_output(self) -> Any:
        """The output Feature, with this stage as origin. The output name is
        frozen here so later input rewiring (e.g. the RawFeatureFilter
        blocklist rewrite) cannot silently rename the output column."""
        from ..features.feature import Feature

        if not self.input_features:
            raise ValueError(f"{self}: set_input must be called before get_output")
        self._fixed_output_name = self.output_name
        return Feature(
            name=self._fixed_output_name,
            ftype=self.output_type,
            origin_stage=self,
            parents=tuple(self.input_features),
            is_response=any(f.is_response for f in self.input_features),
        )

    # ----------------------------------------------------------- persistence
    def get_params(self) -> dict[str, Any]:
        """JSON-able constructor params for stage serialization
        (OpPipelineStageReaderWriter.scala:131-196 equivalent). Subclasses
        override; default takes no extra params."""
        return {}

    def set_params(self, **params: Any) -> "PipelineStage":
        """Apply config-file overrides reflectively (OpWorkflow.setStageParameters,
        core/.../OpWorkflow.scala:179-201)."""
        for k, v in params.items():
            if not hasattr(self, k):
                raise AttributeError(f"{self} has no param '{k}'")
            setattr(self, k, v)
        return self

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.uid})"


class Transformer(PipelineStage):
    """A pure columnar function of its input features (OpTransformer)."""

    def transform_columns(self, *cols: Column, num_rows: int) -> Column:
        raise NotImplementedError

    def transform(self, dataset: Dataset) -> Dataset:
        """Append this stage's output column to the dataset."""
        cols = [dataset[name] for name in self.input_names]
        out = self.transform_columns(*cols, num_rows=dataset.num_rows)
        return dataset.with_column(self.output_name, out)

    def transform_row(self, row: dict[str, Any]) -> Any:
        """Per-row scoring hook (OpTransformer.transformRow) implemented via
        length-1 columns, so there is exactly one transform semantics."""
        from ..types.columns import column_from_values

        cols = []
        for f in self.input_features:
            v = row[f.name]
            col_cls_val = v if isinstance(v, Column) else None
            if col_cls_val is not None:
                cols.append(v)
            else:
                cols.append(column_from_values(f.ftype, [v]))
        out = self.transform_columns(*cols, num_rows=1)
        return out.to_list()[0]


class Model(Transformer):
    """A fitted transformer (UnaryModel etc.). Carries the uid of the
    estimator that produced it so the workflow can swap fitted stages in by
    uid (warm start, OpWorkflow.scala:468)."""

    def __init__(self, operation_name: str, uid: str | None = None, parent_uid: str = ""):
        super().__init__(operation_name, uid=uid)
        self.parent_uid = parent_uid or self.uid

    def get_arrays(self) -> dict[str, Any]:
        """Fitted numpy/jax arrays for checkpointing (orbax-style). Subclasses
        override when they hold learned arrays."""
        return {}


class Estimator(PipelineStage):
    """Learns a Model from data (OpPipelineStage fit)."""

    def fit(self, dataset: Dataset) -> Model:
        model = self.fit_model(dataset)
        model.input_features = self.input_features
        model.parent_uid = self.uid
        model.operation_name = self.operation_name
        # the model's output must replace the estimator's declared output name
        model._fixed_output_name = self.output_name  # type: ignore[attr-defined]
        model.metadata = dict(self.metadata)
        return model

    def fit_model(self, dataset: Dataset) -> Model:
        raise NotImplementedError
