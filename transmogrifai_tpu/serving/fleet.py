"""Replicated scoring fleet — N ``ScoringService`` workers behind a
health-checked router, with hedged retries and replica-loss drain.

``FleetService`` is the horizontal tier over the single-worker serving
plane: each replica keeps its own :class:`~.queue.AdmissionQueue`,
:class:`~.batcher.MicroBatcher`, and shed tiers (they share the
process-global mesh-fingerprinted compile bank, so replica #2's first
batch pays no compilation), while the :class:`~.router.Router` picks a
replica per request off the live queue-depth / in-flight / breaker
gauges and the fleet's heartbeat view (``HostSentinel`` on an
injectable clock — the same machinery the training plane uses for host
loss).

Correctness under failure is the contract, not just throughput:

* **Exactly-once outcomes.** A logical request may own several replica
  attempts (a hedge, an adoption after replica loss); the FIRST settled
  attempt wins, later ones count as ``hedge_duplicates`` and are never
  re-stamped onto the caller's handle.
* **Hedged retries.** A request that misses its deadline-budget
  checkpoint (``hedge_after_fraction`` of its budget elapsed, still
  unsettled) is re-dispatched ONCE to the healthiest peer — and only
  when that peer's router score beats the original replica's by
  ``hedge_score_margin``, so symmetric overload cannot start a hedge
  storm.
* **Replica-loss drain.** ``lose_replica`` decommissions a worker via
  ``ScoringService.stop(mode="reject_new_then_drain")``: the dying
  replica settles its own ledger (queued work sheds as ``stopped``),
  and every orphan whose logical request is still unsettled is adopted
  by a survivor with its REMAINING deadline budget. The fleet-level
  typed invariant

      admitted == completed + quarantined + shed + errors + outstanding

  holds at every instant across re-dispatch (pinned by the chaos soak).

Synchronous mode (``workers=0`` per replica + :meth:`pump_all` /
:meth:`tick`) runs everything on the caller's thread with injectable
clocks — the fleet loadtest drives kills, partitions, and hedges
without a single real sleep.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
import weakref
from typing import Any, Callable, Sequence

from ..analysis import schedule as _schedule
from ..resilience import faults as _faults
from ..resilience.distributed import HeartbeatConfig, HostSentinel
from ..telemetry import events as _tevents
from ..telemetry import metrics as _tm
from . import deadline as _deadline
from .queue import RejectedByAdmission
from .router import Router, RouterConfig
from .service import PendingScore, ScoreRequest, ScoringService, ServiceConfig

log = logging.getLogger(__name__)

__all__ = ["FleetConfig", "FleetRequest", "FleetService"]

#: weakrefs to live fleets — the ``fleet`` exposition source
_LIVE_FLEETS: list = []
_LIVE_LOCK = threading.Lock()


@dataclasses.dataclass
class FleetConfig:
    """Fleet-level knobs; ``service`` is the per-replica template."""

    replicas: int = 2
    service: ServiceConfig = dataclasses.field(default_factory=ServiceConfig)
    router: RouterConfig = dataclasses.field(default_factory=RouterConfig)
    #: seconds without a heartbeat before a replica is declared lost
    heartbeat_timeout: float = 5.0
    #: hedge when this fraction of the deadline budget elapsed unsettled
    hedge_after_fraction: float = 0.5
    #: the healthiest peer must beat the original replica's router score
    #: by this much before a hedge fires (anti-storm guard: symmetric
    #: overload leaves every score equal, so no hedge helps)
    hedge_score_margin: float = 0.15


class _Attempt:
    """One replica-level submission of a logical request."""

    __slots__ = ("replica", "hedge", "superseded")

    def __init__(self, replica: int, hedge: bool = False):
        self.replica = replica
        self.hedge = hedge
        # True once decommission settled this attempt as ``stopped`` on
        # the dying replica — the logical request lives on via adoption
        self.superseded = False


class FleetRequest:
    """One logical request: the caller's handle plus its attempts."""

    __slots__ = (
        "rows", "deadline", "explain", "handle", "submitted_at",
        "attempts", "hedged", "settled",
    )

    def __init__(
        self,
        rows: list[dict],
        deadline: float | None,
        explain: int,
        handle: PendingScore,
        submitted_at: float,
    ):
        self.rows = rows
        self.deadline = deadline
        self.explain = explain
        self.handle = handle
        self.submitted_at = submitted_at
        self.attempts: list[_Attempt] = []
        self.hedged = False
        self.settled = False

    def remaining(self, now: float) -> float | None:
        if self.deadline is None:
            return None
        return self.deadline - (now - self.submitted_at)


class FleetService:
    """N scoring replicas behind load-aware, health-aware dispatch."""

    def __init__(
        self,
        score_fn: Callable | Sequence[Callable],
        config: FleetConfig | None = None,
        clock: Callable[[], float] | None = None,
        replica_clocks: Sequence[Callable[[], float]] | None = None,
    ):
        self.config = config or FleetConfig()
        n = self.config.replicas
        if n < 1:
            raise ValueError("a fleet needs at least one replica")
        self.clock = clock if clock is not None else time.monotonic
        if isinstance(score_fn, (list, tuple)):
            if len(score_fn) != n:
                raise ValueError(
                    f"{len(score_fn)} score_fns for {n} replicas"
                )
            fns = list(score_fn)
        else:
            fns = [score_fn] * n
        clocks = (
            list(replica_clocks) if replica_clocks is not None
            else [self.clock] * n
        )
        if len(clocks) != n:
            raise ValueError(f"{len(clocks)} replica clocks for {n} replicas")
        self.services = [
            ScoringService(
                fns[i], config=self.config.service, clock=clocks[i],
                replica=i,
            )
            for i in range(n)
        ]
        self.sentinel = HostSentinel(
            list(range(n)),
            HeartbeatConfig(
                timeout=self.config.heartbeat_timeout, clock=self.clock
            ),
        )
        self.router = Router(self, self.config.router)
        # instrumented-lock seam: the literal is the static analyzer's
        # canonical key (analysis/concurrency.py + schedule.py). Lock
        # order: the fleet lock is only ever taken from code holding NO
        # service/queue lock (on_settled fires outside them), and nothing
        # under it calls back into a replica.
        self._lock = _schedule.make_lock("serving/fleet.py:FleetService._lock")
        self.lost: set[int] = set()
        self._decommissioning: set[int] = set()
        #: id(logical) -> logical for every admitted-unsettled request
        self._pending: dict[int, FleetRequest] = {}
        #: logicals whose attempt died with a decommissioned replica and
        #: await adoption (filled by _attempt_settled during stop())
        self._adoptable: list[FleetRequest] = []
        # fleet-level typed counters (mutations under self._lock)
        self.admitted = 0
        self.completed = 0
        self.quarantined = 0
        self.errors = 0
        self.shed: dict[str, int] = {"deadline_exceeded": 0, "stopped": 0}
        self.rejected: dict[str, int] = {
            "queue_full": 0, "shedding": 0, "stopped": 0, "deadline": 0,
        }
        self.hedges_fired = 0
        self.hedge_duplicates = 0
        self.orphans_adopted = 0
        self.replicas_lost = 0
        #: registry seam: called with (rows, results, replica, latency)
        #: after a completed/quarantined settle, outside every lock
        self.on_served: Callable[..., None] | None = None
        with _LIVE_LOCK:
            # r is a weakref deref — runs no user code, takes no locks
            _LIVE_FLEETS[:] = [
                r for r in _LIVE_FLEETS if r() is not None  # tp: disable=TPC004
            ]
            _LIVE_FLEETS.append(weakref.ref(self))

    # ---------------------------------------------------------- lifecycle
    @property
    def decommissioning(self) -> set[int]:
        return self._decommissioning

    def live_replicas(self) -> list[int]:
        return [
            i for i in range(len(self.services))
            if i not in self.lost and i not in self._decommissioning
        ]

    def start(self, wait_warmup: bool = False) -> "FleetService":
        for svc in self.services:
            svc.start(wait_warmup=wait_warmup)
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Quiesce every live replica (drain mode — queued work executes).
        After stop() every admitted logical request has a typed outcome."""
        for i in self.live_replicas():
            self.services[i].stop(drain=True, timeout=timeout)
        # belt and braces: a logical request with no live attempt left
        # (all its replicas died and adoption found no survivor) must
        # still settle — silence is never an outcome
        with self._lock:
            leftovers = list(self._pending.values())
        for logical in leftovers:
            self._settle_logical(
                logical, "stopped",
                error=RejectedByAdmission("stopped", "fleet stopped"),
            )

    def __enter__(self) -> "FleetService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---------------------------------------------------------- admission
    def submit(
        self,
        rows: dict | list[dict],
        deadline: float | None = None,
        explain: int = 0,
        pin: int | None = None,
    ) -> PendingScore:
        """Admit one logical request and dispatch it to the router's best
        replica (falling through the order on per-replica rejection —
        queue-full on one worker is not queue-full on the fleet). ``pin``
        forces the first try onto one replica (the loadtest harness pins
        burst hot-spots). Raises the LAST replica's typed rejection when
        every replica refuses, or :class:`~.deadline.DeadlineExceeded`
        when the budget cannot cover admission anywhere."""
        if isinstance(rows, dict):
            rows = [rows]
        if not rows:
            raise ValueError("empty request")
        now = self.clock()
        secs = (
            deadline if deadline is not None
            else self.config.service.default_deadline
        )
        handle = PendingScore(submitted_at=now)
        logical = FleetRequest(
            list(rows), secs, int(explain or 0), handle, submitted_at=now
        )
        order = self.router.order()
        if pin is not None and pin in order:
            order = [pin] + [i for i in order if i != pin]
        if not order:
            with self._lock:
                self.rejected["stopped"] += 1
            raise RejectedByAdmission("stopped", "no routable replicas")
        # admitted + pending registered BEFORE the replica offer: a worker
        # thread may settle the attempt the instant submit publishes it,
        # and the fleet invariant (admitted >= settled at every instant)
        # must never observe the settle before the admission
        with self._lock:
            self.admitted += 1
            self._pending[id(logical)] = logical
        last: RejectedByAdmission | None = None
        for i in order:
            try:
                self._dispatch(logical, i)
                return handle
            except RejectedByAdmission as e:
                last = e
            except _deadline.DeadlineExceeded:
                with self._lock:
                    self.admitted -= 1
                    self._pending.pop(id(logical), None)
                    self.rejected["deadline"] += 1
                raise
        with self._lock:
            self.admitted -= 1
            self._pending.pop(id(logical), None)
            assert last is not None
            self.rejected[last.reason] = self.rejected.get(last.reason, 0) + 1
        raise last

    # ----------------------------------------------------------- dispatch
    def _dispatch(
        self, logical: FleetRequest, replica: int, hedge: bool = False
    ) -> _Attempt:
        """One replica-level attempt with the REMAINING deadline budget.
        Raises the replica's typed rejection or DeadlineExceeded."""
        remaining = logical.remaining(self.clock())
        if remaining is not None and remaining <= 0:
            raise _deadline.DeadlineExceeded("fleet", remaining, 0.0)
        attempt = _Attempt(replica, hedge=hedge)
        with self._lock:
            logical.attempts.append(attempt)
        svc = self.services[replica]
        try:
            svc.submit(
                logical.rows,
                deadline=remaining,
                explain=logical.explain,
                on_settled=lambda req, L=logical, a=attempt: (
                    self._attempt_settled(L, a, req)
                ),
            )
        except BaseException:
            with self._lock:
                logical.attempts.remove(attempt)
            raise
        self.router.record_dispatch(replica, hedge=hedge)
        return attempt

    def _count_outcome_locked(self, outcome: str) -> None:
        if outcome == "completed":
            self.completed += 1
        elif outcome == "quarantined":
            self.quarantined += 1
        elif outcome == "error":
            self.errors += 1
        else:
            self.shed[outcome] = self.shed.get(outcome, 0) + 1

    def _attempt_settled(
        self, logical: FleetRequest, attempt: _Attempt, req: ScoreRequest
    ) -> None:
        """ScoreRequest.on_settled seam — idempotent de-dup: the first
        attempt to settle stamps the logical handle, later ones count as
        hedge duplicates; a decommission-``stopped`` attempt defers the
        logical to adoption instead of settling it."""
        h = req.handle
        with self._lock:
            if attempt.superseded:
                return
            if (
                h.outcome == "stopped"
                and attempt.replica in self._decommissioning
            ):
                attempt.superseded = True
                if not logical.settled:
                    self._adoptable.append(logical)
                return
            if logical.settled:
                self.hedge_duplicates += 1
                return
            logical.settled = True
            self._pending.pop(id(logical), None)
            self._count_outcome_locked(h.outcome or "error")
        lh = logical.handle
        lh.results = h.results
        lh.error = h.error
        lh.outcome = h.outcome
        # carry the REPLICA clock's completion stamp: on the virtual-time
        # harness the fleet clock lags a replica mid-drain, and latency
        # must be completion-on-the-worker minus fleet arrival
        lh.completed_at = (
            h.completed_at if h.completed_at is not None else self.clock()
        )
        lh._event.set()
        hook = self.on_served
        if hook is not None and h.outcome in ("completed", "quarantined"):
            try:
                hook(
                    logical.rows, h.results, attempt.replica,
                    (lh.completed_at or 0.0) - lh.submitted_at,
                )
            except Exception:  # a broken observer must not kill serving
                log.exception("on_served hook failed")

    def _settle_logical(
        self,
        logical: FleetRequest,
        outcome: str,
        results: list[dict] | None = None,
        error: BaseException | None = None,
    ) -> None:
        """Settle a logical request directly (adoption dead-ends) —
        first-wins like the attempt path."""
        with self._lock:
            if logical.settled:
                return
            logical.settled = True
            self._pending.pop(id(logical), None)
            self._count_outcome_locked(outcome)
        h = logical.handle
        h.results = results
        h.error = error
        h.outcome = outcome
        h.completed_at = self.clock()
        h._event.set()

    # -------------------------------------------------------- replica loss
    def lose_replica(self, replica: int, reason: str = "killed") -> int:
        """Decommission one replica: reject-new-then-drain stop (its own
        ledger reconciles — queued work sheds as ``stopped``), then adopt
        every orphan whose logical request is still unsettled onto the
        healthiest survivors with the remaining deadline budget. Returns
        the adopted count. Idempotent per replica."""
        with self._lock:
            if replica in self.lost or replica in self._decommissioning:
                return 0
            self._decommissioning.add(replica)
        self.sentinel.declare_lost(replica)
        try:
            self.services[replica].stop(mode="reject_new_then_drain")
        finally:
            with self._lock:
                self.lost.add(replica)
                self._decommissioning.discard(replica)
                orphans = list(self._adoptable)
                self._adoptable.clear()
                self.replicas_lost += 1
        _tm.REGISTRY.counter("tptpu_fleet_replicas_lost_total").inc()
        _tevents.emit(
            "replica_lost", replica=replica, reason=reason,
            orphans=len(orphans),
        )
        adopted = 0
        for logical in orphans:
            if logical.settled:
                continue
            try:
                placed = False
                last: RejectedByAdmission | None = None
                for i in self.router.order():
                    try:
                        self._dispatch(logical, i)
                        placed = True
                        break
                    except RejectedByAdmission as e:
                        last = e
                if placed:
                    adopted += 1
                else:
                    # no survivor took it — a TYPED outcome, never silence
                    self._settle_logical(
                        logical, "stopped",
                        error=last or RejectedByAdmission(
                            "stopped", "no adoptive replica"
                        ),
                    )
            except _deadline.DeadlineExceeded as e:
                self._settle_logical(logical, "deadline_exceeded", error=e)
        with self._lock:
            self.orphans_adopted += adopted
        return adopted

    # -------------------------------------------------------------- ticking
    def tick(self, now: float | None = None) -> None:
        """One control-plane heartbeat on the fleet clock: fire scripted
        replica kills, beat un-partitioned replicas, declare
        heartbeat-stale replicas lost (adopting their work), then check
        every pending request's hedge checkpoint."""
        t = now if now is not None else self.clock()
        plan = _faults.active()
        if plan is not None:
            for r in plan.replicas_to_kill(t):
                if isinstance(r, int) and 0 <= r < len(self.services):
                    self.lose_replica(r, reason="kill_replica")
        for i in self.live_replicas():
            if plan is not None and plan.replica_partitioned(i, t):
                continue  # partitioned: beats never arrive
            self.sentinel.beat(i)
        for h in list(self.sentinel.dead_hosts()):
            if isinstance(h, int):
                self.lose_replica(h, reason="heartbeat_timeout")
        self._maybe_hedge(t)

    def _maybe_hedge(self, now: float) -> None:
        with self._lock:
            candidates = [
                L for L in self._pending.values()
                if not L.settled and not L.hedged and L.deadline is not None
                and (now - L.submitted_at)
                > self.config.hedge_after_fraction * L.deadline
                and L.attempts
            ]
        for logical in candidates:
            origin = logical.attempts[-1].replica
            exclude = {a.replica for a in logical.attempts}
            target = self.router.pick(exclude=exclude)
            if target is None:
                continue
            gain = self.router.score(target) - self.router.score(origin)
            if not gain > self.config.hedge_score_margin:
                continue
            with self._lock:
                if logical.settled or logical.hedged:
                    continue
                logical.hedged = True
            try:
                self._dispatch(logical, target, hedge=True)
            except (RejectedByAdmission, _deadline.DeadlineExceeded):
                # the original attempt is still in flight; let it race
                # its own deadline rather than force an early outcome
                continue
            with self._lock:
                self.hedges_fired += 1
            _tm.REGISTRY.counter("tptpu_fleet_hedges_fired_total").inc()
            _tevents.emit(
                "hedge_fired", fromReplica=origin, toReplica=target,
                elapsedMs=round((now - logical.submitted_at) * 1e3, 3),
            )

    # ------------------------------------------------------------- pumping
    def pump_all(self) -> int:
        """One synchronous pump round across live replicas (workers=0
        mode); returns settled request count."""
        total = 0
        for i in self.live_replicas():
            total += self.services[i].pump()
        return total

    def pump_until_quiet(self, max_rounds: int = 10_000) -> int:
        total = 0
        for _ in range(max_rounds):
            n = self.pump_all()
            if n == 0:
                return total
            total += n
        return total  # pragma: no cover - bounded-loop backstop

    # --------------------------------------------------------------- state
    def stats(self) -> dict[str, Any]:
        with self._lock:
            settled = (
                self.completed + self.quarantined + self.errors
                + sum(self.shed.values())
            )
            out = {
                "replicas": len(self.services),
                "liveReplicas": len(self.services) - len(self.lost),
                "lostReplicas": sorted(self.lost),
                "admitted": self.admitted,
                "completed": self.completed,
                "quarantined": self.quarantined,
                "errors": self.errors,
                "shed": dict(self.shed),
                "rejected": dict(self.rejected),
                "outstanding": self.admitted - settled,
                "hedgesFired": self.hedges_fired,
                "hedgeDuplicates": self.hedge_duplicates,
                "orphansAdopted": self.orphans_adopted,
                "replicasLost": self.replicas_lost,
            }
        out["router"] = self.router.stats()
        out["sentinel"] = self.sentinel.stats()
        out["perReplica"] = [svc.stats() for svc in self.services]
        return out

    def reconcile(self) -> dict[str, Any]:
        """The fleet-level typed invariant plus every replica's own:
        ``reconciled`` is True only when the fleet ledger matches its
        pending set AND each replica's outstanding equals its queued +
        in-flight requests (exact at pump boundaries)."""
        with self._lock:
            settled = (
                self.completed + self.quarantined + self.errors
                + sum(self.shed.values())
            )
            outstanding = self.admitted - settled
            ok = outstanding == len(self._pending) and outstanding >= 0
            pending = len(self._pending)
        per = []
        for i, svc in enumerate(self.services):
            s = svc.stats()
            backlog = svc.queue.depth_requests() + svc._in_flight_requests
            replica_ok = s["outstanding"] == backlog
            ok = ok and replica_ok
            per.append(
                {"replica": i, "outstanding": s["outstanding"],
                 "backlog": backlog, "reconciled": replica_ok}
            )
        return {
            "outstanding": outstanding,
            "pending": pending,
            "perReplica": per,
            "reconciled": ok,
        }


def _fleet_source() -> dict[str, Any]:
    """Aggregate fleet counters across live fleets — the ``fleet`` ledger
    source of ``telemetry.render_prometheus()``."""
    out = {
        "fleets": 0, "replicas": 0, "liveReplicas": 0, "admitted": 0,
        "completed": 0, "shedTotal": 0, "rejectedTotal": 0, "errors": 0,
        "hedgesFired": 0, "hedgeDuplicates": 0, "orphansAdopted": 0,
        "replicasLost": 0,
    }
    with _LIVE_LOCK:
        refs = list(_LIVE_FLEETS)
    for ref in refs:
        fleet = ref()
        if fleet is None:
            continue
        try:
            s = fleet.stats()
        except Exception:  # a half-built fleet must not kill exposition
            continue
        out["fleets"] += 1
        out["replicas"] += s["replicas"]
        out["liveReplicas"] += s["liveReplicas"]
        out["admitted"] += s["admitted"]
        out["completed"] += s["completed"]
        out["shedTotal"] += sum(s["shed"].values())
        out["rejectedTotal"] += sum(s["rejected"].values())
        out["errors"] += s["errors"]
        out["hedgesFired"] += s["hedgesFired"]
        out["hedgeDuplicates"] += s["hedgeDuplicates"]
        out["orphansAdopted"] += s["orphansAdopted"]
        out["replicasLost"] += s["replicasLost"]
    return out


_tm.REGISTRY.register_source("fleet", _fleet_source)
