"""The standing scoring service — admission, micro-batching, deadlines,
shedding, and graceful degradation over one ``score_function`` closure.

``ScoringService`` assembles the library pieces PRs 1–7 built into the
long-lived path ROADMAP item 1 names: requests enter through a bounded
:class:`~.queue.AdmissionQueue`, assemble into micro-batches on the
:class:`~.batcher.MicroBatcher` (riding the closure's ``FusionPlanner``
buffer and banked executables — :meth:`start` pre-warms the program bank
and primes fusion), execute under the tightest member's
:class:`~.deadline.DeadlineBudget` (stage-family checkpoints inside
``local/scoring.py`` reject late requests early), and degrade through
the :class:`~.shedding.LoadShedder` tiers when queue depth, in-flight
rows, or open breakers say the service is past capacity.

Every outcome is TYPED and COUNTED — the reconciliation invariant

    admitted == completed + quarantined + shed + errors + outstanding

holds at every instant (pinned by the chaos soak tests), and
``stop(drain=True)`` quiesces cleanly: admissions close, the queue
drains, workers join, no threads leak.

Synchronous mode (``workers=0`` + :meth:`pump`) runs the whole loop on
the caller's thread with an injectable clock — the loadtest harness and
the chaos suite drive overload scenarios without a single real sleep.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
import weakref
from typing import Any, Callable

from ..analysis import schedule as _schedule
from ..resilience import faults as _faults
from ..telemetry import metrics as _tm
from . import deadline as _deadline
from .batcher import BatchPlan, MicroBatcher
from .queue import AdmissionQueue, RejectedByAdmission
from .shedding import LoadShedder, ShedConfig

log = logging.getLogger(__name__)

__all__ = ["PendingScore", "ScoreRequest", "ScoringService", "ServiceConfig"]

#: outcome labels a finished request can carry
OUTCOMES = ("completed", "quarantined", "deadline_exceeded", "stopped", "error")

#: weakrefs to live services — the ``service`` exposition source
_LIVE_SERVICES: list = []
_LIVE_LOCK = threading.Lock()


@dataclasses.dataclass
class ServiceConfig:
    """Tuning knobs (each has a matching env var documented in
    docs/serving.md)."""

    max_queue_rows: int = 2048      # admission queue bound
    max_batch_rows: int = 256       # micro-batch assembly cap
    max_wait: float = 0.005         # worker-mode assembly wait (real s)
    workers: int = 1                # 0 = synchronous pump mode
    default_deadline: float | None = None   # per-request budget seconds
    shed: ShedConfig = dataclasses.field(default_factory=ShedConfig)


class PendingScore:
    """Future-like handle for one submitted request."""

    __slots__ = (
        "_event", "results", "error", "outcome",
        "submitted_at", "completed_at",
    )

    def __init__(self, submitted_at: float):
        self._event = threading.Event()
        self.results: list[dict] | None = None
        self.error: BaseException | None = None
        self.outcome: str | None = None
        self.submitted_at = submitted_at
        self.completed_at: float | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> list[dict]:
        """The per-row results; raises the typed rejection on a shed
        request (quarantined requests RETURN — their rows carry default
        predictions, which is the graceful-degradation contract)."""
        if not self._event.wait(timeout):
            raise TimeoutError("request not finished")
        if self.error is not None:
            raise self.error
        return self.results  # type: ignore[return-value]

    def latency(self) -> float | None:
        if self.completed_at is None:
            return None
        return self.completed_at - self.submitted_at


class ScoreRequest:
    __slots__ = (
        "rows", "budget", "handle", "enqueued_at", "explain", "on_settled",
    )

    def __init__(
        self,
        rows: list[dict],
        budget: _deadline.DeadlineBudget | None,
        handle: PendingScore,
        enqueued_at: float,
        explain: int = 0,
        on_settled: Callable[["ScoreRequest"], None] | None = None,
    ):
        self.rows = rows
        self.budget = budget
        self.handle = handle
        self.enqueued_at = enqueued_at
        self.explain = explain
        # fleet seam: called with the settled request AFTER its outcome is
        # stamped and its event set, outside every service lock
        self.on_settled = on_settled


class ScoringService:
    """Long-lived async scoring over one score-function closure."""

    def __init__(
        self,
        score_fn: Callable,
        config: ServiceConfig | None = None,
        clock: Callable[[], float] | None = None,
        replica: Any = None,
    ):
        self.score_fn = score_fn
        self.config = config or ServiceConfig()
        self.clock = clock if clock is not None else time.monotonic
        # fleet identity: replica-keyed faults match against this via the
        # ambient replica_scope the batch loop installs (None = standalone)
        self.replica = replica
        self.queue = AdmissionQueue(self.config.max_queue_rows)
        self.batcher = MicroBatcher(
            self.queue, self.config.max_batch_rows, clock=self.clock
        )
        self.shedder = LoadShedder(
            self.config.shed, capacity=self.config.max_queue_rows
        )
        # instrumented-lock seam: the literal is the static analyzer's
        # canonical key (analysis/concurrency.py + schedule.py)
        self._lock = _schedule.make_lock(
            "serving/service.py:ScoringService._lock"
        )
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._started = False
        self._in_flight_rows = 0
        self._in_flight_requests = 0
        # harness hook: called with (real_seconds, simulated_seconds,
        # executed_rows) after each batch execution, BEFORE completions
        # are stamped — the loadtest harness advances its virtual clock
        # here so latencies include service time without any real sleeps
        self.on_batch_cost: Callable[[float, float, int], None] | None = None
        # typed outcome counters (mutations under self._lock)
        self.admitted = 0
        self.completed = 0
        self.quarantined = 0
        self.errors = 0
        self.batches = 0
        self.shed: dict[str, int] = {"deadline_exceeded": 0, "stopped": 0}
        self.rejected: dict[str, int] = {
            "queue_full": 0, "shedding": 0, "stopped": 0, "deadline": 0,
        }
        with _LIVE_LOCK:
            # r is a weakref deref — runs no user code, takes no locks
            _LIVE_SERVICES[:] = [
                r for r in _LIVE_SERVICES if r() is not None  # tp: disable=TPC004
            ]
            _LIVE_SERVICES.append(weakref.ref(self))

    # ------------------------------------------------------------ lifecycle
    def start(self, wait_warmup: bool = False, timeout: float = 60.0) -> "ScoringService":
        """Idempotent: pre-warms the banked scoring executables
        (``compiler/warmup.py`` — including the fused_serve programs),
        primes the closure's fusion planner from fit-static widths, builds
        the fused scoring graph so batch #1 pays no plan compilation, and
        launches the worker threads."""
        from ..compiler import warmup as _warmup

        with self._lock:
            if self._started:
                return self
            self._started = True
        _warmup.start_warmup(_warmup.SCORE_PROGRAMS, scope="score")
        if wait_warmup:
            _warmup.join_warmup(timeout=timeout)
        fusion = getattr(self.score_fn, "fusion", None)
        if fusion is not None:
            try:
                fusion.prime()
            except Exception:  # priming is an optimization, never fatal
                log.debug("fusion prime failed", exc_info=True)
        prime_fused = getattr(self.score_fn, "prime_fused", None)
        if prime_fused is not None:
            try:
                prime_fused()
            except Exception:  # never fatal — the staged loop remains
                log.debug("fused prime failed", exc_info=True)
        for i in range(self.config.workers):
            th = threading.Thread(
                target=self._worker, daemon=True, name=f"tptpu-serve-{i}"
            )
            self._threads.append(th)
            th.start()
        return self

    def stop(
        self,
        drain: bool = True,
        timeout: float = 30.0,
        mode: str = "drain",
    ) -> list[ScoreRequest]:
        """Quiesce: close admissions, drain (or shed) the queue, join
        workers. After stop() the queue is empty, every admitted request
        has a typed outcome, and no service thread is alive. The
        queue-depth / in-flight gauges reset to zero on EVERY exit path
        (including the worker-leak alarm) — a stopped service must not
        freeze its last pre-quiesce value into the Prometheus exposition
        as if rows were still in flight.

        ``mode="reject_new_then_drain"`` is the fleet decommission path: a
        submit racing the stop gets the typed ``RejectedByAdmission
        ("stopped")`` the instant admissions close, queued requests are
        NOT executed here — each is settled ``stopped`` (so this replica's
        own ledger reconciles) and returned for the fleet to adopt onto
        survivors. The default mode returns ``[]``."""
        if mode not in ("drain", "reject_new_then_drain"):
            raise ValueError(f"unknown stop mode {mode!r}")
        orphans: list[ScoreRequest] = []
        try:
            self.queue.close()
            self._stop.set()
            for th in self._threads:
                th.join(timeout=timeout)
                if th.is_alive():  # pragma: no cover - the deadlock alarm
                    raise RuntimeError(f"service worker {th.name} leaked")
            self._threads.clear()
            if drain and mode == "drain":
                while self.pump():
                    pass
            for req in self.queue.drain():
                self._finish(
                    req, "stopped", error=RejectedByAdmission("stopped")
                )
                if mode == "reject_new_then_drain":
                    orphans.append(req)
            self.shedder.reset()
        finally:
            _tm.REGISTRY.gauge("tptpu_serve_queue_depth").set(0)
            _tm.REGISTRY.gauge("tptpu_serve_in_flight_rows").set(0)
        return orphans

    def __enter__(self) -> "ScoringService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ admission
    def submit(
        self,
        rows: dict | list[dict],
        deadline: float | None = None,
        explain: int = 0,
        on_settled: Callable[[ScoreRequest], None] | None = None,
    ) -> PendingScore:
        """Admit one request (one row dict, or a small list scored as a
        unit). ``explain=k`` asks for top-k LOCO attributions beside each
        row's scores (carried through micro-batch assembly; under load the
        shedder drops explain work first, so the rows may come back with
        ``attributions: None``). Raises :class:`RejectedByAdmission`
        (queue full / shedding tier / stopped) or
        :class:`~.deadline.DeadlineExceeded` (the budget cannot cover the
        pipeline p95 — including the explain family's p95 for explain
        requests — even before queuing) — admission control rejects
        early, it never blocks."""
        if isinstance(rows, dict):
            rows = [rows]
        if not rows:
            raise ValueError("empty request")
        explain = int(explain or 0)
        if explain < 0:
            raise ValueError(f"explain must be >= 0, got {explain}")
        now = self.clock()
        if self._stop.is_set() or self.queue.closed:
            self._count_rejected("stopped")
            raise RejectedByAdmission("stopped")
        # backpressure: the tier reflects THIS request's world, not the
        # last batch's (bursts between pumps must start rejecting)
        self._update_shedder()
        if self.shedder.reject_admissions:
            self._count_rejected("shedding")
            raise RejectedByAdmission(
                "shedding", f"load {self.shedder.load:.3f}"
            )
        budget = None
        secs = deadline if deadline is not None else self.config.default_deadline
        if secs is not None:
            budget = _deadline.DeadlineBudget(secs, clock=self.clock, started=now)
            # explain requests must budget for the explain family too —
            # its p95 rides the same serve-latency histograms
            required = _deadline.pipeline_p95()
            if explain:
                required += _deadline.family_p95("explain")
            if not budget.covers(required=required):
                self._count_rejected("deadline")
                _tm.REGISTRY.counter(
                    "tptpu_serve_deadline_exceeded_total"
                ).inc()
                raise _deadline.DeadlineExceeded(
                    "admission", budget.remaining(), required
                )
        handle = PendingScore(submitted_at=now)
        req = ScoreRequest(
            list(rows), budget, handle, enqueued_at=now, explain=explain,
            on_settled=on_settled,
        )
        try:
            # offer + admitted count under ONE critical section: a worker
            # can pop and settle the request the instant offer() publishes
            # it, and the reconciliation invariant (admitted >= settled at
            # every instant) must never observe the settle before the
            # admission. Safe nesting: nothing acquires self._lock while
            # holding the queue lock.
            with self._lock:
                self.queue.offer(req)
                self.admitted += 1
        except RejectedByAdmission as e:
            self._count_rejected(e.reason)
            raise
        _tm.REGISTRY.counter("tptpu_serve_admitted_total").inc()
        return handle

    def _count_rejected(self, reason: str) -> None:
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1
        _tm.REGISTRY.counter("tptpu_serve_rejected_total").inc()

    # ------------------------------------------------------------ execution
    def pump(self) -> int:
        """Synchronously assemble and execute ONE micro-batch on the
        caller's thread; returns the number of requests it settled (0 when
        the queue was empty). The workerless twin of the service loop —
        the loadtest harness's whole engine."""
        plan = self.batcher.next_batch(wait=0.0)
        if plan is None or plan.empty:
            self._update_shedder()
            return 0
        return self._execute(plan)

    def _worker(self) -> None:
        cfg = self.config
        while True:
            plan = self.batcher.next_batch(wait=max(cfg.max_wait, 1e-3))
            if plan is not None and not plan.empty:
                try:
                    self._execute(plan)
                except Exception:  # pragma: no cover - belt and braces
                    log.exception("service batch execution failed")
            elif self._stop.is_set() and self.queue.depth_requests() == 0:
                return

    def _execute(self, plan: BatchPlan) -> int:
        for req in plan.expired:
            self._finish(
                req, "deadline_exceeded",
                error=_deadline.DeadlineExceeded(
                    "queue", -1.0 if req.budget is None
                    else req.budget.remaining(),
                    _deadline.pipeline_p95(),
                ),
            )
            _tm.REGISTRY.counter("tptpu_serve_deadline_exceeded_total").inc()
        if not plan.requests:
            self._update_shedder()
            return len(plan.expired)
        n_rows = len(plan.rows)
        with self._lock:
            self._in_flight_rows += n_rows
            self._in_flight_requests += len(plan.requests)
            self.batches += 1
        _tm.REGISTRY.gauge("tptpu_serve_in_flight_rows").set(
            self._in_flight_rows
        )
        self._update_shedder()
        # deadline outcomes are PER REQUEST, not per batch: the batch runs
        # under its tightest member's budget, and when that budget trips a
        # stage-family checkpoint mid-execution, only the members whose own
        # budget can no longer cover the pipeline are shed — the rest
        # (including members that never asked for a deadline) re-execute
        # without the tripped member. Each retry sheds at least the
        # tripping member, so the loop is bounded by the member count.
        pending = list(plan.requests)
        while pending:
            rows = [r for req in pending for r in req.rows]
            budget = None
            for req in pending:
                b = req.budget
                if b is not None and (
                    budget is None or b.remaining() < budget.remaining()
                ):
                    budget = b
            # the batch explains at the LARGEST member k (co-batched
            # members share one sweep); each member's slice is trimmed
            # back to its own k below
            explain_k = max((req.explain for req in pending), default=0)
            fault_plan = _faults.active()
            sim0 = (
                fault_plan.simulated_seconds if fault_plan is not None
                else 0.0
            )
            t0 = time.perf_counter()
            out: list[dict] | None = None
            error: BaseException | None = None
            try:
                with _faults.replica_scope(self.replica), \
                        _deadline.active(budget):
                    out = (
                        self.score_fn.batch(rows, explain=explain_k)
                        if explain_k
                        else self.score_fn.batch(rows)
                    )
            except _deadline.DeadlineExceeded as e:
                error = e
            except Exception as e:  # contained: one batch, typed outcome
                error = e
                log.warning(
                    "service batch of %d rows failed (%s: %s)",
                    len(rows), type(e).__name__, e,
                )
            real = time.perf_counter() - t0
            sim = (
                fault_plan.simulated_seconds - sim0
                if fault_plan is not None else 0.0
            )
            if self.on_batch_cost is not None:
                self.on_batch_cost(real, sim, len(rows))
            if error is None:
                quarantined_rows = self._quarantined_rows()
                off = 0
                for req in pending:
                    k = len(req.rows)
                    req_out = out[off:off + k]
                    hit = any(
                        i in quarantined_rows for i in range(off, off + k)
                    )
                    off += k
                    if explain_k:
                        _fit_attributions(req_out, req.explain)
                    self._finish(
                        req, "quarantined" if hit else "completed",
                        results=req_out,
                    )
                break
            if not isinstance(error, _deadline.DeadlineExceeded):
                for req in pending:
                    self._finish(req, "error", error=error)
                break
            # shed exactly the members whose own budget is now spent (the
            # tripping tightest budget is always among them); guarantee
            # progress even if covers() flickers back true
            required = _deadline.pipeline_p95()
            spent = [
                req for req in pending
                if req.budget is not None
                and not req.budget.covers(required=required)
            ]
            if not spent:
                spent = [
                    req for req in pending if req.budget is budget
                ] or pending[:1]
            for req in spent:
                self._finish(req, "deadline_exceeded", error=error)
                _tm.REGISTRY.counter(
                    "tptpu_serve_deadline_exceeded_total"
                ).inc()
            pending = [req for req in pending if req.handle.outcome is None]
        with self._lock:
            self._in_flight_rows -= n_rows
            self._in_flight_requests -= len(plan.requests)
        _tm.REGISTRY.gauge("tptpu_serve_in_flight_rows").set(
            self._in_flight_rows
        )
        self._update_shedder()
        return len(plan.requests) + len(plan.expired)

    def _quarantined_rows(self) -> set[int]:
        """Flat row indices the closure quarantined in the batch it just
        scored (thread-local per-batch view of the QuarantineLog)."""
        qlog = getattr(self.score_fn, "quarantine", None)
        if qlog is None:
            return set()
        try:
            return qlog.batch_rows()
        except Exception:
            return set()

    def _finish(
        self,
        req: ScoreRequest,
        outcome: str,
        results: list[dict] | None = None,
        error: BaseException | None = None,
    ) -> None:
        h = req.handle
        h.results = results
        h.error = error
        h.outcome = outcome
        h.completed_at = self.clock()
        with self._lock:
            if outcome == "completed":
                self.completed += 1
            elif outcome == "quarantined":
                self.quarantined += 1
            elif outcome == "error":
                self.errors += 1
            else:
                self.shed[outcome] = self.shed.get(outcome, 0) + 1
        if outcome == "completed":
            _tm.REGISTRY.counter("tptpu_serve_completed_total").inc()
        elif outcome in ("deadline_exceeded", "stopped"):
            _tm.REGISTRY.counter("tptpu_serve_shed_total").inc()
        h._event.set()
        cb = req.on_settled
        if cb is not None:
            # outside every service lock (the callback may take the fleet
            # lock; lock-order discipline forbids nesting it under ours)
            try:
                cb(req)
            except Exception:  # a broken observer must not kill the loop
                log.exception("on_settled callback failed")

    # -------------------------------------------------------------- signals
    def _breaker_open_fraction(self) -> float:
        breakers = getattr(self.score_fn, "breakers", None)
        if not breakers:
            return 0.0
        states = [br.state for br in list(breakers.values())]
        return states.count("open") / len(states) if states else 0.0

    def _update_shedder(self) -> None:
        self.shedder.update(
            self.queue.depth_rows(),
            self._in_flight_rows,
            self._breaker_open_fraction(),
        )

    # ---------------------------------------------------------------- state
    def stats(self) -> dict[str, Any]:
        """Typed counters + the reconciliation fields. ``outstanding`` is
        admitted-but-unfinished (queued or in flight); at quiesce it is 0
        and ``admitted == completed + quarantined + shed + errors``."""
        with self._lock:
            settled = (
                self.completed + self.quarantined + self.errors
                + sum(self.shed.values())
            )
            return {
                "admitted": self.admitted,
                "completed": self.completed,
                "quarantined": self.quarantined,
                "errors": self.errors,
                "batches": self.batches,
                "shed": dict(self.shed),
                "rejected": dict(self.rejected),
                "outstanding": self.admitted - settled,
                "queueDepthRows": self.queue.depth_rows(),
                "queuePeakRows": self.queue.peak_rows,
                "inFlightRows": self._in_flight_rows,
                "shedding": self.shedder.stats(),
                "batcher": self.batcher.stats(),
            }


def _fit_attributions(rows_out: list[dict], k: int) -> None:
    """Reconcile a member's slice of a shared explain sweep with its OWN
    request: members that never asked lose the key, members that asked
    for fewer than the batch's k keep their |contribution|-largest k
    (row dicts are per-row and slices are disjoint, so mutation is
    safe)."""
    for r in rows_out:
        if k <= 0:
            r.pop("attributions", None)
            continue
        a = r.get("attributions")
        if a and len(a) > k:
            r["attributions"] = dict(
                sorted(a.items(), key=lambda kv: -abs(kv[1]))[:k]
            )


def _service_source() -> dict[str, Any]:
    """Aggregate standing-service counters across live services — the
    ``service`` ledger source of ``telemetry.render_prometheus()``."""
    out = {
        "services": 0, "admitted": 0, "completed": 0, "quarantined": 0,
        "shedTotal": 0, "rejectedTotal": 0, "errors": 0,
        "queueDepthRows": 0, "inFlightRows": 0, "shedTier": 0,
    }
    with _LIVE_LOCK:
        refs = list(_LIVE_SERVICES)
    for ref in refs:
        svc = ref()
        if svc is None:
            continue
        try:
            s = svc.stats()
        except Exception:  # a half-built service must not kill exposition
            continue
        out["services"] += 1
        out["admitted"] += s["admitted"]
        out["completed"] += s["completed"]
        out["quarantined"] += s["quarantined"]
        out["shedTotal"] += sum(s["shed"].values())
        out["rejectedTotal"] += sum(s["rejected"].values())
        out["errors"] += s["errors"]
        out["queueDepthRows"] += s["queueDepthRows"]
        out["inFlightRows"] += s["inFlightRows"]
        out["shedTier"] = max(out["shedTier"], s["shedding"]["tier"])
    return out


_tm.REGISTRY.register_source("service", _service_source)
