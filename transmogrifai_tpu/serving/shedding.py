"""Backpressure + tiered load shedding with hysteresis.

Under sustained overload a scorer must degrade in ORDER — cheapest
observability first, admissions last — and re-admit smoothly instead of
flapping. The :class:`LoadShedder` computes a load signal from queue
depth, in-flight rows, and the fraction of open circuit breakers, and
maps it onto four cumulative tiers:

====  ================  ============================================
tier  name              sheds
====  ================  ============================================
1     ``shed_explain``  LOCO attribution sweeps (``explain=k`` work)
2     ``shed_detail``   per-stage detail spans (telemetry only)
3     ``shed_drift``    drift-window observation (monitoring only)
4     ``reject``        new admissions (typed ``RejectedByAdmission``)
====  ================  ============================================

Explain sweeps are the first casualty: they multiply the predict cost by
the lane count, and a late explanation is worth strictly less than an
on-time score — so attribution work yields before any other
observability does (rows shed this way are counted per row on
``tptpu_serve_explain_shed_total`` and the attribution ledger).

Each tier has an ENTER threshold and a strictly lower EXIT threshold
(hysteresis): a tier engages when load rises to its enter point and only
disengages once load falls below its exit point, so a service hovering
at a boundary does not oscillate between shedding and re-admitting on
every batch. Every transition increments the tier-transition counter and
emits a ``load_shed`` event.

Tier 1 raises the process-wide explain-shed flag ``local/scoring.py``
checks before an attribution sweep; tier 2 suppresses detail spans
through ``telemetry.spans.set_detail_suppressed`` (the scoring loop
already consults ``stage_detail``); tier 3 raises the drift-shed flag
checked before drift-window observation. All are restored the moment the
shedder drops back below the exit threshold.
"""
from __future__ import annotations

import dataclasses

from ..analysis import schedule as _schedule
from ..telemetry import events as _tevents
from ..telemetry import metrics as _tm
from ..telemetry import spans as _tspans

__all__ = [
    "LoadShedder", "ShedConfig", "TIER_NAMES", "drift_shed", "explain_shed",
]

TIER_NAMES = ("normal", "shed_explain", "shed_detail", "shed_drift", "reject")

# process-wide shed flags are REFCOUNTS of shedder contributions, not
# booleans (TPL001: mutations hold the lock): two standing services in
# one process each contribute while at/above the tier, so an idle
# service's transition (or reset) can never clear the suppression an
# overloaded one still needs. Reads go through the lock-free accessors —
# a stale read during a transition costs one extra/missing drift
# observation or explain sweep, never correctness.
_LOCK = _schedule.make_lock("serving/shedding.py:_LOCK")
_STATE = {"explain": 0, "detail": 0, "drift": 0}


def explain_shed() -> bool:
    """True while ANY shedder is at tier >= 1 (scoring skips the
    attribution sweep for the batch — explain is the first casualty)."""
    return _STATE["explain"] > 0


def drift_shed() -> bool:
    """True while ANY shedder is at tier >= 3 (scoring skips the drift
    window observe for the batch)."""
    return _STATE["drift"] > 0


def reset_process_flags_for_tests() -> None:
    """Zero the process-wide shed refcounts and lift span suppression.

    Test isolation only: a shedder abandoned mid-tier (no ``reset()``)
    leaks its contribution into ``_STATE``; production code must use
    :meth:`LoadShedder.reset` so co-resident services keep theirs."""
    with _LOCK:
        _STATE["explain"] = 0
        _STATE["detail"] = 0
        _STATE["drift"] = 0
    _tspans.set_detail_suppressed(False)


def _shift(kind: str, delta: int) -> None:
    """Move one shedder's contribution to a process flag; applies the
    boolean to the spans plane when the count crosses zero. Caller holds
    the shedder's own lock; this takes _LOCK then (for detail) the spans
    lock — both leaves, no cycle."""
    if not delta:
        return
    with _LOCK:
        _STATE[kind] = max(0, _STATE[kind] + delta)
        active = _STATE[kind] > 0
    if kind == "detail":
        _tspans.set_detail_suppressed(active)


@dataclasses.dataclass
class ShedConfig:
    """Tier thresholds as fractions of queue capacity (load = (queued +
    in-flight rows) / capacity + breaker_weight * fraction of breakers
    open). Enter > exit per tier = the hysteresis band."""

    explain_enter: float = 0.35
    explain_exit: float = 0.20
    detail_enter: float = 0.50
    detail_exit: float = 0.35
    drift_enter: float = 0.70
    drift_exit: float = 0.50
    reject_enter: float = 0.90
    reject_exit: float = 0.65
    breaker_weight: float = 0.5

    def __post_init__(self) -> None:
        pairs = (
            ("explain", self.explain_enter, self.explain_exit),
            ("detail", self.detail_enter, self.detail_exit),
            ("drift", self.drift_enter, self.drift_exit),
            ("reject", self.reject_enter, self.reject_exit),
        )
        for name, enter, exit_ in pairs:
            if not 0.0 < exit_ < enter:
                raise ValueError(
                    f"{name}: need 0 < exit ({exit_}) < enter ({enter})"
                )

    def enter_for(self, tier: int) -> float:
        return (
            self.explain_enter, self.detail_enter, self.drift_enter,
            self.reject_enter,
        )[tier - 1]

    def exit_for(self, tier: int) -> float:
        return (
            self.explain_exit, self.detail_exit, self.drift_exit,
            self.reject_exit,
        )[tier - 1]


class LoadShedder:
    """Hysteretic tier controller for one service (thread-safe)."""

    def __init__(self, config: ShedConfig | None = None, capacity: int = 2048):
        self.config = config or ShedConfig()
        self.capacity = max(1, capacity)
        self._lock = _schedule.make_lock(
            "serving/shedding.py:LoadShedder._lock"
        )
        self.tier = 0
        self.load = 0.0
        self.transitions = 0
        self.tier_entries = {name: 0 for name in TIER_NAMES[1:]}

    # ------------------------------------------------------------- update
    def update(
        self, queued_rows: int, in_flight_rows: int, breakers_open_frac: float
    ) -> int:
        """Recompute the tier from the current load signal; applies the
        side effects (span suppression, drift flag, event) on change and
        returns the new tier."""
        load = (
            (queued_rows + in_flight_rows) / self.capacity
            + self.config.breaker_weight * breakers_open_frac
        )
        with self._lock:
            self.load = load
            tier = self.tier
            # climb through every tier whose ENTER threshold load reached
            while tier < 4 and load >= self.config.enter_for(tier + 1):
                tier += 1
            # descend only below the EXIT threshold (hysteresis)
            while tier > 0 and load < self.config.exit_for(tier):
                tier -= 1
            if tier == self.tier:
                return tier
            prev, self.tier = self.tier, tier
            self.transitions += 1
            if tier > prev:
                for t in range(prev + 1, tier + 1):
                    self.tier_entries[TIER_NAMES[t]] += 1
            # side effects INSIDE the lock: two concurrent updates must
            # apply their contribution shifts in transition order, or a
            # 0→2 racing a 2→0 would leave the process flags wrong.
            # Safe: the shift/metrics/event locks taken below never wrap
            # an acquisition of this shedder's lock
            _shift("explain", int(tier >= 1) - int(prev >= 1))
            _shift("detail", int(tier >= 2) - int(prev >= 2))
            _shift("drift", int(tier >= 3) - int(prev >= 3))
            _tm.REGISTRY.counter("tptpu_serve_shed_transitions_total").inc()
            _tm.REGISTRY.gauge("tptpu_serve_shed_tier").set(tier)
            _tevents.emit(
                "load_shed", tier=TIER_NAMES[tier], previous=TIER_NAMES[prev],
                load=round(load, 4),
            )
        return tier

    # ------------------------------------------------------------- state
    @property
    def reject_admissions(self) -> bool:
        return self.tier >= 4

    def reset(self) -> None:
        """Back to normal (service shutdown) — withdraws THIS shedder's
        contribution to the process flags (another service still past its
        thresholds keeps its suppression)."""
        with self._lock:
            prev, self.tier = self.tier, 0
            self.load = 0.0
            _shift("explain", -int(prev >= 1))
            _shift("detail", -int(prev >= 2))
            _shift("drift", -int(prev >= 3))
        _tm.REGISTRY.gauge("tptpu_serve_shed_tier").set(0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "tier": self.tier,
                "tierName": TIER_NAMES[self.tier],
                "load": round(self.load, 4),
                "transitions": self.transitions,
                "tierEntries": dict(self.tier_entries),
            }
