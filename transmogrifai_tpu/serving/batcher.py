"""Dynamic micro-batch assembly over the admission queue.

One executed batch amortizes the fixed costs of the scoring closure —
the schema sentinel's type census, the fused ``[N, width]`` featurize
plane (``featurize/engine.py``), and the bucketed compiled predict — so
the batcher greedily assembles the largest batch available up to
``max_rows``, without holding latency hostage: it never WAITS for a
fuller batch beyond the (real-time, worker-mode) ``max_wait``; the
synchronous pump path takes whatever is queued right now.

Assembly also performs the second deadline gate: members whose budget
expired while queuing, or whose remaining time no longer covers the
pipeline p95 (:func:`serving.deadline.pipeline_p95`), are split out as
``expired`` — the service sheds them with typed ``DeadlineExceeded``
outcomes instead of spending a dispatch on them. The survivors' queue
wait lands as one ``serve/queue`` span per assembled batch.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable

from ..telemetry import spans as _tspans
from . import deadline as _deadline
from .queue import AdmissionQueue

__all__ = ["BatchPlan", "MicroBatcher"]


@dataclasses.dataclass
class BatchPlan:
    """One assembled micro-batch: live members, their flattened rows, and
    the members shed at assembly time."""

    requests: list[Any]
    rows: list[dict]
    expired: list[Any]
    #: tightest member budget (installed around the batch execution)
    budget: Any | None
    max_wait_s: float = 0.0

    @property
    def empty(self) -> bool:
        return not self.requests and not self.expired


class MicroBatcher:
    """Assembles :class:`BatchPlan`\\ s from an :class:`AdmissionQueue`."""

    def __init__(
        self,
        queue: AdmissionQueue,
        max_rows: int = 256,
        clock: Callable[[], float] | None = None,
    ):
        import time

        self.queue = queue
        self.max_rows = max(1, max_rows)
        self.clock = clock if clock is not None else time.monotonic
        self.batches_assembled = 0
        self.rows_assembled = 0

    def next_batch(self, wait: float = 0.0) -> BatchPlan | None:
        """One batch off the queue head, or None when nothing is queued
        (after at most ``wait`` real seconds in worker mode)."""
        popped = self.queue.pop_many(self.max_rows, wait=wait)
        if not popped:
            return None
        now = self.clock()
        live: list[Any] = []
        rows: list[dict] = []
        expired: list[Any] = []
        budget = None
        max_wait = 0.0
        # one p95 lookup per assembled batch, not per member
        required = _deadline.pipeline_p95()
        for req in popped:
            enq = getattr(req, "enqueued_at", None)
            if enq is not None:
                max_wait = max(max_wait, now - enq)
            b = getattr(req, "budget", None)
            if b is not None and not b.covers(required=required):
                expired.append(req)
                continue
            live.append(req)
            rows.extend(req.rows)
            if b is not None and (
                budget is None or b.remaining() < budget.remaining()
            ):
                budget = b
        if live:
            self.batches_assembled += 1
            self.rows_assembled += len(rows)
            # queue-wait observability: one span per assembled batch, timed
            # on the service clock (virtual under the loadtest harness)
            _tspans.record_span(
                "serve/queue", now - max_wait, max_wait,
                rows=len(rows), requests=len(live),
            )
        return BatchPlan(
            requests=live, rows=rows, expired=expired, budget=budget,
            max_wait_s=max_wait,
        )

    def stats(self) -> dict:
        return {
            "batchesAssembled": self.batches_assembled,
            "rowsAssembled": self.rows_assembled,
            "maxBatchRows": self.max_rows,
            "pipelineP95Ms": round(_deadline.pipeline_p95() * 1e3, 3),
        }
