"""Open-loop serve load testing on a virtual clock — seeded, sleepless.

Closed-loop benchmarks (issue the next request when the last returns)
cannot see overload: arrival pressure collapses to service capacity and
latency looks flat right up to the cliff. This harness is OPEN-LOOP: a
seeded arrival schedule at a fixed rate (optionally multiplied through
``FaultPlan.burst_arrivals`` windows) submits requests regardless of how
the service is doing, so queue growth, deadline sheds, and admission
rejections appear exactly as they would under real traffic.

Nothing sleeps. Time is a :class:`VirtualClock` the service runs on:

* arrivals advance the clock to their scheduled instant;
* each pumped batch advances it by the batch's measured REAL execution
  seconds (bench mode) or an injected ``service_time`` (tests) plus any
  ``slow_stage`` SIMULATED seconds the fault plan charged — so chaos
  latency shows up in the percentiles without ever sleeping;
* per-request latency = completion − arrival, both virtual.

The emitted report carries p50/p95/p99 latency, shed rate, goodput
(healthy completions per virtual second), the typed outcome taxonomy,
and the reconciliation verdict — the shape ``bench.py serve-loadtest``
lands in ``BENCH_r06.json`` so overload behavior joins the regression
trail.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

from ..resilience import faults as _faults
from ..telemetry import spans as _tspans
from . import deadline as _deadline
from .queue import RejectedByAdmission
from .service import ScoringService, ServiceConfig

__all__ = ["VirtualClock", "LoadSchedule", "run_loadtest"]


class VirtualClock:
    """Monotonic virtual time; advanced explicitly by the harness."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError("virtual time cannot go backwards")
        self.now += dt
        return self.now


@dataclasses.dataclass
class LoadSchedule:
    """Seeded open-loop arrival schedule."""

    rate: float                 # nominal arrivals per virtual second
    duration: float             # virtual seconds of arrivals
    seed: int = 0

    def arrivals(self, plan: "_faults.FaultPlan | None" = None) -> list[float]:
        """Arrival instants: inter-arrival 1/(rate*mult) where ``mult`` is
        the fault plan's burst multiplier at the current instant — the
        same plan replays the same burst every run."""
        if self.rate <= 0 or self.duration <= 0:
            raise ValueError("need rate > 0 and duration > 0")
        out: list[float] = []
        t = 0.0
        while True:
            mult = plan.arrival_multiplier(t) if plan is not None else 1.0
            t += 1.0 / (self.rate * mult)
            if t >= self.duration:
                return out
            out.append(t)


def run_loadtest(
    score_fn: Callable,
    rows: list[dict],
    rate: float,
    duration: float,
    seed: int = 0,
    deadline: float | None = None,
    config: ServiceConfig | None = None,
    service_time: Callable[[int], float] | None = None,
    plan: "_faults.FaultPlan | None" = None,
) -> dict[str, Any]:
    """One open-loop run; returns the metrics report (see module
    docstring). ``rows`` is the pool the seeded rng draws request payloads
    from; ``service_time`` (a callable invoked once per executed batch)
    replaces measured real execution time with deterministic virtual
    seconds — tests and regression benches use a constant; ``plan`` is an
    ALREADY INSTALLED FaultPlan whose bursts/slow stages drive the
    chaos."""
    rng = np.random.default_rng(seed)
    clock = VirtualClock()
    # deterministic mode (injected service_time) must virtualize the
    # TELEMETRY clock too: the serve-family histograms feed the deadline
    # checkpoints' p95s, and left on the real clock they record host
    # execution speed — a loaded machine would shed requests a fast one
    # completes, making the "machine-independent" report host-dependent.
    # On the virtual clock the family seconds are exactly the slow_stage
    # simulated charges. Bench mode (service_time=None) keeps real time.
    prev_spans_clock = _tspans.get_clock()
    if service_time is not None:
        _tspans.set_clock(clock)
    cfg = config or ServiceConfig()
    cfg = dataclasses.replace(cfg, workers=0)
    if deadline is not None:
        cfg = dataclasses.replace(cfg, default_deadline=deadline)
    service = ScoringService(score_fn, cfg, clock=clock)

    def _advance(real: float, sim: float, rows_executed: int) -> None:
        base = (
            service_time(rows_executed) if service_time is not None else real
        )
        clock.advance(base + sim)

    service.on_batch_cost = _advance
    service.start()
    schedule = LoadSchedule(rate=rate, duration=duration, seed=seed)
    arrivals = schedule.arrivals(plan)
    idx = rng.integers(0, len(rows), size=max(1, len(arrivals)))
    handles = []
    max_depth = 0
    # discrete-event engine: ONE worker whose busy/free timeline is
    # ``free_at``. A batch starts the moment the worker is free and work
    # is queued; arrivals scheduled DURING a batch enqueue behind it
    # (that is what makes the loop open: queue depth, deadline burn, and
    # shed tiers grow exactly as they would under real sustained traffic,
    # instead of the worker magically draining between every arrival).
    free_at = 0.0

    def _serve_until(horizon: float | None) -> float:
        """Run batches whose start instant lands before ``horizon``
        (None = run until the queue drains); returns the updated
        ``free_at``."""
        busy = free_at
        while service.queue.depth_requests() > 0:
            start = max(busy, clock.now)
            if horizon is not None and start >= horizon:
                break
            clock.advance(start - clock.now)
            if not service.pump():  # everything left expired/settled
                break
            busy = clock.now  # pump advanced by the batch's cost
        return busy

    try:
        for i, t in enumerate(arrivals):
            free_at = _serve_until(t)
            clock.advance(max(0.0, t - clock.now))
            try:
                handles.append(service.submit(dict(rows[int(idx[i])])))
            except (RejectedByAdmission, _deadline.DeadlineExceeded):
                pass  # counted in the service's typed rejection taxonomy
            max_depth = max(max_depth, service.queue.depth_rows())
        # arrivals over: drain whatever is still queued
        _serve_until(None)
        while service.pump():
            pass
        service.stop(drain=True)
    finally:
        _tspans.set_clock(prev_spans_clock)
    end = clock.now

    stats = service.stats()
    latencies = sorted(
        h.latency() for h in handles
        if h.outcome in ("completed", "quarantined") and h.latency() is not None
    )

    def _pct(q: float) -> float | None:
        if not latencies:
            return None
        return round(
            float(np.percentile(latencies, q, method="nearest")) * 1e3, 3
        )

    shed_total = sum(stats["shed"].values())
    rejected_total = sum(stats["rejected"].values())
    settled = (
        stats["completed"] + stats["quarantined"] + stats["errors"] + shed_total
    )
    offered = len(arrivals)
    return {
        "rate": rate,
        "duration_s": duration,
        "seed": seed,
        "offered": offered,
        "admitted": stats["admitted"],
        "completed": stats["completed"],
        "quarantined": stats["quarantined"],
        "errors": stats["errors"],
        "shed": dict(stats["shed"]),
        "rejected": dict(stats["rejected"]),
        "shed_total": shed_total,
        "rejected_total": rejected_total,
        "shed_rate": (
            round((shed_total + rejected_total) / offered, 4) if offered else 0.0
        ),
        "latency_ms": {"p50": _pct(50), "p95": _pct(95), "p99": _pct(99)},
        "goodput_rows_per_s": (
            round(stats["completed"] / end, 2) if end > 0 else 0.0
        ),
        "max_queue_depth_rows": max(max_depth, stats["queuePeakRows"]),
        "batches": stats["batches"],
        "shed_tier_entries": stats["shedding"]["tierEntries"],
        "virtual_end_s": round(end, 4),
        # the hard invariant the chaos suite pins: every admitted request
        # settled with exactly one typed outcome, nothing leaked
        "reconciled": (
            stats["admitted"] == settled and stats["outstanding"] == 0
        ),
    }
