"""Open-loop serve load testing on a virtual clock — seeded, sleepless.

Closed-loop benchmarks (issue the next request when the last returns)
cannot see overload: arrival pressure collapses to service capacity and
latency looks flat right up to the cliff. This harness is OPEN-LOOP: a
seeded arrival schedule at a fixed rate (optionally multiplied through
``FaultPlan.burst_arrivals`` windows) submits requests regardless of how
the service is doing, so queue growth, deadline sheds, and admission
rejections appear exactly as they would under real traffic.

Nothing sleeps. Time is a :class:`VirtualClock` the service runs on:

* arrivals advance the clock to their scheduled instant;
* each pumped batch advances it by the batch's measured REAL execution
  seconds (bench mode) or an injected ``service_time`` (tests) plus any
  ``slow_stage`` SIMULATED seconds the fault plan charged — so chaos
  latency shows up in the percentiles without ever sleeping;
* per-request latency = completion − arrival, both virtual.

The emitted report carries p50/p95/p99 latency, shed rate, goodput
(healthy completions per virtual second), the typed outcome taxonomy,
and the reconciliation verdict — the shape ``bench.py serve-loadtest``
lands in ``BENCH_r06.json`` so overload behavior joins the regression
trail.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

from ..resilience import faults as _faults
from ..telemetry import spans as _tspans
from . import deadline as _deadline
from .queue import RejectedByAdmission
from .service import ScoringService, ServiceConfig

__all__ = [
    "VirtualClock", "LoadSchedule", "run_loadtest", "run_fleet_loadtest",
]


class VirtualClock:
    """Monotonic virtual time; advanced explicitly by the harness."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError("virtual time cannot go backwards")
        self.now += dt
        return self.now


@dataclasses.dataclass
class LoadSchedule:
    """Seeded open-loop arrival schedule."""

    rate: float                 # nominal arrivals per virtual second
    duration: float             # virtual seconds of arrivals
    seed: int = 0

    def arrivals(self, plan: "_faults.FaultPlan | None" = None) -> list[float]:
        """Arrival instants: inter-arrival 1/(rate*mult) where ``mult`` is
        the fault plan's burst multiplier at the current instant — the
        same plan replays the same burst every run."""
        if self.rate <= 0 or self.duration <= 0:
            raise ValueError("need rate > 0 and duration > 0")
        out: list[float] = []
        t = 0.0
        while True:
            mult = plan.arrival_multiplier(t) if plan is not None else 1.0
            t += 1.0 / (self.rate * mult)
            if t >= self.duration:
                return out
            out.append(t)


def run_loadtest(
    score_fn: Callable,
    rows: list[dict],
    rate: float,
    duration: float,
    seed: int = 0,
    deadline: float | None = None,
    config: ServiceConfig | None = None,
    service_time: Callable[[int], float] | None = None,
    plan: "_faults.FaultPlan | None" = None,
) -> dict[str, Any]:
    """One open-loop run; returns the metrics report (see module
    docstring). ``rows`` is the pool the seeded rng draws request payloads
    from; ``service_time`` (a callable invoked once per executed batch)
    replaces measured real execution time with deterministic virtual
    seconds — tests and regression benches use a constant; ``plan`` is an
    ALREADY INSTALLED FaultPlan whose bursts/slow stages drive the
    chaos."""
    rng = np.random.default_rng(seed)
    clock = VirtualClock()
    # deterministic mode (injected service_time) must virtualize the
    # TELEMETRY clock too: the serve-family histograms feed the deadline
    # checkpoints' p95s, and left on the real clock they record host
    # execution speed — a loaded machine would shed requests a fast one
    # completes, making the "machine-independent" report host-dependent.
    # On the virtual clock the family seconds are exactly the slow_stage
    # simulated charges. Bench mode (service_time=None) keeps real time.
    prev_spans_clock = _tspans.get_clock()
    if service_time is not None:
        _tspans.set_clock(clock)
    cfg = config or ServiceConfig()
    cfg = dataclasses.replace(cfg, workers=0)
    if deadline is not None:
        cfg = dataclasses.replace(cfg, default_deadline=deadline)
    service = ScoringService(score_fn, cfg, clock=clock)

    def _advance(real: float, sim: float, rows_executed: int) -> None:
        base = (
            service_time(rows_executed) if service_time is not None else real
        )
        clock.advance(base + sim)

    service.on_batch_cost = _advance
    service.start()
    schedule = LoadSchedule(rate=rate, duration=duration, seed=seed)
    arrivals = schedule.arrivals(plan)
    idx = rng.integers(0, len(rows), size=max(1, len(arrivals)))
    handles = []
    max_depth = 0
    # discrete-event engine: ONE worker whose busy/free timeline is
    # ``free_at``. A batch starts the moment the worker is free and work
    # is queued; arrivals scheduled DURING a batch enqueue behind it
    # (that is what makes the loop open: queue depth, deadline burn, and
    # shed tiers grow exactly as they would under real sustained traffic,
    # instead of the worker magically draining between every arrival).
    free_at = 0.0

    def _serve_until(horizon: float | None) -> float:
        """Run batches whose start instant lands before ``horizon``
        (None = run until the queue drains); returns the updated
        ``free_at``."""
        busy = free_at
        while service.queue.depth_requests() > 0:
            start = max(busy, clock.now)
            if horizon is not None and start >= horizon:
                break
            clock.advance(start - clock.now)
            if not service.pump():  # everything left expired/settled
                break
            busy = clock.now  # pump advanced by the batch's cost
        return busy

    try:
        for i, t in enumerate(arrivals):
            free_at = _serve_until(t)
            clock.advance(max(0.0, t - clock.now))
            try:
                handles.append(service.submit(dict(rows[int(idx[i])])))
            except (RejectedByAdmission, _deadline.DeadlineExceeded):
                pass  # counted in the service's typed rejection taxonomy
            max_depth = max(max_depth, service.queue.depth_rows())
        # arrivals over: drain whatever is still queued
        _serve_until(None)
        while service.pump():
            pass
        service.stop(drain=True)
    finally:
        _tspans.set_clock(prev_spans_clock)
    end = clock.now

    stats = service.stats()
    latencies = sorted(
        h.latency() for h in handles
        if h.outcome in ("completed", "quarantined") and h.latency() is not None
    )

    def _pct(q: float) -> float | None:
        if not latencies:
            return None
        return round(
            float(np.percentile(latencies, q, method="nearest")) * 1e3, 3
        )

    shed_total = sum(stats["shed"].values())
    rejected_total = sum(stats["rejected"].values())
    settled = (
        stats["completed"] + stats["quarantined"] + stats["errors"] + shed_total
    )
    offered = len(arrivals)
    return {
        "rate": rate,
        "duration_s": duration,
        "seed": seed,
        "offered": offered,
        "admitted": stats["admitted"],
        "completed": stats["completed"],
        "quarantined": stats["quarantined"],
        "errors": stats["errors"],
        "shed": dict(stats["shed"]),
        "rejected": dict(stats["rejected"]),
        "shed_total": shed_total,
        "rejected_total": rejected_total,
        "shed_rate": (
            round((shed_total + rejected_total) / offered, 4) if offered else 0.0
        ),
        "latency_ms": {"p50": _pct(50), "p95": _pct(95), "p99": _pct(99)},
        "goodput_rows_per_s": (
            round(stats["completed"] / end, 2) if end > 0 else 0.0
        ),
        "max_queue_depth_rows": max(max_depth, stats["queuePeakRows"]),
        "batches": stats["batches"],
        "shed_tier_entries": stats["shedding"]["tierEntries"],
        "virtual_end_s": round(end, 4),
        # the hard invariant the chaos suite pins: every admitted request
        # settled with exactly one typed outcome, nothing leaked
        "reconciled": (
            stats["admitted"] == settled and stats["outstanding"] == 0
        ),
    }


def run_fleet_loadtest(
    score_fn: Callable,
    rows: list[dict],
    rate: float,
    duration: float,
    replicas: int = 2,
    seed: int = 0,
    deadline: float | None = None,
    config: ServiceConfig | None = None,
    service_time: Callable[[int], float] | None = None,
    plan: "_faults.FaultPlan | None" = None,
    fleet_config: "FleetConfig | None" = None,
    reconcile_every: int = 1,
    on_fleet: Callable[[Any], Callable[[float], None] | None] | None = None,
) -> dict[str, Any]:
    """Open-loop loadtest over a :class:`~.fleet.FleetService`: the same
    seeded arrival schedule, dispatched through the router onto
    ``replicas`` workers, each on its OWN virtual clock (a replica's
    clock is its busy timeline; the shared fleet clock is arrival time).
    Every arrival instant also ticks the fleet control plane, so scripted
    ``kill_replica`` / ``partition_replica`` faults, heartbeat aging, and
    hedge checkpoints all fire in virtual time. ``reconcile_every=k``
    checks the fleet-level typed invariant at every k-th arrival
    (``reconciled_every_instant`` in the report); ``dropped`` counts
    logical requests that finished with NO typed outcome and must be 0.

    ``on_fleet`` is the control-plane integration seam: called once with
    the started fleet (build a ModelRegistry, attach a
    RetrainController, ...); its optional return value is a per-instant
    callback invoked right after every ``fleet.tick(t)`` — in the
    arrival loop AND the drain loop — so a supervised control loop (the
    retrain state machine) advances at every virtual instant the fleet
    does."""
    from .fleet import FleetConfig, FleetService

    if replicas < 1:
        raise ValueError("need replicas >= 1")
    rng = np.random.default_rng(seed)
    gclock = VirtualClock()
    rclocks = [VirtualClock() for _ in range(replicas)]
    # deterministic mode virtualizes the telemetry clock onto the FLEET
    # clock (see run_loadtest) — family seconds then reflect only the
    # plan's simulated charges, never host speed
    prev_spans_clock = _tspans.get_clock()
    if service_time is not None:
        _tspans.set_clock(gclock)
    cfg = config or ServiceConfig()
    cfg = dataclasses.replace(cfg, workers=0)
    if deadline is not None:
        cfg = dataclasses.replace(cfg, default_deadline=deadline)
    fc = dataclasses.replace(
        fleet_config or FleetConfig(), replicas=replicas, service=cfg
    )
    fleet = FleetService(
        score_fn, fc, clock=gclock, replica_clocks=rclocks
    )
    for i, svc in enumerate(fleet.services):
        def _advance(real: float, sim: float, n: int, _c=rclocks[i]) -> None:
            base = service_time(n) if service_time is not None else real
            _c.advance(base + sim)

        svc.on_batch_cost = _advance
    fleet.start()
    tick_hook = on_fleet(fleet) if on_fleet is not None else None
    schedule = LoadSchedule(rate=rate, duration=duration, seed=seed)
    arrivals = schedule.arrivals(plan)
    idx = rng.integers(0, len(rows), size=max(1, len(arrivals)))
    handles = []
    max_depth = 0
    reconciled_every_instant = True

    def _serve_replica_until(i: int, horizon: float | None) -> None:
        """Run replica ``i``'s batches whose start lands before
        ``horizon`` (None = one pump pass only happens in the drain
        loop; here we drain until the horizon)."""
        svc = fleet.services[i]
        c = rclocks[i]
        while svc.queue.depth_requests() > 0:
            if horizon is not None and c.now >= horizon:
                break
            if not svc.pump():  # everything left expired/settled
                break

    try:
        for k, t in enumerate(arrivals):
            for i in fleet.live_replicas():
                _serve_replica_until(i, t)
            gclock.advance(max(0.0, t - gclock.now))
            # idle time passes for replicas with an empty queue
            for i in fleet.live_replicas():
                c = rclocks[i]
                if (
                    fleet.services[i].queue.depth_requests() == 0
                    and c.now < t
                ):
                    c.advance(t - c.now)
            fleet.tick(t)
            if tick_hook is not None:
                tick_hook(t)
            pin = plan.burst_replica(t) if plan is not None else None
            try:
                handles.append(
                    fleet.submit(dict(rows[int(idx[k])]), pin=pin)
                )
            except (RejectedByAdmission, _deadline.DeadlineExceeded):
                pass  # counted in the fleet's typed rejection taxonomy
            max_depth = max(
                max_depth,
                sum(s.queue.depth_rows() for s in fleet.services),
            )
            if reconcile_every and k % reconcile_every == 0:
                if not fleet.reconcile()["reconciled"]:
                    reconciled_every_instant = False
        # arrivals over: drain, still ticking (hedges and scripted kills
        # keep firing in virtual time until the fleet is quiet)
        while True:
            settled = 0
            for i in fleet.live_replicas():
                settled += fleet.services[i].pump()
            t = max([gclock.now] + [c.now for c in rclocks])
            gclock.advance(t - gclock.now)
            fleet.tick(t)
            if tick_hook is not None:
                tick_hook(t)
            if settled == 0 and all(
                fleet.services[i].queue.depth_requests() == 0
                for i in fleet.live_replicas()
            ):
                break
        fleet.stop()
    finally:
        _tspans.set_clock(prev_spans_clock)
    end = max([gclock.now] + [c.now for c in rclocks])

    stats = fleet.stats()
    recon = fleet.reconcile()
    latencies = sorted(
        h.latency() for h in handles
        if h.outcome in ("completed", "quarantined")
        and h.latency() is not None
    )

    def _pct(q: float) -> float | None:
        if not latencies:
            return None
        return round(
            float(np.percentile(latencies, q, method="nearest")) * 1e3, 3
        )

    shed_total = sum(stats["shed"].values())
    rejected_total = sum(stats["rejected"].values())
    settled_total = (
        stats["completed"] + stats["quarantined"] + stats["errors"]
        + shed_total
    )
    offered = len(arrivals)
    dropped = sum(1 for h in handles if h.outcome is None)
    return {
        "rate": rate,
        "duration_s": duration,
        "seed": seed,
        "replicas": replicas,
        "offered": offered,
        "admitted": stats["admitted"],
        "completed": stats["completed"],
        "quarantined": stats["quarantined"],
        "errors": stats["errors"],
        "shed": dict(stats["shed"]),
        "rejected": dict(stats["rejected"]),
        "shed_total": shed_total,
        "rejected_total": rejected_total,
        "shed_rate": (
            round((shed_total + rejected_total) / offered, 4)
            if offered else 0.0
        ),
        "latency_ms": {"p50": _pct(50), "p95": _pct(95), "p99": _pct(99)},
        "goodput_rows_per_s": (
            round(stats["completed"] / end, 2) if end > 0 else 0.0
        ),
        "max_queue_depth_rows": max_depth,
        "virtual_end_s": round(end, 4),
        "hedges_fired": stats["hedgesFired"],
        "hedge_duplicates": stats["hedgeDuplicates"],
        "orphans_adopted": stats["orphansAdopted"],
        "replicas_lost": stats["replicasLost"],
        "lost_replicas": stats["lostReplicas"],
        "router_dispatched": stats["router"]["dispatched"],
        "per_replica": [
            {
                "admitted": s["admitted"],
                "completed": s["completed"],
                "shed": dict(s["shed"]),
                "rejected": dict(s["rejected"]),
                "outstanding": s["outstanding"],
                "batches": s["batches"],
            }
            for s in stats["perReplica"]
        ],
        # exactly-once accounting: no logical request may end silent
        "dropped": dropped,
        "reconciled": (
            stats["admitted"] == settled_total
            and stats["outstanding"] == 0
            and recon["reconciled"]
            and dropped == 0
        ),
        "reconciled_every_instant": reconciled_every_instant,
    }
