"""Standing scoring service — the long-lived online path over one
``score_function`` closure (ROADMAP item 1).

The library pieces PRs 1–7 built (warm compile bank, fused featurize
plane, schema sentinel, circuit breakers, drift windows, telemetry)
assemble here into a service that stays up under overload and faults:

* :mod:`.queue` — bounded admission (typed :class:`RejectedByAdmission`),
* :mod:`.batcher` — dynamic micro-batch assembly onto the fusion buffer,
* :mod:`.deadline` — per-request budgets propagated through the
  sentinel → featurize → dispatch stage families
  (:class:`DeadlineExceeded` rejects early, never late),
* :mod:`.shedding` — backpressure + tiered load shedding with hysteresis,
* :mod:`.service` — the service loop (:class:`ScoringService`),
* :mod:`.loadtest` — the seeded open-loop arrival harness on a virtual
  clock (``bench.py serve-loadtest`` / ``serve-fleet``),
* :mod:`.fleet` / :mod:`.router` — N replicas behind health × load
  dispatch with hedged retries and replica-loss drain
  (:class:`FleetService`),
* :mod:`.registry` — versioned rollout: shadow scoring and
  sentinel-gated canary promotion (:class:`ModelRegistry`).

See docs/serving.md ("Overload & graceful degradation", "Fleet
operation").
"""
from .batcher import BatchPlan, MicroBatcher
from .deadline import DeadlineBudget, DeadlineExceeded
from .fleet import FleetConfig, FleetRequest, FleetService
from .loadtest import (
    LoadSchedule,
    VirtualClock,
    run_fleet_loadtest,
    run_loadtest,
)
from .queue import AdmissionQueue, RejectedByAdmission
from .registry import ModelRegistry
from .router import Router, RouterConfig
from .service import PendingScore, ScoringService, ServiceConfig
from .shedding import LoadShedder, ShedConfig

__all__ = [
    "AdmissionQueue",
    "BatchPlan",
    "DeadlineBudget",
    "DeadlineExceeded",
    "FleetConfig",
    "FleetRequest",
    "FleetService",
    "LoadSchedule",
    "LoadShedder",
    "MicroBatcher",
    "ModelRegistry",
    "PendingScore",
    "RejectedByAdmission",
    "Router",
    "RouterConfig",
    "ScoringService",
    "ServiceConfig",
    "ShedConfig",
    "VirtualClock",
    "run_fleet_loadtest",
    "run_loadtest",
]
