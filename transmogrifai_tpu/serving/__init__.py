"""Standing scoring service — the long-lived online path over one
``score_function`` closure (ROADMAP item 1).

The library pieces PRs 1–7 built (warm compile bank, fused featurize
plane, schema sentinel, circuit breakers, drift windows, telemetry)
assemble here into a service that stays up under overload and faults:

* :mod:`.queue` — bounded admission (typed :class:`RejectedByAdmission`),
* :mod:`.batcher` — dynamic micro-batch assembly onto the fusion buffer,
* :mod:`.deadline` — per-request budgets propagated through the
  sentinel → featurize → dispatch stage families
  (:class:`DeadlineExceeded` rejects early, never late),
* :mod:`.shedding` — backpressure + tiered load shedding with hysteresis,
* :mod:`.service` — the service loop (:class:`ScoringService`),
* :mod:`.loadtest` — the seeded open-loop arrival harness on a virtual
  clock (``bench.py serve-loadtest``).

See docs/serving.md ("Overload & graceful degradation").
"""
from .batcher import BatchPlan, MicroBatcher
from .deadline import DeadlineBudget, DeadlineExceeded
from .loadtest import LoadSchedule, VirtualClock, run_loadtest
from .queue import AdmissionQueue, RejectedByAdmission
from .service import PendingScore, ScoringService, ServiceConfig
from .shedding import LoadShedder, ShedConfig

__all__ = [
    "AdmissionQueue",
    "BatchPlan",
    "DeadlineBudget",
    "DeadlineExceeded",
    "LoadSchedule",
    "LoadShedder",
    "MicroBatcher",
    "PendingScore",
    "RejectedByAdmission",
    "ScoringService",
    "ServiceConfig",
    "ShedConfig",
    "VirtualClock",
    "run_loadtest",
]
