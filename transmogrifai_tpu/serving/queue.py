"""Bounded admission queue — the service's only intake.

A standing scorer's first defense against overload is refusing work it
cannot hold: the queue is bounded in ROWS (requests carry 1..k rows), an
offer against a full queue raises the typed
:class:`RejectedByAdmission` instead of growing memory, and every depth
change lands in the ``tptpu_serve_queue_depth`` gauge so backpressure is
observable the moment it starts. FIFO order is preserved; the service's
micro-batcher pops contiguous runs of requests off the head.

The queue never sleeps on the caller's behalf in tests: ``pop_many``
takes an optional real-time wait (worker mode); the synchronous pump
path passes ``wait=0`` and the loadtest harness drives everything on a
virtual clock.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any

from ..analysis import schedule as _schedule
from ..telemetry import metrics as _tm

__all__ = ["AdmissionQueue", "RejectedByAdmission"]

#: admission-rejection reasons (the typed taxonomy)
REJECT_REASONS = ("queue_full", "shedding", "stopped")


class RejectedByAdmission(RuntimeError):
    """The service refused to accept a request: the queue is full, the
    load shedder is in its reject tier, or the service is stopping.
    ``reason`` is one of ``queue_full`` / ``shedding`` / ``stopped``."""

    def __init__(self, reason: str, detail: str = ""):
        if reason not in REJECT_REASONS:
            raise ValueError(f"unknown rejection reason {reason!r}")
        self.reason = reason
        super().__init__(
            f"rejected by admission ({reason})" + (f": {detail}" if detail else "")
        )


class AdmissionQueue:
    """Bounded FIFO of scoring requests, measured in rows.

    ``item_rows(item)`` must return the item's row count; anything with
    ``.rows`` works by default."""

    def __init__(self, max_rows: int = 2048):
        if max_rows < 1:
            raise ValueError("max_rows must be >= 1")
        self.max_rows = max_rows
        self._lock = _schedule.make_lock(
            "serving/queue.py:AdmissionQueue._lock"
        )
        # the Condition WRAPS the queue lock: one lock, one graph node
        self._not_empty = threading.Condition(self._lock)
        self._items: deque[Any] = deque()
        self._rows = 0
        self._closed = False
        self.peak_rows = 0
        self._gauge = _tm.REGISTRY.gauge("tptpu_serve_queue_depth")

    @staticmethod
    def item_rows(item: Any) -> int:
        rows = getattr(item, "rows", None)
        return len(rows) if rows is not None else 1

    # -------------------------------------------------------------- intake
    def offer(self, item: Any) -> None:
        """Enqueue or raise :class:`RejectedByAdmission`."""
        n = self.item_rows(item)
        with self._not_empty:
            if self._closed:
                raise RejectedByAdmission("stopped")
            if self._rows + n > self.max_rows:
                raise RejectedByAdmission(
                    "queue_full",
                    f"{self._rows}+{n} rows > bound {self.max_rows}",
                )
            self._items.append(item)
            self._rows += n
            if self._rows > self.peak_rows:
                self.peak_rows = self._rows
            self._gauge.set(self._rows)
            self._not_empty.notify()

    # ------------------------------------------------------------- drain
    def pop_many(self, max_rows: int, wait: float = 0.0) -> list[Any]:
        """Pop a FIFO run of requests totalling at most ``max_rows`` rows
        (always at least one request when the queue is non-empty, so a
        single oversized request can still make progress). Blocks up to
        ``wait`` REAL seconds for the first item (worker mode); ``wait=0``
        returns immediately (pump mode)."""
        out: list[Any] = []
        with self._not_empty:
            if not self._items and wait > 0:
                self._not_empty.wait(timeout=wait)
            taken = 0
            while self._items:
                n = self.item_rows(self._items[0])
                if out and taken + n > max_rows:
                    break
                out.append(self._items.popleft())
                taken += n
            if out:
                self._rows -= taken
                self._gauge.set(self._rows)
        return out

    def drain(self) -> list[Any]:
        """Everything still queued, atomically (service shutdown)."""
        with self._lock:
            out = list(self._items)
            self._items.clear()
            self._rows = 0
            self._gauge.set(0)
        return out

    # ------------------------------------------------------------- state
    def depth_rows(self) -> int:
        return self._rows

    def depth_requests(self) -> int:
        return len(self._items)

    def close(self) -> None:
        """Refuse further offers; queued items stay for draining. Wakes
        blocked poppers so worker threads can observe shutdown."""
        with self._not_empty:
            self._closed = True
            self._not_empty.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed
