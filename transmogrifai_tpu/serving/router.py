"""Load-aware, health-aware dispatch across fleet replicas.

The ``Router`` turns the gauges every replica already exports — queue
depth in rows, in-flight rows, breaker-open fraction — plus the fleet's
heartbeat view into one scalar ``score`` per replica:

    score(i) = health(i) - load_weight * load(i)

``health`` is 0.0 for a replica that is lost, stopped, partitioned, or
heartbeat-stale (unroutable), else ``1 - breaker_weight *
breaker_open_fraction``; ``load`` is the replica's occupied capacity
fraction (queued + in-flight rows over its admission bound). Dispatch
``order()`` sorts by descending score with the replica index as the
deterministic tie-break, so the same fleet state always routes the same
way — the loadtest twin runs depend on it.

The router holds no request state; its only mutable fields are dispatch
counters (under its own instrumented lock, a leaf in the lock order —
nothing is acquired while holding it).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Iterable

from ..analysis import schedule as _schedule
from ..resilience import faults as _faults

__all__ = ["Router", "RouterConfig"]


@dataclasses.dataclass
class RouterConfig:
    """Scoring weights. Defaults keep health dominant: a half-open-breaker
    replica (health 0.5) still beats an idle dead one (health 0)."""

    #: how hard open breakers depress health (1.0 = all-open means 0)
    breaker_weight: float = 1.0
    #: how hard occupancy depresses the dispatch score
    load_weight: float = 0.5


class Router:
    """Health × load dispatch policy over a fleet's replicas."""

    def __init__(self, fleet: Any, config: RouterConfig | None = None):
        self.fleet = fleet
        self.config = config or RouterConfig()
        # instrumented-lock seam: the literal is the static analyzer's
        # canonical key (analysis/concurrency.py + schedule.py)
        self._lock = _schedule.make_lock("serving/router.py:Router._lock")
        #: per-replica dispatch counts (mutations under self._lock)
        self.dispatched: dict[Any, int] = {}
        self.hedge_dispatched: dict[Any, int] = {}

    # ------------------------------------------------------------- signals
    def routable(self, i: int) -> bool:
        """A replica the router may dispatch to: alive, started, not
        scripted into a partition, heartbeat fresh."""
        fleet = self.fleet
        if i in fleet.lost or i in fleet.decommissioning:
            return False
        plan = _faults.active()
        if plan is not None and plan.replica_partitioned(i, fleet.clock()):
            return False
        return i not in fleet.sentinel.dead_hosts()

    def health(self, i: int) -> float:
        """0.0 = unroutable; else 1 minus the breaker-open penalty."""
        if not self.routable(i):
            return 0.0
        svc = self.fleet.services[i]
        frac = svc._breaker_open_fraction()
        return max(0.0, 1.0 - self.config.breaker_weight * frac)

    def load(self, i: int) -> float:
        """Occupied capacity fraction: queued + in-flight rows over the
        replica's admission bound."""
        svc = self.fleet.services[i]
        cap = max(1, svc.config.max_queue_rows)
        return (svc.queue.depth_rows() + svc._in_flight_rows) / cap

    def score(self, i: int) -> float:
        h = self.health(i)
        if h <= 0.0:
            return float("-inf")
        return h - self.config.load_weight * self.load(i)

    # ------------------------------------------------------------ dispatch
    def order(self, exclude: Iterable[int] = ()) -> list[int]:
        """Routable replicas, best score first, index tie-broken —
        deterministic for identical fleet state."""
        skip = set(exclude)
        scored = [
            (i, self.score(i))
            for i in range(len(self.fleet.services))
            if i not in skip
        ]
        live = [(i, s) for i, s in scored if s != float("-inf")]
        live.sort(key=lambda t: (-t[1], t[0]))
        return [i for i, _ in live]

    def pick(self, exclude: Iterable[int] = ()) -> int | None:
        """Best routable replica, or None when the whole fleet is down."""
        order = self.order(exclude)
        return order[0] if order else None

    def record_dispatch(self, i: int, hedge: bool = False) -> None:
        with self._lock:
            self.dispatched[i] = self.dispatched.get(i, 0) + 1
            if hedge:
                self.hedge_dispatched[i] = (
                    self.hedge_dispatched.get(i, 0) + 1
                )

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "dispatched": dict(self.dispatched),
                "hedgeDispatched": dict(self.hedge_dispatched),
            }
