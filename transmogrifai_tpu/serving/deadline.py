"""Per-request deadline budgets, propagated through the scoring pipeline.

A standing service cannot afford to *execute* a request it has already
lost: a request that spent its latency budget queuing (or in a slow
upstream stage) must be rejected **early** — before the expensive stage
families run — not returned late. The mechanism:

* :class:`DeadlineBudget` — one request's remaining time, measured on an
  injectable clock (the TPL004 seam; the loadtest harness runs budgets on
  a virtual clock, so deadline dynamics are testable without sleeps).
  ``consume()`` adds *simulated* seconds — ``FaultPlan.slow_stage`` chaos
  burns budgets deterministically through this path.
* :func:`active` — installs a budget thread-locally around one
  ``score_fn.batch`` execution (the service installs the tightest budget
  of the micro-batch's members).
* :func:`checkpoint` — called by ``local/scoring.py`` at each stage-family
  boundary (sentinel → featurize → dispatch): when the active budget's
  remaining time cannot cover that family's **p95** from the PR-7 serving
  latency histograms (``tptpu_serve_seconds{stage=...}``), it raises
  :class:`DeadlineExceeded` instead of letting the family execute. With
  no recorded history the required time is 0 and only a fully-spent
  budget rejects — the service learns its own latency floor as it runs.

``DeadlineExceeded`` is a typed rejection: the service maps it to
per-request outcomes and counts it (``deadline_exceeded`` events,
``tptpu_serve_deadline_exceeded_total``).
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Iterator

from ..telemetry import events as _tevents
from ..telemetry import metrics as _tm

__all__ = [
    "DeadlineBudget",
    "DeadlineExceeded",
    "PIPELINE_FAMILIES",
    "active",
    "checkpoint",
    "consume",
    "current",
    "family_p95",
    "pipeline_p95",
]

#: stage families in execution order — the serving pipeline the budget
#: crosses (matches the ``tptpu_serve_seconds`` histogram labels)
PIPELINE_FAMILIES = ("sentinel", "featurize", "dispatch")


class DeadlineExceeded(RuntimeError):
    """A request's remaining budget cannot cover the upcoming stage family
    (or is already spent). Typed so the service and callers can tell
    "rejected early by deadline" from every other failure."""

    def __init__(self, family: str, remaining: float, required: float):
        self.family = family
        self.remaining = remaining
        self.required = required
        super().__init__(
            f"deadline exceeded before {family}: "
            f"{remaining * 1e3:.3f} ms remaining < "
            f"{required * 1e3:.3f} ms required (family p95)"
        )


class DeadlineBudget:
    """One request's latency budget on an injectable clock.

    ``remaining()`` = budget − (clock elapsed since ``started``) −
    simulated seconds consumed via :meth:`consume` (slow-stage chaos)."""

    __slots__ = ("budget", "clock", "started", "simulated")

    def __init__(
        self,
        budget: float,
        clock: Callable[[], float] | None = None,
        started: float | None = None,
    ):
        self.budget = float(budget)
        self.clock = clock if clock is not None else time.monotonic
        self.started = self.clock() if started is None else started
        self.simulated = 0.0

    def elapsed(self) -> float:
        return (self.clock() - self.started) + self.simulated

    def remaining(self) -> float:
        return self.budget - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def consume(self, seconds: float) -> None:
        """Burn ``seconds`` of SIMULATED time (no real sleep)."""
        self.simulated += seconds

    def covers(
        self,
        families: tuple[str, ...] = PIPELINE_FAMILIES,
        required: float | None = None,
    ) -> bool:
        """True when the remaining budget covers the summed p95 of the
        given stage families (the admission-time pre-check). Callers
        checking many budgets in one pass precompute ``required`` once —
        each ``pipeline_p95`` call is three locked histogram-quantile
        scans, invariant within a batch."""
        rem = self.remaining()
        if required is None:
            required = pipeline_p95(families)
        return rem > 0.0 and rem >= required


_TLS = threading.local()


def current() -> DeadlineBudget | None:
    return getattr(_TLS, "budget", None)


@contextlib.contextmanager
def active(budget: DeadlineBudget | None) -> Iterator[DeadlineBudget | None]:
    """Install ``budget`` for this thread's scoring checkpoints (None is a
    no-op installation, so callers need no branching)."""
    prev = getattr(_TLS, "budget", None)
    _TLS.budget = budget
    try:
        yield budget
    finally:
        _TLS.budget = prev


def family_p95(family: str) -> float:
    """The stage family's p95 seconds from the serving latency histograms
    (0.0 when that family has no recorded history yet)."""
    h = _tm.REGISTRY.histogram(
        "tptpu_serve_seconds", labels={"stage": family}
    )
    q = h.quantile(0.95)
    return 0.0 if q is None else float(q)


def pipeline_p95(families: tuple[str, ...] = PIPELINE_FAMILIES) -> float:
    return sum(family_p95(f) for f in families)


def checkpoint(family: str) -> None:
    """Stage-family boundary check (called from the scoring hot path —
    near-free with no active budget): reject early when the remaining
    budget can't cover the family's p95. Emits the ``deadline_exceeded``
    event; the ``tptpu_serve_deadline_exceeded_total`` counter is
    maintained by the SERVICE per shed request outcome (one trip here can
    shed several co-batched members — counting both would double-book)."""
    b = current()
    if b is None:
        return
    required = family_p95(family)
    remaining = b.remaining()
    if remaining <= 0.0 or remaining < required:
        _tevents.emit(
            "deadline_exceeded", family=family,
            remainingMs=round(remaining * 1e3, 3),
            requiredMs=round(required * 1e3, 3),
        )
        raise DeadlineExceeded(family, remaining, required)


def consume(seconds: float) -> None:
    """Burn simulated seconds from the active budget (slow-stage chaos);
    no-op without one."""
    b = current()
    if b is not None and seconds:
        b.consume(seconds)
