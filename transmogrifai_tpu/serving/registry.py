"""Versioned model rollout over a fleet: shadow scoring and
sentinel-gated canary promotion.

The ``ModelRegistry`` owns the mapping ``version -> score_function`` and
two rollout modes that never bet the fleet on an unproven model:

* **Shadow** — the candidate scores a MIRROR of served traffic (the
  fleet's ``on_served`` seam hands it every completed request's rows),
  its predictions are compared against what was actually served, and
  nothing it produces ever reaches a caller. Zero risk, full-traffic
  evidence.
* **Canary** — the candidate takes over a SUBSET of replicas (an atomic
  ``score_fn`` swap between batches, so no request is ever dropped by
  the rollout itself) while the registry re-scores every canary-served
  request with the control model. :meth:`evaluate_canary` feeds the
  per-side latency and the agreement / score-delta quality metrics to a
  :class:`~..telemetry.runlog.RegressionSentinel` diff and checks the
  attribution-drift alert counter; any finding rolls the subset back to
  the control model (``canary_rollback`` event, typed taxonomy in the
  event's ``codes``), a clean run promotes fleet-wide.

Rollback taxonomy (the event's ``codes`` field): ``TPR001`` canary-side
latency regression, ``TPR004`` quality regression (agreement drop /
score-error growth), ``attribution_drift`` fresh drift alerts during
the canary window.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Iterable

from ..analysis import schedule as _schedule
from ..insights import ledger as _iledger
from ..telemetry import events as _tevents
from ..telemetry import metrics as _tm
from ..telemetry.runlog import RegressionSentinel, RunTolerances

log = logging.getLogger(__name__)

__all__ = ["ModelRegistry"]


def _scalar(row: dict) -> float | None:
    """A comparable scalar from one served result row: the ``prediction``
    inside the first rendered prediction map, else the first numeric
    value. None when the row carries nothing comparable."""
    if not isinstance(row, dict):
        return None
    for v in row.values():
        if isinstance(v, dict) and "prediction" in v:
            try:
                return float(v["prediction"])
            except (TypeError, ValueError):
                continue
    for v in row.values():
        if isinstance(v, (int, float)):
            return float(v)
    return None


def _compare(served: list[dict], mirror: list[dict]) -> tuple[int, int, float]:
    """(compared, agreements, abs-delta sum) over paired result rows."""
    compared = agree = 0
    delta = 0.0
    for a, b in zip(served, mirror):
        x, y = _scalar(a), _scalar(b)
        if x is None or y is None:
            continue
        compared += 1
        d = abs(x - y)
        delta += d
        if d < 1e-9 or round(x) == round(y):
            agree += 1
    return compared, agree, delta


class ModelRegistry:
    """Versioned score-function rollout over one :class:`FleetService`."""

    def __init__(self, fleet: Any, tolerances: RunTolerances | None = None):
        self.fleet = fleet
        self.tolerances = tolerances or RunTolerances()
        # instrumented-lock seam: the literal is the static analyzer's
        # canonical key; LEAF lock — nothing else is acquired under it
        # and no foreign callable runs while it is held
        self._lock = _schedule.make_lock(
            "serving/registry.py:ModelRegistry._lock"
        )
        self._versions: dict[str, Callable] = {}
        self.serving: str | None = None
        self._shadow: dict[str, Any] | None = None
        self._canary: dict[str, Any] | None = None
        self.rollbacks = 0
        self.promotions = 0
        fleet.on_served = self._on_served

    # ------------------------------------------------------------ versions
    def register(self, version: str, score_fn: Callable) -> "ModelRegistry":
        with self._lock:
            self._versions[version] = score_fn
        return self

    def deploy(self, version: str) -> None:
        """Serve ``version`` fleet-wide — an atomic per-replica
        ``score_fn`` swap between batches; in-flight batches finish on
        the model they started with, queued requests score on the new
        one, nothing is dropped."""
        with self._lock:
            fn = self._versions[version]
        for svc in self.fleet.services:
            svc.score_fn = fn
        with self._lock:
            self.serving = version

    # -------------------------------------------------------------- shadow
    def start_shadow(self, version: str, sample_every: int = 1) -> None:
        """Mirror every ``sample_every``-th served request through the
        candidate; its output is compared, never served."""
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        with self._lock:
            if self._shadow is not None:
                raise RuntimeError("a shadow is already running")
            self._shadow = {
                "version": version, "fn": self._versions[version],
                "sample_every": sample_every, "seen": 0, "compared": 0,
                "agreements": 0, "absDelta": 0.0, "mirrorErrors": 0,
            }

    def shadow_report(self) -> dict[str, Any]:
        with self._lock:
            if self._shadow is None:
                raise RuntimeError("no shadow running")
            s = self._shadow
            compared = s["compared"]
            return {
                "version": s["version"],
                "seen": s["seen"],
                "compared": compared,
                "agreement": (
                    s["agreements"] / compared if compared else None
                ),
                "meanAbsDelta": (
                    s["absDelta"] / compared if compared else None
                ),
                "mirrorErrors": s["mirrorErrors"],
            }

    def stop_shadow(self) -> dict[str, Any]:
        report = self.shadow_report()
        with self._lock:
            self._shadow = None
        return report

    # -------------------------------------------------------------- canary
    def start_canary(
        self,
        version: str,
        replicas: Iterable[int] = (0,),
        tolerances: RunTolerances | None = None,
    ) -> None:
        """Promote ``version`` onto a replica subset; every request those
        replicas serve is re-scored by the control model for the gate."""
        subset = sorted(set(replicas))
        with self._lock:
            if self._canary is not None:
                raise RuntimeError("a canary is already running")
            fn = self._versions[version]
            if not subset:
                raise ValueError("canary needs at least one replica")
            for i in subset:
                if not 0 <= i < len(self.fleet.services):
                    raise ValueError(f"no replica {i}")
            self._canary = {
                "version": version, "fn": fn, "replicas": set(subset),
                "tolerances": tolerances or self.tolerances,
                "controlFns": {
                    i: self.fleet.services[i].score_fn for i in subset
                },
                "compared": 0, "agreements": 0, "absDelta": 0.0,
                "canaryLatency": 0.0, "canaryServed": 0,
                "controlLatency": 0.0, "controlServed": 0,
                "mirrorErrors": 0,
                "driftAlertsAt": _iledger.snapshot()["attributionDriftAlerts"],
            }
        for i in subset:
            self.fleet.services[i].score_fn = fn

    def _canary_metrics_locked(self) -> dict[str, Any]:
        c = self._canary
        assert c is not None
        compared = c["compared"]
        return {
            "version": c["version"],
            "replicas": sorted(c["replicas"]),
            "compared": compared,
            "agreement": c["agreements"] / compared if compared else None,
            "scoreError": c["absDelta"] / compared if compared else None,
            "canaryServed": c["canaryServed"],
            "controlServed": c["controlServed"],
            "canaryLatency": (
                c["canaryLatency"] / c["canaryServed"]
                if c["canaryServed"] else None
            ),
            "controlLatency": (
                c["controlLatency"] / c["controlServed"]
                if c["controlServed"] else None
            ),
            "mirrorErrors": c["mirrorErrors"],
        }

    def canary_report(self) -> dict[str, Any]:
        with self._lock:
            if self._canary is None:
                raise RuntimeError("no canary running")
            return self._canary_metrics_locked()

    def evaluate_canary(self) -> dict[str, Any]:
        """Gate the canary: sentinel-diff the canary window against the
        control side (latency phase + agreement / score-error quality),
        add any fresh attribution-drift alerts, then roll back on ANY
        finding or promote on none. Returns the decision record."""
        with self._lock:
            if self._canary is None:
                raise RuntimeError("no canary running")
            c = self._canary
            m = self._canary_metrics_locked()
            tol = c["tolerances"]
            drift_before = c["driftAlertsAt"]
        codes: list[str] = []
        if m["compared"]:
            baseline = {
                "run": {
                    "phases": {
                        "serve": {"seconds": m["controlLatency"] or 0.0}
                    },
                    "quality": {"agreement": 1.0, "score_error": 0.0},
                }
            }
            current = {
                "run": {
                    "phases": {
                        "serve": {"seconds": m["canaryLatency"] or 0.0}
                    },
                    "quality": {
                        "agreement": m["agreement"],
                        "score_error": m["scoreError"],
                    },
                }
            }
            report = RegressionSentinel(baseline, tol).check(current)
            codes.extend(sorted({f.code for f in report.findings}))
        drift_now = _iledger.snapshot()["attributionDriftAlerts"]
        if drift_now > drift_before:
            codes.append("attribution_drift")
        decision = dict(m)
        decision["codes"] = codes
        if codes:
            self.rollback(codes=codes)
            decision["decision"] = "rollback"
        else:
            self.promote()
            decision["decision"] = "promote"
        return decision

    def rollback(self, codes: Iterable[str] = ()) -> None:
        """Restore the control model on every canary replica (atomic swap
        again — zero dropped requests) and record the typed reason."""
        with self._lock:
            if self._canary is None:
                raise RuntimeError("no canary running")
            c = self._canary
            self._canary = None
            self.rollbacks += 1
        for i, fn in c["controlFns"].items():
            self.fleet.services[i].score_fn = fn
        _tm.REGISTRY.counter("tptpu_canary_rollbacks_total").inc()
        _tevents.emit(
            "canary_rollback", version=c["version"],
            replicas=sorted(c["replicas"]), codes=list(codes),
        )

    def promote(self) -> None:
        """Fleet-wide promotion of a clean canary."""
        with self._lock:
            if self._canary is None:
                raise RuntimeError("no canary running")
            c = self._canary
            self._canary = None
            self.promotions += 1
            self._versions.setdefault(c["version"], c["fn"])
        for svc in self.fleet.services:
            svc.score_fn = c["fn"]
        with self._lock:
            self.serving = c["version"]
        _tevents.emit(
            "canary_promoted", version=c["version"],
            replicas=sorted(c["replicas"]),
        )

    # ------------------------------------------------------------ observer
    def _on_served(
        self, rows: list[dict], results: list[dict] | None,
        replica: int, latency: float,
    ) -> None:
        """The fleet's ``on_served`` seam (called outside every fleet /
        service lock). Mirror scoring runs HERE, on the settling thread —
        never under the registry lock."""
        if results is None:
            return
        with self._lock:
            shadow = self._shadow
            canary = self._canary
            run_shadow = False
            if shadow is not None:
                shadow["seen"] += 1
                run_shadow = shadow["seen"] % shadow["sample_every"] == 0
            if canary is not None:
                if replica in canary["replicas"]:
                    canary["canaryServed"] += 1
                    canary["canaryLatency"] += latency
                else:
                    canary["controlServed"] += 1
                    canary["controlLatency"] += latency
        if shadow is not None and run_shadow:
            self._mirror(shadow, shadow["fn"], rows, results)
        if canary is not None and replica in canary["replicas"]:
            # re-score the canary-served rows with the CONTROL model; the
            # quality gate compares what the canary said against what the
            # control would have said on identical traffic
            control = next(iter(canary["controlFns"].values()))
            self._mirror(canary, control, rows, results)

    def _mirror(
        self, state: dict, fn: Callable, rows: list[dict],
        served: list[dict],
    ) -> None:
        try:
            mirror = fn.batch([dict(r) for r in rows])
        except Exception:
            with self._lock:
                state["mirrorErrors"] += 1
            log.debug("mirror scoring failed", exc_info=True)
            return
        compared, agree, delta = _compare(served, mirror)
        with self._lock:
            state["compared"] += compared
            state["agreements"] += agree
            state["absDelta"] += delta
