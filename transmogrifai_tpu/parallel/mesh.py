"""Mesh construction + sharding helpers.

A 2-D ("data", "model") mesh covers every parallelism the reference has
(SURVEY.md §2.6): rows shard over "data" (Spark's RDD partitions), model
candidates / hyperparameter grid points shard over "model" (the driver
thread pool, OpValidator.scala:363-367). On one chip both axes have size 1
and everything degenerates to plain jit.
"""
from __future__ import annotations

import numpy as np

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_data: int | None = None, n_model: int = 1, devices=None):
    """A ("data", "model") Mesh over ``devices`` (default: all available)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_data is None:
        n_data = len(devices) // n_model
    n = n_data * n_model
    if n > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_model} needs {n} devices, have {len(devices)}"
        )
    return Mesh(
        np.asarray(devices[:n]).reshape(n_data, n_model),
        (DATA_AXIS, MODEL_AXIS),
    )


def auto_mesh(min_devices: int = 2):
    """The all-devices data-parallel mesh, or None on a single device.

    The None return is the one-chip fast path: callers fall back to plain
    jit (no shard_map overhead, no padding).
    """
    import jax

    devices = jax.devices()
    if len(devices) < min_devices:
        return None
    return make_mesh(n_data=len(devices), n_model=1, devices=devices)


def pad_rows(x: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Zero-pad axis 0 to a multiple of ``multiple`` (static shard shapes).

    Returns (padded, original_n). Zero rows are monoid-neutral for the
    sum-style reductions in transmogrifai_tpu.parallel.reductions; reductions
    that are not (min/max) mask padding explicitly via the returned count.
    """
    n = x.shape[0]
    rem = n % multiple
    if rem == 0:
        return x, n
    pad = multiple - rem
    padded = np.concatenate(
        [x, np.zeros((pad,) + x.shape[1:], dtype=x.dtype)], axis=0
    )
    return padded, n


def shard_rows(mesh, x):
    """Place ``x`` row-sharded over the data axis (rows must divide evenly —
    use pad_rows first)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(DATA_AXIS, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def shard_grid(mesh, x):
    """Place stacked per-candidate arrays sharded over the model axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(MODEL_AXIS, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))
