"""Mesh construction + sharding helpers.

A 2-D ("data", "model") mesh covers every parallelism the reference has
(SURVEY.md §2.6): rows shard over "data" (Spark's RDD partitions), model
candidates / hyperparameter grid points shard over "model" (the driver
thread pool, OpValidator.scala:363-367). On one chip both axes have size 1
and everything degenerates to plain jit.
"""
from __future__ import annotations

import threading

import numpy as np

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_data: int | None = None, n_model: int = 1, devices=None):
    """A ("data", "model") Mesh over ``devices`` (default: all available)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_data is None:
        n_data = len(devices) // n_model
    n = n_data * n_model
    if n > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_model} needs {n} devices, have {len(devices)}"
        )
    return Mesh(
        np.asarray(devices[:n]).reshape(n_data, n_model),
        (DATA_AXIS, MODEL_AXIS),
    )


def auto_mesh(min_devices: int = 2):
    """The all-devices data-parallel mesh, or None on a single device.

    The None return is the one-chip fast path: callers fall back to plain
    jit (no shard_map overhead, no padding).
    """
    import jax

    devices = jax.devices()
    if len(devices) < min_devices:
        return None
    return make_mesh(n_data=len(devices), n_model=1, devices=devices)


# --------------------------------------------------------------------------
# execution mesh: the workflow-level default parallelism context.
#
# The reference row-partitions EVERY stage by construction
# (FitStagesUtil.scala:96-118 — everything is an RDD operation). Here the
# equivalent substrate is an ambient mesh that Workflow.train/score install
# around the fit/score phases: estimator fit paths consult
# ``execution_mesh()`` and, when one is active, run row-sharded (trees via
# shard_map+psum histograms, solvers via GSPMD row sharding). On a single
# device the context stays None and everything is plain jit — zero cost.
# --------------------------------------------------------------------------
_EXECUTION_MESH = None


def execution_mesh():
    """The ambient mesh installed by the workflow (None = single-device)."""
    return _EXECUTION_MESH


def set_execution_mesh(mesh) -> None:
    global _EXECUTION_MESH
    _EXECUTION_MESH = mesh


class use_execution_mesh:
    """Context manager installing ``mesh`` as the ambient execution mesh.

    ``use_execution_mesh(None)`` explicitly forces single-device execution
    (the A/B lever the sharded-vs-not parity tests use)."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._saved = None

    def __enter__(self):
        global _EXECUTION_MESH
        self._saved = _EXECUTION_MESH
        _EXECUTION_MESH = self.mesh
        return self.mesh

    def __exit__(self, *exc):
        global _EXECUTION_MESH
        _EXECUTION_MESH = self._saved
        return False


_AUTO_MESH_CACHE: list = []
_AUTO_MESH_LOCK = threading.Lock()


def default_execution_mesh():
    """The mesh Workflow installs when the user didn't pick one: all devices
    data-parallel when >1 device is visible (cached — Mesh identity matters
    for the lru_cached shard_map kernels), else None. Set TPTPU_MESH=0 to
    force single-device execution everywhere. Thread-safe: concurrent
    first callers (service workers racing a train) must agree on ONE
    mesh object, or the lru_cached kernels fork per identity."""
    import os

    if os.environ.get("TPTPU_MESH", "") == "0":
        return None
    if not _AUTO_MESH_CACHE:
        with _AUTO_MESH_LOCK:
            if not _AUTO_MESH_CACHE:
                _AUTO_MESH_CACHE.append(auto_mesh())
    return _AUTO_MESH_CACHE[0]


def data_row_multiple() -> int:
    """Row-count multiple required to shard over the ambient mesh's data
    axis (1 when no mesh is active). Callers pad with mask-0 rows — inert
    in every mask-weighted solver — before shard_rows_if_active."""
    mesh = execution_mesh()
    return 1 if mesh is None else mesh.shape[DATA_AXIS]


def model_lane_multiple() -> int:
    """Lane-count multiple required to shard candidate lanes over the
    ambient mesh's model axis (1 when no mesh is active). The sharded
    sweep (parallel/fit.py::sweep_parallel_fit) pads lane counts onto
    ``compiler.bucketing`` buckets rounded up to this multiple."""
    mesh = execution_mesh()
    return 1 if mesh is None else mesh.shape[MODEL_AXIS]


def shard_rows_if_active(x):
    """Row-shard ``x`` over the ambient execution mesh (rows must already be
    a multiple of data_row_multiple()) — identity when no mesh is active.
    This is how solver-family fits join the row-partitioned substrate: XLA
    (GSPMD) propagates the sharding through the jitted solver and inserts
    the gradient psums."""
    mesh = execution_mesh()
    if mesh is None:
        return x
    if isinstance(x, np.ndarray):
        x = np.ascontiguousarray(x)  # device arrays reshard directly
    return shard_rows(mesh, x)


def pad_rows(x: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Zero-pad axis 0 to a multiple of ``multiple`` (static shard shapes).

    Returns (padded, original_n). Zero rows are monoid-neutral for the
    sum-style reductions in transmogrifai_tpu.parallel.reductions; reductions
    that are not (min/max) mask padding explicitly via the returned count.
    """
    n = x.shape[0]
    rem = n % multiple
    if rem == 0:
        return x, n
    pad = multiple - rem
    padded = np.concatenate(
        [x, np.zeros((pad,) + x.shape[1:], dtype=x.dtype)], axis=0
    )
    return padded, n


def shard_rows(mesh, x):
    """Place ``x`` row-sharded over the data axis (rows must divide evenly —
    use pad_rows first)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(DATA_AXIS, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def shard_grid(mesh, x):
    """Place stacked per-candidate arrays sharded over the model axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(MODEL_AXIS, *([None] * (np.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))
