"""JAX API compatibility for the sharded-collective plane.

``shard_map`` graduated from ``jax.experimental.shard_map`` (where the
replication-check kwarg is ``check_rep``) to top-level ``jax.shard_map``
(where it is ``check_vma``). Every kernel in transmogrifai_tpu.parallel and
models/trees.py goes through this wrapper so the whole sharded reduction
plane — pcolumn_stats, pxtx, phistogram, ring_gram, segment reduces, the
tree grower — runs on either JAX generation instead of dying with an
ImportError on the first collective.
"""
from __future__ import annotations

from functools import partial


def shard_map(f=None, **kwargs):
    """Version-portable ``shard_map``; accepts the new-style ``check_vma``
    kwarg and translates for the experimental API. Usable directly or as
    ``partial(shard_map, mesh=..., ...)`` like the real one."""
    import jax

    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl

        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    if f is None:
        return partial(impl, **kwargs)
    return impl(f, **kwargs)


def abstract_mesh(*axes: tuple):
    """A device-free ``AbstractMesh`` over ``(name, size)`` axes — the
    SPMD auditor's trace substrate: shard_map kernels trace and lower
    over it on ANY host (a 1-chip CI runner included), no real 2x4 mesh
    required. Returns None when this jax generation has no AbstractMesh
    (the auditor degrades to a real-device mesh or a TPS000 finding)."""
    try:
        from jax.sharding import AbstractMesh
    except ImportError:
        return None
    try:
        return AbstractMesh(tuple((str(n), int(s)) for n, s in axes))
    except TypeError:
        # older keyword-style constructor
        names = tuple(str(n) for n, _ in axes)
        sizes = tuple(int(s) for _, s in axes)
        try:
            return AbstractMesh(axis_sizes=sizes, axis_names=names)
        except TypeError:
            return None
