"""Distributed execution plane: device meshes, sharded monoid reductions,
and data/model-parallel fit wrappers.

The reference's distributed substrate is Apache Spark (SURVEY.md §5.8):
row-partition data parallelism, shuffle-based map-reduce aggregation, and a
driver thread pool for concurrent model×grid fits. The TPU-native mapping
(SURVEY.md §2.6):

  Spark mechanism                      here
  ---------------------------------    ----------------------------------
  RDD row partitions over executors    batch-dim sharding over mesh axis
                                       "data" (`shard_rows`)
  monoid reduceByKey / treeAggregate   `shard_map` + `lax.psum` reductions
                                       (`pcolumn_stats`, `pxtx`, ...)
  driver pool for model×grid fits      mesh axis "model" + vmap over stacked
                                       hyperparams (`grid_parallel_fit`)
  XGBoost Rabit allreduce              `psum` inside the training step

Everything is expressed against a `jax.sharding.Mesh`, so the same code runs
on one chip, a v5e pod slice over ICI, or a multi-host DCN mesh — XLA inserts
the collectives.
"""
from .guarded import guarded_collective  # noqa: F401
from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    auto_mesh,
    make_mesh,
    pad_rows,
    shard_grid,
    shard_rows,
)
from .reductions import (  # noqa: F401
    pcolumn_stats,
    pcontingency,
    phistogram,
    pxtx,
)
from .fit import data_parallel_fit, grid_parallel_fit  # noqa: F401
from .ring import pad_cols, ring_corr, ring_gram, shard_cols  # noqa: F401
from .multihost import (  # noqa: F401
    DCN_AXIS,
    dcn_data_spec,
    global_column_stats,
    host_row_slice,
    ingest_global_array,
    initialize_distributed,
    make_global_array,
    make_multihost_mesh,
    padded_rows,
    read_host_block,
)
from .segments import (  # noqa: F401
    aggregate_events_on_device,
    factorize_keys,
    psegment_reduce,
)
