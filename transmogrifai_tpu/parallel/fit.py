"""Data- and model-parallel fit wrappers.

`data_parallel_fit` runs one solver over rows sharded across the mesh's
"data" axis — the jit/GSPMD path: inputs carry NamedShardings, XLA propagates
them through the solver and inserts psum for the gradient reductions (the
scaling-book recipe; replaces Spark's row-partitioned fits).

`grid_parallel_fit` vmaps a solver over stacked hyperparameter arrays and
shards the stacked axis over "model" — the reference's 8-thread candidate
pool (OpValidator.scala:363-367) becomes one compiled sweep.

`sweep_parallel_fit` is the CV candidate sweep's pjit path: the batched
GLM solvers (models/solvers.py) already stack candidates on a lane axis,
so instead of vmapping a scalar solver this route places the lane tensors
on the explicit per-axis PartitionSpecs of ``parallel.sweep.SweepLayout``
(lanes over "model", rows over "data") and dispatches ONE donated compiled
program per fold — fold k+1's dispatch releases fold k's X/y/mask device
buffers, and the lane-param buffers alias straight into the output
intercept (TPJ003-verified).
"""
from __future__ import annotations

from functools import lru_cache
from typing import Any, Callable, Sequence

import numpy as np

from .mesh import MODEL_AXIS, pad_rows, shard_grid, shard_rows


# one jitted wrapper per (solver, mesh, static kwargs) — rebuilding jax.jit
# per call would recompile every fit (see reductions.py kernel caches)
@lru_cache(maxsize=None)
def _jitted_fit(fit_fn, _mesh, static_names: tuple):
    import jax

    return jax.jit(fit_fn, static_argnames=static_names)


@lru_cache(maxsize=None)
def _jitted_lane_sweep(fit_fn, mesh, layout, static_items: tuple,
                       donate: bool):
    """The pjit'd lane-sweep twin of ``fit_fn`` (a batched GLM solver):
    in/out shardings from ``layout`` over ``mesh``, statics baked into the
    closure (pjit rejects kwargs alongside in_shardings), and — when
    ``donate`` — every input buffer donated (SWEEP_DONATE_ARGNUMS)."""
    import jax

    from .sweep import SWEEP_DONATE_ARGNUMS

    base = getattr(fit_fn, "__wrapped__", fit_fn)
    statics = dict(static_items)

    def sweep(x, y, row_masks, reg_params, elastic_nets):
        return base(x, y, row_masks, reg_params, elastic_nets, **statics)

    return jax.jit(
        sweep,
        in_shardings=layout.in_shardings(mesh),
        out_shardings=layout.out_shardings(mesh),
        donate_argnums=SWEEP_DONATE_ARGNUMS if donate else (),
    )


def sweep_parallel_fit(
    fit_fn: Callable[..., Any],
    name: str,
    mesh,
    x: np.ndarray,
    y: np.ndarray,
    row_masks: np.ndarray,
    reg_params: np.ndarray,
    elastic_nets: np.ndarray,
    **static_kwargs: Any,
):
    """One sharded, donated GLM sweep dispatch over ``mesh``.

    ``fit_fn`` is a batched solver taking ``(x [N,D], y [N], masks [K,N],
    regs [K], ens [K], **statics) -> GLMParams``. Lanes pad onto the
    shared ``compiler.bucketing`` buckets rounded up to the model-axis
    size (pads recorded in compileStats → the run ledger's per-fold lane
    occupancy); rows zero-pad to the data-axis multiple with mask-0
    padding (inert in every mask-weighted solver). Inputs are placed
    explicitly on the SweepLayout PartitionSpecs — no implicit reshard —
    and the program is admitted through the TPJ bank gate (aot_call).

    All five input buffers are donated (``TPTPU_DONATE=0`` opts out):
    they are freshly device_put here, so the caller's host arrays stay
    valid while fold k's device buffers free at fold k+1's dispatch.
    Returns GLMParams sliced back to the real lane count."""
    import os
    import warnings

    from ..compiler import bucketing
    from ..utils.aot import aot_call
    from .sweep import SweepLayout, mesh_lane_capacity

    layout = SweepLayout()
    n_model = mesh_lane_capacity(mesh)
    n_data = int(np.prod(list(mesh.shape.values()))) // n_model

    x = np.asarray(x, dtype=np.float32)
    y = np.asarray(y, dtype=np.float32)
    row_masks = np.asarray(row_masks, dtype=np.float32)
    reg_params = np.asarray(reg_params, dtype=np.float32)
    elastic_nets = np.asarray(elastic_nets, dtype=np.float32)

    # lane padding onto the shared buckets (recorded for the run ledger)
    k, (row_masks, reg_params, elastic_nets) = bucketing.bucket_sweep_lanes(
        row_masks, reg_params, elastic_nets, multiple=n_model
    )
    # row padding to the data-axis multiple; mask-0 pad rows are inert
    xp, _ = pad_rows(x, n_data)
    yp, _ = pad_rows(y, n_data)
    rpad = xp.shape[0] - row_masks.shape[1]
    if rpad:
        row_masks = np.pad(row_masks, ((0, 0), (0, rpad)))

    donate = os.environ.get("TPTPU_DONATE", "1") != "0"
    if donate and mesh.devices.flat[0].platform == "cpu":
        # CPU device_put can be zero-copy: the placed shard may alias the
        # caller's numpy memory, and donating an aliased buffer lets XLA
        # write sweep outputs straight into the caller's arrays. Place
        # private copies instead — the donated scribble then lands in
        # memory only the output Array owns. (Real accelerators copy
        # host→device anyway, so this is CPU-only.)
        xp, yp = xp.copy(), yp.copy()
        row_masks = row_masks.copy()
        reg_params = reg_params.copy()
        elastic_nets = elastic_nets.copy()
    jitted = _jitted_lane_sweep(
        fit_fn, mesh, layout,
        tuple(sorted(static_kwargs.items())), donate,
    )
    placed = layout.place(mesh, xp, yp, row_masks, reg_params, elastic_nets)
    with warnings.catch_warnings():
        # x/y/mask donations that cannot alias the (smaller) outputs
        # still free at dispatch; jax's "not usable" warning is the
        # expected half of the contract, not a defect signal here
        warnings.filterwarnings("ignore", message=".*donated buffers.*")
        out = aot_call(
            f"{name}@{n_data}x{n_model}", jitted, placed, {}
        )
    if out.weights.shape[0] > k:
        out = type(out)(
            weights=out.weights[:k], intercept=out.intercept[:k]
        )
    return out


@lru_cache(maxsize=None)
def _jitted_sweep(fit_fn, _mesh, static_items: tuple):
    import jax

    static_kwargs = dict(static_items)

    def sweep(xx, yy, mm, *grid):
        return jax.vmap(
            lambda *gp: fit_fn(xx, yy, mm, *gp, **static_kwargs)
        )(*grid)

    return jax.jit(sweep)


def data_parallel_fit(
    fit_fn: Callable[..., Any],
    mesh,
    x: np.ndarray,
    y: np.ndarray,
    row_mask: np.ndarray,
    *args: Any,
    **kwargs: Any,
):
    """Run ``fit_fn(x, y, row_mask, *args, **kwargs)`` with rows sharded over
    the mesh's data axis. Padding rows get row_mask 0, so any solver that
    weights by row_mask (all of models/solvers.py) is unaffected."""
    n_shards = int(np.prod(list(mesh.shape.values()))) // mesh.shape[MODEL_AXIS]
    xp, n = pad_rows(np.asarray(x, dtype=np.float32), n_shards)
    yp, _ = pad_rows(np.asarray(y, dtype=np.float32), n_shards)
    mp, _ = pad_rows(np.asarray(row_mask, dtype=np.float32), n_shards)
    with mesh:
        return _jitted_fit(fit_fn, mesh, tuple(kwargs))(
            shard_rows(mesh, xp),
            shard_rows(mesh, yp),
            shard_rows(mesh, mp),
            *args,
            **kwargs,
        )


def grid_parallel_fit(
    fit_fn: Callable[..., Any],
    mesh,
    x: np.ndarray,
    y: np.ndarray,
    row_mask: np.ndarray,
    grid_arrays: Sequence[np.ndarray],
    **static_kwargs: Any,
):
    """vmap ``fit_fn`` over stacked hyperparameter arrays, sharding the grid
    axis over the mesh's "model" axis (and rows over "data").

    grid_arrays: per-hyperparam stacked values, each [G, ...]. G must divide
    the model-axis size or vice versa; G is padded up by repeating the last
    point (extra fits are discarded)."""
    import jax

    n_model = mesh.shape[MODEL_AXIS]
    n_data = int(np.prod(list(mesh.shape.values()))) // n_model
    g = grid_arrays[0].shape[0]
    pad = (-g) % n_model
    padded = []
    for a in grid_arrays:
        a = np.asarray(a, dtype=np.float32)
        if pad:
            a = np.concatenate([a, np.repeat(a[-1:], pad, axis=0)], axis=0)
        padded.append(a)
    xp, _ = pad_rows(np.asarray(x, dtype=np.float32), n_data)
    yp, _ = pad_rows(np.asarray(y, dtype=np.float32), n_data)
    mp, _ = pad_rows(np.asarray(row_mask, dtype=np.float32), n_data)

    sweep = _jitted_sweep(fit_fn, mesh, tuple(sorted(static_kwargs.items())))
    with mesh:
        out = sweep(
            shard_rows(mesh, xp),
            shard_rows(mesh, yp),
            shard_rows(mesh, mp),
            *[shard_grid(mesh, a) for a in padded],
        )
    if pad:
        out = jax.tree_util.tree_map(lambda t: t[:g], out)
    return out
