"""Data- and model-parallel fit wrappers.

`data_parallel_fit` runs one solver over rows sharded across the mesh's
"data" axis — the jit/GSPMD path: inputs carry NamedShardings, XLA propagates
them through the solver and inserts psum for the gradient reductions (the
scaling-book recipe; replaces Spark's row-partitioned fits).

`grid_parallel_fit` vmaps a solver over stacked hyperparameter arrays and
shards the stacked axis over "model" — the reference's 8-thread candidate
pool (OpValidator.scala:363-367) becomes one compiled sweep.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Any, Callable, Sequence

import numpy as np

from .mesh import MODEL_AXIS, pad_rows, shard_grid, shard_rows


# one jitted wrapper per (solver, mesh, static kwargs) — rebuilding jax.jit
# per call would recompile every fit (see reductions.py kernel caches)
@lru_cache(maxsize=None)
def _jitted_fit(fit_fn, _mesh, static_names: tuple):
    import jax

    return jax.jit(fit_fn, static_argnames=static_names)


@lru_cache(maxsize=None)
def _jitted_sweep(fit_fn, _mesh, static_items: tuple):
    import jax

    static_kwargs = dict(static_items)

    def sweep(xx, yy, mm, *grid):
        return jax.vmap(
            lambda *gp: fit_fn(xx, yy, mm, *gp, **static_kwargs)
        )(*grid)

    return jax.jit(sweep)


def data_parallel_fit(
    fit_fn: Callable[..., Any],
    mesh,
    x: np.ndarray,
    y: np.ndarray,
    row_mask: np.ndarray,
    *args: Any,
    **kwargs: Any,
):
    """Run ``fit_fn(x, y, row_mask, *args, **kwargs)`` with rows sharded over
    the mesh's data axis. Padding rows get row_mask 0, so any solver that
    weights by row_mask (all of models/solvers.py) is unaffected."""
    n_shards = int(np.prod(list(mesh.shape.values()))) // mesh.shape[MODEL_AXIS]
    xp, n = pad_rows(np.asarray(x, dtype=np.float32), n_shards)
    yp, _ = pad_rows(np.asarray(y, dtype=np.float32), n_shards)
    mp, _ = pad_rows(np.asarray(row_mask, dtype=np.float32), n_shards)
    with mesh:
        return _jitted_fit(fit_fn, mesh, tuple(kwargs))(
            shard_rows(mesh, xp),
            shard_rows(mesh, yp),
            shard_rows(mesh, mp),
            *args,
            **kwargs,
        )


def grid_parallel_fit(
    fit_fn: Callable[..., Any],
    mesh,
    x: np.ndarray,
    y: np.ndarray,
    row_mask: np.ndarray,
    grid_arrays: Sequence[np.ndarray],
    **static_kwargs: Any,
):
    """vmap ``fit_fn`` over stacked hyperparameter arrays, sharding the grid
    axis over the mesh's "model" axis (and rows over "data").

    grid_arrays: per-hyperparam stacked values, each [G, ...]. G must divide
    the model-axis size or vice versa; G is padded up by repeating the last
    point (extra fits are discarded)."""
    import jax

    n_model = mesh.shape[MODEL_AXIS]
    n_data = int(np.prod(list(mesh.shape.values()))) // n_model
    g = grid_arrays[0].shape[0]
    pad = (-g) % n_model
    padded = []
    for a in grid_arrays:
        a = np.asarray(a, dtype=np.float32)
        if pad:
            a = np.concatenate([a, np.repeat(a[-1:], pad, axis=0)], axis=0)
        padded.append(a)
    xp, _ = pad_rows(np.asarray(x, dtype=np.float32), n_data)
    yp, _ = pad_rows(np.asarray(y, dtype=np.float32), n_data)
    mp, _ = pad_rows(np.asarray(row_mask, dtype=np.float32), n_data)

    sweep = _jitted_sweep(fit_fn, mesh, tuple(sorted(static_kwargs.items())))
    with mesh:
        out = sweep(
            shard_rows(mesh, xp),
            shard_rows(mesh, yp),
            shard_rows(mesh, mp),
            *[shard_grid(mesh, a) for a in padded],
        )
    if pad:
        out = jax.tree_util.tree_map(lambda t: t[:g], out)
    return out
