"""SweepLayout — explicit per-axis PartitionSpecs for the CV candidate sweep.

The candidate sweep's tensors fall into three roles (SURVEY.md §2.6: the
reference's 8-thread driver pool becomes a batch axis of one compiled
program; here that axis additionally shards over the mesh):

* **plane** — the fold's shared feature matrix ``x [N, D]`` and target
  ``y [N]``: rows shard over ``DATA_AXIS``, features replicate.
* **lane** — per-candidate tensors stacked on axis 0 (``row_masks [K, N]``,
  ``reg_params [K]``, ``elastic_nets [K]``): candidate lanes shard over
  ``MODEL_AXIS``; the mask's row axis additionally shards over
  ``DATA_AXIS`` so each device holds only its (lane-block × row-block)
  tile.
* **fold outputs** — the fitted ``GLMParams`` (``weights [K, D]``,
  ``intercept [K]``): lanes shard over ``MODEL_AXIS``, mirroring the lane
  inputs so no gather is needed before the caller slices real lanes out.

Declaring the layout explicitly (instead of letting GSPMD infer it from
one device_put) is what makes the TPS story auditable: inputs land exactly
on the declared specs, the lowered program carries those annotations, and
the TPS006 census can prove no hidden resharding was inserted.

The donated, pjit'd program built over this layout lives in
``parallel/fit.py::sweep_parallel_fit``; this module also registers the
sweep programs with the TPJ/TPS auditors (``program_trace_specs``).
"""
from __future__ import annotations

import dataclasses

from .mesh import DATA_AXIS, MODEL_AXIS

#: positional donation contract of the sharded sweep program: every input
#: buffer (x, y, row_masks, reg_params, elastic_nets) is declared donated,
#: so fold k's device buffers are released at fold k+1's dispatch and the
#: lane-param buffers alias directly into the output intercept lane vector
#: (the aliasing TPJ003 verifies in the lowered StableHLO).
SWEEP_DONATE_ARGNUMS = (0, 1, 2, 3, 4)


@dataclasses.dataclass(frozen=True)
class SweepLayout:
    """Per-axis PartitionSpecs for one GLM sweep dispatch.

    Frozen + hashable so jitted-program caches can key on it; axis names
    default to the canonical 2-D ("data", "model") mesh vocabulary."""

    data_axis: str = DATA_AXIS
    model_axis: str = MODEL_AXIS

    # ---- per-tensor specs ------------------------------------------------
    def plane_spec(self):
        """x [N, D]: rows over data, features replicated."""
        from jax.sharding import PartitionSpec as P

        return P(self.data_axis, None)

    def target_spec(self):
        """y [N]: rows over data."""
        from jax.sharding import PartitionSpec as P

        return P(self.data_axis)

    def lane_mask_spec(self):
        """row_masks [K, N]: lanes over model, rows over data."""
        from jax.sharding import PartitionSpec as P

        return P(self.model_axis, self.data_axis)

    def lane_spec(self):
        """per-lane hyperparams [K]: lanes over model."""
        from jax.sharding import PartitionSpec as P

        return P(self.model_axis)

    def out_weights_spec(self):
        """weights [K, D]: lanes over model, features replicated."""
        from jax.sharding import PartitionSpec as P

        return P(self.model_axis, None)

    def out_lane_spec(self):
        """intercept [K]: lanes over model."""
        from jax.sharding import PartitionSpec as P

        return P(self.model_axis)

    # ---- sharding bundles ------------------------------------------------
    def in_shardings(self, mesh) -> tuple:
        """NamedShardings for ``(x, y, row_masks, reg_params,
        elastic_nets)`` — the GLM batched-solver argument order."""
        from jax.sharding import NamedSharding

        return (
            NamedSharding(mesh, self.plane_spec()),
            NamedSharding(mesh, self.target_spec()),
            NamedSharding(mesh, self.lane_mask_spec()),
            NamedSharding(mesh, self.lane_spec()),
            NamedSharding(mesh, self.lane_spec()),
        )

    def out_shardings(self, mesh):
        """GLMParams-shaped sharding pytree for the sweep outputs."""
        from jax.sharding import NamedSharding

        from ..models.solvers import GLMParams

        return GLMParams(
            weights=NamedSharding(mesh, self.out_weights_spec()),
            intercept=NamedSharding(mesh, self.out_lane_spec()),
        )

    def place(self, mesh, x, y, row_masks, reg_params, elastic_nets):
        """device_put every input on its declared sharding — explicit
        placement, so dispatch never triggers an implicit reshard."""
        import jax

        return tuple(
            jax.device_put(a, s)
            for a, s in zip(
                (x, y, row_masks, reg_params, elastic_nets),
                self.in_shardings(mesh),
            )
        )


def mesh_lane_capacity(mesh) -> int:
    """Model-axis size of ``mesh`` (1 when mesh is None) — the lane-count
    multiple the sweep pads onto so lanes shard evenly."""
    if mesh is None:
        return 1
    return int(mesh.shape[MODEL_AXIS])


# --------------------------------------------------------------------------
# compiled-program contract audit (analysis/program.py TPJ0xx +
# analysis/spmd.py TPS006 census — this module is listed in SPEC_MODULES)
# --------------------------------------------------------------------------
def _spec_mesh():
    """The auditors' sweep mesh: all visible devices on the MODEL axis.

    Unlike the shard_map kernels, the pjit'd sweep carries its layout as
    jit in/out shardings, and this jax generation cannot lower those over
    a device-free AbstractMesh — so the spec substrate is a real mesh
    (1 × n_devices; on a one-chip CI runner that is the degenerate 1×1
    mesh, which traces and lowers the same program family)."""
    import jax

    from .mesh import make_mesh

    return make_mesh(n_data=1, n_model=len(jax.devices()))


def program_trace_specs():
    """Register the sharded GLM sweep programs with the program auditor.

    Buckets cross the ``compiler.bucketing`` pow2(<=64) / 32-multiple
    boundary (all multiples of 8, so an 8-wide model axis divides every
    bucket). Statics are baked into the pjit closure (pjit rejects kwargs
    when in_shardings are given) so ``build`` returns empty statics;
    ``base_fn``/``static_argnames``/``donate_argnums`` give TPJ003 the
    donation twin to lower — the lane-param → intercept alias must land
    as ``tf.aliasing_output`` in the StableHLO."""
    import jax

    from ..models.solvers import (
        fit_linear_batched,
        fit_logistic_binary_batched,
    )
    from .fit import _jitted_lane_sweep

    mesh = _spec_mesh()
    layout = SweepLayout()

    def _glm_args(k: int):
        f32 = "float32"
        return (
            jax.ShapeDtypeStruct((16, 3), f32),   # x
            jax.ShapeDtypeStruct((16,), f32),     # y
            jax.ShapeDtypeStruct((k, 16), f32),   # row_masks
            jax.ShapeDtypeStruct((k,), f32),      # reg_params
            jax.ShapeDtypeStruct((k,), f32),      # elastic_nets
        )

    lin_statics = (("fit_intercept", True), ("num_iters", 2))
    log_statics = (
        ("fit_intercept", True), ("num_iters", 2),
        ("standardization", True),
    )
    return [
        dict(
            name="sweep_linear_sharded",
            fn=_jitted_lane_sweep(
                fit_linear_batched, mesh, layout, lin_statics, True
            ),
            build=lambda k: (_glm_args(k), {}),
            buckets=(8, 64, 96),
            bucket_axis="lanes",
            donate_argnums=SWEEP_DONATE_ARGNUMS,
            base_fn=getattr(fit_linear_batched, "__wrapped__", None),
            static_argnames=("num_iters", "fit_intercept"),
        ),
        dict(
            name="sweep_logistic_binary_sharded",
            fn=_jitted_lane_sweep(
                fit_logistic_binary_batched, mesh, layout, log_statics, True
            ),
            build=lambda k: (_glm_args(k), {}),
            buckets=(8, 64, 96),
            bucket_axis="lanes",
            donate_argnums=SWEEP_DONATE_ARGNUMS,
            base_fn=getattr(
                fit_logistic_binary_batched, "__wrapped__", None
            ),
            static_argnames=(
                "num_iters", "fit_intercept", "standardization"
            ),
        ),
    ]
