"""Feature-axis (column) sharding with ring collectives — the wide-axis
analog of ring attention / sequence parallelism (SURVEY.md §5.7).

The reference has no sequence models; its honest "long axis" is the feature
axis — hashing vectorizers go up to MaxNumOfFeatures = 2^17 columns
(core/.../stages/impl/feature/Transmogrifier.scala:56), and SanityChecker
needs the F×F feature-feature gram (SanityChecker.scala:464-470). At that
width a replicated gram build no longer fits next to the data in one chip's
HBM. The ring layout fixes it with exactly the ring-attention communication
pattern:

  * every device holds one column block X_k of shape [N, F/d];
  * the gram is built in d ring steps — at step s each device multiplies its
    resident block against a rotating block and passes the rotating block to
    its ring neighbor (`lax.ppermute` over ICI), overlapping the MXU matmul
    of step s with the neighbor exchange for step s+1;
  * device k ends holding the row block G_k = X_kᵀ·X, i.e. the gram sharded
    over its first axis — X is never all-gathered, and peak per-device
    memory is O(N·F/d + F·F/d).
"""
from __future__ import annotations

from functools import lru_cache, partial

import numpy as np

from .mesh import DATA_AXIS


def pad_cols(x: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Zero-pad axis 1 to a multiple of ``multiple``; zero columns are
    monoid-neutral for gram/sum reductions. Returns (padded, original_f)."""
    f = x.shape[1]
    rem = f % multiple
    if rem == 0:
        return x, f
    pad = multiple - rem
    padded = np.concatenate(
        [x, np.zeros((x.shape[0], pad), dtype=x.dtype)], axis=1
    )
    return padded, f


def shard_cols(mesh, x):
    """Place ``x`` column-sharded over the ring (data) axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(x, NamedSharding(mesh, P(None, DATA_AXIS)))


@lru_cache(maxsize=None)
def _ring_gram_kernel(mesh):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    d = mesh.shape[DATA_AXIS]
    perm = [(i, (i + 1) % d) for i in range(d)]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, DATA_AXIS),),
        out_specs=P(DATA_AXIS, None),
        check_vma=False,
    )
    def body(xl):
        # xl: this device's resident column block [N, Fl]
        fl = xl.shape[1]
        idx = lax.axis_index(DATA_AXIS)

        def step(s, carry):
            rot, out = carry
            # after s neighbor passes the rotating block originated on ring
            # position (idx - s) mod d — that's the gram column block it fills
            j = (idx - s) % d
            blk = xl.T @ rot  # MXU matmul, overlapped with the ppermute below
            out = lax.dynamic_update_slice(out, blk, (0, j * fl))
            rot = lax.ppermute(rot, DATA_AXIS, perm)
            return rot, out

        out0 = jnp.zeros((fl, fl * d), dtype=xl.dtype)
        _, out = lax.fori_loop(0, d, step, (xl, out0))
        return out

    return jax.jit(body)


def program_trace_specs():
    """Register the ring-gram kernel with the program auditor: the one
    ppermute-based program in the plane — tracing it keeps the TPS
    collective census honest about permute collectives, not just
    psum-family reductions. AbstractMesh traces device-free; the ring
    step count is mesh-static so the kernel traces at any column width."""
    import jax

    from .compat import abstract_mesh
    from .mesh import make_mesh

    mesh = abstract_mesh((DATA_AXIS, 8), ("model", 1))
    if mesh is None:
        mesh = make_mesh(n_data=len(jax.devices()), n_model=1)
    d = int(mesh.shape[DATA_AXIS])
    return [
        dict(
            name="ring_gram", fn=_ring_gram_kernel(mesh), buckets=(1, 2),
            bucket_axis="cols",
            build=lambda b: (
                (jax.ShapeDtypeStruct((32, b * d), np.float32),), {},
            ),
        ),
    ]


def ring_gram(x: np.ndarray, mesh) -> np.ndarray:
    """XᵀX [F, F] of a column-sharded matrix via ring passes over ICI.

    Drop-in alternative to parallel.reductions.pxtx for matrices whose
    feature axis, not row axis, is the long one (hashed text planes); rows
    stay resident, columns ride the ring.
    """
    from .guarded import guarded_collective

    d = mesh.shape[DATA_AXIS]
    xp, f = pad_cols(np.asarray(x, dtype=np.float32), d)
    xs = shard_cols(mesh, xp)
    g = np.asarray(
        guarded_collective("ring_gram", _ring_gram_kernel(mesh), xs),
        dtype=np.float64,
    )
    return g[:f, :f]


def ring_corr(x: np.ndarray, mesh) -> np.ndarray:
    """Pearson correlation matrix [F, F] with the gram built over the ring.

    Centering/normalization uses per-column moments (cheap, O(N·F/d) per
    device); only the quadratic F×F term rides the ring. Constant columns
    get correlation 0 (the reference's NaN-corr columns are treated as
    uninformative, SanityChecker.scala:464-470).
    """
    x = np.asarray(x, dtype=np.float64)
    n = x.shape[0]
    mean = x.mean(axis=0)
    g = ring_gram(x - mean, mesh)  # centered gram: covariance * n
    var = np.clip(np.diag(g), 0.0, None)
    denom = np.sqrt(np.outer(var, var))
    with np.errstate(invalid="ignore", divide="ignore"):
        corr = np.where(denom > 0, g / np.where(denom > 0, denom, 1.0), 0.0)
    np.fill_diagonal(corr, np.where(var > n * 1e-18, 1.0, 0.0))
    return corr
