"""Sharded monoid reductions: the map-reduce plane of every estimator.

The reference expresses all statistics as commutative-monoid map-reduce
(SequenceAggregators, Statistics.colStats, reduceByKey in
SanityChecker.scala:252-348) so results are partition-order-invariant. Here
each reduction is a `shard_map` whose per-shard body computes the local
summary and `lax.psum`s it over the data axis — the direct ICI analog of
Spark's treeAggregate, with the same order-invariance guarantee.

All kernels take rows padded to the shard multiple (parallel.mesh.pad_rows);
padding is either monoid-neutral (zeros for sums) or masked via ``n_valid``.
"""
from __future__ import annotations

from functools import lru_cache, partial

import numpy as np

from .guarded import guarded_collective
from .mesh import DATA_AXIS, pad_rows, shard_rows


def _data_spec(*trailing):
    from jax.sharding import PartitionSpec as P

    return P(DATA_AXIS, *trailing)


#: the canonical guarded-collective seam now lives in parallel/guarded.py
#: (one module for the resilience guard, the SPMD analyzer and the
#: collective tracer to instrument); the old private name stays importable
#: for callers that grew around it
_guarded = guarded_collective


# Jitted shard_map kernels are built once per mesh (jax.sharding.Mesh is
# hashable) and reused — a fresh closure + jax.jit per call would retrace and
# recompile on every reduction, costing SanityChecker/RawFeatureFilter
# hundreds of ms per stats call. jit's own cache handles per-shape variants.
@lru_cache(maxsize=None)
def _stats_kernels(mesh):
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_data_spec(None),),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    def pass1(xs):
        v = xs[:, -1:]
        data = xs[:, :-1]
        cnt = jax.lax.psum(v.sum(), DATA_AXIS)
        s = jax.lax.psum((data * v).sum(axis=0), DATA_AXIS)
        big = jnp.finfo(jnp.float32).max
        mn = jax.lax.pmin(
            jnp.where(v > 0, data, big).min(axis=0), DATA_AXIS
        )
        mx = jax.lax.pmax(
            jnp.where(v > 0, data, -big).max(axis=0), DATA_AXIS
        )
        return cnt, s, mn, mx

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_data_spec(None), P()),
        out_specs=P(),
        check_vma=False,
    )
    def pass2(xs, mean):
        v = xs[:, -1:]
        c = (xs[:, :-1] - mean[None, :]) * v
        return jax.lax.psum((c * c).sum(axis=0), DATA_AXIS)

    return jax.jit(pass1), jax.jit(pass2)


def pcolumn_stats(x: np.ndarray, mesh) -> dict[str, np.ndarray]:
    """Per-column count/mean/centered-M2/min/max over a row-sharded matrix.

    Mirrors Statistics.colStats (used by SanityChecker.scala:464) as a
    psum/pmin/pmax tree over the mesh's data axis. Two passes — sums first,
    then CENTERED squared deviations — because device arithmetic is float32
    and raw-moment variance (sumsq - n·mean²) catastrophically cancels for
    columns with |mean| >> std. Padding rows are excluded via the
    row-validity weight column appended internally. Runs behind the active
    CollectiveGuard when a FailoverController is installed.
    """
    return _guarded("pcolumn_stats", _pcolumn_stats, x, mesh)


def _pcolumn_stats(x: np.ndarray, mesh) -> dict[str, np.ndarray]:
    n_shards = mesh.shape[DATA_AXIS]
    xp, n = pad_rows(np.asarray(x, dtype=np.float32), n_shards)
    valid = np.zeros((xp.shape[0], 1), dtype=np.float32)
    valid[:n] = 1.0
    xp = np.concatenate([xp, valid], axis=1)

    pass1, pass2 = _stats_kernels(mesh)
    xs = shard_rows(mesh, xp)
    cnt, s, mn, mx = pass1(xs)
    cnt_f = float(np.asarray(cnt))
    mean = np.asarray(s, dtype=np.float64) / max(cnt_f, 1.0)
    m2 = pass2(xs, mean.astype(np.float32))
    return {
        "count": np.asarray(cnt),
        "mean": mean,
        "m2": np.asarray(m2, dtype=np.float64),
        "min": np.asarray(mn),
        "max": np.asarray(mx),
    }


def pcentered_gram(x: np.ndarray, mesh) -> tuple[np.ndarray, np.ndarray, float]:
    """(centered XᵀX, column means, n) over row-sharded X.

    The covariance/correlation building block: per-shard mean-subtraction
    (mask-aware for padding) keeps float32 matmuls numerically safe where a
    raw-moment XᵀX would cancel (see pcolumn_stats). One MXU matmul + psum
    per pass over ICI. Runs behind the active CollectiveGuard when a
    FailoverController is installed.
    """
    return guarded_collective("pcentered_gram", _pcentered_gram, x, mesh)


def _pcentered_gram(x: np.ndarray, mesh) -> tuple[np.ndarray, np.ndarray, float]:
    n_shards = mesh.shape[DATA_AXIS]
    xp, n = pad_rows(np.asarray(x, dtype=np.float32), n_shards)
    valid = np.zeros((xp.shape[0], 1), dtype=np.float32)
    valid[:n] = 1.0
    xp = np.concatenate([xp, valid], axis=1)

    sums, gram = _gram_kernels(mesh)
    xs = shard_rows(mesh, xp)
    s = np.asarray(sums(xs), dtype=np.float64)
    mean = s / max(n, 1)
    g = np.asarray(gram(xs, mean.astype(np.float32)), dtype=np.float64)
    return g, mean, float(n)


@lru_cache(maxsize=None)
def _gram_kernels(mesh):
    import jax
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_data_spec(None),),
        out_specs=P(),
        check_vma=False,
    )
    def sums(xs):
        v = xs[:, -1:]
        return jax.lax.psum((xs[:, :-1] * v).sum(axis=0), DATA_AXIS)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_data_spec(None), P()),
        out_specs=P(),
        check_vma=False,
    )
    def gram(xs, mean):
        v = xs[:, -1:]
        c = (xs[:, :-1] - mean[None, :]) * v
        return jax.lax.psum(c.T @ c, DATA_AXIS)

    return jax.jit(sums), jax.jit(gram)


def pxtx(x: np.ndarray, mesh) -> np.ndarray:
    """XᵀX over row-sharded X: per-shard MXU matmul + psum over ICI.

    The correlation/covariance building block (SanityChecker's feature-label
    and feature-feature correlation matrix, SanityChecker.scala:464-470).
    Zero padding rows are monoid-neutral. Runs behind the active
    CollectiveGuard when a FailoverController is installed.
    """
    return _guarded("pxtx", _pxtx, x, mesh)


def _pxtx(x: np.ndarray, mesh) -> np.ndarray:
    n_shards = mesh.shape[DATA_AXIS]
    xp, _ = pad_rows(np.asarray(x, dtype=np.float32), n_shards)
    return np.asarray(_xtx_kernel(mesh)(shard_rows(mesh, xp)), dtype=np.float64)


@lru_cache(maxsize=None)
def _xtx_kernel(mesh):
    import jax
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_data_spec(None),),
        out_specs=P(),
        check_vma=False,
    )
    def body(xs):
        return jax.lax.psum(xs.T @ xs, DATA_AXIS)

    return jax.jit(body)


def phistogram(
    codes: np.ndarray, num_bins: int, mesh, weights: np.ndarray | None = None
) -> np.ndarray:
    """Per-column histograms of integer codes: one-hot matmul per shard +
    psum (RawFeatureFilter's FeatureDistribution bins, the GBDT histogram
    primitive). codes [N, F] int32 in [0, num_bins); rows with code < 0 are
    skipped (doubles as the padding mask). Runs behind the active
    CollectiveGuard when a FailoverController is installed."""
    return _guarded("phistogram", _phistogram, codes, num_bins, mesh, weights)


def _phistogram(
    codes: np.ndarray, num_bins: int, mesh, weights: np.ndarray | None
) -> np.ndarray:
    n_shards = mesh.shape[DATA_AXIS]
    codes = np.asarray(codes, dtype=np.int32)
    cp, n = pad_rows(codes + 1, n_shards)  # padding rows become code 0 = skip
    cp = cp - 1
    if weights is None:
        w = np.ones(codes.shape[0], dtype=np.float32)
    else:
        w = np.asarray(weights, dtype=np.float32)
    wp, _ = pad_rows(w, n_shards)
    body = _hist_kernel(mesh, num_bins)
    return np.asarray(body(shard_rows(mesh, cp), shard_rows(mesh, wp)))


@lru_cache(maxsize=None)
def _hist_kernel(mesh, num_bins: int):
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_data_spec(None), _data_spec()),
        out_specs=P(),
        check_vma=False,
    )
    def body(cs, ws):
        valid = (cs >= 0).astype(jnp.float32) * ws[:, None]
        onehot = jax.nn.one_hot(jnp.maximum(cs, 0), num_bins, dtype=jnp.float32)
        hist = jnp.einsum("nf,nfb->fb", valid, onehot)
        return jax.lax.psum(hist, DATA_AXIS)

    return jax.jit(body)


#: rows per device round for pcontingency: float32 cell counts are exact up
#: to 2^24, so bounding each round's per-shard rows keeps every per-shard
#: partial integral (the psum across shards can round above 2^24, bounded by
#: f32 eps ~1e-7 relative — not the +1-increment saturation of an unchunked
#: accumulate); rounds accumulate in float64 on host.
_CONTINGENCY_CHUNK_ROWS = 1 << 23


def pcontingency(
    group_onehot: np.ndarray, label_onehot: np.ndarray, mesh
) -> np.ndarray:
    """Contingency tables group×label via sharded matmul + psum
    (SanityChecker's Cramér's V contingency build, :252-348).

    Counts within one device round stay below float32's 2^24 integer limit;
    rounds are summed in float64 host-side, so large-N tables are exact.
    Runs behind the active CollectiveGuard when a FailoverController is
    installed.
    """
    return guarded_collective(
        "pcontingency", _pcontingency, group_onehot, label_onehot, mesh
    )


def _pcontingency(
    group_onehot: np.ndarray, label_onehot: np.ndarray, mesh
) -> np.ndarray:
    n_shards = mesh.shape[DATA_AXIS]
    fn = _contingency_kernel(mesh)
    total = np.zeros(
        (group_onehot.shape[1], label_onehot.shape[1]), dtype=np.float64
    )
    step = _CONTINGENCY_CHUNK_ROWS * n_shards
    for i in range(0, group_onehot.shape[0], step):
        gp, _ = pad_rows(
            np.asarray(group_onehot[i:i + step], dtype=np.float32), n_shards
        )
        lp, _ = pad_rows(
            np.asarray(label_onehot[i:i + step], dtype=np.float32), n_shards
        )
        total += np.asarray(fn(shard_rows(mesh, gp), shard_rows(mesh, lp)))
    return total


@lru_cache(maxsize=None)
def _contingency_kernel(mesh):
    import jax
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_data_spec(None), _data_spec(None)),
        out_specs=P(),
        check_vma=False,
    )
    def body(gs, ls):
        return jax.lax.psum(gs.T @ ls, DATA_AXIS)

    return jax.jit(body)


# --------------------------------------------------------------------------
# trace-spec registration (analysis/program.py TPJ + analysis/spmd.py TPS)
# --------------------------------------------------------------------------
def _spec_trace_mesh():
    """The auditors' 8-way data mesh: device-free AbstractMesh when this
    jax has one (traces anywhere), else a real mesh over the visible
    devices. The lru_cached kernel factories accept either — both are
    hashable and shard_map traces over both."""
    from .compat import abstract_mesh

    mesh = abstract_mesh((DATA_AXIS, 8), ("model", 1))
    if mesh is not None:
        return mesh
    import jax

    from .mesh import make_mesh

    return make_mesh(n_data=len(jax.devices()), n_model=1)


def program_trace_specs():
    """Register the sharded-reduction kernels with the program auditor
    (same contract as models/gbdt.py etc.): each entry traces the jitted
    shard_map kernel over representative row buckets, so the TPJ IR
    lints AND the TPS static collective census see exactly the programs
    the stats plane dispatches."""
    import jax
    import numpy as np

    mesh = _spec_trace_mesh()
    n_shards = int(mesh.shape[DATA_AXIS])
    f = 4  # representative column count (+1 validity appended by callers)

    def rows(b):
        return b * n_shards

    def mat(b, cols, dtype=np.float32):
        return jax.ShapeDtypeStruct((rows(b), cols), dtype)

    pass1, pass2 = _stats_kernels(mesh)
    sums, gram = _gram_kernels(mesh)
    mean = jax.ShapeDtypeStruct((f,), np.float32)
    return [
        dict(
            name="pstats_pass1", fn=pass1, buckets=(8, 16),
            build=lambda b: ((mat(b, f + 1),), {}),
        ),
        dict(
            name="pstats_pass2", fn=pass2, buckets=(8, 16),
            build=lambda b: ((mat(b, f + 1), mean), {}),
        ),
        dict(
            name="pgram_sums", fn=sums, buckets=(8, 16),
            build=lambda b: ((mat(b, f + 1),), {}),
        ),
        dict(
            name="pgram_centered", fn=gram, buckets=(8, 16),
            build=lambda b: ((mat(b, f + 1), mean), {}),
        ),
        dict(
            name="pxtx", fn=_xtx_kernel(mesh), buckets=(8, 16),
            build=lambda b: ((mat(b, f),), {}),
        ),
        dict(
            name="phistogram", fn=_hist_kernel(mesh, 16), buckets=(8, 16),
            build=lambda b: (
                (mat(b, f, np.int32),
                 jax.ShapeDtypeStruct((rows(b),), np.float32)),
                {},
            ),
        ),
        dict(
            name="pcontingency", fn=_contingency_kernel(mesh),
            buckets=(8, 16),
            build=lambda b: ((mat(b, 3), mat(b, 2)), {}),
        ),
    ]
