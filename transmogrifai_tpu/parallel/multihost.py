"""Multi-host execution: the DCN-spanning distributed backend.

Reference substrate: Apache Spark driver⇄executor RPC + shuffle + XGBoost's
Rabit tracker (SURVEY.md §5.8). TPU-native replacement:

  * control plane — `jax.distributed.initialize` (one process per host),
    after which `jax.devices()` spans every host's chips;
  * data plane — a global `Mesh` whose leading axis factors (dcn, ici):
    collectives between chips on one host ride ICI, cross-host hops ride
    DCN. `shard_map`/`pjit` programs written against
    transmogrifai_tpu.parallel run unchanged — XLA routes `psum` over the
    hierarchy;
  * ingest — each host reads only its row block (`host_row_slice`), then
    `make_global_array` assembles a globally-sharded array from per-host
    locals without gathering anywhere.

The monoid discipline (every estimator = map rows → commutative reduce)
means nothing else changes for multi-host: the same `pcolumn_stats`/`pxtx`/
`phistogram` reductions are correct whatever the mesh spans — that is WHY
the reference's Spark shuffle maps onto plain psum (SURVEY.md §2.6).

Row layout contract (shared by every helper here): the global row count is
padded up to a multiple of the TOTAL device count; host h owns the padded
block [h·chunk, (h+1)·chunk) with chunk = padded // n_hosts; padding rows
live at the global tail and are excluded from statistics via a validity
column, exactly like parallel.reductions.
"""
from __future__ import annotations

import logging
import os
from functools import lru_cache, partial

import numpy as np

from .mesh import DATA_AXIS, MODEL_AXIS

log = logging.getLogger(__name__)

#: DCN (cross-host) mesh axis name — leading so cross-host traffic is the
#: outermost collective dimension
DCN_AXIS = "dcn"


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    auto: bool = False,
) -> None:
    """Bring up the cross-host control plane (idempotent).

    Explicit arguments win; otherwise JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID are read from the environment. With
    ``auto=True`` and nothing configured, `jax.distributed.initialize()` is
    called bare so Cloud TPU pod metadata auto-detection can kick in (do
    NOT set auto on single-machine setups — bare initialize errors there).
    Single-process configurations without ``auto`` no-op.
    """
    import jax

    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None

    configured = coordinator_address is not None or (
        num_processes is not None and num_processes > 1
    )
    if not configured and not auto:
        return
    try:
        if configured:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        else:
            jax.distributed.initialize()  # Cloud TPU pod auto-detection
    except RuntimeError as e:  # already initialized
        if "already" not in str(e).lower():
            raise


def make_multihost_mesh(n_model: int = 1):
    """A ("dcn", "data", "model") mesh over every device of every host.

    Chips within one host form the ("data", "model") submesh (ICI); the
    leading "dcn" axis spans hosts. Use `dcn_data_spec()` to shard rows over
    BOTH host and chip axes; `psum` over ("dcn", "data") reduces globally.
    """
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices())
    n_hosts = jax.process_count()
    per_host = len(devices) // n_hosts
    if per_host * n_hosts != len(devices):
        raise RuntimeError(
            f"uneven device counts: {len(devices)} devices / {n_hosts} hosts"
        )
    n_data = per_host // n_model
    if n_data * n_model != per_host:
        raise ValueError(
            f"n_model={n_model} does not divide per-host device count {per_host}"
        )
    return Mesh(
        devices.reshape(n_hosts, n_data, n_model),
        (DCN_AXIS, DATA_AXIS, MODEL_AXIS),
    )


def dcn_data_spec(*trailing):
    """PartitionSpec sharding rows over (dcn, data) jointly."""
    from jax.sharding import PartitionSpec as P

    return P((DCN_AXIS, DATA_AXIS), *trailing)


def _total_devices(mesh) -> int:
    return int(np.prod([mesh.shape[a] for a in mesh.axis_names]))


def padded_rows(num_rows: int, mesh) -> int:
    """num_rows rounded up to a multiple of the mesh's total device count
    (the global row axis must divide evenly for (dcn, data) sharding)."""
    t = _total_devices(mesh)
    return (num_rows + t - 1) // t * t


def host_row_slice(num_rows: int, mesh=None) -> slice:
    """The half-open range of REAL rows this host should read.

    Hosts own equal blocks of the PADDED row space (chunk = padded //
    n_hosts, consistent with `make_global_array`'s (dcn, data) sharding);
    the returned slice is that block clipped to the real rows — trailing
    hosts may own fewer (or zero) real rows, with the remainder of their
    block being padding.
    """
    import jax

    n_hosts = jax.process_count()
    pid = jax.process_index()
    if mesh is not None:
        chunk = padded_rows(num_rows, mesh) // n_hosts
    else:
        chunk = (num_rows + n_hosts - 1) // n_hosts
    return slice(min(pid * chunk, num_rows), min((pid + 1) * chunk, num_rows))


def read_host_block(
    fetch, num_rows: int, mesh=None, retry_policy=None
) -> np.ndarray:
    """This host's real-row block via ``fetch(slice)``, behind the PR-1
    ``RetryPolicy`` — parity with readers/streaming.py chunk fetches, which
    already retried while per-host ingest did not. Transient errors
    (flaky NFS, object-store hiccups, injected ``fail_chunk_read`` faults)
    back off and retry; fatal ones fail immediately."""
    from ..resilience import faults
    from ..resilience.retry import default_io_policy

    sl = host_row_slice(num_rows, mesh)
    token = f"host-block[{sl.start}:{sl.stop})"

    def attempt():
        plan = faults.active()
        if plan is not None:
            plan.on_stream_chunk(token)
        return fetch(sl)

    policy = retry_policy or default_io_policy()
    rows, attempts = policy.call(attempt)
    if attempts > 1:
        log.warning("host ingest %s fetched after %d attempts", token, attempts)
    return np.asarray(rows)


def ingest_global_array(fetch, num_rows: int, mesh, retry_policy=None):
    """The resilient per-host ingest path: ``host_row_slice`` → retried
    ``fetch`` → zero-pad to this host's block → ``make_global_array``.
    ``fetch(slice)`` returns this host's REAL rows; trailing hosts whose
    block is partly padding get the remainder zero-filled here (padding
    rows are excluded from statistics via the validity column, as
    everywhere in parallel.reductions)."""
    import jax

    if mesh is None:
        raise ValueError(
            "ingest_global_array requires a mesh (the global array's "
            "sharding); single-device callers can use read_host_block "
            "directly — their block is all the real rows"
        )
    local = read_host_block(fetch, num_rows, mesh, retry_policy)
    padded = padded_rows(num_rows, mesh)
    chunk = padded // jax.process_count()
    if local.shape[0] > chunk:
        raise ValueError(
            f"fetch returned {local.shape[0]} rows, more than this host's "
            f"{chunk}-row block"
        )
    if local.shape[0] < chunk:
        pad = np.zeros(
            (chunk - local.shape[0],) + local.shape[1:], dtype=local.dtype
        )
        local = np.concatenate([local, pad], axis=0)
    return make_global_array(local, mesh, padded)


def make_global_array(local_rows: np.ndarray, mesh, num_rows: int):
    """Assemble a globally row-sharded array from this host's row block.

    ``num_rows`` must be a multiple of the mesh's total device count (use
    `padded_rows`); ``local_rows`` must be this host's full block
    (num_rows // n_hosts rows). No host ever holds the global array.
    """
    import jax

    t = _total_devices(mesh)
    if num_rows % t != 0:
        raise ValueError(
            f"num_rows={num_rows} must be a multiple of the total device "
            f"count {t} — pad first (parallel.multihost.padded_rows)"
        )
    n_hosts = jax.process_count()
    chunk = num_rows // n_hosts
    if local_rows.shape[0] != chunk:
        raise ValueError(
            f"local block has {local_rows.shape[0]} rows, expected "
            f"{chunk} (= padded num_rows // n_hosts)"
        )
    return jax.make_array_from_process_local_data(
        jax.sharding.NamedSharding(
            mesh, dcn_data_spec(*([None] * (local_rows.ndim - 1)))
        ),
        local_rows,
        global_shape=(num_rows, *local_rows.shape[1:]),
    )


# jitted kernels are built once per mesh (see parallel.reductions — a fresh
# closure + jit per call would retrace and recompile on every stats call)
@lru_cache(maxsize=None)
def _global_stats_kernels(mesh):
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    axes = (DCN_AXIS, DATA_AXIS)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(dcn_data_spec(None),),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def pass1(xs):
        v = xs[:, -1:]
        cnt = jax.lax.psum(v.sum(), axes)
        s = jax.lax.psum((xs[:, :-1] * v).sum(axis=0), axes)
        return cnt, s

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(dcn_data_spec(None), P()),
        out_specs=P(),
        check_vma=False,
    )
    def pass2(xs, mean):
        v = xs[:, -1:]
        c = (xs[:, :-1] - mean[None, :]) * v
        return jax.lax.psum((c * c).sum(axis=0), axes)

    return jax.jit(pass1), jax.jit(pass2)


def global_column_stats(x_local: np.ndarray, mesh, num_rows: int) -> dict:
    """Per-column count/mean/var across hosts: per-host row blocks in,
    global statistics out.

    ``x_local`` is this host's REAL rows (`host_row_slice(num_rows, mesh)`);
    padding to the sharded block size plus the validity column are handled
    here, and the variance uses the same two-pass centered-M2 scheme as
    `parallel.reductions.pcolumn_stats` (raw-moment variance cancels
    catastrophically in float32). Cross-host traffic is one psum of the
    per-column partials per pass — never the data. Runs behind the active
    CollectiveGuard when a FailoverController is installed.
    """
    from .guarded import guarded_collective

    return guarded_collective(
        "global_column_stats", _global_column_stats, x_local, mesh, num_rows
    )


def program_trace_specs():
    """Register the DCN-spanning stats kernels with the program auditor:
    traced over a device-free 2x4 ("dcn", "data") AbstractMesh — two
    hosts of four chips — so the TPJ IR lints and the TPS collective
    census inspect the exact cross-host programs without a pod."""
    import jax

    from .compat import abstract_mesh

    mesh = abstract_mesh((DCN_AXIS, 2), (DATA_AXIS, 4), (MODEL_AXIS, 1))
    if mesh is None:  # ancient jax: fall back to the real-device mesh
        mesh = make_multihost_mesh()
    total = 1
    for name in mesh.axis_names:
        total *= int(mesh.shape[name])
    f = 4

    def mat(b, cols):
        return jax.ShapeDtypeStruct((b * total, cols), np.float32)

    pass1, pass2 = _global_stats_kernels(mesh)
    mean = jax.ShapeDtypeStruct((f,), np.float32)
    return [
        dict(
            name="global_stats_pass1", fn=pass1, buckets=(8, 16),
            build=lambda b: ((mat(b, f + 1),), {}),
        ),
        dict(
            name="global_stats_pass2", fn=pass2, buckets=(8, 16),
            build=lambda b: ((mat(b, f + 1), mean), {}),
        ),
    ]


def _global_column_stats(x_local: np.ndarray, mesh, num_rows: int) -> dict:
    import jax

    n_hosts = jax.process_count()
    padded = padded_rows(num_rows, mesh)
    chunk = padded // n_hosts
    x_local = np.asarray(x_local, dtype=np.float32)
    f = x_local.shape[1]
    block = np.zeros((chunk, f + 1), dtype=np.float32)
    block[: len(x_local), :f] = x_local
    block[: len(x_local), f] = 1.0  # validity — padding rows stay 0

    xg = make_global_array(block, mesh, padded)
    pass1, pass2 = _global_stats_kernels(mesh)
    cnt, s = pass1(xg)
    cnt_f = float(np.asarray(cnt))
    mean = np.asarray(s, dtype=np.float64) / max(cnt_f, 1.0)
    m2 = np.asarray(pass2(xg, mean.astype(np.float32)), dtype=np.float64)
    return {
        "count": cnt_f,
        "mean": mean,
        "var": m2 / max(cnt_f, 1.0),
    }
