"""Device-side segment aggregation — event streams reduced per key on the
mesh.

Reference: the Aggregate/Conditional readers fold per-key event sequences
host-side (readers/.../DataReader.scala:206-360, Spark groupBy shuffle).
SURVEY.md §5.7 names long event-sequence aggregation as this framework's
"long axis": the TPU-native equivalent is ``jax.ops.segment_sum``-style
reductions over sorted keys, sharded over the data axis — each shard
reduces its local slice and a ``psum`` combines the per-key partials, so
the whole monoid fold rides ICI instead of a shuffle.

Supported monoids map to the aggregator registry (features/aggregators.py):
sum / max / min / mean / count / logical-or. Keys must be dense ints in
[0, num_segments) (factorize host-side once).
"""
from __future__ import annotations

from functools import lru_cache, partial

import numpy as np

from .mesh import DATA_AXIS

_NEUTRAL = {
    "sum": 0.0,
    "mean": 0.0,
    "count": 0.0,
    "or": 0.0,
    "max": -np.inf,
    "min": np.inf,
}


@lru_cache(maxsize=None)
def _segment_kernels(mesh, num_segments: int, op: str):
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    neutral = _NEUTRAL[op]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    )
    def reduce_shard(values, seg_ids):
        # local segment reduction on this shard
        if op in ("sum", "mean", "count", "or"):
            local = jax.ops.segment_sum(
                values, seg_ids, num_segments=num_segments
            )
            total = jax.lax.psum(local, DATA_AXIS)
        elif op == "max":
            local = jax.ops.segment_max(
                values, seg_ids, num_segments=num_segments
            )
            total = jax.lax.pmax(local, DATA_AXIS)
        else:  # min
            local = jax.ops.segment_min(
                values, seg_ids, num_segments=num_segments
            )
            total = jax.lax.pmin(local, DATA_AXIS)
        return total

    return jax.jit(reduce_shard)


def program_trace_specs():
    """Register the segment-reduce kernels (sum + max — the psum and the
    pmax lowering families) with the program auditor."""
    import jax

    from .compat import abstract_mesh
    from .mesh import make_mesh

    mesh = abstract_mesh((DATA_AXIS, 8), ("model", 1))
    if mesh is None:
        mesh = make_mesh(n_data=len(jax.devices()), n_model=1)
    total = 1
    for name in mesh.axis_names:
        total *= int(mesh.shape[name])

    def build(b):
        n = b * total
        return (
            (jax.ShapeDtypeStruct((n,), np.float32),
             jax.ShapeDtypeStruct((n,), np.int32)),
            {},
        )

    return [
        dict(
            name="psegment_sum", fn=_segment_kernels(mesh, 16, "sum"),
            buckets=(8, 16), build=build,
        ),
        dict(
            name="psegment_max", fn=_segment_kernels(mesh, 16, "max"),
            buckets=(8, 16), build=build,
        ),
    ]


def psegment_reduce(
    values: np.ndarray,
    seg_ids: np.ndarray,
    num_segments: int,
    mesh,
    op: str = "sum",
) -> np.ndarray:
    """Per-segment reduction of ``values`` by dense int keys over the mesh.

    op: 'sum' | 'mean' | 'max' | 'min' | 'count' | 'or'. Rows added as
    padding carry the op's neutral element and segment id 0 with zero
    weight, so results are shard- and padding-invariant.
    """
    import jax.numpy as jnp

    if op not in _NEUTRAL:
        raise ValueError(f"unknown segment op {op!r}")
    values = np.asarray(values, dtype=np.float32)
    seg_ids = np.asarray(seg_ids, dtype=np.int32)
    if op == "count":
        values = np.ones_like(values, dtype=np.float32)
    if op == "or":
        values = (values != 0).astype(np.float32)
    if op == "mean":
        # one kernel dispatch: sums land in segments [0, S), counts in
        # [S, 2S) by offsetting a ones copy's segment ids
        s = int(num_segments)
        both = psegment_reduce(
            np.concatenate([values, np.ones_like(values)]),
            np.concatenate([seg_ids, seg_ids + s]),
            2 * s,
            mesh,
            op="sum",
        )
        sums, counts = both[:s], both[s:]
        with np.errstate(invalid="ignore"):
            return np.where(counts > 0, sums / np.maximum(counts, 1), np.nan)

    n = len(values)
    shards = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    pad = (-n) % shards
    if pad:
        values = np.concatenate(
            [values, np.full(pad, _NEUTRAL[op], dtype=np.float32)]
        )
        # padded rows: segment 0 with neutral value — for sum/count/or the
        # neutral is 0 (no effect); for max/min the neutral is ∓inf
        seg_ids = np.concatenate([seg_ids, np.zeros(pad, dtype=np.int32)])

    # compile-cache discipline: num_segments is data-dependent (the unique
    # key count), so pad it to the next power of two — the jitted kernel set
    # stays O(log max-segments) instead of one program per distinct count
    padded_segments = 1 << max(int(num_segments) - 1, 0).bit_length()
    from .guarded import guarded_collective

    kernel = _segment_kernels(
        mesh, padded_segments, "sum" if op in ("count", "or") else op
    )
    out = np.asarray(
        guarded_collective(
            "psegment_reduce", kernel, jnp.asarray(values),
            jnp.asarray(seg_ids),
        )
    )
    out = out[:num_segments]

    if op == "or":
        out = (out > 0).astype(np.float32)
    return out


def factorize_keys(keys) -> tuple[np.ndarray, list]:
    """Host-side key densification: (dense int ids, sorted unique keys)."""
    uniq = sorted(set(keys))
    index = {k: i for i, k in enumerate(uniq)}
    return np.asarray([index[k] for k in keys], dtype=np.int32), uniq


def aggregate_events_on_device(
    keys,
    values: np.ndarray,
    mesh,
    op: str = "sum",
) -> dict:
    """Convenience: group ``values`` by arbitrary ``keys`` with the given
    monoid on the mesh; returns {key: reduced value}."""
    seg_ids, uniq = factorize_keys(keys)
    out = psegment_reduce(values, seg_ids, len(uniq), mesh, op=op)
    return {k: float(out[i]) for i, k in enumerate(uniq)}
