"""The canonical guarded-collective seam — ONE entry point for every
sharded reduction, and the dynamic side of the SPMD contract auditor.

Every collective-dispatching reduction in the parallel plane
(``pcolumn_stats`` / ``pxtx`` / ``phistogram`` / ``global_column_stats``)
funnels through :func:`guarded_collective`. Historically the seam was a
private ``_guarded`` in ``reductions.py`` that ``multihost.py`` imported
at call time; promoting it here gives the resilience layer, the SPMD
analyzer (:mod:`~transmogrifai_tpu.analysis.spmd`) and the collective
tracer a single module to instrument.

Two duties, layered so the hot path stays free:

* **resilience** — when a ``FailoverController`` is installed
  (``resilience/distributed.py``), the call runs behind its
  ``CollectiveGuard``: straggler deadline + bounded retry, then
  ``HostLostError``. No controller = direct call.
* **tracing** — under ``TPTPU_COLLECTIVE_TRACE=1`` (default OFF: zero
  wrappers, the env var is latched at import exactly like
  ``analysis/schedule.py``'s lock tracing) every ISSUE of a collective —
  retries included, the wrapper sits below the guard's retry loop —
  appends ``(sequence#, name)`` to the tape of every live simulated
  host. ``analysis.spmd.reconcile_collective_orders`` then asserts all
  hosts' tapes are identical (a lost host's tape must be a prefix of the
  survivors') and every entry is explained by the static seam census —
  the third static-vs-runtime reconciler after the transfer census and
  the lock-order graph. The classic SPMD deadlock is precisely a tape
  divergence: one host issuing a collective the others never reach.

Cross-process capture mirrors the lock tracer: set
``TPTPU_COLLECTIVE_TRACE_OUT=<path>`` and an atexit hook dumps the tapes
as JSON for the parent to reconcile.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Any, Callable

__all__ = [
    "TRACE_ENV",
    "TRACE_OUT_ENV",
    "guarded_collective",
    "trace_enabled",
    "set_tracing",
    "collective_tapes",
    "reset_tapes",
    "mark_host_lost",
    "dump_tapes",
    "load_tapes",
]

TRACE_ENV = "TPTPU_COLLECTIVE_TRACE"
TRACE_OUT_ENV = "TPTPU_COLLECTIVE_TRACE_OUT"

#: host -> [(seq, name), ...]; writes hold _TAPE_LOCK (TPL001)
_TAPES: dict[int, list] = {}
#: hosts that stopped recording mid-run (failover) — their tape is
#: expected to be a PREFIX of the survivors'
_LOST: set = set()
_TAPE_LOCK = threading.Lock()
#: participant count, latched on the first recorded collective so a
#: mid-run env change cannot fork the host set
_N_HOSTS: int | None = None
_DUMP_REGISTERED = False


def _env_on() -> bool:
    return os.environ.get(TRACE_ENV, "0").strip().lower() not in (
        "", "0", "false", "off",
    )


#: latched at import (the zero-wrappers contract): with tracing off,
#: guarded_collective is the exact pre-promotion ``_guarded`` body —
#: tests flip it through set_tracing(), subprocess suites set the env
#: var before the interpreter starts
_TRACING = _env_on()


def trace_enabled() -> bool:
    """True when collective-tape recording is active."""
    return _TRACING


def set_tracing(on: bool) -> bool:
    """Test seam: flip tracing in-process (the env-var latch is
    import-time). Returns the previous state. Does not clear tapes —
    call :func:`reset_tapes` for isolation."""
    global _TRACING
    prev = _TRACING
    _TRACING = bool(on)
    if on:
        _register_dump()
    return prev


def _register_dump() -> None:
    global _DUMP_REGISTERED
    if _DUMP_REGISTERED:
        return
    with _TAPE_LOCK:
        if not _DUMP_REGISTERED:
            out = os.environ.get(TRACE_OUT_ENV)
            if out:
                atexit.register(dump_tapes, out)
            _DUMP_REGISTERED = True


def _live_hosts() -> list[int]:
    """Participants still recording. The count is latched on first use;
    the CPU simulation issues each collective once on behalf of every
    live host, so every live tape advances together — which is exactly
    the invariant the reconciler later asserts."""
    global _N_HOSTS
    if _N_HOSTS is None:
        from ..resilience.distributed import simulated_host_count

        _N_HOSTS = simulated_host_count()
    return [h for h in range(_N_HOSTS) if h not in _LOST]


def _record(name: str) -> None:
    with _TAPE_LOCK:
        for h in _live_hosts():
            tape = _TAPES.setdefault(h, [])
            tape.append((len(tape), name))


def mark_host_lost(host: Any) -> None:
    """Close ``host``'s tape (failover pulse — called by the
    FailoverController when it declares a host lost under tracing).
    The lost tape stops advancing; the reconciler requires it to be a
    prefix of every survivor's tape."""
    if not _TRACING:
        return
    with _TAPE_LOCK:
        try:
            _LOST.add(int(host))
        except (TypeError, ValueError):
            return


def guarded_collective(name: str, fn: Callable, *args: Any) -> Any:
    """Run one sharded reduction through the canonical seam.

    No installed FailoverController and tracing off = direct call, zero
    extra work on the hot path. With a controller, the call runs behind
    its CollectiveGuard (straggler deadline + bounded retry, then
    HostLostError). With tracing on, every ATTEMPT records onto the live
    hosts' tapes — the recorder sits below the guard so a retried
    collective tapes once per issue, matching what real transports do.
    """
    from ..resilience import distributed

    run = fn
    if _TRACING:
        def run(*a):  # noqa: E306 - the traced twin of fn
            _record(name)
            return fn(*a)

    guard = distributed.active_collective_guard()
    if guard is None:
        return run(*args)
    return guard.run(name, run, *args)


# ------------------------------------------------------------------ tapes
def collective_tapes() -> dict[str, Any]:
    """JSON-able snapshot of the per-host collective tapes (the shape
    :func:`~transmogrifai_tpu.analysis.spmd.reconcile_collective_orders`
    consumes)."""
    with _TAPE_LOCK:
        hosts = {
            str(h): [[s, n] for s, n in tape]
            for h, tape in sorted(_TAPES.items())
        }
        lost = sorted(_LOST)
        n = _N_HOSTS
    return {
        "traced": _TRACING,
        "nHosts": n if n is not None else len(hosts),
        "hosts": hosts,
        "lost": lost,
    }


def reset_tapes() -> None:
    """Drop every recorded tape and re-latch the host count (test
    isolation)."""
    global _N_HOSTS
    with _TAPE_LOCK:
        _TAPES.clear()
        _LOST.clear()
        _N_HOSTS = None


def dump_tapes(path: str) -> None:
    """Write the tape snapshot as JSON (the atexit hook of a traced
    subprocess run)."""
    doc = collective_tapes()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_tapes(path: str) -> dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


# the env-latched registration runs at the BOTTOM: the atexit hook needs
# dump_tapes bound, and a traced subprocess imports this module exactly
# once before any collective fires
if _TRACING:
    _register_dump()
