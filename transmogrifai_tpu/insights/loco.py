"""RecordInsightsLOCO — batched leave-one-covariate-out explanations.

Reference: core/.../stages/impl/insights/RecordInsightsLOCO.scala:45-347.
For each derived vector column group (text-hash and date columns
aggregated per parent feature, strategy LeaveOutVector), zero it out,
re-score, and report the top-K score differences.

TPU improvement over the reference (SURVEY.md §7 step 7, ROADMAP item 4):
the reference loops per row re-scoring one modified vector at a time; the
previous revision of this module batched the rows but still made one
model call per column group. Here the whole sweep is ONE program family:

* every perturbation lane shares the fused ``[N, width]`` feature plane —
  lane ``g`` is the plane with group ``g``'s column slice zeroed — and the
  sweep dispatches as one ``[lanes × N, width]`` model call (the same
  batched predict program the scoring path already banks, so the sweep
  rides the persistent executable bank instead of compiling per group);
* lane counts are padded onto the shared shape buckets
  (``compiler/bucketing.lane_bucket``) so near-miss group counts reuse one
  program, and the pad/dedup bookkeeping lands in compileStats
  (``record_sweep``) exactly like the GLM candidate sweeps;
* groups whose slice is already all-zero across the batch are DEDUPED out
  before dispatch — zeroing them changes nothing, so their contribution
  is exactly 0.0 without a model call;
* when ``lanes × N × width`` exceeds the memory budget
  (``TPTPU_EXPLAIN_LANE_BUDGET`` float32 elements, default 2^23 ≈ 32 MB)
  the sweep runs as a loop of bucketed lane chunks through the same
  program family instead of one monolithic dispatch.

Every sweep records on the attribution ledger (``insights/ledger.py``):
rows/s, lane dispatch/dedup/pad counts, per-group contribution
statistics, and the vector-metadata fallbacks that silently anonymized
column groups before the ledger existed (surfaced as TPX007 by the
serving-plan auditor).
"""
from __future__ import annotations

import logging
import os

import numpy as np

from ..models.base import PredictorModel
from ..stages.base import Model
from ..stages.metadata import VectorMetadata
from ..types import OPVector, TextMap
from ..types.columns import Column, MapColumn, VectorColumn
from . import ledger as _ledger

log = logging.getLogger(__name__)

ABS = "abs"
POSITIVE_NEGATIVE = "positive_negative"

#: max float32 elements a single perturbation dispatch may materialize
#: (lanes × rows × width); larger sweeps loop over bucketed lane chunks
_DEFAULT_LANE_BUDGET = 1 << 23


def _lane_budget() -> int:
    try:
        return max(
            1, int(os.environ.get(
                "TPTPU_EXPLAIN_LANE_BUDGET", str(_DEFAULT_LANE_BUDGET)
            ))
        )
    except ValueError:
        return _DEFAULT_LANE_BUDGET


def _column_groups(
    meta: VectorMetadata | None, dim: int, count_fallback: bool = True
) -> list[tuple[str, list[int]]]:
    """Group hashed-text/date columns by parent feature; pivot/numeric
    columns stay individual (RecordInsightsLOCO text aggregation).

    When ``meta`` is absent or inconsistent with the vector width the
    grouping degrades to anonymous per-column groups — that degradation
    used to be silent; it now counts ``metaFallbacks`` on the attribution
    ledger (and the serving-plan auditor reports it as TPX007)."""
    if meta is None or meta.size != dim:
        if count_fallback:
            _ledger.stats().count_meta_fallback()
            log.warning(
                "LOCO column groups degraded to anonymous per-column "
                "groups: vector metadata %s (width %d) — attributions "
                "will name col_<j> instead of features (TPX007)",
                "absent" if meta is None
                else f"size {meta.size} != {dim}",
                dim,
            )
        return [(f"col_{j}", [j]) for j in range(dim)]
    groups: dict[str, list[int]] = {}
    order: list[str] = []
    for j, cm in enumerate(meta.columns):
        if cm.descriptor_value is not None and cm.descriptor_value.startswith("hash_"):
            key = f"{'_'.join(cm.parent_names)}(text)"
        elif cm.descriptor_value is not None:
            key = "_".join(cm.parent_names)  # date components aggregate
        else:
            key = cm.make_name()
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(j)
    return [(k, groups[k]) for k in order]


#: public alias (the serving closure and the train-time profiler group
#: the same way the transformer does)
column_groups = _column_groups


def _floor_lane_bucket(k: int) -> int:
    """Largest lane-bucket boundary <= ``k``, so ``lane_bucket`` of any
    chunk of this size — or a smaller padded tail — never exceeds it.
    Derived from ``compiler.bucketing.lane_bucket`` itself (one source
    of truth for the boundary ladder; a few dozen probes at most)."""
    from ..compiler.bucketing import lane_bucket

    b = max(1, k)
    while b > 1 and lane_bucket(b) > b:
        b -= 1
    return b


def base_from_arrays(
    prob: np.ndarray | None, pred: np.ndarray | None
) -> tuple[np.ndarray, np.ndarray | None]:
    """(base score, base class) from already-rendered prediction arrays —
    the probability of each row's OWN predicted class for classifiers,
    the prediction itself for regressors. Shared by the staged sweep and
    the fused graph's in-dispatch lanes."""
    if prob is not None:
        prob = np.asarray(prob)
        base_class = np.argmax(prob, axis=1)
        rows = np.arange(len(prob))
        return prob[rows, base_class].astype(np.float64), base_class
    return np.asarray(pred, dtype=np.float64), None


def scores_from_outputs(
    pred_p: np.ndarray | None,
    prob_p: np.ndarray | None,
    base_class: np.ndarray | None,
    lanes: int,
    n: int,
) -> np.ndarray:
    """[lanes, N] perturbed scores tracked against each row's BASE class
    (so perturbed scores of different classes are never compared) — the
    one place the lane-output → score convention lives."""
    if prob_p is not None and base_class is not None:
        return prob_p.reshape(lanes, n, -1)[:, np.arange(n), base_class]
    return np.asarray(pred_p, dtype=np.float64).reshape(lanes, n)


def group_masks(
    groups: list[tuple[str, list[int]]], width: int, lanes: int | None = None
) -> np.ndarray:
    """[lanes, width] f32 column masks for the in-graph sweep: lane g is
    1.0 on group g's column slice. Rows beyond ``len(groups)`` (bucket
    padding) stay all-zero — an unperturbed plane whose diff is exactly
    0, sliced off by the caller."""
    out = np.zeros((lanes or len(groups), width), dtype=np.float32)
    for g, (_, idxs) in enumerate(groups):
        out[g, idxs] = 1.0
    return out


def _base_scores(
    model: PredictorModel,
    x: np.ndarray,
    base_prob: np.ndarray | None = None,
    base_pred: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray | None]:
    """Per-row base score tracked against the BASE prediction's class
    (RecordInsightsLOCO tracks the original class's probability, so
    perturbed scores of different classes are never compared). Callers
    that already hold the batch's PredictionColumn pass its arrays in and
    skip the extra base dispatch."""
    if base_prob is not None or base_pred is not None:
        return base_from_arrays(base_prob, base_pred)
    pred, prob, _ = model.predict_arrays(x)
    return base_from_arrays(prob, pred)


def explain_batch(
    model: PredictorModel,
    x: np.ndarray,
    groups: list[tuple[str, list[int]]],
    base_prob: np.ndarray | None = None,
    base_pred: np.ndarray | None = None,
) -> tuple[np.ndarray, dict[str, int]]:
    """LOCO contribution matrix ``[N, G]`` for one feature plane.

    ``diffs[i, g]`` = base score of row ``i`` minus its score with group
    ``g``'s columns zeroed (positive = the group pushed the score UP).
    One batched program family: dedup → lane bucketing → ``[lanes×N, D]``
    dispatch(es) under the memory budget. ``base_prob``/``base_pred``
    reuse an already-computed base prediction (the serving path passes
    the batch's PredictionColumn arrays).

    Returns ``(diffs, sweep_info)`` where ``sweep_info`` carries the lane
    bookkeeping (``lanes`` dispatched incl. pads, ``deduped``, ``padded``,
    ``dispatches``) for the caller's ledger record — the caller owns the
    clock read, so it records rows/seconds in ONE ``record_explain``."""
    from ..compiler import stats as cstats
    from ..compiler.bucketing import lane_bucket

    x = np.ascontiguousarray(x, dtype=np.float32)
    n, dim = x.shape
    g_count = len(groups)
    diffs = np.zeros((n, g_count), dtype=np.float64)
    info = {"lanes": 0, "deduped": 0, "padded": 0, "dispatches": 0}
    if n == 0 or g_count == 0:
        return diffs, info
    base, base_class = _base_scores(model, x, base_prob, base_pred)

    # dedup: a group whose slice is all-zero across the batch cannot move
    # any score — its contribution is exactly 0.0, no lane dispatched
    live: list[int] = []
    for g, (_, idxs) in enumerate(groups):
        if np.any(x[:, idxs]):
            live.append(g)
    info["deduped"] = g_count - len(live)
    if not live:
        return diffs, info

    # lane chunks under the memory budget, each padded onto the shared
    # shape buckets so the dispatch shapes form a small program family.
    # The chunk size is FLOORED to a bucket boundary: a chunk sized
    # budget//(n*dim) would be rounded UP by lane_bucket and the padded
    # dispatch could materialize ~2x the budget — flooring guarantees
    # every chunk (including a padded final partial) stays <= per_chunk
    per_chunk = _floor_lane_bucket(
        max(1, _lane_budget() // max(1, n * dim))
    )
    for start in range(0, len(live), per_chunk):
        chunk = live[start:start + per_chunk]
        k = len(chunk)
        kb = lane_bucket(k)
        pad = kb - k
        plane = np.broadcast_to(x, (kb, n, dim)).copy()
        for lane, g in enumerate(chunk):
            plane[lane, :, groups[g][1]] = 0.0
        # pad lanes replay lane 0 (already zeroed) — inert, sliced off
        pred_p, prob_p, _ = model.predict_arrays(
            plane.reshape(kb * n, dim)
        )
        scores = scores_from_outputs(pred_p, prob_p, base_class, kb, n)
        for lane, g in enumerate(chunk):
            diffs[:, g] = base - scores[lane]
        cstats.stats().record_sweep(lanes=k, padded=pad)
        info["lanes"] += kb
        info["padded"] += pad
        info["dispatches"] += 1
    return diffs, info


def top_k_maps(
    diffs: np.ndarray,
    names: list[str],
    top_k: int,
    strategy: str = ABS,
) -> tuple[list[dict[str, float]], np.ndarray]:
    """Per-row top-k maps (ranked insertion order) + per-group hit counts.

    Selection semantics match the reference exactly: ``abs`` takes the k
    largest |contribution|s; ``positive_negative`` takes the k most
    positive AND k most negative (RecordInsightsLOCO.scala:91)."""
    n, g_count = diffs.shape
    k = min(top_k, g_count)
    hits = np.zeros(g_count, dtype=np.int64)
    values: list[dict[str, float]] = []
    for i in range(n):
        row = diffs[i]
        if strategy == ABS:
            picked = list(np.argsort(-np.abs(row))[:k])
        else:
            # topK most positive AND topK most negative
            # (RecordInsightsLOCO.scala:91 PositiveNegative strategy)
            order = np.argsort(-row)
            pos = [j for j in order[:k] if row[j] > 0]
            neg = [j for j in order[::-1][:k] if row[j] < 0]
            picked = pos + [j for j in neg if j not in pos]
        hits[picked] += 1
        values.append({names[j]: float(row[j]) for j in picked})
    return values, hits


def reference_loop(
    model: PredictorModel,
    x: np.ndarray,
    groups: list[tuple[str, list[int]]],
) -> np.ndarray:
    """The pre-batched implementation — one model call PER COLUMN GROUP —
    kept as the golden oracle for the parity suite (never on a hot
    path)."""
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[0]
    base, base_class = _base_scores(model, x)
    diffs = np.zeros((n, len(groups)), dtype=np.float64)
    rows = np.arange(n)
    for gi, (_, idxs) in enumerate(groups):
        x2 = x.copy()
        x2[:, idxs] = 0.0
        pred, prob, _ = model.predict_arrays(x2)
        if prob is not None and base_class is not None:
            diffs[:, gi] = base - prob[rows, base_class]
        else:
            diffs[:, gi] = base - np.asarray(pred, dtype=np.float64)
    return diffs


class RecordInsightsLOCO(Model):
    """Transformer[OPVector] -> TextMap of top-K column contributions.

    A ``Model`` (not a plain Transformer) so workflow persistence saves the
    wrapped predictor's arrays; the nested model round-trips via
    class-name + params in ``get_params`` and namespaced arrays.
    """

    input_types = (OPVector,)
    output_type = TextMap

    def __init__(
        self,
        model: PredictorModel,
        top_k: int = 20,
        strategy: str = ABS,
        uid: str | None = None,
    ):
        super().__init__("recordInsightsLOCO", uid=uid)
        self.model = model
        self.top_k = top_k
        self.strategy = strategy
        #: (metadata object, dim, groups) — metadata is fit-static, so a
        #: metadata-less vector logs/counts its degradation ONCE per
        #: stage, not once per scored batch. The cache HOLDS the metadata
        #: object (identity compared with ``is``): an id()-keyed cache
        #: could serve stale groups after the id is recycled by GC
        self._groups_cache: tuple | None = None

    def get_params(self):
        return {
            "top_k": self.top_k,
            "strategy": self.strategy,
            "model_class": type(self.model).__name__,
            "model_params": self.model.get_params(),
        }

    def get_arrays(self):
        return {f"model__{k}": v for k, v in self.model.get_arrays().items()}

    @classmethod
    def from_params(cls, params: dict, arrays: dict) -> "RecordInsightsLOCO":
        from ..workflow.persistence import construct_stage

        params = dict(params)
        model = construct_stage(
            params.pop("model_class"),
            params.pop("model_params"),
            {k[len("model__"):]: v for k, v in arrays.items()
             if k.startswith("model__")},
        )
        return cls(model=model, **params)

    def transform_columns(self, *cols: Column, num_rows: int) -> MapColumn:
        from ..telemetry import spans as _tspans

        vec = cols[-1]
        assert isinstance(vec, VectorColumn)
        x = np.asarray(vec.values, dtype=np.float32)
        cached = self._groups_cache
        if (
            cached is None
            or cached[0] is not vec.metadata
            or cached[1] != x.shape[1]
        ):
            cached = self._groups_cache = (
                vec.metadata, x.shape[1],
                _column_groups(vec.metadata, x.shape[1]),
            )
        groups = cached[2]
        t0 = _tspans.clock()
        diffs, info = explain_batch(self.model, x, groups)
        names = [name for name, _ in groups]
        values, hits = top_k_maps(
            diffs[:num_rows], names, self.top_k, self.strategy
        )
        led = _ledger.stats()
        led.record_explain(
            num_rows, _tspans.clock() - t0, lanes=info["lanes"],
            deduped=info["deduped"], padded=info["padded"],
        )
        led.record_groups(names, diffs[:num_rows], hits)
        return MapColumn(TextMap, values)
