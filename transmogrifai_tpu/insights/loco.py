"""RecordInsightsLOCO — per-row leave-one-covariate-out explanations.

Reference: core/.../stages/impl/insights/RecordInsightsLOCO.scala:45-347.
For each derived vector column (text-hash and date columns aggregated per
parent feature, strategy LeaveOutVector), zero it out, re-score, and report
the top-K score differences as a map column.

TPU improvement over the reference (SURVEY.md §7 step 7): the reference
loops per row re-scoring one modified vector at a time; here the whole
(rows × groups) sweep is BATCHED — one model call per column group over all
rows at once.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from ..models.base import PredictorModel
from ..stages.base import Model
from ..stages.metadata import VectorMetadata
from ..types import OPVector, TextMap
from ..types.columns import Column, MapColumn, VectorColumn

ABS = "abs"
POSITIVE_NEGATIVE = "positive_negative"


def _column_groups(meta: VectorMetadata | None, dim: int) -> list[tuple[str, list[int]]]:
    """Group hashed-text/date columns by parent feature; pivot/numeric
    columns stay individual (RecordInsightsLOCO text aggregation)."""
    if meta is None or meta.size != dim:
        return [(f"col_{j}", [j]) for j in range(dim)]
    groups: dict[str, list[int]] = {}
    order: list[str] = []
    for j, cm in enumerate(meta.columns):
        if cm.descriptor_value is not None and cm.descriptor_value.startswith("hash_"):
            key = f"{'_'.join(cm.parent_names)}(text)"
        elif cm.descriptor_value is not None:
            key = "_".join(cm.parent_names)  # date components aggregate
        else:
            key = cm.make_name()
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(j)
    return [(k, groups[k]) for k in order]


class RecordInsightsLOCO(Model):
    """Transformer[OPVector] -> TextMap of top-K column contributions.

    A ``Model`` (not a plain Transformer) so workflow persistence saves the
    wrapped predictor's arrays; the nested model round-trips via
    class-name + params in ``get_params`` and namespaced arrays.
    """

    input_types = (OPVector,)
    output_type = TextMap

    def __init__(
        self,
        model: PredictorModel,
        top_k: int = 20,
        strategy: str = ABS,
        uid: str | None = None,
    ):
        super().__init__("recordInsightsLOCO", uid=uid)
        self.model = model
        self.top_k = top_k
        self.strategy = strategy

    def get_params(self):
        return {
            "top_k": self.top_k,
            "strategy": self.strategy,
            "model_class": type(self.model).__name__,
            "model_params": self.model.get_params(),
        }

    def get_arrays(self):
        return {f"model__{k}": v for k, v in self.model.get_arrays().items()}

    @classmethod
    def from_params(cls, params: dict, arrays: dict) -> "RecordInsightsLOCO":
        from ..workflow.persistence import construct_stage

        params = dict(params)
        model = construct_stage(
            params.pop("model_class"),
            params.pop("model_params"),
            {k[len("model__"):]: v for k, v in arrays.items()
             if k.startswith("model__")},
        )
        return cls(model=model, **params)

    def _score(self, x: np.ndarray, base_class: np.ndarray | None = None):
        """Per-row score tracked against the BASE prediction's class
        (RecordInsightsLOCO tracks the original class's probability, so
        perturbed scores of different classes are never compared)."""
        pred, prob, raw = self.model.predict_arrays(x)
        if prob is None:
            return pred, None
        if base_class is None:
            base_class = prob.argmax(axis=1)
        rows = np.arange(len(prob))
        return prob[rows, base_class], base_class

    def transform_columns(self, *cols: Column, num_rows: int) -> MapColumn:
        vec = cols[-1]
        assert isinstance(vec, VectorColumn)
        x = np.asarray(vec.values, dtype=np.float32)
        base, base_class = self._score(x)
        groups = _column_groups(vec.metadata, x.shape[1])

        diffs = np.zeros((num_rows, len(groups)), dtype=np.float64)
        for gi, (_, idxs) in enumerate(groups):
            x2 = x.copy()
            x2[:, idxs] = 0.0
            diffs[:, gi] = base - self._score(x2, base_class)[0]

        names = [name for name, _ in groups]
        values: list[dict] = []
        k = min(self.top_k, len(groups))
        for i in range(num_rows):
            row = diffs[i]
            if self.strategy == ABS:
                picked = list(np.argsort(-np.abs(row))[:k])
            else:
                # topK most positive AND topK most negative
                # (RecordInsightsLOCO.scala:91 PositiveNegative strategy)
                order = np.argsort(-row)
                pos = [j for j in order[:k] if row[j] > 0]
                neg = [j for j in order[::-1][:k] if row[j] < 0]
                picked = pos + [j for j in neg if j not in pos]
            values.append({names[j]: float(row[j]) for j in picked})
        return MapColumn(TextMap, values)
