"""``attributionStats`` — the explainability plane's process-wide ledger.

The third stage-family ledger beside ``compiler.stats`` (compileStats)
and ``featurize.stats`` (featurizeStats): one thread-safe counter object
records every record-insights event — rows explained with their
wall-clock (so the snapshot reports explain rows/s against plain scoring
throughput), perturbation-lane dispatches with their dedup/pad
bookkeeping, the vector-metadata fallbacks that silently anonymized
column groups before this ledger existed, and the degradation counters
(explain work shed under load, explain skipped on a spent deadline
budget, attribution-drift alerts).

Per feature group it accumulates the streaming attribution statistics
the drift monitor and the bench report read: mean |contribution|, the
sign mix (how often the group pushed the score up vs down), and top-k
hit counts (how often the group made a row's returned top-k).

Counters are cumulative per process; consumers wanting a per-phase view
take ``snapshot()`` before and ``delta(before)`` after (the bench
``explain`` mode does). The counter dict, lock, and delta arithmetic
come from :class:`telemetry.metrics.LedgerCore` — the same shared
re-entrant lock under compileStats/featurizeStats, so a
``telemetry.snapshot_lock()`` read is consistent across all ledgers.
The ledger registers itself as the ``attribution`` source of
``telemetry.render_prometheus()``.
"""
from __future__ import annotations

import numpy as np

from ..telemetry import metrics as _tm

_COUNTER_KEYS = (
    "rowsExplained",         # rows that received LOCO attributions
    "explainBatches",        # explain sweeps executed (one per scored batch)
    "laneDispatches",        # perturbation-lane model dispatches (post-dedup,
                             # incl. bucket-pad lanes)
    "lanesDeduped",          # lanes skipped because the group slice was
                             # already all-zero for the whole batch (diff==0
                             # without a model call)
    "lanesPadded",           # inert lanes added by shape-bucket padding
    "metaFallbacks",         # vector metadata absent/mismatched: LOCO fell
                             # back to anonymous per-column groups (TPX007)
    "explainShedRows",       # rows whose explain work was shed by the load
                             # shedder (tier 1, the first casualty)
    "explainDeadlineSkips",  # explain sweeps skipped because the request's
                             # remaining budget could not cover the explain
                             # family's p95
    "explainErrors",         # sweeps that errored mid-flight (contained:
                             # scores kept, attributions degraded to None)
    "attributionDriftAlerts",  # fresh attribution-drift alerts (model-
                             # behavior drift, not input drift)
    "profilesCaptured",      # train-time baseline attribution profiles
    "explainBudgetSkips",    # fused-graph explain sweeps skipped because
                             # lanes x rows x width exceeded the lane
                             # budget for a single dispatch (scores kept)
)


class AttributionStats(_tm.LedgerCore):
    """Thread-safe counters; explain wall-clock seconds and per-group
    streaming statistics ride along."""

    def __init__(self) -> None:
        super().__init__(_COUNTER_KEYS)
        self._explain_s = 0.0
        #: group name -> [rows, sum|c|, positive, negative, topKHits]
        self._groups: dict[str, list[float]] = {}

    # ------------------------------------------------------------ recording
    def record_explain(
        self,
        rows: int,
        seconds: float,
        lanes: int,
        deduped: int = 0,
        padded: int = 0,
    ) -> None:
        """One explain sweep: ``rows`` rows × ``lanes`` dispatched lanes
        in ``seconds`` (``deduped`` lanes skipped, ``padded`` inert)."""
        with self._lock:
            self._counts["rowsExplained"] += rows
            self._counts["explainBatches"] += 1
            self._counts["laneDispatches"] += lanes
            self._counts["lanesDeduped"] += deduped
            self._counts["lanesPadded"] += padded
            self._explain_s += seconds

    def record_groups(
        self,
        names: list[str],
        diffs: np.ndarray,
        topk_counts: np.ndarray | None = None,
    ) -> None:
        """Streaming per-group statistics from one sweep's ``[N, G]``
        contribution matrix (``topk_counts[g]`` = rows where group ``g``
        made the returned top-k)."""
        if diffs.size == 0:
            return
        n = diffs.shape[0]
        sum_abs = np.abs(diffs).sum(axis=0)
        pos = (diffs > 0).sum(axis=0)
        neg = (diffs < 0).sum(axis=0)
        with self._lock:
            for g, name in enumerate(names):
                cell = self._groups.setdefault(name, [0.0] * 5)
                cell[0] += n
                cell[1] += float(sum_abs[g])
                cell[2] += int(pos[g])
                cell[3] += int(neg[g])
                if topk_counts is not None:
                    cell[4] += int(topk_counts[g])

    def count_meta_fallback(self) -> None:
        self.bump("metaFallbacks")

    def count_shed(self, rows: int) -> None:
        self.bump("explainShedRows", rows)

    def count_deadline_skip(self) -> None:
        self.bump("explainDeadlineSkips")

    def count_budget_skip(self) -> None:
        self.bump("explainBudgetSkips")

    def count_error(self) -> None:
        self.bump("explainErrors")

    def count_drift_alert(self) -> None:
        self.bump("attributionDriftAlerts")

    def count_profile(self) -> None:
        self.bump("profilesCaptured")

    # ------------------------------------------------------------ reporting
    def snapshot(self) -> dict:
        """JSON-able view. ``explainRowsPerSec`` is rows over sweep
        seconds; ``groups`` reports the streaming per-group statistics
        (mean |contribution|, sign mix, top-k hit counts)."""
        with self._lock:
            out: dict = dict(self._counts)
            out["explainSeconds"] = round(self._explain_s, 4)
            groups = {
                name: _group_cell(cell)
                for name, cell in sorted(self._groups.items())
            }
        out["explainRowsPerSec"] = (
            round(out["rowsExplained"] / out["explainSeconds"])
            if out["explainSeconds"] > 0 else None
        )
        out["groups"] = groups
        return out

    def reset(self) -> None:
        with self._lock:
            self._reset_counts()
            self._explain_s = 0.0
            self._groups = {}


def _group_cell(cell: list[float]) -> dict:
    rows = int(cell[0])
    signed = cell[2] + cell[3]
    return {
        "rows": rows,
        "meanAbsContribution": (
            round(cell[1] / rows, 6) if rows else None
        ),
        "positive": int(cell[2]),
        "negative": int(cell[3]),
        "positiveFraction": _tm.ratio(cell[2], signed),
        "topKHits": int(cell[4]),
    }


_STATS = AttributionStats()
_tm.REGISTRY.register_source("attribution", _STATS.snapshot)


def stats() -> AttributionStats:
    return _STATS


def snapshot() -> dict:
    return _STATS.snapshot()


def delta(before: dict) -> dict:
    """Per-phase view: current snapshot minus an earlier ``snapshot()``
    (rates recomputed from the deltas, not differenced)."""
    now = _STATS.snapshot()
    out: dict = _tm.counter_delta(now, before, _COUNTER_KEYS)
    out["explainSeconds"] = _tm.float_delta(
        now, before, "explainSeconds", ndigits=4
    )
    out["explainRowsPerSec"] = (
        round(out["rowsExplained"] / out["explainSeconds"])
        if out["explainSeconds"] > 0 else None
    )
    before_groups = before.get("groups", {})
    groups = {}
    for name, cell in now["groups"].items():
        prev = before_groups.get(name, {})
        rows = cell["rows"] - prev.get("rows", 0)
        if rows:
            groups[name] = {
                "rows": rows,
                "topKHits": cell["topKHits"] - prev.get("topKHits", 0),
            }
    out["groups"] = groups
    return out
