"""Model interpretability (reference: ModelInsights, RecordInsightsLOCO).

Beyond the reference's offline surfaces this package carries the
serving-speed explainability plane (ROADMAP item 4): the batched LOCO
program family (:mod:`.loco`), the process-wide attribution ledger
(:mod:`.ledger`, the ``attribution`` Prometheus source), and attribution
drift — model-behavior drift detection over contribution distributions
(:mod:`.drift`). See docs/observability.md."""
from .model_insights import model_insights  # noqa: F401
from .loco import (  # noqa: F401
    RecordInsightsLOCO,
    column_groups,
    explain_batch,
    top_k_maps,
)
from .correlation import RecordInsightsCorr, RecordInsightsCorrModel  # noqa: F401
from .drift import (  # noqa: F401
    AttributionDriftMonitor,
    compute_attribution_profile,
)
from . import ledger as attribution_ledger  # noqa: F401
