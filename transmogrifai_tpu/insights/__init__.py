"""Model interpretability (reference: ModelInsights, RecordInsightsLOCO)."""
from .model_insights import model_insights  # noqa: F401
from .loco import RecordInsightsLOCO  # noqa: F401
from .correlation import RecordInsightsCorr, RecordInsightsCorrModel  # noqa: F401
