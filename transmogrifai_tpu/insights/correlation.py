"""RecordInsightsCorr — correlation-based per-record explanations.

Reference: core/.../stages/impl/insights/RecordInsightsCorr.scala:55-220.
Fit: Pearson (or Spearman) correlation of every feature column with every
prediction column, plus column stats for normalization. Transform: per
record, importance = corr[pred, feature] · normalized feature value; the
top-K |importance| columns per prediction are reported as a map.

All device math is two matmuls (XᵀY correlation and the normalize-multiply),
so unlike the reference's RDD stats pass this fits in one fused XLA program.
"""
from __future__ import annotations

import json

import numpy as np

from ..stages.base import Estimator, Model
from ..stages.metadata import VectorMetadata
from ..types import OPVector, TextMap
from ..types.columns import Column, MapColumn, PredictionColumn, VectorColumn

MIN_MAX = "minmax"
Z_SCORE = "zscore"
NONE = "none"


def _scores_matrix(col: Column) -> np.ndarray:
    """Prediction columns become [N, C] scores; plain vectors pass through."""
    if isinstance(col, PredictionColumn):
        if col.probability is not None:
            return np.asarray(col.probability, dtype=np.float64)
        return np.asarray(col.prediction, dtype=np.float64)[:, None]
    assert isinstance(col, VectorColumn)
    return np.asarray(col.values, dtype=np.float64)


class RecordInsightsCorr(Estimator):
    """BinaryEstimator[(Prediction|OPVector, OPVector)] → TextMap."""

    output_type = TextMap

    def __init__(
        self,
        top_k: int = 20,
        norm_type: str = MIN_MAX,
        correlation_type: str = "pearson",
        uid: str | None = None,
    ):
        super().__init__("recordInsightsCorr", uid=uid)
        self.top_k = top_k
        self.norm_type = norm_type
        self.correlation_type = correlation_type

    def get_params(self):
        return {
            "top_k": self.top_k,
            "norm_type": self.norm_type,
            "correlation_type": self.correlation_type,
        }

    def fit_model(self, dataset) -> "RecordInsightsCorrModel":
        pred_name, vec_name = self.input_names
        scores = _scores_matrix(dataset[pred_name])
        vec = dataset[vec_name]
        assert isinstance(vec, VectorColumn)
        x = np.asarray(vec.values, dtype=np.float64)

        if self.correlation_type == "spearman":
            from scipy.stats import rankdata  # pragma: no cover - optional

            x_c = rankdata(x, axis=0)
            s_c = rankdata(scores, axis=0)
        else:
            x_c, s_c = x, scores
        xs = (x_c - x_c.mean(0)) / np.where(x_c.std(0) == 0, 1.0, x_c.std(0))
        ss = (s_c - s_c.mean(0)) / np.where(s_c.std(0) == 0, 1.0, s_c.std(0))
        corr = ss.T @ xs / len(x)  # [C, D]
        corr = np.nan_to_num(corr)

        if self.norm_type == MIN_MAX:
            lo, hi = x.min(0), x.max(0)
            scale = np.where(hi > lo, hi - lo, 1.0)
            norm = ("minmax", lo, scale)
        elif self.norm_type == Z_SCORE:
            mu, sd = x.mean(0), np.where(x.std(0) == 0, 1.0, x.std(0))
            norm = ("zscore", mu, sd)
        else:
            norm = ("none", np.zeros(x.shape[1]), np.ones(x.shape[1]))
        self.metadata["numPredCols"] = int(corr.shape[0])
        return RecordInsightsCorrModel(
            corr, norm[0], norm[1], norm[2], self.top_k, vec.metadata
        )


class RecordInsightsCorrModel(Model):
    output_type = TextMap

    def __init__(self, corr, norm_kind, shift, scale, top_k, meta=None, uid=None):
        super().__init__("recordInsightsCorr", uid=uid)
        self.corr = np.asarray(corr, dtype=np.float64)
        self.norm_kind = norm_kind
        self.shift = np.asarray(shift, dtype=np.float64)
        self.scale = np.asarray(scale, dtype=np.float64)
        self.top_k = top_k
        self._meta: VectorMetadata | None = meta

    def get_params(self):
        return {"top_k": self.top_k, "norm_kind": self.norm_kind}

    def get_arrays(self):
        return {"corr": self.corr, "shift": self.shift, "scale": self.scale}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(
            arrays["corr"], params["norm_kind"], arrays["shift"],
            arrays["scale"], params["top_k"],
        )

    def _names(self, dim: int) -> list[str]:
        if self._meta is not None and self._meta.size == dim:
            return self._meta.column_names()
        return [f"col_{j}" for j in range(dim)]

    def transform_columns(self, *cols: Column, num_rows: int) -> MapColumn:
        vec = cols[-1]
        assert isinstance(vec, VectorColumn)
        x = np.asarray(vec.values, dtype=np.float64)
        if self._meta is None:
            self._meta = vec.metadata
        normalized = (x - self.shift[None, :]) / self.scale[None, :]
        # importance [N, C, D]
        imp = self.corr[None, :, :] * normalized[:, None, :]
        names = self._names(x.shape[1])
        out = []
        k = min(self.top_k, x.shape[1])
        for r in range(num_rows):
            row: dict[str, str] = {}
            scores = imp[r]  # [C, D]
            order = np.argsort(-np.abs(scores), axis=1)[:, :k]
            for ci in range(scores.shape[0]):
                for j in order[ci]:
                    row.setdefault(
                        names[int(j)],
                        json.dumps([[ci, float(scores[ci, int(j)])]]),
                    )
            out.append(row)
        return MapColumn(TextMap, out)
