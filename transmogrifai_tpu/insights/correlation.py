"""RecordInsightsCorr — correlation-based per-record explanations.

Reference: core/.../stages/impl/insights/RecordInsightsCorr.scala:55-220.
Fit: Pearson (or Spearman) correlation of every feature column with every
prediction column, plus column stats for normalization. Transform: per
record, importance = corr[pred, feature] · normalized feature value; the
top-K |importance| columns per prediction are reported as a map.

The fit is two matmuls (XᵀY correlation + normalization stats); the
transform processes rows in fixed-size blocks with top-k selection via
argpartition, so memory stays at block×D per prediction column.
"""
from __future__ import annotations

import json

import numpy as np

from ..stages.base import Estimator, Model
from ..stages.metadata import VectorMetadata
from ..types import OPVector, TextMap
from ..types.columns import Column, MapColumn, PredictionColumn, VectorColumn

MIN_MAX = "minmax"
Z_SCORE = "zscore"
NONE = "none"


def _scores_matrix(col: Column) -> np.ndarray:
    """Prediction columns become [N, C] scores; plain vectors pass through."""
    if isinstance(col, PredictionColumn):
        if col.probability is not None:
            return np.asarray(col.probability, dtype=np.float64)
        return np.asarray(col.prediction, dtype=np.float64)[:, None]
    assert isinstance(col, VectorColumn)
    return np.asarray(col.values, dtype=np.float64)


class RecordInsightsCorr(Estimator):
    """BinaryEstimator[(Prediction|OPVector, OPVector)] → TextMap."""

    output_type = TextMap

    def __init__(
        self,
        top_k: int = 20,
        norm_type: str = MIN_MAX,
        correlation_type: str = "pearson",
        uid: str | None = None,
    ):
        super().__init__("recordInsightsCorr", uid=uid)
        self.top_k = top_k
        self.norm_type = norm_type
        self.correlation_type = correlation_type

    def get_params(self):
        return {
            "top_k": self.top_k,
            "norm_type": self.norm_type,
            "correlation_type": self.correlation_type,
        }

    def fit_model(self, dataset) -> "RecordInsightsCorrModel":
        pred_name, vec_name = self.input_names
        scores = _scores_matrix(dataset[pred_name])
        vec = dataset[vec_name]
        assert isinstance(vec, VectorColumn)
        x = np.asarray(vec.values, dtype=np.float64)

        if self.correlation_type == "spearman":
            from scipy.stats import rankdata  # pragma: no cover - optional

            x_c = rankdata(x, axis=0)
            s_c = rankdata(scores, axis=0)
        else:
            x_c, s_c = x, scores
        x_sd = x_c.std(0)
        s_sd = s_c.std(0)
        xs = (x_c - x_c.mean(0)) / np.where(x_sd == 0, 1.0, x_sd)
        ss = (s_c - s_c.mean(0)) / np.where(s_sd == 0, 1.0, s_sd)
        corr = ss.T @ xs / len(x)  # [C, D]
        corr = np.nan_to_num(corr)

        if self.norm_type == MIN_MAX:
            lo, hi = x.min(0), x.max(0)
            scale = np.where(hi > lo, hi - lo, 1.0)
            norm = ("minmax", lo, scale)
        elif self.norm_type == Z_SCORE:
            mu, sd = x.mean(0), np.where(x.std(0) == 0, 1.0, x.std(0))
            norm = ("zscore", mu, sd)
        else:
            norm = ("none", np.zeros(x.shape[1]), np.ones(x.shape[1]))
        self.metadata["numPredCols"] = int(corr.shape[0])
        return RecordInsightsCorrModel(
            corr, norm[0], norm[1], norm[2], self.top_k, vec.metadata
        )


class RecordInsightsCorrModel(Model):
    output_type = TextMap

    def __init__(self, corr, norm_kind, shift, scale, top_k, meta=None, uid=None):
        super().__init__("recordInsightsCorr", uid=uid)
        self.corr = np.asarray(corr, dtype=np.float64)
        self.norm_kind = norm_kind
        self.shift = np.asarray(shift, dtype=np.float64)
        self.scale = np.asarray(scale, dtype=np.float64)
        self.top_k = top_k
        self._meta: VectorMetadata | None = meta

    def get_params(self):
        return {"top_k": self.top_k, "norm_kind": self.norm_kind}

    def get_arrays(self):
        return {"corr": self.corr, "shift": self.shift, "scale": self.scale}

    @classmethod
    def from_params(cls, params, arrays):
        return cls(
            arrays["corr"], params["norm_kind"], arrays["shift"],
            arrays["scale"], params["top_k"],
        )

    def _names(self, dim: int) -> list[str]:
        if self._meta is not None and self._meta.size == dim:
            return self._meta.column_names()
        return [f"col_{j}" for j in range(dim)]

    #: rows per block — bounds peak memory at BLOCK×D per prediction column
    #: instead of N×C×D for the whole score set
    _BLOCK = 1 << 16

    def transform_columns(self, *cols: Column, num_rows: int) -> MapColumn:
        vec = cols[-1]
        assert isinstance(vec, VectorColumn)
        x = np.asarray(vec.values, dtype=np.float64)
        if self._meta is None:
            self._meta = vec.metadata
        names = self._names(x.shape[1])
        d = x.shape[1]
        k = min(self.top_k, d)
        out: list[dict[str, str]] = []
        for start in range(0, num_rows, self._BLOCK):
            xb = x[start:start + self._BLOCK]
            nb = len(xb)
            normalized = (xb - self.shift[None, :]) / self.scale[None, :]
            # per feature: the list of [prediction-index, importance] pairs
            # over ALL prediction columns it ranks top-k for (the reference
            # emits one pair per prediction index, RecordInsightsCorr.scala)
            acc: list[dict[str, list]] = [{} for _ in range(nb)]
            for ci in range(self.corr.shape[0]):
                imp = normalized * self.corr[ci][None, :]  # [nb, D]
                mag = np.abs(imp)
                if k < d:
                    idx = np.argpartition(-mag, k - 1, axis=1)[:, :k]
                else:
                    idx = np.broadcast_to(np.arange(d), (nb, d)).copy()
                # deterministic order inside the top-k: |importance| desc
                sub = np.take_along_axis(mag, idx, axis=1)
                idx = np.take_along_axis(idx, np.argsort(-sub, axis=1), axis=1)
                for r in range(nb):
                    row_imp = imp[r]
                    row_acc = acc[r]
                    for j in idx[r]:
                        row_acc.setdefault(names[int(j)], []).append(
                            [ci, float(row_imp[j])]
                        )
            out.extend(
                {name: json.dumps(pairs) for name, pairs in row.items()}
                for row in acc
            )
        return MapColumn(TextMap, out)
