"""Attribution drift — model-BEHAVIOR drift detection over LOCO sweeps.

The PR-2 ``DriftSentinel`` watches the INPUT distribution (per-raw-feature
fill rate + value histograms vs the training profiles). That misses a
whole failure class: the inputs can look exactly like training while the
model's *reasons* shift — a feature group that used to dominate the
prediction goes quiet (upstream pipeline silently zeroing a slice, a
vocabulary rotating out from under a hashed text plane), or a group that
was noise at fit time starts carrying the score. Attribution drift
catches that by comparing the distribution of per-group LOCO
contributions at serve time against a baseline captured at train time:

* :func:`compute_attribution_profile` — run once by ``Workflow.train()``
  over a bounded sample of training rows: per column group, a
  ``StreamingHistogram`` of signed contributions + mean |contribution|;
  persisted in the model manifest as ``attributionProfiles`` next to
  ``servingProfiles``;
* :class:`AttributionDriftMonitor` — the serving-side comparator (same
  chunked-sliding-window + Jensen-Shannon machinery as the input-drift
  sentinel, fed by every ``explain=k`` sweep): per group, the JS
  divergence of serve-time contributions vs the baseline histogram, with
  ``ok`` / ``warn`` / ``alert`` statuses. Fresh alerts emit an
  ``attribution_drift`` event, bump
  ``tptpu_attribution_drift_alerts_total``, and count on the attribution
  ledger.

Torn or corrupt baseline groups disable monitoring for that group only —
a damaged artifact must degrade observability, never scoring.
"""
from __future__ import annotations

import logging
from typing import Any

import numpy as np

from ..analysis import schedule as _schedule
from ..resilience.sentinel import (
    DriftConfig,
    _Window,
    histogram_js_divergence,
)
from ..telemetry import events as _tevents
from ..telemetry import metrics as _tm
from ..utils.streaming_histogram import StreamingHistogram, histogram_from_values
from . import ledger as _ledger
from .loco import column_groups, explain_batch

log = logging.getLogger(__name__)

__all__ = [
    "AttributionDriftMonitor",
    "compute_attribution_profile",
]


def compute_attribution_profile(
    model,
    x: np.ndarray,
    meta,
    max_rows: int = 256,
    max_bins: int = 64,
) -> dict[str, Any]:
    """Baseline per-group contribution profile from training rows.

    Runs ONE batched LOCO sweep over an evenly-spaced sample of at most
    ``max_rows`` rows (bounded cost: the profile must stay well under the
    2% train-overhead guard) and sketches each group's signed
    contribution distribution. JSON-able; rides the model manifest."""
    x = np.asarray(x, dtype=np.float32)
    total = n = x.shape[0]
    if n == 0 or x.ndim != 2 or x.shape[1] == 0:
        return {"rows": 0, "groups": {}}
    if n > max_rows:
        # deterministic evenly-spaced sample — no RNG in the train path
        idx = np.linspace(0, n - 1, max_rows).astype(np.int64)
        x = x[idx]
        n = max_rows
    from ..telemetry import spans as _tspans

    groups = column_groups(meta, x.shape[1])
    t0 = _tspans.clock()
    diffs, info = explain_batch(model, x, groups)
    _ledger.stats().record_explain(
        n, _tspans.clock() - t0, lanes=info["lanes"],
        deduped=info["deduped"], padded=info["padded"],
    )
    out_groups: dict[str, Any] = {}
    for g, (name, _) in enumerate(groups):
        col = diffs[:, g]
        out_groups[name] = {
            "count": int(n),
            "meanAbs": round(float(np.abs(col).mean()), 8),
            "histogram": histogram_from_values(col, max_bins=max_bins).to_json(),
        }
    _ledger.stats().count_profile()
    return {"rows": int(n), "sampledFrom": int(total), "groups": out_groups}


class AttributionDriftMonitor:
    """Serve-time comparator over the attribution window (one instance
    per scoring closure; thread-safe like the input-drift sentinel:
    per-group window locks, a report lock for alert bookkeeping)."""

    def __init__(
        self,
        profile: dict[str, Any] | None,
        config: DriftConfig | None = None,
    ):
        self.config = config or DriftConfig()
        self.baselines: dict[str, StreamingHistogram] = {}
        self.torn: list[str] = []
        self.rows_observed = 0
        self.alerts_total = 0
        self._alerting: set[str] = set()
        for name, prof in ((profile or {}).get("groups") or {}).items():
            try:
                self.baselines[name] = StreamingHistogram.from_json(
                    prof["histogram"]
                )
            except Exception as e:
                log.warning(
                    "attribution drift: baseline for group '%s' is torn or "
                    "corrupt (%s); monitoring disabled for it", name, e,
                )
                self.torn.append(name)
        self._windows = {
            name: _Window(self.config) for name in self.baselines
        }
        # per-group lock FAMILY: one node in the lock-order graphs
        self._window_locks = {
            name: _schedule.make_lock(
                "insights/drift.py:AttributionDriftMonitor._window_locks[]"
            )
            for name in self.baselines
        }
        self._report_lock = _schedule.make_lock(
            "insights/drift.py:AttributionDriftMonitor._report_lock"
        )

    @property
    def enabled(self) -> bool:
        return bool(self.baselines)

    def observe(self, names: list[str], diffs: np.ndarray) -> None:
        """Feed one sweep's ``[N, G]`` contribution matrix into the
        per-group sliding windows (one vectorized bulk merge per group)."""
        if not self.baselines or diffs.size == 0:
            return
        n = diffs.shape[0]
        with self._report_lock:
            self.rows_observed += n
        for g, name in enumerate(names):
            w = self._windows.get(name)
            if w is None:
                continue  # group unseen at train time: no baseline
            vals = np.asarray(diffs[:, g], dtype=np.float64)
            with self._window_locks[name]:
                w.observe_bulk(vals, n, 0)

    def report(self) -> dict[str, Any]:
        """Per-group serve-vs-train contribution JS divergence with
        ``ok``/``warn``/``alert`` statuses; fresh alerts emit the
        ``attribution_drift`` event and count everywhere they should."""
        groups: dict[str, Any] = {}
        alerts: list[str] = []
        for name, baseline in self.baselines.items():
            w = self._windows[name]
            with self._window_locks[name]:
                rows = w.rows
                hist = w.histogram()
            if rows < self.config.min_rows:
                groups[name] = {"status": "insufficient", "rows": rows}
                continue
            js = histogram_js_divergence(
                baseline, hist, self.config.compare_bins
            )
            status = "ok"
            if js > self.config.js_warn:
                status = "warn"
            if js > self.config.js_threshold:
                status = "alert"
            groups[name] = {
                "status": status,
                "rows": rows,
                "jsDivergence": round(js, 6),
            }
            if status == "alert":
                alerts.append(name)
                with self._report_lock:
                    fresh = name not in self._alerting
                    if fresh:
                        self._alerting.add(name)
                        self.alerts_total += 1
                if fresh:
                    _ledger.stats().count_drift_alert()
                    _tm.REGISTRY.counter(
                        "tptpu_attribution_drift_alerts_total"
                    ).inc()
                    _tevents.emit(
                        "attribution_drift", group=name,
                        jsDivergence=round(js, 4),
                    )
                    log.warning(
                        "attribution drift: group '%s' contribution "
                        "distribution drifted (js=%.3f) — the model's "
                        "reasons changed, check upstream features", name, js,
                    )
            else:
                with self._report_lock:
                    self._alerting.discard(name)
        with self._report_lock:
            return {
                "enabled": self.enabled,
                "rowsObserved": self.rows_observed,
                "tornGroups": list(self.torn),
                "alerts": alerts,
                "attributionDriftAlertsTotal": self.alerts_total,
                "groups": groups,
            }
