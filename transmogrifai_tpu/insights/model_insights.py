"""ModelInsights — merged per-feature diagnostics for a fitted workflow.

Reference: core/.../ModelInsights.scala:74-850 (extractFromStages :444):
feature history + SanityChecker statistics + selector validation summary +
model feature importances, grouped per raw feature with one record per
derived vector column.

Feature contributions:
  * GLMs: |coefficient| per column (mean over classes for multinomial);
  * tree ensembles: split-frequency importance from the stored tree arrays;
  * MLP: L2 norm of the first-layer weight row.
"""
from __future__ import annotations

from typing import Any

import numpy as np

from ..models.base import PredictorModel
from ..selector.model_selector import SelectedModel
from ..prep.derived_filter import FeatureRemovalModel


def _tree_split_importance(split_feats: list[np.ndarray], dim: int) -> np.ndarray:
    counts = np.zeros(dim, dtype=np.float64)
    for sf in split_feats:
        flat = np.asarray(sf).reshape(-1)
        valid = flat[flat >= 0]
        np.add.at(counts, valid, 1.0)
    total = counts.sum()
    return counts / total if total > 0 else counts


def feature_contributions(model: PredictorModel, dim: int) -> np.ndarray:
    """Per-vector-column contribution scores for any supported model."""
    from ..models.gbdt import (
        BoostedBinaryModel,
        BoostedMultiModel,
        BoostedRegressionModel,
        ForestClassifierModel,
        ForestRegressionModel,
    )
    from ..models.linear import LinearRegressionModel
    from ..models.logistic import LogisticRegressionModel
    from ..models.mlp import MLPClassifierModel

    if isinstance(model, SelectedModel):
        return feature_contributions(model.best_model, dim)
    if isinstance(model, (LogisticRegressionModel, LinearRegressionModel)):
        w = np.abs(np.asarray(model.weights, dtype=np.float64))
        return w if w.ndim == 1 else w.mean(axis=1)
    if isinstance(model, MLPClassifierModel):
        return np.linalg.norm(model.params[0]["w"], axis=1)
    if isinstance(model, (BoostedBinaryModel, BoostedRegressionModel, ForestRegressionModel)):
        return _tree_split_importance([model.trees.split_feat], dim)
    if isinstance(model, BoostedMultiModel):
        return _tree_split_importance(
            [t.split_feat for t in model.trees_per_class], dim
        )
    if isinstance(model, ForestClassifierModel):
        return _tree_split_importance(
            [t.split_feat for t in model.forests_per_class], dim
        )
    return np.zeros(dim)


def model_insights(workflow_model) -> dict[str, Any]:
    """One JSON document of per-feature insights (ModelInsights.scala:74)."""
    fitted = workflow_model.fitted
    selected: SelectedModel | None = None
    removal: FeatureRemovalModel | None = None
    for stage in fitted.values():
        if isinstance(stage, SelectedModel):
            selected = stage
        if isinstance(stage, FeatureRemovalModel):
            removal = stage

    # column stats from the SanityChecker ledger (pre-drop indexing)
    checker_columns: list[dict[str, Any]] = []
    for stage in fitted.values():
        summ = stage.metadata.get("sanityCheckerSummary")
        if summ:
            checker_columns = summ["columns"]
            break

    # final-model column metadata (post-drop)
    final_meta = removal.new_metadata if removal is not None else None
    kept = removal.indices_to_keep if removal is not None else None

    dim = final_meta.size if final_meta is not None else (
        len(checker_columns) if checker_columns else 0
    )
    contributions = (
        feature_contributions(selected, dim) if selected is not None and dim else
        np.zeros(dim)
    )

    raw_types = {f.name: f.ftype.__name__ for f in workflow_model.raw_features}
    # stage chain per result feature (all derived columns of the model
    # vector share the lineage of the vector feature)
    stages_applied: list[str] = []
    for f in workflow_model.result_features:
        try:
            stages_applied = f.history()["stages"]
            break
        except Exception:
            pass

    rff = workflow_model.rff_results or {}
    rff_metrics = rff.get("rawFeatureDistributions", {})
    rff_excluded = rff.get("exclusionReasons", [])

    features: dict[str, dict[str, Any]] = {}

    def record(parent: str, entry: dict[str, Any]) -> None:
        if parent not in features:
            features[parent] = {
                "featureName": parent,
                "featureType": raw_types.get(parent, "?"),
                "derivedFeatures": [],
                # RawFeatureFilter ledger (FeatureInsights.metrics /
                # exclusionReasons, ModelInsights.scala:338-348)
                "metrics": rff_metrics.get(parent, {}),
                "exclusionReasons": (
                    rff_excluded.get(parent, [])
                    if isinstance(rff_excluded, dict) else []
                ),
            }
        features[parent]["derivedFeatures"].append(entry)

    if final_meta is not None:
        for j, cm in enumerate(final_meta.columns):
            pre = kept[j] if kept is not None else j
            stats = checker_columns[pre] if pre < len(checker_columns) else {}
            record(
                cm.parent_names[0] if cm.parent_names else "?",
                {
                    "derivedFeatureName": cm.make_name(),
                    "stagesApplied": stages_applied,
                    "derivedFeatureGroup": cm.grouping,
                    "derivedFeatureValue": cm.indicator_value
                    or cm.descriptor_value,
                    "indicatorValue": cm.indicator_value,
                    "descriptorValue": cm.descriptor_value,
                    "corr": stats.get("corr_label"),
                    "cramersV": stats.get("cramers_v"),
                    "mean": stats.get("mean"),
                    "variance": stats.get("variance"),
                    "contribution": float(contributions[j]) if j < len(contributions) else None,
                    "excluded": False,
                },
            )
    # columns the checker dropped still appear, flagged excluded
    for pre, stats in enumerate(checker_columns):
        if stats.get("dropped"):
            record(
                stats.get("parent") or stats["name"],
                {
                    "derivedFeatureName": stats["name"],
                    "stagesApplied": stages_applied,
                    "derivedFeatureGroup": None,
                    "derivedFeatureValue": None,
                    "corr": stats.get("corr_label"),
                    "cramersV": stats.get("cramers_v"),
                    "mean": stats.get("mean"),
                    "variance": stats.get("variance"),
                    "contribution": 0.0,
                    "excluded": True,
                    "exclusionReasons": stats.get("reasons", []),
                },
            )

    # stageInfo: uid -> operation + params for every fitted stage
    # (ModelInsights.stageInfo, RawFeatureFilterConfig etc ride along)
    stage_info: dict[str, Any] = {}
    for uid, stage in fitted.items():
        entry: dict[str, Any] = {
            "operationName": getattr(stage, "operation_name", type(stage).__name__),
            "stageClass": type(stage).__name__,
        }
        try:
            entry["params"] = stage.get_params()
        except Exception:
            pass
        stage_info[uid] = entry

    sel_summary = selected.summary if selected is not None else None
    label = workflow_model.label_summary
    if label is None and workflow_model.selector_info is not None:
        label = {
            "labelName": workflow_model.selector_info["labelName"],
            "problemKind": workflow_model.selector_info["problemKind"],
        }
    elif label is not None and workflow_model.selector_info is not None:
        label = {
            **label,
            "problemKind": workflow_model.selector_info["problemKind"],
        }
    return {
        "label": label,
        "features": sorted(features.values(), key=lambda d: d["featureName"]),
        "selectedModelInfo": sel_summary,
        "trainingParams": workflow_model.training_params,
        "stageInfo": stage_info,
        "trainRows": workflow_model.train_rows,
        "blocklistedFeatures": workflow_model.blocklisted,
        "rawFeatureFilterResults": workflow_model.rff_results,
    }
