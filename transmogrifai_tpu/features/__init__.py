"""Feature graph (reference: features/.../features/)."""
from .feature import Feature, FeatureGeneratorStage  # noqa: F401
from .builder import FeatureBuilder, from_dataset  # noqa: F401
