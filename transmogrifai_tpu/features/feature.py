"""Feature — a node in the lineage-traced feature DAG.

Reference: features/.../features/Feature.scala:52 and FeatureLike.scala:48.
A Feature is a typed, named handle produced by an origin stage from parent
features. The user never builds a pipeline forward: they declare result
features and the workflow walks ``parents``/``origin_stage`` backwards to
reconstruct the stage DAG (core/.../OpWorkflow.scala:90-110).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable

from ..types import FeatureType
from ..types.columns import Column, column_from_values
from ..stages.base import PipelineStage, Transformer
from ..utils import uid as uid_util


@dataclasses.dataclass(eq=False)
class Feature:
    name: str
    ftype: type
    origin_stage: PipelineStage | None = None
    parents: tuple["Feature", ...] = ()
    is_response: bool = False
    uid: str = ""
    #: feature distributions attached by RawFeatureFilter
    distributions: tuple = ()

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = uid_util.make_uid("Feature")

    # ----------------------------------------------------------- lineage ops
    @property
    def is_raw(self) -> bool:
        return isinstance(self.origin_stage, FeatureGeneratorStage)

    def transform_with(self, stage: PipelineStage, *others: "Feature") -> Any:
        """Apply a 1..4-ary stage to this feature (+ others)
        (FeatureLike.transformWith, FeatureLike.scala:210-283)."""
        stage.set_input(self, *others)
        return stage.get_output()

    def _live_parents(self) -> tuple["Feature", ...]:
        """Current upstream features. Traversals follow the origin stage's
        LIVE wiring (not the frozen ``parents`` tuple) so DAG rewrites — e.g.
        the RawFeatureFilter blocklist — propagate to lineage queries."""
        stage = self.origin_stage
        if stage is not None and not isinstance(stage, FeatureGeneratorStage):
            return tuple(stage.input_features)
        return self.parents

    def parent_stages(self) -> dict[PipelineStage, int]:
        """All ancestor stages mapped to their distance from this feature
        (FeatureLike.parentStages, FeatureLike.scala:363). Distance is the
        LONGEST path so a stage is fitted only after everything it needs."""
        dists: dict[PipelineStage, int] = {}

        def visit(feature: "Feature", depth: int) -> None:
            stage = feature.origin_stage
            if stage is None:
                return
            if dists.get(stage, -1) >= depth:
                return  # already visited at this depth or deeper
            dists[stage] = depth
            for p in feature._live_parents():
                visit(p, depth + 1)

        visit(self, 0)
        return dists

    def raw_features(self) -> list["Feature"]:
        """All raw-feature leaves under this feature. Two distinct raw
        features sharing a name is an error — they would silently read each
        other's data in the materialized dataset."""
        seen: dict[str, Feature] = {}

        def visit(f: "Feature") -> None:
            if f.is_raw or f.origin_stage is None:
                prior = seen.get(f.name)
                if prior is not None and prior.uid != f.uid:
                    raise ValueError(
                        f"Two distinct raw features named '{f.name}' in one DAG"
                    )
                seen[f.name] = f
            for p in f._live_parents():
                visit(p)

        visit(self)
        return list(seen.values())

    def history(self) -> dict[str, Any]:
        """Originating raw features + stage operation path (FeatureLike.history)."""
        stages = sorted(
            (s for s in self.parent_stages()), key=lambda s: s.uid
        )
        return {
            "originFeatures": sorted(f.name for f in self.raw_features()),
            "stages": [s.operation_name for s in stages],
        }

    def copy_with_origin(self, stage: PipelineStage, parents: tuple["Feature", ...]) -> "Feature":
        return dataclasses.replace(self, origin_stage=stage, parents=parents)

    def __repr__(self) -> str:
        kind = "response" if self.is_response else "predictor"
        return f"Feature[{self.ftype.__name__}]({self.name!r}, {kind})"

    def __hash__(self) -> int:
        return hash(self.uid)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Feature) and other.uid == self.uid


class FeatureGeneratorStage(Transformer):
    """DAG leaf: extracts one raw feature from user records
    (features/.../stages/FeatureGeneratorStage.scala:67-115).

    ``extract_fn`` maps one source record (any Python object) to a raw value;
    ``aggregate_fn`` optionally monoid-combines multiple events per key
    (aggregate readers). When data already arrives columnar (from_dataset),
    ``extract_fn`` is None and the column passes through by name.
    """

    def __init__(
        self,
        name: str,
        ftype: type,
        extract_fn: Callable[[Any], Any] | None = None,
        aggregate_fn: Callable[[Iterable[Any]], Any] | None = None,
        is_response: bool = False,
        uid: str | None = None,
    ):
        super().__init__(operation_name=f"featureGen_{name}", uid=uid)
        self.feature_name = name
        self.ftype = ftype
        self.extract_fn = extract_fn
        self.aggregate_fn = aggregate_fn
        self.is_response = is_response

    @property
    def output_name(self) -> str:  # type: ignore[override]
        return self.feature_name

    def get_output(self) -> Feature:
        return Feature(
            name=self.feature_name,
            ftype=self.ftype,
            origin_stage=self,
            parents=(),
            is_response=self.is_response,
        )

    def extract_column(self, records: Iterable[Any]) -> Column:
        records = list(records)
        if self.extract_fn:
            values = [self.extract_fn(r) for r in records]
        elif records and isinstance(records[0], dict):
            # from_dataset features carry no extract_fn (data arrives
            # columnar at train time) — dict records (file/record streams
            # scoring a trained model) extract by feature name so the same
            # raw features work on both sources. Row-dict streams from the
            # readers carry every header key in every record, so "name in
            # the first record" reliably separates row-dicts from raw map
            # VALUES for OPMap features (a value-map coincidentally
            # carrying a key equal to the feature name in record 0 is the
            # one ambiguous case — pass an explicit extract_fn there). A
            # missing name on a non-map feature is a schema mismatch
            # (typo'd header) and must not silently become an all-missing
            # column.
            from .. import types as _T

            if self.feature_name in records[0]:
                values = [r.get(self.feature_name) for r in records]
            elif _T.is_subtype(self.ftype, _T.OPMap):
                values = records  # records ARE the raw map values
            else:
                raise KeyError(
                    f"Raw feature '{self.feature_name}' missing from the "
                    f"record stream (record keys: "
                    f"{sorted(records[0])[:8]}...)"
                )
        else:
            values = records
        return column_from_values(self.ftype, values)

    def transform_columns(self, *cols: Column, num_rows: int) -> Column:
        raise TypeError("FeatureGeneratorStage runs in the reader, not the DAG")
