"""FeatureBuilder — typed raw-feature declaration.

Reference: features/.../features/FeatureBuilder.scala:48 (per-type factories)
and :232 (``fromDataFrame``: infer one feature per column, split response vs
predictors).

Usage (mirrors the reference's fluent API):

    age  = FeatureBuilder.Real("age").extract(lambda p: p["age"]).as_predictor()
    surv = FeatureBuilder.RealNN("survived").extract(lambda p: p["survived"]).as_response()

    # or columnar auto-inference:
    response, predictors = from_dataset(ds, response="survived")
"""
from __future__ import annotations

from typing import Any, Callable, Iterable

import numpy as np

from .. import types as T
from ..dataset import Dataset
from ..types.columns import (
    Column,
    ListColumn,
    MapColumn,
    NumericColumn,
    SetColumn,
    TextColumn,
    VectorColumn,
)
from .feature import Feature, FeatureGeneratorStage


class _TypedBuilder:
    def __init__(self, name: str, ftype: type):
        self.name = name
        self.ftype = ftype
        self._extract_fn: Callable[[Any], Any] | None = None
        self._aggregate_fn: Callable[[Iterable[Any]], Any] | None = None

    def extract(self, fn: Callable[[Any], Any]) -> "_TypedBuilder":
        self._extract_fn = fn
        return self

    def aggregate(self, fn: Callable[[Iterable[Any]], Any]) -> "_TypedBuilder":
        """Custom monoid aggregator for event-grouped readers
        (FeatureBuilder aggregate; aggregators/MonoidAggregatorDefaults.scala)."""
        self._aggregate_fn = fn
        return self

    def _build(self, is_response: bool) -> Feature:
        stage = FeatureGeneratorStage(
            name=self.name,
            ftype=self.ftype,
            extract_fn=self._extract_fn,
            aggregate_fn=self._aggregate_fn,
            is_response=is_response,
        )
        return stage.get_output()

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        return self._build(is_response=True)


class _FeatureBuilderMeta(type):
    def __getattr__(cls, type_name: str) -> Callable[[str], _TypedBuilder]:
        ftype = T.FEATURE_TYPES_BY_NAME.get(type_name)
        if ftype is None:
            raise AttributeError(f"FeatureBuilder.{type_name}: unknown feature type")

        def factory(name: str) -> _TypedBuilder:
            return _TypedBuilder(name, ftype)

        return factory


class FeatureBuilder(metaclass=_FeatureBuilderMeta):
    """``FeatureBuilder.<TypeName>(name)`` for all 53 feature types."""


def infer_feature_type(col: Column) -> type:
    """Physical column -> feature type, for auto-inference from data.

    Mirrors FeatureBuilder.fromDataFrame's schema-directed mapping
    (FeatureBuilder.scala:232): numerics stay Real/Integral/Binary, strings
    become Text (refined to PickList downstream by the smart vectorizers).
    """
    if isinstance(col, NumericColumn):
        return col.feature_type
    if isinstance(col, TextColumn):
        return col.feature_type
    if isinstance(col, SetColumn):
        return T.MultiPickList
    if isinstance(col, ListColumn):
        return col.feature_type
    if isinstance(col, MapColumn):
        return col.feature_type
    if isinstance(col, VectorColumn):
        return T.OPVector
    raise TypeError(f"Cannot infer feature type for {type(col).__name__}")


def from_dataset(
    dataset: Dataset,
    response: str,
    response_type: type = T.RealNN,
) -> tuple[Feature, list[Feature]]:
    """(response, predictors) from a columnar dataset — the
    ``FeatureBuilder.fromDataFrame`` equivalent (FeatureBuilder.scala:232).

    The response must be numeric and non-null; predictors get one raw feature
    per remaining column with types inferred from physical storage.
    """
    if response not in dataset:
        raise ValueError(
            f"Response feature '{response}' not found in columns {list(dataset)}"
        )
    resp_col = dataset[response]
    if T.is_subtype(response_type, T.Text):
        # categorical text label: the caller indexes it into class ids
        # downstream (e.g. .string_indexed(), OpIrisSimple.scala:58)
        if not isinstance(resp_col, TextColumn):
            raise TypeError(
                f"Response '{response}' declared {response_type.__name__} but "
                f"stored as {type(resp_col).__name__}"
            )
        if any(v is None for v in resp_col.values):
            raise ValueError(f"Response '{response}' contains missing values")
    elif not isinstance(resp_col, NumericColumn):
        raise TypeError(
            f"Response '{response}' must be numeric, got {type(resp_col).__name__}"
        )
    elif not resp_col.mask.all():
        raise ValueError(f"Response '{response}' contains missing values")

    resp = FeatureGeneratorStage(response, response_type, is_response=True).get_output()
    predictors = [
        FeatureGeneratorStage(name, infer_feature_type(col)).get_output()
        for name, col in dataset.columns.items()
        if name != response
    ]
    return resp, predictors
