"""Monoid aggregators — event aggregation semantics for raw features.

Reference: features/.../aggregators/MonoidAggregatorDefaults.scala:41 (default
registry), Numerics.scala, Text.scala, Sets.scala, Lists.scala, Maps.scala,
Geolocation.scala, OPVector.scala, TimeBasedAggregator.scala,
CustomMonoidAggregator.scala.

Every aggregator is a *commutative-monoid* fold ``present(plus*(prepare(v)))``
so results are shard-order-invariant — exactly the property that lets the
aggregate/conditional readers run as segment reductions on device
(SURVEY.md §2.6: monoid reduceByKey → psum-style reductions). The host path
here folds per key; the vectorized numeric path is
``transmogrifai_tpu.parallel.reductions``.

Missing values: ``prepare(None)`` returns the monoid zero, matching the
reference's Option-typed accumulators.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Iterable

from .. import types as T


class MonoidAggregator:
    """prepare → plus (associative+commutative, zero identity) → present."""

    #: monoid identity (must be treated as immutable)
    zero: Any = None

    def prepare(self, value: Any) -> Any:
        return value

    def plus(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def present(self, acc: Any) -> Any:
        return acc

    def __call__(self, values: Iterable[Any]) -> Any:
        acc = self.zero
        for v in values:
            acc = self.plus(acc, self.prepare(v))
        return self.present(acc)


def _opt(op: Callable[[Any, Any], Any]) -> Callable[[Any, Any], Any]:
    """Lift a binary op over None-as-zero (the reference's Option monoid)."""

    def lifted(a: Any, b: Any) -> Any:
        if a is None:
            return b
        if b is None:
            return a
        return op(a, b)

    return lifted


class _Lifted(MonoidAggregator):
    """Aggregator over None-able scalars with a lifted binary op."""

    def __init__(self, op: Callable[[Any, Any], Any]):
        self._plus = _opt(op)

    def plus(self, a: Any, b: Any) -> Any:
        return self._plus(a, b)


class SumNumeric(_Lifted):
    """SumReal/SumRealNN/SumCurrency/SumIntegral (Numerics.scala:51-54)."""

    def __init__(self) -> None:
        super().__init__(lambda a, b: a + b)


class MaxNumeric(_Lifted):
    """MaxDate/MaxDateTime/... (Numerics.scala:70-75)."""

    def __init__(self) -> None:
        super().__init__(max)


class MinNumeric(_Lifted):
    def __init__(self) -> None:
        super().__init__(min)


class MeanNumeric(MonoidAggregator):
    """MeanReal/MeanPercent — (sum, count) pairs (Numerics.scala:86-106).

    Percent values are clamped to [0, 1] at prepare (PercentPrepare,
    Numerics.scala:124): x<0 → 0, x>1 → scaled by 1e-2 iff <=100 else 1.
    """

    def __init__(self, is_percent: bool = False):
        self.is_percent = is_percent

    def prepare(self, value: Any) -> Any:
        if value is None:
            return None
        v = float(value)
        if self.is_percent:
            v = _prepare_percent(v)
        return (v, 1)

    def plus(self, a: Any, b: Any) -> Any:
        if a is None:
            return b
        if b is None:
            return a
        return (a[0] + b[0], a[1] + b[1])

    def present(self, acc: Any) -> Any:
        if acc is None:
            return None
        s, n = acc
        return s / n if n else None


def _prepare_percent(v: float) -> float:
    if v < 0.0:
        return 0.0
    if v > 1.0:
        return v / 100.0 if v <= 100.0 else 1.0
    return v


class LogicalOr(_Lifted):
    """Binary default (Numerics.scala:118)."""

    def __init__(self) -> None:
        super().__init__(lambda a, b: bool(a) or bool(b))


class LogicalAnd(_Lifted):
    def __init__(self) -> None:
        super().__init__(lambda a, b: bool(a) and bool(b))


class LogicalXor(_Lifted):
    def __init__(self) -> None:
        super().__init__(lambda a, b: bool(a) != bool(b))


class ConcatText(_Lifted):
    """ConcatTextWithSeparator (Text.scala:41-68): Text/TextArea join with
    " ", everything else (Email/URL/ID/...) with ","."""

    def __init__(self, separator: str = ","):
        super().__init__(lambda a, b: f"{a}{separator}{b}")


class ModeText(MonoidAggregator):
    """ModePickList (Text.scala:73): most frequent value; ties break to the
    lexicographically smallest."""

    zero: dict = {}

    def prepare(self, value: Any) -> Any:
        return {} if value is None else {str(value): 1}

    def plus(self, a: dict, b: dict) -> dict:
        if not a:
            return b
        if not b:
            return a
        out = dict(a)
        for k, v in b.items():
            out[k] = out.get(k, 0) + v
        return out

    def present(self, acc: dict) -> Any:
        if not acc:
            return None
        return min(acc.items(), key=lambda kv: (-kv[1], kv[0]))[0]


class UnionSet(MonoidAggregator):
    """UnionMultiPickList (Sets.scala)."""

    zero: frozenset = frozenset()

    def prepare(self, value: Any) -> Any:
        return frozenset(value) if value else frozenset()

    def plus(self, a: frozenset, b: frozenset) -> frozenset:
        return a | b


class ConcatList(MonoidAggregator):
    """ConcatTextList/ConcatDateList/... (Lists.scala)."""

    zero: tuple = ()

    def prepare(self, value: Any) -> Any:
        return tuple(value) if value else ()

    def plus(self, a: tuple, b: tuple) -> tuple:
        return a + b

    def present(self, acc: tuple) -> list:
        return list(acc)


class GeolocationMidpoint(MonoidAggregator):
    """Geographic midpoint (Geolocation.scala:42-133): average unit-sphere
    (x, y, z) weighted by point count, then project back to lat/lon.
    Accuracy presents as the max of the inputs' accuracy codes (the
    reference reconstructs it from a bounding-prism width — divergence
    documented, same monotone intent)."""

    zero = None  # (x, y, z, weight, acc_max)

    def prepare(self, value: Any) -> Any:
        if not value:
            return None
        lat, lon, acc = float(value[0]), float(value[1]), float(value[2])
        la, lo = math.radians(lat), math.radians(lon)
        return (
            math.cos(la) * math.cos(lo),
            math.cos(la) * math.sin(lo),
            math.sin(la),
            1.0,
            acc,
        )

    def plus(self, a: Any, b: Any) -> Any:
        if a is None:
            return b
        if b is None:
            return a
        w = a[3] + b[3]
        return (
            (a[0] * a[3] + b[0] * b[3]) / w,
            (a[1] * a[3] + b[1] * b[3]) / w,
            (a[2] * a[3] + b[2] * b[3]) / w,
            w,
            max(a[4], b[4]),
        )

    def present(self, acc: Any) -> Any:
        if acc is None:
            return []
        x, y, z, _, a = acc
        lat = math.degrees(math.atan2(z, math.sqrt(x * x + y * y)))
        lon = math.degrees(math.atan2(y, x))
        return [lat, lon, a]


class CombineVector(MonoidAggregator):
    """CombineVector (OPVector.scala:43): vector concatenation."""

    zero: tuple = ()

    def prepare(self, value: Any) -> Any:
        return tuple(value) if value is not None else ()

    def plus(self, a: tuple, b: tuple) -> tuple:
        return a + b

    def present(self, acc: tuple) -> list:
        return list(acc)


class SumVector(MonoidAggregator):
    """SumVector (OPVector.scala:54): elementwise sum."""

    zero: tuple = ()

    def prepare(self, value: Any) -> Any:
        return tuple(value) if value is not None else ()

    def plus(self, a: tuple, b: tuple) -> tuple:
        if not a:
            return b
        if not b:
            return a
        if len(a) != len(b):
            raise ValueError(f"SumVector dims differ: {len(a)} vs {len(b)}")
        return tuple(x + y for x, y in zip(a, b))

    def present(self, acc: tuple) -> list:
        return list(acc)


class UnionMap(MonoidAggregator):
    """Map union with a per-value scalar monoid (Maps.scala:43-125)."""

    zero: dict = {}

    def __init__(self, value_agg: MonoidAggregator):
        self.value_agg = value_agg

    def prepare(self, value: Any) -> Any:
        if not value:
            return {}
        return {k: self.value_agg.prepare(v) for k, v in value.items()}

    def plus(self, a: dict, b: dict) -> dict:
        if not a:
            return b
        if not b:
            return a
        out = dict(a)
        for k, v in b.items():
            out[k] = self.value_agg.plus(out[k], v) if k in out else v
        return out

    def present(self, acc: dict) -> dict:
        return {k: self.value_agg.present(v) for k, v in acc.items()}


class CustomMonoidAggregator(MonoidAggregator):
    """User-supplied monoid (CustomMonoidAggregator.scala)."""

    def __init__(self, zero: Any, plus: Callable[[Any, Any], Any]):
        self.zero = zero
        self._plus = plus

    def plus(self, a: Any, b: Any) -> Any:
        return self._plus(a, b)


class LastAggregator(MonoidAggregator):
    """TimeBasedAggregator.scala: keep the value with the latest event time.
    Accumulator is (time, value); prepare is called with (value, time) via
    ``prepare_event``."""

    newer_wins = True
    zero = None

    def prepare(self, value: Any) -> Any:
        return self.prepare_event(value, 0)

    def prepare_event(self, value: Any, time: int) -> Any:
        return None if value is None else (time, value)

    def plus(self, a: Any, b: Any) -> Any:
        if a is None:
            return b
        if b is None:
            return a
        pick_b = (b[0] >= a[0]) if self.newer_wins else (b[0] < a[0])
        return b if pick_b else a

    def present(self, acc: Any) -> Any:
        return None if acc is None else acc[1]


class FirstAggregator(LastAggregator):
    newer_wins = False


# --------------------------------------------------------------------------
# Default registry (MonoidAggregatorDefaults.scala:52-120)
# --------------------------------------------------------------------------

def aggregator_of(ftype: type) -> MonoidAggregator:
    """Default aggregator for a feature type."""
    # map families first: resolve by per-value semantics
    if T.is_subtype(ftype, T.OPMap):
        return UnionMap(_map_value_aggregator(ftype))
    for base, make in _DEFAULTS:
        if T.is_subtype(ftype, base):
            return make()
    raise ValueError(f"No default aggregator for {ftype.__name__}")


def _map_value_aggregator(map_type: type) -> MonoidAggregator:
    value_type = getattr(map_type, "value_type", None)
    if map_type is T.Prediction:
        return MeanNumeric()  # UnionMeanPredicition
    if value_type is None:
        return ConcatText()
    if T.is_subtype(value_type, T.Percent):
        return MeanNumeric(is_percent=True)  # UnionMeanPercentMap
    if T.is_subtype(value_type, T.Date):
        return MaxNumeric()  # UnionMaxDate(Time)Map
    if T.is_subtype(value_type, T.Binary):
        return LogicalOr()  # UnionBinaryMap
    if T.is_subtype(value_type, (T.Real, T.Integral)):
        return SumNumeric()  # UnionRealMap / UnionIntegralMap / UnionCurrencyMap
    if T.is_subtype(value_type, T.MultiPickList):
        return UnionSet()  # UnionMultiPickListMap
    if T.is_subtype(value_type, T.Geolocation):
        return GeolocationMidpoint()  # UnionGeolocationMidpointMap
    if T.is_subtype(value_type, (T.Text, T.TextArea)):
        sep = " " if value_type in (T.Text, T.TextArea) else ","
        return ConcatText(sep)  # UnionConcat*Map
    return ConcatText()


# Ordered most-specific-first; first matching base wins. Text subtypes
# (Email/URL/...) concat with "," while plain Text/TextArea use " "
# (Text.scala:56-67).
_DEFAULTS: list[tuple[type, Callable[[], MonoidAggregator]]] = [
    (T.OPVector, CombineVector),
    (T.Geolocation, GeolocationMidpoint),
    (T.DateList, ConcatList),  # covers DateTimeList
    (T.TextList, ConcatList),
    (T.MultiPickList, UnionSet),
    (T.Binary, LogicalOr),
    (T.Percent, lambda: MeanNumeric(is_percent=True)),
    (T.Date, MaxNumeric),  # covers DateTime; before Integral
    (T.Integral, SumNumeric),
    (T.Real, SumNumeric),  # covers RealNN, Currency
    (T.PickList, ModeText),
    (T.ComboBox, ConcatText),
    (T.TextArea, lambda: ConcatText(" ")),
    (T.Email, ConcatText),
    (T.URL, ConcatText),
    (T.ID, ConcatText),
    (T.Phone, ConcatText),
    (T.Base64, ConcatText),
    (T.Country, ConcatText),
    (T.State, ConcatText),
    (T.City, ConcatText),
    (T.PostalCode, ConcatText),
    (T.Street, ConcatText),
    (T.Text, lambda: ConcatText(" ")),
]
