"""`python -m transmogrifai_tpu` → the CLI (cli/.../CliExec.scala parity)."""
from .cli import main

main()
