"""Compiled-program contract auditor (analysis/program.py, TPJ0xx):
seeded positive/negative corpus for every TPJ code — including a
reconstruction of the PR-11 constant-vs-traced-arg contract as the
TPJ001 positive — the bucket-boundary TPJ005 fingerprint invariants
across ``compiler.bucketing.lane_bucket`` boundaries (padded-vs-unpadded
lane-0-replay twins included), warmup-map reconciliation (TPJ010),
three-way transfer-census agreement on a fitted flagship flow (TPJ006),
the unified comment-directive parser, the bank-admission audit gate
(``TPTPU_PROGRAM_AUDIT=1``) with its overhead guard, the CLI
``--programs`` gate, and the whole-registry <30 s bound.
Marker: ``analysis``.
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from transmogrifai_tpu.analysis import findings as F
from transmogrifai_tpu.analysis import program as P

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(report):
    return [f.code for f in report.findings]


# ---------------------------------------------------------------- directives
class TestDirectives:
    def test_unified_and_legacy_spellings_parse(self):
        assert F.parse_directives("# tp: ok") == [("tp", "ok", "")]
        assert F.parse_directives("# tplint: disable=TPL003") == [
            ("tplint", "disable", "TPL003")
        ]
        assert F.parse_directives("x = 1  # tpc: lock(metrics.py:REG.lock)") \
            == [("tpc", "lock", "metrics.py:REG.lock")]
        assert F.parse_directives("# tpj: disable=TPJ001,TPJ004") == [
            ("tpj", "disable", "TPJ001"), ("tpj", "disable", "TPJ004"),
        ]

    def test_suppression_honours_family_and_unified_prefixes(self):
        assert F.suppressed("# tp: ok", "TPL001")
        assert F.suppressed("# tp: disable=TPJ007", "TPJ007")
        assert F.suppressed("# tpj: ok", "TPJ007")
        assert F.suppressed("# tplint: disable=TPL003", "TPL003")
        # a different family's prefix must NOT leak across
        assert not F.suppressed("# tpc: ok", "TPL001")
        assert not F.suppressed("# tpj: ok", "TPC001")
        assert not F.suppressed("# tp: disable=TPJ007", "TPJ008")

    def test_annotations_shared_parser(self):
        assert F.annotations("# tpc: guarded(k)", "guarded", "tpc") == ["k"]
        assert F.annotations("# tp: lock(a.py:L)", "lock", "tpc") == \
            ["a.py:L"]
        assert F.annotations("# tpc: lock(x)", "guarded", "tpc") == []

    def test_trailing_rationale_does_not_corrupt_disable_code(self):
        # the old substring parsers honored this shape; the shared
        # grammar must too (review regression)
        line = "x = f()  # tplint: disable=TPL003 SEE DOCS"
        assert F.suppressed(line, "TPL003")
        assert F.suppressed("y()  # tp: disable=TPC004 — weakref prune",
                            "TPC004")
        assert not F.suppressed(line, "TPL004")

    def test_legacy_spelling_warns_once(self, caplog):
        F._warned_legacy.discard("tplint")
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="transmogrifai_tpu.analysis.findings"):
            F.parse_directives("# tplint: ok")
            F.parse_directives("# tplint: ok")
        hits = [r for r in caplog.records if "deprecated" in r.message]
        assert len(hits) == 1


# ----------------------------------------------------------------- IR corpus
def _trace_report(fn, *args, statics=None, name="probe", **spec_kw):
    spec = P.ProgramSpec(
        name=name, fn=fn,
        build=lambda b: (args, statics or {}),
        buckets=(1,), **spec_kw,
    )
    return P.audit_spec(spec)


class TestIRChecks:
    def test_tpj001_constant_folded_model_array_flagged(self):
        """The PR-11 contract reconstruction: a model array closed over
        by the program bakes into the jaxpr as a giant constant — one
        executable per model, exactly what structural-fingerprint keying
        exists to prevent."""
        import jax

        baked = np.ones((256, 256), dtype=np.float32)  # 256 KiB

        def scores(x):
            return x @ baked

        rep = _trace_report(
            scores, jax.ShapeDtypeStruct((4, 256), "float32"),
            name="baked",
        )
        assert "TPJ001" in _codes(rep)
        f = rep.by_code("TPJ001")[0]
        assert f.detail["nbytes"] == baked.nbytes
        assert f.severity is F.Severity.ERROR

    def test_tpj001_traced_arg_negative(self):
        import jax

        def scores(x, w):
            return x @ w

        rep = _trace_report(
            scores,
            jax.ShapeDtypeStruct((4, 256), "float32"),
            jax.ShapeDtypeStruct((256, 256), "float32"),
            name="traced",
        )
        assert "TPJ001" not in _codes(rep)

    def test_tpj001_small_constant_tolerated(self):
        import jax

        table = np.arange(8, dtype=np.float32)

        def f(x):
            return x + table

        rep = _trace_report(
            f, jax.ShapeDtypeStruct((8,), "float32"), name="small"
        )
        assert "TPJ001" not in _codes(rep)

    def test_tpj002_x64_leak_flagged(self):
        import jax

        def f(x):
            return x.astype("float64").sum()

        with jax.experimental.enable_x64():
            rep = _trace_report(
                f, jax.ShapeDtypeStruct((4,), "float32"), name="x64"
            )
        assert "TPJ002" in _codes(rep)
        assert rep.by_code("TPJ002")[0].severity is F.Severity.ERROR

    def test_tpj002_weak_output_warned_strong_negative(self):
        import jax
        import jax.numpy as jnp

        # an all-literal computation escapes as a weak-typed OUTPUT: its
        # dtype is decided by the caller's promotion rules, not pinned
        rep = _trace_report(
            lambda x: jnp.sin(2.0),
            jax.ShapeDtypeStruct((4,), "float32"), name="weakout",
        )
        weak = rep.by_code("TPJ002")
        assert weak and weak[0].severity is F.Severity.WARNING

        rep = _trace_report(
            lambda x: x * 2.0,
            jax.ShapeDtypeStruct((4,), "float32"), name="strong",
        )
        assert "TPJ002" not in _codes(rep)

    def test_tpj004_host_callback_flagged(self):
        import jax

        def f(x):
            jax.debug.print("x = {}", x)
            return x * 2

        rep = _trace_report(
            f, jax.ShapeDtypeStruct((4,), "float32"), name="cb"
        )
        assert "TPJ004" in _codes(rep)

    def test_tpj004_clean_program_negative(self):
        import jax

        rep = _trace_report(
            lambda x: x * 2, jax.ShapeDtypeStruct((4,), "float32"),
            name="clean",
        )
        assert _codes(rep) == []

    def test_tpj003_unaliased_donation_flagged(self):
        """Donating an arg that can never alias the output (dtype
        mismatch) is a dead declaration."""
        import jax

        def f(x, y):
            return y * 2.0

        spec = P.ProgramSpec(
            name="deaddonate", fn=jax.jit(f), base_fn=f,
            build=lambda b: (
                (
                    jax.ShapeDtypeStruct((8,), "int32"),
                    jax.ShapeDtypeStruct((8,), "float32"),
                ),
                {},
            ),
            buckets=(1,), donate_argnums=(0,),
        )
        rep = P.audit_spec(spec)
        assert "TPJ003" in _codes(rep)

    def test_tpj003_aliased_donation_negative(self):
        import jax

        def f(x):
            return x * 2.0

        spec = P.ProgramSpec(
            name="livedonate", fn=jax.jit(f), base_fn=f,
            build=lambda b: (
                (jax.ShapeDtypeStruct((8,), "float32"),), {}
            ),
            buckets=(1,), donate_argnums=(0,),
        )
        rep = P.audit_spec(spec)
        assert "TPJ003" not in _codes(rep)

    def test_tpj005_structure_fork_flagged(self):
        """A program whose structure depends on the bucket (a python
        branch on lane count) forks the compiled family."""
        import jax
        import jax.numpy as jnp

        def f(x):
            if x.shape[0] > 4:  # structure forks on the bucketed axis
                return jnp.sort(x)
            return x * 2

        spec = P.ProgramSpec(
            name="fork", fn=f,
            build=lambda k: (
                (jax.ShapeDtypeStruct((k,), "float32"),), {}
            ),
            buckets=(4, 8), bucket_axis="lanes",
        )
        rep = P.audit_spec(spec)
        assert "TPJ005" in _codes(rep)
        detail = rep.by_code("TPJ005")[0].detail
        assert set(detail["fingerprints"]) == {"4", "8"}

    def test_tpj000_untraceable_program_degrades(self):
        def boom(x):
            raise RuntimeError("no trace for you")

        spec = P.ProgramSpec(
            name="boom", fn=boom,
            build=lambda k: ((np.zeros(3, np.float32),), {}),
            buckets=(1,),
        )
        rep = P.audit_spec(spec)
        assert _codes(rep) == ["TPJ000"]


# ----------------------------------------------------- bucket-boundary TPJ005
class TestBucketBoundaries:
    """The GLM sweep programs must keep ONE jaxpr structure across every
    ``lane_bucket`` family boundary — pow2 (<=64) and 32-multiples — so a
    future bucket-schedule change that forks program structure fails CI
    here."""

    def _fingerprints(self, name, buckets):
        spec = [s for s in P.collect_specs([name]) if s.name == name][0]
        out = {}
        for b in buckets:
            args, statics = spec.build(b)
            closed = P._trace_closed(spec.fn, args, statics)
            out[b] = P.jaxpr_fingerprint(closed)
        return out

    def test_glm_sweep_structure_stable_across_lane_buckets(self):
        from transmogrifai_tpu.compiler.bucketing import lane_bucket

        buckets = sorted({lane_bucket(k) for k in (3, 5, 17, 33, 65, 90)})
        assert any(b <= 64 for b in buckets) and any(b > 64 for b in buckets)
        for name in ("logistic_binary_batched", "linear_batched"):
            fps = self._fingerprints(name, buckets)
            assert len(set(fps.values())) == 1, (name, fps)

    def test_padded_vs_unpadded_lane0_replay_twins(self):
        """k=5 padded onto the 8-bucket must be the SAME program as a
        native k=8 sweep (the pad replays lane 0 — structure identical,
        shapes identical after padding)."""
        from transmogrifai_tpu.compiler.bucketing import (
            lane_bucket, pad_lane_arrays,
        )

        k = 5
        bucket = lane_bucket(k)
        assert bucket == 8
        rm = np.ones((k, 16), np.float32)
        reg = np.zeros(k, np.float32)
        en = np.zeros(k, np.float32)
        padded = pad_lane_arrays(bucket, rm, reg, en)
        assert all(a.shape[0] == bucket for a in padded)

        from transmogrifai_tpu.models.solvers import (
            fit_logistic_binary_batched,
        )

        x = np.zeros((16, 3), np.float32)
        y = np.zeros(16, np.float32)
        statics = dict(num_iters=2, fit_intercept=True, standardization=True)
        fp_padded = P.jaxpr_fingerprint(P._trace_closed(
            fit_logistic_binary_batched, (x, y, *padded), statics
        ))
        native = (np.ones((8, 16), np.float32), np.zeros(8, np.float32),
                  np.zeros(8, np.float32))
        fp_native = P.jaxpr_fingerprint(P._trace_closed(
            fit_logistic_binary_batched, (x, y, *native), statics
        ))
        assert fp_padded == fp_native

    def test_serving_programs_stable_across_batch_buckets(self):
        for name in ("bin_data", "predict_boosted", "predict_forest",
                     "fused_serve", "fused_serve_explain"):
            fps = self._fingerprints(name, (8, 16, 32))
            assert len(set(fps.values())) == 1, (name, fps)


# -------------------------------------------------------------- registry
class TestRegistry:
    def test_every_warmup_mapped_program_registers_a_spec(self):
        from transmogrifai_tpu.compiler import warmup as W

        mapped = set(W.SCORE_PROGRAMS)
        for fam in W._FAMILY_PROGRAMS.values():
            mapped.update(fam)
        registered = {s.name for s in P.collect_specs()}
        assert mapped <= registered, mapped - registered

    def test_registry_audit_is_tpj_clean_modulo_baseline(self):
        """Every program in SCORE_PROGRAMS + the fused builders audits
        clean except the two ACCEPTED fused-ingest TPJ003s carried by the
        committed baseline."""
        from transmogrifai_tpu.analysis import lint as L
        from transmogrifai_tpu.compiler import warmup as W

        rep = P.audit_programs(include_ast=False)
        traced = rep.data["programs"]
        assert set(W.SCORE_PROGRAMS) <= set(traced)
        baseline = L.load_baseline(os.path.join(REPO,
                                                "program_baseline.json"))
        fresh = L.new_findings(rep, baseline)
        assert fresh == [], [f.render() for f in fresh]

    def test_tpj010_unregistered_map_entry_flagged(self):
        rep = P.warmup_map_findings(
            specs=P.collect_specs(),
            score_programs=frozenset({"predict_boosted", "ghost_program"}),
            family_programs={},
        )
        assert "TPJ010" in _codes(rep)
        assert "ghost_program" in rep.by_code("TPJ010")[0].message

    def test_tpj010_unmapped_scoring_spec_flagged(self):
        spec = P.ProgramSpec(
            name="orphan_scorer", fn=lambda x: x,
            build=lambda b: ((), {}), scoring=True,
        )
        rep = P.warmup_map_findings(
            specs=[spec], score_programs=frozenset(), family_programs={},
        )
        assert "TPJ010" in _codes(rep)
        assert "orphan_scorer" in rep.by_code("TPJ010")[0].message

    def test_tpj010_negative_consistent_maps(self):
        rep = P.warmup_map_findings(specs=P.collect_specs())
        assert "TPJ010" not in _codes(rep)

    def test_broken_registration_surfaces_as_tpj000(self, monkeypatch):
        """A module whose program_trace_specs() raises must show up as a
        TPJ000 finding, not silently shrink the audited set."""
        monkeypatch.setattr(
            P, "SPEC_MODULES",
            P.SPEC_MODULES + ("transmogrifai_tpu.no_such_module",),
        )
        rep = P.audit_programs(include_ast=False)
        mods = [
            f for f in rep.by_code("TPJ000")
            if "no_such_module" in f.subject
        ]
        assert mods and "MISSING" in mods[0].message

    def test_whole_registry_pass_under_pinned_bound(self):
        t0 = time.monotonic()
        rep = P.audit_programs(root=REPO)
        elapsed = time.monotonic() - t0
        assert elapsed < 30.0, f"--programs pass took {elapsed:.1f}s"
        assert len(rep.data["programs"]) >= 14


# ------------------------------------------------------------- AST (TPJ007-9)
def _hazards(src, rel="transmogrifai_tpu/models/x.py"):
    return P.tracing_hazard_source(textwrap.dedent(src), rel)


class TestTracingHazards:
    def test_tpj007_if_while_on_traced_flagged(self):
        rep = _hazards("""
            import jax

            @jax.jit
            def f(x, y):
                if x > 0:
                    return y
                while y < 3:
                    y = y + 1
                return y
        """)
        assert _codes(rep) == ["TPJ007", "TPJ007"]

    def test_tpj007_static_shape_isnone_negatives(self):
        rep = _hazards("""
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("mode",))
            def f(x, grp, *, mode):
                if mode == "a":
                    return x
                if x.ndim == 2:
                    return x
                if grp is None:
                    return x
                if isinstance(grp, tuple):
                    return x
                return x
        """)
        assert _codes(rep) == []

    def test_tpj008_sync_coercions_flagged(self):
        rep = _hazards("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                a = x.item()
                b = float(x)
                c = np.asarray(x)
                return a + b + c.sum()
        """)
        assert _codes(rep) == ["TPJ008", "TPJ008", "TPJ008"]

    def test_tpj008_negatives_on_statics_and_hosts(self):
        rep = _hazards("""
            import jax
            import numpy as np
            from functools import partial

            @partial(jax.jit, static_argnames=("k",))
            def f(x, *, k):
                return x * float(k)

            def host_path(rows):
                return np.asarray(rows)
        """)
        assert _codes(rep) == []

    def test_tpj009_closure_capture_flagged_both_scopes(self):
        rep = _hazards("""
            import jax
            import numpy as np

            TABLE = np.asarray([1.0, 2.0])

            @jax.jit
            def module_capture(z):
                return z + TABLE

            def factory():
                w = np.zeros((4, 4))
                @jax.jit
                def inner(z):
                    return z @ w
                return inner
        """)
        assert _codes(rep) == ["TPJ009", "TPJ009"]

    def test_tpj009_negative_passed_as_arg(self):
        rep = _hazards("""
            import jax
            import numpy as np

            @jax.jit
            def f(z, w):
                return z @ w

            def caller():
                w = np.zeros((4, 4))
                return f(np.ones(4), w)
        """)
        assert _codes(rep) == []

    def test_wrap_by_name_jit_detected(self):
        rep = _hazards("""
            import jax

            def f(x):
                if x > 0:
                    return x
                return -x

            g = jax.jit(f)
        """)
        assert _codes(rep) == ["TPJ007"]

    def test_suppression_unified_and_tpj_dialects(self):
        rep = _hazards("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:  # tpj: ok — two-shape family is intentional
                    return x
                return -x

            @jax.jit
            def g(x):
                if x > 0:  # tp: disable=TPJ007
                    return x
                return -x
        """)
        assert _codes(rep) == []

    def test_repo_surface_is_hazard_clean(self):
        rep = P.tracing_hazards_paths(root=REPO)
        assert _codes(rep) == [], [f.render() for f in rep.findings]


# ------------------------------------------------------- census third leg
class TestThreeWayCensus:
    def test_program_counts_fused_vs_staged(self):
        counts = P.program_transfer_counts(fused=object())
        assert counts["hostToDevicePerBatch"] == 1
        assert counts["deviceToHostPerBatch"] == 1
        empty = P.program_transfer_counts(plan=[])
        assert empty["hostToDevicePerBatch"] == 0

    def test_tpj006_disagreement_flagged_and_agreement_clean(self):
        static = {"hostToDeviceTransfers": 1, "deviceToHostTransfers": 1}
        ok = P.reconcile_program_census(
            static, {"hostToDevicePerBatch": 1, "deviceToHostPerBatch": 1}
        )
        assert _codes(ok) == []
        bad = P.reconcile_program_census(
            static, {"hostToDevicePerBatch": 2, "deviceToHostPerBatch": 1}
        )
        assert _codes(bad) == ["TPJ006"]
        assert bad.by_code("TPJ006")[0].detail["programH2d"] == 2

    def test_reconcile_transfer_census_grows_program_leg(self):
        from transmogrifai_tpu.telemetry import runlog as rl

        runtime = {"h2dTransfers": 3, "h2dBytes": 300,
                   "d2hTransfers": 3, "d2hBytes": 288}
        static = {"hostToDeviceTransfers": 1, "deviceToHostTransfers": 1,
                  "downBytesPerRow": 1.0}
        rec = rl.reconcile_transfer_census(
            runtime, static, rows=288, batches=3, check_uploads=True,
            program_counts={"hostToDevicePerBatch": 1,
                            "deviceToHostPerBatch": 1},
        )
        assert rec["consistent"] and rec["programConsistent"]
        bad = rl.reconcile_transfer_census(
            runtime, static, rows=288, batches=3,
            program_counts={"hostToDevicePerBatch": 2,
                            "deviceToHostPerBatch": 2},
        )
        assert not bad["programConsistent"]
        assert not bad["consistent"]


# -------------------------------------------------- fitted flagship flow
@pytest.fixture(scope="module")
def flagship():
    from transmogrifai_tpu.dataset import Dataset
    from transmogrifai_tpu.features import from_dataset
    from transmogrifai_tpu.local.scoring import score_function
    from transmogrifai_tpu.models.logistic import LogisticRegression
    from transmogrifai_tpu.ops import transmogrify
    from transmogrifai_tpu.selector import BinaryClassificationModelSelector
    from transmogrifai_tpu.types.columns import column_from_values
    from transmogrifai_tpu.utils import uid as uid_util
    from transmogrifai_tpu.workflow.workflow import Workflow
    import transmogrifai_tpu.types as T

    rng = np.random.default_rng(17)
    n = 128
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    city = [["bern", "kyiv", "oslo", "lomé"][i % 4] for i in range(n)]
    label = (x1 + 0.5 * x2 > 0).astype(float)
    ds = Dataset.of({
        "label": column_from_values(T.RealNN, label),
        "age": column_from_values(T.Real, x1),
        "income": column_from_values(T.Real, x2),
        "city": column_from_values(T.PickList, city),
    })
    uid_util.reset()
    resp, preds = from_dataset(ds, response="label")
    vec = resp.sanity_check(
        transmogrify(list(preds)), remove_bad_features=True
    )
    pred = BinaryClassificationModelSelector(
        seed=7, num_folds=2,
        models=[(LogisticRegression(), {"reg_param": [0.01]})],
    ).set_input(resp, vec).get_output()
    model = (
        Workflow().set_result_features(pred).set_input_dataset(ds).train()
    )
    rows = [
        {"age": float(a), "income": float(b), "city": c}
        for a, b, c in zip(x1, x2, city)
    ]
    return {"model": model, "rows": rows, "score_function": score_function}


class TestFittedFlow:
    def test_audit_programs_true_on_fitted_closure(self, flagship,
                                                   monkeypatch):
        monkeypatch.setenv("TPTPU_HOST_PREDICT_MAX", "4")
        fn = flagship["score_function"](flagship["model"])
        fn.batch(flagship["rows"][:32])
        rep = fn.audit(programs=True)
        js = rep.to_json()
        codes = {f["code"] for f in js["findings"]}
        # the fitted fused program audits clean modulo the ACCEPTED
        # fused-ingest TPJ003 (see program_baseline.json)
        assert codes <= {"TPJ003"}, codes
        assert "fused_serve" in js["programs"]
        assert js["programTransferCounts"]["hostToDevicePerBatch"] == 1

    def test_three_way_census_exact_agreement(self, flagship, monkeypatch):
        from transmogrifai_tpu.telemetry import runlog as rl

        monkeypatch.setenv("TPTPU_HOST_PREDICT_MAX", "4")
        fn = flagship["score_function"](flagship["model"])
        rows = flagship["rows"][:32]
        fn.batch(rows)  # bring-up
        before = rl.snapshot()
        for _ in range(3):
            fn.batch(rows)
        runtime = rl.delta(before)
        js = fn.audit(programs=True).to_json()
        rec = rl.reconcile_transfer_census(
            runtime, js["transferCensus"], rows=96, batches=3,
            check_uploads=True,
            program_counts=js["programTransferCounts"],
        )
        assert rec["programConsistent"], rec
        assert rec["consistent"], rec

    def test_fitted_fused_program_tpj001_guard(self, flagship, monkeypatch):
        """The fitted program's model arrays arrive as traced args — no
        giant constant ever folds into the fused jaxpr."""
        monkeypatch.setenv("TPTPU_HOST_PREDICT_MAX", "4")
        fn = flagship["score_function"](flagship["model"])
        fn.batch(flagship["rows"][:32])
        rep = fn.audit(programs=True)
        assert rep.by_code("TPJ001") == []


# ------------------------------------------------------ bank admission
class TestBankAdmission:
    def test_audit_gate_rejects_contract_violator(self, tmp_path,
                                                  monkeypatch):
        import jax

        from transmogrifai_tpu.compiler import stats as cstats
        from transmogrifai_tpu.utils import aot

        monkeypatch.setenv("TPTPU_COMPILE_CACHE", str(tmp_path))
        monkeypatch.setenv("TPTPU_PROGRAM_AUDIT", "1")
        baked = np.ones((256, 256), dtype=np.float32)
        jfn = jax.jit(lambda x: (x @ baked).sum())
        before = cstats.snapshot()
        out = aot.aot_call(
            "tpj_violator", jfn, (np.ones((4, 256), np.float32),), {}
        )
        assert np.isfinite(float(out))
        aot._drain_exports()
        delta = cstats.delta(before)
        assert delta["programAuditRejected"] == 1
        blobs = [
            f for base, _, fs in os.walk(tmp_path) for f in fs
            if f.endswith(".jaxexec") and "tpj_violator" in f
        ]
        assert blobs == []

    def test_audit_gate_admits_clean_program(self, tmp_path, monkeypatch):
        import jax

        from transmogrifai_tpu.compiler import stats as cstats
        from transmogrifai_tpu.utils import aot

        monkeypatch.setenv("TPTPU_COMPILE_CACHE", str(tmp_path))
        monkeypatch.setenv("TPTPU_PROGRAM_AUDIT", "1")
        jfn = jax.jit(lambda x, w: (x @ w).sum())
        before = cstats.snapshot()
        aot.aot_call(
            "tpj_clean", jfn,
            (np.ones((4, 8), np.float32), np.ones((8, 8), np.float32)), {},
        )
        aot._drain_exports()
        delta = cstats.delta(before)
        assert delta["programsAudited"] >= 1
        assert delta["programAuditRejected"] == 0
        blobs = [
            f for base, _, fs in os.walk(tmp_path) for f in fs
            if f.endswith(".jaxexec") and "tpj_clean" in f
        ]
        assert len(blobs) == 1

    def test_audit_gate_admits_warning_only_program(self, tmp_path,
                                                    monkeypatch):
        """WARNING findings (e.g. a weak-typed auxiliary output) are
        reported, not refused — only ERROR-class contract violations
        block a blob."""
        import jax

        from transmogrifai_tpu.compiler import stats as cstats
        from transmogrifai_tpu.utils import aot

        monkeypatch.setenv("TPTPU_COMPILE_CACHE", str(tmp_path))
        monkeypatch.setenv("TPTPU_PROGRAM_AUDIT", "1")
        jfn = jax.jit(lambda x: (x.sum(), 1.0 + 2.0))  # weak 2nd output
        before = cstats.snapshot()
        aot.aot_call(
            "tpj_weak_out", jfn, (np.ones((4,), np.float32),), {}
        )
        aot._drain_exports()
        delta = cstats.delta(before)
        assert delta["programAuditRejected"] == 0
        blobs = [
            f for _, _, fs in os.walk(tmp_path)
            for f in fs if "tpj_weak_out" in f
        ]
        assert len(blobs) == 1

    def test_gate_off_overhead_is_noise(self):
        """<2% overhead guard, absolute-cost pattern: with the env unset
        the admission gate is one dict read — not measurable against a
        1 ms budget for a thousand checks."""
        t0 = time.perf_counter()
        for _ in range(1000):
            os.environ.get("TPTPU_PROGRAM_AUDIT", "0") == "1"
        assert time.perf_counter() - t0 < 0.01


# --------------------------------------------------------------- CLI gate
def _run_cli(args, cwd=REPO, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "transmogrifai_tpu", "lint", *args],
        capture_output=True, text=True, cwd=cwd, env=env,
    )


@pytest.mark.slow
class TestCLI:
    def test_programs_gate_green_against_committed_baseline(self):
        proc = _run_cli(
            ["--programs", "--program-baseline", "program_baseline.json"]
        )
        assert proc.returncode in (0, 1), proc.stdout + proc.stderr
        assert "program finding(s)" in proc.stdout
        assert "programs traced" in proc.stdout

    def test_missing_program_baseline_exits_3(self):
        proc = _run_cli(
            ["--programs", "--program-baseline", "no_such_baseline.json"]
        )
        assert proc.returncode == 3, proc.stdout + proc.stderr
        assert "baseline file not found" in proc.stderr

    def test_all_runs_every_gate_with_summary_table(self):
        proc = _run_cli(["--all"])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        for fam in ("TPL", "TPC", "TPJ"):
            assert fam in proc.stdout
        assert "gate" in proc.stdout and "baselined" in proc.stdout
