"""NaiveBayes / LinearSVC / GLM / Isotonic / BinScore tests.

Parity model: core/src/test/.../classification/OpNaiveBayesTest.scala,
OpLinearSVCTest.scala, regression/OpGeneralizedLinearRegressionTest.scala,
IsotonicRegressionCalibratorTest.scala, evaluators/OpBinScoreEvaluatorTest.scala.
"""
import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.evaluators import BinScoreEvaluator
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models import (
    GeneralizedLinearRegression,
    IsotonicRegressionCalibrator,
    LinearSVC,
    NaiveBayes,
)
from transmogrifai_tpu.types.columns import NumericColumn, VectorColumn


def _sep_data(n=200, d=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (x @ w > 0).astype(np.float32)
    return x, y


def test_linear_svc_separable():
    x, y = _sep_data()
    m = LinearSVC(reg_param=0.01).fit_arrays(x, y, np.ones(len(y), np.float32))
    pred, prob, raw = m.predict_arrays(x)
    assert prob is None and raw.shape == (len(y), 2)
    assert (pred == y).mean() > 0.95


def test_naive_bayes_multinomial():
    rng = np.random.default_rng(1)
    n = 300
    y = rng.integers(0, 2, n).astype(np.float32)
    # class-dependent count features (non-negative)
    rates = np.array([[5.0, 1.0, 1.0], [1.0, 1.0, 5.0]])
    x = rng.poisson(rates[y.astype(int)]).astype(np.float32)
    m = NaiveBayes().fit_arrays(x, y, np.ones(n, np.float32))
    pred, prob, raw = m.predict_arrays(x)
    assert prob.shape == (n, 2)
    np.testing.assert_allclose(prob.sum(1), 1.0, atol=1e-9)
    assert (pred == y).mean() > 0.85


def test_naive_bayes_rejects_negative():
    x = np.array([[1.0, -1.0]], dtype=np.float32)
    y = np.array([0.0], dtype=np.float32)
    with pytest.raises(ValueError, match="non-negative"):
        NaiveBayes().fit_arrays(x, y, np.ones(1, np.float32))


def test_naive_bayes_bernoulli():
    rng = np.random.default_rng(2)
    n = 400
    y = rng.integers(0, 2, n).astype(np.float32)
    p = np.where(y[:, None] > 0, 0.8, 0.2)
    x = (rng.random((n, 3)) < p).astype(np.float32)
    m = NaiveBayes(model_kind="bernoulli").fit_arrays(x, y, np.ones(n, np.float32))
    pred, prob, _ = m.predict_arrays(x)
    assert (pred == y).mean() > 0.8


@pytest.mark.parametrize("family,link", [
    ("gaussian", "identity"),
    ("poisson", "log"),
    ("gamma", "log"),
    ("binomial", "logit"),
])
def test_glm_families_recover_signal(family, link):
    rng = np.random.default_rng(3)
    n, d = 3000, 3
    x = rng.normal(size=(n, d)).astype(np.float32) * 0.3
    w = np.array([0.5, -0.4, 0.3])
    eta = x @ w + 0.2
    if family == "gaussian":
        y = eta + rng.normal(scale=0.05, size=n)
    elif family == "poisson":
        y = rng.poisson(np.exp(eta)).astype(np.float64)
    elif family == "gamma":
        mu = np.exp(eta)
        y = rng.gamma(shape=20.0, scale=mu / 20.0)
    else:
        y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(np.float64)
    est = GeneralizedLinearRegression(family=family, link=link)
    m = est.fit_arrays(x.astype(np.float32), y.astype(np.float32),
                       np.ones(n, np.float32))
    mu_hat, _, _ = m.predict_arrays(x)
    assert np.isfinite(mu_hat).all()
    corr = np.corrcoef(mu_hat, eta)[0, 1]
    assert corr > 0.8, f"{family}/{link} fit failed: corr={corr}"


def test_glm_gaussian_matches_ols():
    rng = np.random.default_rng(4)
    n, d = 200, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = np.array([1.0, -2.0, 0.5])
    y = (x @ w + 3.0).astype(np.float32)
    m = GeneralizedLinearRegression().fit_arrays(x, y, np.ones(n, np.float32))
    np.testing.assert_allclose(m.weights, w, atol=1e-3)
    assert abs(m.intercept - 3.0) < 1e-3


def test_isotonic_calibrator_monotone():
    # classic: noisy monotone scores; PAV output must be non-decreasing
    rng = np.random.default_rng(5)
    n = 100
    score = np.sort(rng.random(n))
    label = (score + rng.normal(scale=0.2, size=n) > 0.5).astype(np.float64)
    ds = Dataset.of({
        "label": NumericColumn(T.RealNN, label, np.ones(n, bool)),
        "score": NumericColumn(T.RealNN, score, np.ones(n, bool)),
    })
    lbl = FeatureBuilder.RealNN("label").as_response()
    sc = FeatureBuilder.RealNN("score").as_predictor()
    est = IsotonicRegressionCalibrator().set_input(lbl, sc)
    model = est.fit(ds)
    out = model.transform(ds)[model.output_name]
    vals = out.values
    assert (np.diff(vals) >= -1e-12).all()
    assert vals.min() >= 0.0 and vals.max() <= 1.0


def test_isotonic_simple_pav():
    # Spark IsotonicRegressionTest-style fixture: y = (1,2,3) with violation
    y = np.array([3.0, 1.0, 2.0])
    s = np.array([1.0, 2.0, 3.0])
    ds = Dataset.of({
        "label": NumericColumn(T.RealNN, y, np.ones(3, bool)),
        "score": NumericColumn(T.RealNN, s, np.ones(3, bool)),
    })
    lbl = FeatureBuilder.RealNN("label").as_response()
    sc = FeatureBuilder.RealNN("score").as_predictor()
    model = IsotonicRegressionCalibrator().set_input(lbl, sc).fit(ds)
    out = model.transform(ds)[model.output_name].values
    assert (np.diff(out) >= -1e-12).all()
    np.testing.assert_allclose(out.sum(), y.sum(), atol=1e-9)


def test_antitonic_calibrator():
    n = 50
    score = np.linspace(0, 1, n)
    label = 1.0 - score  # perfectly decreasing
    ds = Dataset.of({
        "label": NumericColumn(T.RealNN, label, np.ones(n, bool)),
        "score": NumericColumn(T.RealNN, score, np.ones(n, bool)),
    })
    lbl = FeatureBuilder.RealNN("label").as_response()
    sc = FeatureBuilder.RealNN("score").as_predictor()
    model = IsotonicRegressionCalibrator(isotonic=False).set_input(lbl, sc).fit(ds)
    out = model.transform(ds)[model.output_name].values
    assert (np.diff(out) <= 1e-12).all()
    np.testing.assert_allclose(out, label, atol=1e-9)


def test_bin_score_evaluator():
    # OpBinScoreEvaluatorTest.scala-style: 4 points, 4 bins
    y = np.array([1.0, 0.0, 1.0, 0.0])
    prob = np.array([[0.01, 0.99], [0.99, 0.01], [0.3, 0.7], [0.6, 0.4]])
    ev = BinScoreEvaluator(num_bins=4)
    m = ev.evaluate_arrays(y, prob[:, 1] > 0.5, prob)
    assert m["BrierScore"] == pytest.approx(
        np.mean((prob[:, 1] - y) ** 2)
    )
    assert len(m["binCenters"]) == 4
    assert sum(m["numberOfDataPoints"]) == 4
    assert not ev.is_larger_better


def test_bin_score_constant_scores():
    y = np.array([1.0, 0.0])
    prob = np.array([[0.5, 0.5], [0.5, 0.5]])
    m = BinScoreEvaluator(num_bins=10).evaluate_arrays(y, y, prob)
    assert m["numberOfDataPoints"][0] == 2


def test_persistence_roundtrip_new_models(tmp_path):
    """New model families survive the manifest+npz round trip."""
    from transmogrifai_tpu.workflow.persistence import construct_stage

    x, y = _sep_data()
    svc = LinearSVC(reg_param=0.01).fit_arrays(x, y, np.ones(len(y), np.float32))
    re = construct_stage("LinearSVCModel", svc.get_params(), svc.get_arrays())
    np.testing.assert_allclose(re.weights, svc.weights)

    glm = GeneralizedLinearRegression(family="poisson").fit_arrays(
        x, np.abs(y).astype(np.float32), np.ones(len(y), np.float32))
    re2 = construct_stage("GeneralizedLinearRegressionModel",
                          glm.get_params(), glm.get_arrays())
    assert re2.family == "poisson" and re2.link == "log"


def test_make_candidates_expands_names():
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, make_candidates,
    )
    cands = make_candidates("BinaryClassification", ["OpNaiveBayes", "OpLinearSVC"])
    assert len(cands) == 2
    est, grid = cands[1]
    assert isinstance(est, LinearSVC) and "reg_param" in grid
    sel = BinaryClassificationModelSelector(models=cands)
    assert len(sel.models) == 2
    with pytest.raises(ValueError, match="not a Regression model"):
        make_candidates("Regression", ["OpNaiveBayes"])


def test_svc_standardization_flag_changes_fit():
    x, y = _sep_data()
    x = x * np.array([10.0, 0.1, 1.0, 1.0], dtype=np.float32)  # uneven scales
    m_std = LinearSVC(reg_param=0.5).fit_arrays(x, y, np.ones(len(y), np.float32))
    m_raw = LinearSVC(reg_param=0.5, standardization=False).fit_arrays(
        x, y, np.ones(len(y), np.float32))
    assert not np.allclose(m_std.weights, m_raw.weights)


def test_fit_linear_no_intercept_scale_only():
    """code-review r3: fit_linear with fit_intercept=False must not center
    x or y — the centered fit bakes an implicit intercept into training
    that predict never applies."""
    import jax.numpy as jnp
    import numpy as np

    from transmogrifai_tpu.models.solvers import fit_linear

    rng = np.random.default_rng(0)
    n, d = 300, 6
    x = rng.normal(size=(n, d)).astype(np.float32) + 5.0
    w = rng.normal(size=d).astype(np.float32)
    y = (x @ w + 0.05 * rng.normal(size=n)).astype(np.float32)
    mask = np.ones(n, np.float32)
    out = fit_linear(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask), 0.0, 0.0,
        num_iters=3000, fit_intercept=False,
    )
    pred = x @ np.asarray(out.weights)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    # through-origin data: the scale-only no-intercept fit recovers it
    assert rmse < 0.2, rmse
    assert float(out.intercept) == 0.0
