"""Continuous-retraining control loop suite (resilience/retrain.py):
drift-alert quorum/debounce, chunked traffic collection with torn-chunk
quarantine, warm-start retrain with crash-resume across seeded
``crash_retrain``, the run-ledger gate BEFORE the canary, canary
promote / rollback / timeout through the real ModelRegistry, the
provable ``max_retrains`` + backoff bound, the ``drift_cleared``
hysteresis pairing, the events subscriber seam, and the ``retrain`` /
streaming-chunk ledger exposure.

Everything runs on injectable/virtual clocks — zero real sleeps.
Markers: retrain, serving, faults.
"""
import csv
import os
import time

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.resilience import faults
from transmogrifai_tpu.resilience.faults import SimulatedCrash
from transmogrifai_tpu.resilience.retrain import (
    RetrainConfig,
    RetrainController,
    chunk_fit_stats,
    ledger_snapshot,
    warm_start_workflow_trainer,
)
from transmogrifai_tpu.resilience.retry import RetryPolicy, TransientError
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.serving import (
    FleetConfig,
    FleetService,
    ModelRegistry,
    ServiceConfig,
)
from transmogrifai_tpu.telemetry import events as tevents
from transmogrifai_tpu.telemetry import metrics as tmetrics
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.utils import uid as uid_util
from transmogrifai_tpu.workflow.workflow import Workflow

pytestmark = [pytest.mark.retrain, pytest.mark.serving, pytest.mark.faults]


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


class Fn:
    """Score-function double: ``prediction = offset + x1`` per row."""

    def __init__(self, offset=0.0):
        self.offset = float(offset)

    def batch(self, rows, explain=0):
        return [
            {"pred": {"prediction": self.offset + float(r.get("x1", 0.0))}}
            for r in rows
        ]


class FakeFleet:
    """The minimal fleet surface the controller integrates with: the
    ``on_served`` seam plus a services list for registry doubles."""

    def __init__(self, n=2):
        self.on_served = None
        self.services = [ScoringStub() for _ in range(n)]

    def serve(self, rows, replica=0, latency=0.01):
        hook = self.on_served
        results = self.services[replica].score_fn.batch(rows)
        if hook is not None:
            hook(rows, results, replica, latency)


class ScoringStub:
    def __init__(self):
        self.score_fn = Fn()


class FakeRegistry:
    """Scripted registry double recording the rollout calls the
    controller makes; ``decision`` scripts evaluate_canary."""

    def __init__(self, decision="promote", compared=10):
        self.decision = decision
        self.compared = compared
        self.calls = []
        self.serving = None
        self._canary = None

    def register(self, version, fn):
        self.calls.append(("register", version))

    def start_canary(self, version, replicas=(0,), tolerances=None):
        if self._canary is not None:
            raise RuntimeError("a canary is already running")
        self._canary = version
        self.calls.append(("start_canary", version, tuple(replicas)))

    def canary_report(self):
        if self._canary is None:
            raise RuntimeError("no canary running")
        return {"compared": self.compared, "version": self._canary}

    def evaluate_canary(self):
        version, self._canary = self._canary, None
        self.calls.append(("evaluate_canary", version))
        if self.decision == "promote":
            self.serving = version
            return {
                "decision": "promote", "compared": self.compared,
                "agreement": 1.0, "codes": [],
            }
        return {
            "decision": "rollback", "compared": self.compared,
            "agreement": 0.0, "codes": ["TPR004"],
        }

    def rollback(self, codes=()):
        if self._canary is None:
            raise RuntimeError("no canary running")
        self._canary = None
        self.calls.append(("rollback", tuple(codes)))


def _run_doc(auroc=0.9, serve_s=0.01):
    return {
        "run": {
            "phases": {"serve": {"seconds": serve_s}},
            "quality": {"auroc": auroc},
            "deviceMemory": {"deviceBytesInUse": 1024,
                             "devicePeakBytes": 4096},
        }
    }


def _scripted_trainer(script):
    """``script`` is a list: each entry is an Exception instance to raise
    or an (version, fn, run_doc) tuple to return, consumed per call."""
    calls = []

    def trainer(chunks, ctx):
        calls.append(dict(ctx, chunks=len(chunks)))
        step = script.pop(0)
        if isinstance(step, BaseException):
            raise step
        return step

    trainer.calls = calls
    return trainer


def _controller(trainer, clock=None, fleet=None, registry=None,
                baseline=None, **cfg_kw):
    clock = clock or FakeClock()
    fleet = fleet or FakeFleet()
    registry = registry if registry is not None else FakeRegistry()
    cfg_kw.setdefault("quorum", 1)
    cfg_kw.setdefault("cooldown", 0.0)
    cfg_kw.setdefault("collect_rows", 8)
    cfg_kw.setdefault("chunk_rows", 4)
    cfg_kw.setdefault("min_canary_served", 1)
    cfg_kw.setdefault(
        "backoff",
        RetryPolicy(max_attempts=4, base_delay=10.0, max_delay=80.0,
                    jitter=0.0),
    )
    ctl = RetrainController(
        fleet, registry, trainer, config=RetrainConfig(**cfg_kw),
        clock=clock, baseline_run=baseline,
    )
    return ctl, clock, fleet, registry


def _alert(feature="x1"):
    tevents.emit("drift_alert", feature=feature)


def _collect(fleet, ctl, clock, rows=None, n=8):
    rows = rows or [{"x1": float(i), "city": "a"} for i in range(n)]
    for r in rows:
        fleet.serve([r])
    return ctl.tick(clock.now)


@pytest.fixture(autouse=True)
def _detach(request):
    """Every test detaches its controllers (the events subscriber list is
    process-global)."""
    ctls = []
    request.node._retrain_ctls = ctls
    yield
    for c in ctls:
        c.close()


def _track(request, ctl):
    request.node._retrain_ctls.append(ctl)
    return ctl


# ----------------------------------------------------------- trigger/debounce
class TestTriggerDebounce:
    def test_quorum_of_distinct_features(self, request):
        trainer = _scripted_trainer([])
        ctl, clock, fleet, _ = _controller(trainer, quorum=2)
        _track(request, ctl)
        _alert("x1")
        assert ctl.tick(0.0) == "idle"
        _alert("x1")  # same feature — still one distinct alerter
        assert ctl.tick(0.0) == "idle"
        _alert("x2")
        assert ctl.tick(0.0) == "collecting"
        assert ctl.stats.snapshot()["retrainsTriggered"] == 1
        assert ctl.stats.snapshot()["alertsSeen"] == 3

    def test_alert_window_prunes_stale_alerts(self, request):
        ctl, clock, _, _ = _controller(
            _scripted_trainer([]), quorum=2, quorum_window=30.0
        )
        _track(request, ctl)
        _alert("x1")
        clock.now = 100.0
        _alert("x2")  # x1's alert is now 100 s old — outside the window
        assert ctl.tick(100.0) == "idle"
        _alert("x1")
        assert ctl.tick(100.0) == "collecting"

    def test_cooldown_blocks_refire_backoff_delays(self, request):
        trainer = _scripted_trainer([
            TransientError("boom"), ("v2", Fn(), _run_doc()),
        ])
        ctl, clock, fleet, _ = _controller(trainer, cooldown=50.0)
        _track(request, ctl)
        _alert("x1")
        assert ctl.tick(1.0) == "collecting"
        _collect(fleet, ctl, clock)  # window full -> retraining
        ctl.tick(1.0)  # trainer fails -> backoff, idle
        assert ctl.state == "idle"
        assert ctl.stats.snapshot()["retrainFailures"] == 1
        led = ctl.ledger()
        assert led["backoffUntil"] > 1.0
        _alert("x1")
        assert ctl.tick(2.0) == "idle"  # x1 still in cooldown
        clock.now = 60.0
        _alert("x1")  # cooldown (51) AND backoff (11) both expired
        assert ctl.tick(60.0) == "collecting"
        assert ctl.stats.snapshot()["retrainsTriggered"] == 2

    def test_trigger_event_emitted(self, request):
        tevents.reset_for_tests()
        ctl, clock, _, _ = _controller(_scripted_trainer([]))
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        kinds = [e["kind"] for e in tevents.recent(10)]
        assert "retrain_triggered" in kinds


# ------------------------------------------------------------ collect + chunk
class TestCollection:
    def test_window_seals_chunks_and_fit_stats(self, request):
        trainer = _scripted_trainer([("v2", Fn(), _run_doc())])
        ctl, clock, fleet, _ = _controller(
            trainer, collect_rows=8, chunk_rows=4
        )
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock, n=8)
        ctl.tick(0.0)  # retraining runs
        assert trainer.calls and trainer.calls[0]["chunks"] == 2
        assert trainer.calls[0]["rows"] == 8
        stats = trainer.calls[0]["fitStats"]
        assert "x1" in stats and stats["x1"].total_count == 8
        assert ctl.stats.snapshot()["chunksCollected"] == 2

    def test_corrupt_chunk_quarantined_never_trained(
        self, request, fault_plan
    ):
        fault_plan.corrupt_new_chunk(times=1)
        trainer = _scripted_trainer([("v2", Fn(), _run_doc())])
        ctl, clock, fleet, _ = _controller(
            trainer, collect_rows=8, chunk_rows=4
        )
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        # 12 rows: the first sealed chunk (rows 0-3) is torn and must
        # not count toward the window — clean rows refill it
        _collect(fleet, ctl, clock, n=12)
        ctl.tick(0.0)
        s = ctl.stats.snapshot()
        assert s["chunksCorrupted"] == 1
        assert ("retrain_chunk", "chunk-1") in fault_plan.fired
        assert trainer.calls[0]["chunks"] == 2  # torn chunk excluded
        trained_rows = trainer.calls[0]["rows"]
        assert trained_rows == 8

    def test_chunk_fit_stats_monoid_merge(self):
        chunks = [
            [{"x1": 1.0, "city": "a"}, {"x1": 2.0}],
            [{"x1": 3.0, "x2": 7.0}],
        ]
        stats = chunk_fit_stats(chunks, max_bins=8)
        assert stats["x1"].total_count == 3
        assert stats["x2"].total_count == 1
        assert "city" not in stats  # non-numeric fields skipped


# --------------------------------------------------------- retrain + resume
class TestRetrainResume:
    def test_crash_leaves_machine_in_retraining_then_resumes(self, request):
        trainer = _scripted_trainer([
            SimulatedCrash("mid-fit kill"),
            ("v2", Fn(), _run_doc()),
        ])
        ctl, clock, fleet, reg = _controller(trainer)
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock)
        ctl.tick(0.0)  # crash
        assert ctl.state == "retraining"
        assert trainer.calls[0]["resume"] is False
        ctl.tick(1.0)  # resume attempt
        assert trainer.calls[1]["resume"] is True
        s = ctl.stats.snapshot()
        assert s["retrainCrashes"] == 1 and s["retrainResumes"] == 1
        # crash is NOT a failed attempt: no backoff, loop continued
        assert s["retrainFailures"] == 0
        assert ctl.state == "validating"

    def test_trainer_error_backs_off_to_idle(self, request):
        tevents.reset_for_tests()
        trainer = _scripted_trainer([TransientError("io")])
        ctl, clock, fleet, reg = _controller(trainer)
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock)
        ctl.tick(0.0)
        assert ctl.state == "idle"
        assert ctl.stats.snapshot()["retrainFailures"] == 1
        assert ctl.history[-1]["outcome"] == "failed"
        assert ctl.ledger()["backoffUntil"] > 0.0
        kinds = [e["kind"] for e in tevents.recent(20)]
        assert "retrain_rolled_back" in kinds
        # the failed attempt never touched the registry
        assert reg.calls == []

    def test_warm_start_workflow_resumes_from_layer_checkpoints(
        self, request, fault_plan, tmp_path
    ):
        """The real thing: ``crash_retrain`` kills the warm-start
        ``Workflow.train`` after layer 0; the next tick rebuilds the
        same graph and ``resume=True`` restores the layer-checkpoint
        prefix — retrain-scoped faults never touch non-retrain fits."""
        rng = np.random.default_rng(5)
        n = 48

        def build(chunks, ctx):
            rows = [r for c in chunks for r in c]
            x1 = np.array([float(r["x1"]) for r in rows])
            x2 = np.array([float(r["x2"]) for r in rows])
            label = (x1 + 0.5 * x2 > 0).astype(float)
            uid_util.reset()
            ds = Dataset.of({
                "label": column_from_values(T.RealNN, label),
                "x1": column_from_values(T.Real, x1),
                "x2": column_from_values(T.Real, x2),
            })
            resp, preds = from_dataset(ds, response="label")
            vec = transmogrify(list(preds))
            selector = BinaryClassificationModelSelector(
                seed=7,
                models=[(LogisticRegression(), {"reg_param": [0.01]})],
                num_folds=2,
            )
            pred = selector.set_input(resp, vec).get_output()
            return (
                Workflow().set_result_features(pred).set_input_dataset(ds)
            )

        trainer = warm_start_workflow_trainer(
            build, checkpoint_dir=str(tmp_path / "ckpt")
        )
        fault_plan.crash_retrain(after_layer=0, times=1)
        ctl, clock, fleet, reg = _controller(
            trainer, collect_rows=n, chunk_rows=16
        )
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        rows = [
            {"x1": float(a), "x2": float(b)}
            for a, b in zip(rng.normal(size=n), rng.normal(size=n))
        ]
        _collect(fleet, ctl, clock, rows=rows)
        ctl.tick(0.0)  # crashes after layer 0, stays in retraining
        assert ctl.state == "retraining"
        assert ("retrain_crash", "layer-0") in fault_plan.fired
        ctl.tick(1.0)  # rebuild + resume from the checkpointed prefix
        assert ctl.state == "validating"
        s = ctl.stats.snapshot()
        assert s["retrainCrashes"] == 1 and s["retrainResumes"] == 1
        ctl.tick(2.0)  # no baseline -> gate passes -> canary
        ctl.tick(3.0)
        assert reg.serving == "retrain-001"
        assert ctl.history[-1]["outcome"] == "promoted"
        assert ctl.ledger()["deviceMemoryHighWater"] >= 0


# ------------------------------------------------------------------ the gate
class TestRunLedgerGate:
    def test_worse_model_gated_before_canary(self, request):
        tevents.reset_for_tests()
        trainer = _scripted_trainer([("v2", Fn(), _run_doc(auroc=0.5))])
        ctl, clock, fleet, reg = _controller(
            trainer, baseline=_run_doc(auroc=0.9)
        )
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock)
        ctl.tick(0.0)  # retrain ok -> validating
        ctl.tick(0.0)  # the gate refuses
        assert ctl.state == "idle"
        s = ctl.stats.snapshot()
        assert s["retrainsGated"] == 1
        assert ctl.history[-1]["outcome"] == "gated"
        assert "TPR004" in ctl.history[-1]["codes"]
        # the canary NEVER started: a provably-worse model saw no traffic
        assert all(c[0] != "start_canary" for c in reg.calls)
        evts = [e for e in tevents.recent(20)
                if e["kind"] == "retrain_gated"]
        assert evts and evts[-1]["codes"] == ["TPR004"]
        assert ctl.ledger()["backoffUntil"] > 0.0

    def test_clean_diff_reaches_canary_and_repins_baseline(self, request):
        good = _run_doc(auroc=0.92)
        trainer = _scripted_trainer([("v2", Fn(), good)])
        ctl, clock, fleet, reg = _controller(
            trainer, baseline=_run_doc(auroc=0.9)
        )
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock)
        ctl.tick(0.0)
        ctl.tick(0.0)  # validating -> canarying
        assert ctl.state == "canarying"
        ctl.tick(0.0)  # evaluate -> promote
        assert ctl.state == "idle"
        assert ctl.stats.snapshot()["retrainsPromoted"] == 1
        assert reg.serving == "v2"
        assert ctl.baseline_run is good  # the gate baseline re-pinned


# ------------------------------------------------------------------- canary
class TestCanary:
    def test_rollback_counts_and_backs_off(self, request):
        tevents.reset_for_tests()
        trainer = _scripted_trainer([("v2", Fn(), _run_doc())])
        reg = FakeRegistry(decision="rollback")
        ctl, clock, fleet, _ = _controller(trainer, registry=reg)
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock)
        ctl.tick(0.0)
        ctl.tick(0.0)
        ctl.tick(0.0)
        assert ctl.state == "idle"
        s = ctl.stats.snapshot()
        assert s["retrainsRolledBack"] == 1 and s["retrainsPromoted"] == 0
        assert ctl.history[-1]["outcome"] == "rolled_back"
        assert ctl.ledger()["backoffUntil"] > 0.0
        kinds = [e["kind"] for e in tevents.recent(20)]
        assert "retrain_rolled_back" in kinds

    def test_canary_waits_for_min_served(self, request):
        trainer = _scripted_trainer([("v2", Fn(), _run_doc())])
        reg = FakeRegistry(compared=0)
        ctl, clock, fleet, _ = _controller(
            trainer, registry=reg, min_canary_served=5, canary_timeout=60.0
        )
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock)
        ctl.tick(0.0)
        ctl.tick(0.0)
        assert ctl.state == "canarying"
        ctl.tick(1.0)  # not enough evidence, not timed out -> wait
        assert ctl.state == "canarying"
        assert all(c[0] != "evaluate_canary" for c in reg.calls)
        reg.compared = 5
        ctl.tick(2.0)
        assert ctl.state == "idle"
        assert ctl.stats.snapshot()["retrainsPromoted"] == 1

    def test_canary_timeout_never_promotes_on_silence(self, request):
        trainer = _scripted_trainer([("v2", Fn(), _run_doc())])
        reg = FakeRegistry(compared=0)
        ctl, clock, fleet, _ = _controller(
            trainer, registry=reg, min_canary_served=5, canary_timeout=10.0
        )
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock)
        ctl.tick(0.0)
        ctl.tick(0.0)
        ctl.tick(50.0)  # starved past the timeout
        assert ctl.state == "idle"
        assert ("rollback", ("canary_timeout",)) in reg.calls
        assert ctl.stats.snapshot()["retrainsRolledBack"] == 1
        assert ctl.history[-1]["codes"] == ["canary_timeout"]

    def test_kill_replica_mid_canary_does_not_wedge_evaluation(
        self, fault_plan
    ):
        """Satellite: a seeded ``kill_replica`` takes the canary replica
        down mid-evaluation — orphans are adopted, the fleet ledger still
        reconciles, and ``evaluate_canary()`` completes with a decision
        instead of wedging."""
        clock = FakeClock()
        fc = FleetConfig(
            replicas=2,
            service=ServiceConfig(workers=0, max_queue_rows=64),
        )
        fleet = FleetService(Fn(), config=fc, clock=clock).start()
        try:
            reg = ModelRegistry(fleet).register("v2", Fn(offset=0.0))
            reg.start_canary("v2", replicas=(0,))
            handles = []
            for i in range(6):
                handles.append(fleet.submit({"x1": 0.0}, pin=i % 2))
                fleet.pump_until_quiet()
            assert reg.canary_report()["compared"] >= 3
            fault_plan.kill_replica(0, at=2.0)
            h = fleet.submit({"x1": 1.0}, pin=0)  # in flight on the canary
            handles.append(h)
            clock.now = 2.5
            fleet.tick()  # the scripted kill fires mid-evaluation
            assert 0 in fleet.lost
            fleet.pump_until_quiet()
            decision = reg.evaluate_canary()  # must not wedge or raise
            assert decision["decision"] in ("promote", "rollback")
            assert all(h.outcome is not None for h in handles)  # zero drops
            assert fleet.reconcile()["reconciled"]
        finally:
            fleet.stop()

    def test_external_rollback_is_recorded_not_fatal(self, request):
        trainer = _scripted_trainer([("v2", Fn(), _run_doc())])
        reg = FakeRegistry()
        ctl, clock, fleet, _ = _controller(trainer, registry=reg)
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock)
        ctl.tick(0.0)
        ctl.tick(0.0)
        assert ctl.state == "canarying"
        reg._canary = None  # an operator rolled the canary back under us
        ctl.tick(1.0)
        assert ctl.state == "idle"
        assert ctl.history[-1]["codes"] == ["canary_vanished"]


# --------------------------------------------------------- bounding the loop
class TestBoundedLoop:
    def test_max_retrains_suppresses_further_triggers(self, request):
        trainer = _scripted_trainer([
            TransientError("a"), TransientError("b"),
        ])
        ctl, clock, fleet, _ = _controller(
            trainer, max_retrains=2, cooldown=0.0,
            backoff=RetryPolicy(max_attempts=2, base_delay=1.0,
                                max_delay=2.0, jitter=0.0),
        )
        _track(request, ctl)
        for round_at in (0.0, 100.0, 200.0, 300.0):
            clock.now = round_at
            _alert("x1")
            ctl.tick(round_at)
            _collect(fleet, ctl, clock)
            ctl.tick(round_at)
            assert ctl.state == "idle"
        s = ctl.stats.snapshot()
        # an infinite alert storm produced EXACTLY max_retrains attempts
        assert s["retrainsTriggered"] == 2
        assert s["triggersSuppressed"] >= 1
        assert len(trainer.calls) == 2

    def test_backoff_schedule_escalates(self, request):
        trainer = _scripted_trainer([
            TransientError("1"), TransientError("2"), TransientError("3"),
        ])
        ctl, clock, fleet, _ = _controller(
            trainer, max_retrains=10, cooldown=0.0,
            backoff=RetryPolicy(max_attempts=6, base_delay=10.0,
                                max_delay=100.0, jitter=0.0),
        )
        _track(request, ctl)
        waits = []
        t = 0.0
        for _ in range(3):
            clock.now = t
            _alert("x1")
            ctl.tick(t)
            _collect(fleet, ctl, clock)
            ctl.tick(t)
            waits.append(ctl.ledger()["backoffUntil"] - t)
            t = ctl.ledger()["backoffUntil"] + 1.0
        # exponential: each failed attempt waits longer than the last
        assert waits[0] < waits[1] < waits[2]

    def test_backoff_gates_retrigger_until_expiry(self, request):
        trainer = _scripted_trainer([
            TransientError("x"), ("v2", Fn(), _run_doc()),
        ])
        ctl, clock, fleet, _ = _controller(trainer, cooldown=0.0)
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        _collect(fleet, ctl, clock)
        ctl.tick(0.0)  # fails; backoff = 10s (base_delay... attempt 1)
        until = ctl.ledger()["backoffUntil"]
        assert until > 0.0
        clock.now = until - 1.0
        _alert("x1")
        assert ctl.tick(clock.now) == "idle"  # quorum formed, backing off
        clock.now = until + 1.0
        assert ctl.tick(clock.now) == "collecting"


# ----------------------------------------------------- drift_cleared pairing
class TestDriftClearedHysteresis:
    @pytest.fixture(scope="class")
    def trained(self):
        uid_util.reset()
        rng = np.random.default_rng(3)
        n = 160
        x1 = rng.normal(size=n)
        x2 = rng.normal(size=n)
        label = (x1 + 0.5 * x2 + 0.3 * rng.normal(size=n) > 0).astype(float)
        ds = Dataset.of({
            "label": column_from_values(T.RealNN, label),
            "x1": column_from_values(T.Real, x1),
            "x2": column_from_values(T.Real, x2),
        })
        resp, preds = from_dataset(ds, response="label")
        vec = transmogrify(list(preds))
        selector = BinaryClassificationModelSelector(
            seed=7, models=[(LogisticRegression(), {"reg_param": [0.01]})],
            num_folds=2,
        )
        pred = selector.set_input(resp, vec).get_output()
        model = (
            Workflow().set_result_features(pred).set_input_dataset(ds)
            .train()
        )
        return ds, model

    def test_cleared_emitted_once_on_recovery(self, trained):
        from transmogrifai_tpu.local.scoring import score_function
        from transmogrifai_tpu.resilience import DriftConfig

        ds, model = trained
        tevents.reset_for_tests()
        cfg = DriftConfig(window=40, chunks=4, min_rows=20,
                          js_threshold=0.35)
        fn = score_function(model, drift=cfg)
        plan = faults.FaultPlan().shift_feature("x1", offset=25.0, times=40)
        with faults.installed(plan):
            for r in ds.rows()[:40]:
                fn(r)
        fn.drift.report()  # the sweep emits the alert
        alerts = [e for e in tevents.recent(50)
                  if e["kind"] == "drift_alert"]
        assert [e["feature"] for e in alerts] == ["x1"]
        # the stream recovers: shifted chunks age out of the window
        for r in ds.rows()[40:120]:
            fn(r)
        fn.drift.report()
        cleared = [e for e in tevents.recent(50)
                   if e["kind"] == "drift_cleared"]
        assert [e["feature"] for e in cleared] == ["x1"]
        # hysteresis: further healthy reports do NOT re-emit cleared
        for r in ds.rows()[120:160]:
            fn(r)
        fn.drift.report()
        fn.drift.report()
        cleared = [e for e in tevents.recent(80)
                   if e["kind"] == "drift_cleared"]
        assert len(cleared) == 1

    def test_controller_tracks_drifting_set(self, trained, request):
        ctl, clock, _, _ = _controller(_scripted_trainer([]), quorum=99)
        _track(request, ctl)
        tevents.emit("drift_alert", feature="f1")
        assert "f1" in ctl._drifting
        tevents.emit("drift_cleared", feature="f1")
        assert "f1" not in ctl._drifting
        assert ctl.stats.snapshot()["driftCleared"] == 1

    def test_metadata_carries_retrain_ledger(self, trained, request):
        from transmogrifai_tpu.local.scoring import score_function

        ds, model = trained
        ctl, clock, _, _ = _controller(_scripted_trainer([]))
        _track(request, ctl)
        fn = score_function(model)
        led = fn.metadata()["retrainLedger"]
        assert led is not None and led["state"] == "idle"
        assert model.summary_json()["retrainLedger"]["state"] == "idle"


# ------------------------------------------------------- events subscriber
class TestEventsSubscriberSeam:
    def test_subscribe_receives_after_lock_release(self):
        got = []
        tevents.subscribe(got.append)
        try:
            tevents.emit("drift_alert", feature="zz")
            assert got and got[-1]["kind"] == "drift_alert"
            assert got[-1]["feature"] == "zz"
        finally:
            tevents.unsubscribe(got.append)

    def test_broken_subscriber_never_breaks_emit(self):
        def boom(rec):
            raise RuntimeError("subscriber bug")

        got = []
        tevents.subscribe(boom)
        tevents.subscribe(got.append)
        try:
            tevents.emit("drift_alert", feature="ok")
            assert got  # the healthy subscriber still ran
        finally:
            tevents.unsubscribe(boom)
            tevents.unsubscribe(got.append)

    def test_unsubscribe_stops_delivery(self):
        got = []
        tevents.subscribe(got.append)
        tevents.unsubscribe(got.append)
        tevents.emit("drift_alert", feature="gone")
        assert got == []


# ------------------------------------------------------------ ledger surface
class TestLedgerExposure:
    def test_retrain_source_registered_with_full_catalogue(self):
        snaps = tmetrics.REGISTRY.source_snapshots()
        assert "retrain" in snaps
        led = ledger_snapshot()
        for key in ("retrainsTriggered", "retrainsPromoted",
                    "retrainsRolledBack", "retrainCrashes",
                    "triggersSuppressed", "state", "backoffUntil",
                    "deviceMemoryHighWater"):
            assert key in led

    def test_prometheus_renders_retrain_gauges(self, request):
        from transmogrifai_tpu.telemetry import render_prometheus

        ctl, clock, fleet, _ = _controller(
            _scripted_trainer([("v", Fn(), _run_doc())])
        )
        _track(request, ctl)
        _alert("x1")
        ctl.tick(0.0)
        text = render_prometheus()
        import re

        m = re.search(
            r"^tptpu_retrain_retrains_triggered (\S+)$", text, re.M
        )
        assert m and float(m.group(1)) == 1.0
        m = re.search(
            r"^tptpu_retrain_retrains_promoted (\S+)$", text, re.M
        )
        assert m and float(m.group(1)) == 0.0

    def test_stream_chunk_retry_counters_in_resilience_source(
        self, tmp_path
    ):
        """Satellite: streaming chunk-fetch retries surface in the
        ``resilience`` ledger source (and therefore the Prometheus
        exposition)."""
        from transmogrifai_tpu.readers import FileStreamingReader
        from transmogrifai_tpu.readers.streaming import CHUNK_STATS
        from transmogrifai_tpu.resilience.distributed import (
            _resilience_source,
        )

        CHUNK_STATS.reset_for_tests()
        p = tmp_path / "batch1.csv"
        with open(p, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["a", "b"])
            w.writerow([1, 2])
        old = time.time() - 10
        os.utime(p, (old, old))
        reader = FileStreamingReader(
            str(tmp_path), pattern="*.csv", poll=False
        )
        sleeps = []
        reader.retry_policy = RetryPolicy(
            max_attempts=3, base_delay=0.0, jitter=0.0,
            sleep=sleeps.append,
        )
        plan = faults.FaultPlan().fail_chunk_read(times=1)
        with faults.installed(plan):
            batches = list(reader._batches_iter())
        assert len(batches) == 1
        src = _resilience_source()
        assert src["streamChunkFetches"] == 1
        assert src["streamChunkRetries"] == 1
        assert src["streamChunkAttempts"] == 2
        assert src["streamChunkExhausted"] == 0
        CHUNK_STATS.reset_for_tests()

    def test_exhausted_fetch_counted(self, tmp_path):
        from transmogrifai_tpu.readers import FileStreamingReader
        from transmogrifai_tpu.readers.streaming import CHUNK_STATS

        CHUNK_STATS.reset_for_tests()
        p = tmp_path / "batch1.csv"
        with open(p, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["a"])
            w.writerow([1])
        old = time.time() - 10
        os.utime(p, (old, old))
        reader = FileStreamingReader(
            str(tmp_path), pattern="*.csv", poll=False
        )
        reader.retry_policy = RetryPolicy(
            max_attempts=2, base_delay=0.0, jitter=0.0,
            sleep=lambda s: None,
        )
        plan = faults.FaultPlan().fail_chunk_read(times=5)
        with faults.installed(plan):
            batches = list(reader._batches_iter())
        # the reader defers then drops the unreadable file (no raise) —
        # but the exhausted retry budgets landed in the ledger: once for
        # the first pass, once for the final settle retry
        assert batches == []
        snap = CHUNK_STATS.snapshot()
        assert snap["streamChunkExhausted"] == 2
        assert snap["streamChunkFetches"] == 0
        CHUNK_STATS.reset_for_tests()


# ------------------------------------------------------- integration (fleet)
class TestFleetIntegration:
    def test_on_served_chains_registry_mirror_hook(self, request):
        """The controller wraps the registry's on_served hook instead of
        replacing it: canary mirror comparisons still happen while the
        controller buffers."""
        clock = FakeClock()
        fc = FleetConfig(
            replicas=2,
            service=ServiceConfig(workers=0, max_queue_rows=64),
        )
        fleet = FleetService(Fn(), config=fc, clock=clock).start()
        try:
            reg = ModelRegistry(fleet).register("v2", Fn(offset=0.0))
            trainer = _scripted_trainer([])
            ctl = RetrainController(
                fleet, reg, trainer,
                config=RetrainConfig(collect_rows=4, chunk_rows=2),
                clock=clock,
            )
            _track(request, ctl)
            reg.start_canary("v2", replicas=(0,))
            # force collecting so BOTH hooks have work on the same rows
            with ctl._lock:
                ctl.state = "collecting"
            for i in range(4):
                fleet.submit({"x1": float(i)}, pin=i % 2)
                fleet.pump_until_quiet()
            assert reg.canary_report()["compared"] >= 1  # mirror ran
            assert ctl.ledger()["rowsCollected"] == 4  # buffer ran
            reg.evaluate_canary()
        finally:
            fleet.stop()

    def test_close_detaches_hook_and_subscription(self):
        fleet = FakeFleet()
        reg = FakeRegistry()
        ctl = RetrainController(fleet, reg, _scripted_trainer([]))
        # bound-method EQUALITY (identity differs per attribute access)
        assert fleet.on_served == ctl._on_served
        ctl.close()
        assert fleet.on_served is None
        before = ctl.stats.snapshot()["alertsSeen"]
        _alert("x1")
        assert ctl.stats.snapshot()["alertsSeen"] == before
        ctl.close()  # idempotent
