"""Warm start, workflow-level CV, and SelectedModelCombiner tests.

Mirrors the reference's OpWorkflowCVTest and SelectedModelCombinerTest."""
import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.prep import SanityChecker
from transmogrifai_tpu.selector import (
    BinaryClassificationModelSelector,
    CombinationStrategy,
    RegressionModelSelector,
    SelectedModelCombiner,
)
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.workflow.workflow import Workflow

# selector-training scale: excluded from the default fast suite (README)
pytestmark = pytest.mark.slow


def _binary_ds(n=200, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    label = (x1 + 0.5 * x2 + 0.3 * rng.normal(size=n) > 0).astype(float)
    return Dataset.of({
        "label": column_from_values(T.RealNN, label),
        "x1": column_from_values(T.Real, x1),
        "x2": column_from_values(T.Real, x2),
    })


def _graph(ds, selector_factory=None, sanity_check=True):
    resp, preds = from_dataset(ds, response="label")
    vec = transmogrify(list(preds))
    checked = (
        resp.transform_with(SanityChecker(remove_bad_features=True), vec)
        if sanity_check
        else vec
    )
    factory = selector_factory or (
        lambda: BinaryClassificationModelSelector(seed=3)
    )
    selector = factory()
    pred = selector.set_input(resp, checked).get_output()
    return resp, pred, selector


class TestWarmStart:
    def test_with_model_stages_skips_refit(self):
        ds = _binary_ds()
        resp, pred, selector = _graph(ds)
        model = Workflow().set_result_features(pred).set_input_dataset(ds).train()

        # warm start: same DAG, fitted stages swapped in by uid
        wf2 = (
            Workflow()
            .set_result_features(pred)
            .set_input_dataset(ds)
            .with_model_stages(model)
        )
        fit_calls = []
        orig_fit = SanityChecker.fit

        def spy(self, dataset):
            fit_calls.append(self.uid)
            return orig_fit(self, dataset)

        SanityChecker.fit = spy
        try:
            model2 = wf2.train()
        finally:
            SanityChecker.fit = orig_fit
        assert fit_calls == []  # nothing re-fit
        s1 = model.score(dataset=ds)
        s2 = model2.score(dataset=ds)
        np.testing.assert_allclose(
            s1[pred.name].prediction, s2[pred.name].prediction
        )


class TestWorkflowCV:
    def test_workflow_cv_trains_and_selects(self):
        ds = _binary_ds()
        resp, pred, selector = _graph(ds)
        model = (
            Workflow()
            .set_result_features(pred)
            .set_input_dataset(ds)
            .with_workflow_cv()
            .train()
        )
        summary = model.summary_json()["modelSelectorSummary"]
        assert summary["validationResults"]
        # per-fold metrics exist for the winning candidate
        best = [
            r for r in summary["validationResults"]
            if r["modelName"] == summary["bestModelType"]
        ]
        assert best and all(len(r["metricValues"]) >= 2 for r in best)
        assert summary["holdoutEvaluation"]["AuROC"] > 0.7

    def test_workflow_cv_comparable_to_selector_cv(self):
        """Workflow CV should produce similar (not wildly different) quality
        to selector-level CV on clean data (OpWorkflowCVTest parity)."""
        ds = _binary_ds(seed=1)
        _, pred1, _ = _graph(ds)
        m1 = Workflow().set_result_features(pred1).set_input_dataset(ds).train()
        _, pred2, _ = _graph(ds)
        m2 = (
            Workflow()
            .set_result_features(pred2)
            .set_input_dataset(ds)
            .with_workflow_cv()
            .train()
        )
        a1 = m1.summary_json()["modelSelectorSummary"]["holdoutEvaluation"]["AuROC"]
        a2 = m2.summary_json()["modelSelectorSummary"]["holdoutEvaluation"]["AuROC"]
        assert abs(a1 - a2) < 0.25


class TestSelectedModelCombiner:
    def _selectors(self):
        from transmogrifai_tpu.models.gbdt import RandomForestClassifier

        s1 = BinaryClassificationModelSelector(
            models=[(LogisticRegression(), {"reg_param": [0.01, 0.1]})], seed=3
        )
        s2 = BinaryClassificationModelSelector(
            models=[(RandomForestClassifier(), {"max_depth": [3]})], seed=3
        )
        return s1, s2

    def test_best_strategy_picks_winner(self):
        ds = _binary_ds(seed=2)
        resp, preds = from_dataset(ds, response="label")
        vec = transmogrify(list(preds))
        s1, s2 = self._selectors()
        comb = SelectedModelCombiner(s1, s2, CombinationStrategy.BEST)
        pred = comb.set_input(resp, vec).get_output()
        model = Workflow().set_result_features(pred).set_input_dataset(ds).train()
        summary = model.summary_json()["modelSelectorSummary"]
        assert summary["combinationStrategy"] == "Best"
        assert summary["bestModelType"] in (
            "LogisticRegression", "RandomForestClassifier"
        )
        # validation results from BOTH selectors present
        names = {r["modelName"] for r in summary["validationResults"]}
        assert {"LogisticRegression", "RandomForestClassifier"} <= names

    def test_weighted_strategy_combines_probabilities(self):
        ds = _binary_ds(seed=4)
        resp, preds = from_dataset(ds, response="label")
        vec = transmogrify(list(preds))
        s1, s2 = self._selectors()
        comb = SelectedModelCombiner(s1, s2, CombinationStrategy.WEIGHTED)
        pred = comb.set_input(resp, vec).get_output()
        model = Workflow().set_result_features(pred).set_input_dataset(ds).train()
        summary = model.summary_json()["modelSelectorSummary"]
        assert summary["bestModelType"] == "CombinedModel"
        w = summary["weights"]
        assert len(w) == 2 and abs(sum(w) - 1.0) < 1e-9
        assert summary["holdoutEvaluation"]["AuROC"] > 0.7
        scored = model.score(dataset=ds)
        probs = scored[pred.name].probability
        np.testing.assert_allclose(np.asarray(probs).sum(axis=1), 1.0, atol=1e-6)

    def test_combiner_persistence_round_trip(self, tmp_path):
        from transmogrifai_tpu.workflow.workflow import WorkflowModel

        ds = _binary_ds(seed=5)
        resp, preds = from_dataset(ds, response="label")
        vec = transmogrify(list(preds))
        s1, s2 = self._selectors()
        comb = SelectedModelCombiner(s1, s2, CombinationStrategy.WEIGHTED)
        pred = comb.set_input(resp, vec).get_output()
        model = Workflow().set_result_features(pred).set_input_dataset(ds).train()
        path = str(tmp_path / "m")
        model.save(path)
        m2 = WorkflowModel.load(path)
        s1_ = model.score(dataset=ds)
        s2_ = m2.score(dataset=ds)
        np.testing.assert_allclose(
            s1_[pred.name].prediction, s2_[pred.name].prediction
        )
