"""Feature type system tests (parity: features/.../types tests)."""
import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.types import columns as C


def test_registry_matches_reference():
    # FeatureType.scala:265-325 registers 53 concrete types (the README's
    # "45 types" count excludes some map types).
    assert len(T.ALL_FEATURE_TYPES) == 53
    assert len(set(T.ALL_FEATURE_TYPES)) == 53


def test_lookup_by_name():
    assert T.feature_type_by_name("RealNN") is T.RealNN
    assert T.feature_type_by_name("GeolocationMap") is T.GeolocationMap
    with pytest.raises(ValueError):
        T.feature_type_by_name("NotAType")


def test_hierarchy():
    assert issubclass(T.RealNN, T.Real)
    assert issubclass(T.Currency, T.Real)
    assert issubclass(T.DateTime, T.Date) and issubclass(T.Date, T.Integral)
    assert issubclass(T.PickList, T.Text) and issubclass(T.PickList, T.Categorical)
    assert issubclass(T.Geolocation, T.Location)
    assert issubclass(T.CountryMap, T.OPMap) and T.CountryMap.value_type is T.Country
    assert issubclass(T.Prediction, T.NonNullable)


def test_nullability():
    assert T.Real.is_nullable and not T.RealNN.is_nullable
    assert not T.OPVector.is_nullable
    assert T.Text.is_nullable


def test_numeric_column_roundtrip():
    col = C.column_from_values(T.Real, [1.5, None, 3.0])
    assert isinstance(col, C.NumericColumn)
    assert col.to_list() == [1.5, None, 3.0]
    assert col.mask.tolist() == [True, False, True]


def test_numeric_column_coerces_strings():
    col = C.column_from_values(T.Integral, ["7", None, " 42 ", ""])
    assert col.to_list() == [7, None, 42, None]
    assert col.values.dtype == np.int64


def test_binary_column_parses_tokens():
    col = C.column_from_values(T.Binary, ["true", "false", None, 1, 0.0])
    assert col.to_list() == [True, False, None, True, False]


def test_text_column():
    col = C.column_from_values(T.PickList, ["a", None, "", "b"])
    assert col.to_list() == ["a", None, None, "b"]


def test_set_list_map_columns():
    s = C.column_from_values(T.MultiPickList, [{"x", "y"}, None, set()])
    assert s.to_list() == [frozenset({"x", "y"}), frozenset(), frozenset()]
    l = C.column_from_values(T.TextList, [["a", "b"], None])
    assert l.to_list() == [["a", "b"], []]
    m = C.column_from_values(T.RealMap, [{"k": 1.0}, None])
    assert m.to_list() == [{"k": 1.0}, {}]


def test_vector_column():
    v = C.column_from_values(T.OPVector, [[1, 2], [3, 4]])
    assert v.dim == 2 and len(v) == 2
    assert v.values.dtype == np.float32


def test_prediction_column_keys():
    p = C.PredictionColumn(
        T.Prediction,
        prediction=np.array([1.0]),
        probability=np.array([[0.2, 0.8]]),
        raw=np.array([[-1.0, 1.0]]),
    )
    row = p.to_list()[0]
    assert row["prediction"] == 1.0
    assert row["probability_1"] == pytest.approx(0.8)
    assert row["rawPrediction_0"] == -1.0


def test_take():
    col = C.column_from_values(T.Real, [1.0, None, 3.0, 4.0])
    taken = col.take(np.array([2, 0]))
    assert taken.to_list() == [3.0, 1.0]
