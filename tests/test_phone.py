"""Phone parsing/validation fixture agreement.

Fixtures are the reference's own PhoneNumberParserTest vectors
(core/src/test/.../PhoneNumberParserTest.scala) — parse/validate answers,
cleanNumber over printable ASCII, and the validCountryCode
Jaccard-closest-country cases — plus region-rule spot checks against
libphonenumber's documented metadata.
"""
import numpy as np
import pytest

from transmogrifai_tpu.ops.phone import (
    DEFAULT_COUNTRY_CODES,
    INTERNATIONAL_CODE,
    IsValidPhoneDefaultCountry,
    IsValidPhoneMapDefaultCountry,
    IsValidPhoneNumber,
    ParsePhoneDefaultCountry,
    ParsePhoneNumber,
    clean_number,
    parse_phone,
    valid_country_code,
    validate_phone,
)
from transmogrifai_tpu.types import BinaryMap, Phone, PhoneMap, Text
from transmogrifai_tpu.types.columns import MapColumn, TextColumn, column_from_values

_CODES = [c.upper() for c in DEFAULT_COUNTRY_CODES]
_NAMES = [DEFAULT_COUNTRY_CODES[c].upper() for c in DEFAULT_COUNTRY_CODES]

# PhoneNumberParserTest.scala reference vectors
PNS = ["+15105556666", "510 555 6666", "+1+3456", "+1510334455667788", None]
ANSWER_PARSE = ["+15105556666", "+15105556666", None, "+15103344556", None]
ANSWER_VALID = [True, True, None, True, None]


def test_clean_number_printable_ascii():
    all_ascii = "".join(chr(c) for c in range(32, 127))
    assert clean_number(all_ascii) == "+0123456789"


def test_parse_reference_vectors():
    got = [parse_phone(p, "US") for p in PNS]
    # "+1+3456" parse: reference raises inside Try → None
    assert got == ANSWER_PARSE


def test_validate_reference_vectors():
    got = [validate_phone(p, "US") for p in PNS]
    assert got == ANSWER_VALID


def test_validate_short_and_empty():
    assert validate_phone("1", "US") is None      # < 2 chars
    assert validate_phone("ab", "US") is None     # NOT_A_NUMBER → None
    assert validate_phone(None, "US") is None


def test_international_code_constant():
    assert INTERNATIONAL_CODE == "ZZ"


def test_valid_country_code_explicit_supported_region():
    # an explicit SUPPORTED region outside the configured list is honored
    assert valid_country_code("", "AF", "US", _CODES, _NAMES) == "AF"


def test_valid_country_code_not_found_falls_to_default():
    assert valid_country_code("", "FooBar", "US", (), ()) == "US"


def test_valid_country_code_closest_name_match():
    countries = ["uS", "United St America", "States of America", "Grece",
                 "Switzland", "USA"]
    got = [
        valid_country_code("", c, "US", _CODES, _NAMES) for c in countries
    ]
    assert got == ["US", "US", "US", "GR", "CH", "US"]


def test_valid_country_code_international_overrides():
    assert (
        valid_country_code("+1234566", "CN", "US", _CODES, _NAMES)
        == INTERNATIONAL_CODE
    )


def test_valid_country_code_user_mapping():
    codes = ["US", "CA", "ZW"]
    names = ["UNITED STATES", "CANADA", "ZIMBABWE"]
    cases = ["uS", "CD", "United", "Zimbwe", "USA"]
    got = [valid_country_code("", c, "US", codes, names) for c in cases]
    assert got == ["US", "CD", "US", "ZW", "US"]


def test_region_rules_spot_checks():
    # libphonenumber-documented validity facts
    assert validate_phone("5105556666", "US") is True
    assert validate_phone("15105556666", "US") is True    # own cc prefix
    assert validate_phone("1234567890", "US") is False    # area code 1xx
    assert validate_phone("0612345678", "US") is False    # area code 0xx
    assert validate_phone("+4915123456789", "DE") is True   # DE mobile, 11
    assert validate_phone("+33612345678", "FR") is True     # FR, 9 national
    assert validate_phone("+3361234567", "FR") is False     # FR, 8 national
    assert validate_phone("+919876543210", "IN") is True    # IN mobile
    assert validate_phone("+911234543210", "IN") is False   # IN must start 6-9
    assert validate_phone("+6591234567", "SG") is True      # SG 8 digits
    assert validate_phone("+659123456", "SG") is False


def test_truncate_too_long_non_strict_vs_strict():
    long_num = "+1510334455667788"
    assert parse_phone(long_num, "US", strict=False) == "+15103344556"
    assert parse_phone(long_num, "US", strict=True) is None
    assert validate_phone(long_num, "US", strict=True) is False


def test_parse_phone_default_country_transformer():
    col = column_from_values(Phone, PNS)
    out = ParsePhoneDefaultCountry().transform_columns(
        col, num_rows=len(PNS)
    )
    assert list(out.values) == ANSWER_PARSE


def test_is_valid_phone_transformer_with_region_column():
    phones = column_from_values(Phone, ["510 555 6666", "+15105556666"])
    regions = column_from_values(Text, ["United St America", "CN"])
    stage = IsValidPhoneNumber()
    out = stage.transform_columns(phones, regions, num_rows=2)
    assert out.to_list() == [True, True]


def test_is_valid_phone_map_transformer():
    maps = MapColumn(
        PhoneMap,
        [
            {"home": "5105556666", "bad": "12", "none": None},
            {},
        ],
    )
    stage = IsValidPhoneMapDefaultCountry()
    out = stage.transform_columns(maps, num_rows=2)
    rows = out.to_list()
    # 'bad' parses but is invalid → False kept; None (unparseable) drops
    # (reference collects only SomeValue results)
    assert rows[0] == {"home": True, "bad": False}
    assert rows[1] == {}
    assert out.feature_type is BinaryMap


def test_set_codes_and_countries_rejects_garbage():
    with pytest.raises(ValueError):
        ParsePhoneNumber().set_codes_and_countries({"foo": "bar"})


def test_parse_zw_default_region_reference_vector():
    """PhoneNumberParserTest 'need a country identifyer when the local does
    not match the default': under default region ZW, a bare US-shaped local
    number must NOT validate — only explicit +1 numbers survive."""
    got = [parse_phone(p, "ZW") for p in PNS]
    assert got == ["+15105556666", None, None, "+15103344556", None]
