"""CLI project-generator tests (reference: cli/src/test/.../CliExecTest)."""
import json
import os

import pytest

import numpy as np

import transmogrifai_tpu.types as T
from transmogrifai_tpu.cli import generate_project, infer_problem_kind, main
from transmogrifai_tpu.types.columns import column_from_values


class TestProblemKind:
    def test_binary(self):
        col = column_from_values(T.Integral, [0, 1, 1, 0, None])
        assert infer_problem_kind(col, 5) == "BinaryClassification"

    def test_multiclass_text(self):
        col = column_from_values(T.Text, ["a", "b", "c", "a"])
        assert infer_problem_kind(col, 4) == "MultiClassification"

    def test_multiclass_small_int(self):
        col = column_from_values(T.Integral, [0, 1, 2, 3, 2, 1])
        assert infer_problem_kind(col, 6) == "MultiClassification"

    def test_regression(self):
        col = column_from_values(T.Real, list(np.linspace(0, 10, 50)))
        assert infer_problem_kind(col, 50) == "Regression"


_TITANIC = "/root/reference/test-data/PassengerDataAllWithHeader.csv"
_needs_titanic = pytest.mark.skipif(
    not os.path.exists(_TITANIC), reason="Titanic fixture data not available"
)


@_needs_titanic
class TestGenerateProject:
    def test_gen_titanic(self, tmp_path):
        out = str(tmp_path / "proj")
        info = generate_project(
            "/root/reference/test-data/PassengerDataAllWithHeader.csv",
            response="Survived",
            output_dir=out,
            id_field="PassengerId",
            project_name="TitanicGen",
        )
        assert info["kind"] == "BinaryClassification"
        for f in ("main.py", "README.md", "params.json"):
            assert os.path.exists(os.path.join(out, f))
        src = open(os.path.join(out, "main.py")).read()
        assert "BinaryClassificationModelSelector" in src
        assert "Survived" in src
        compile(src, "main.py", "exec")  # generated code parses

    def test_cli_main(self, tmp_path, capsys):
        out = str(tmp_path / "proj2")
        main([
            "gen", "--input",
            "/root/reference/test-data/PassengerDataAllWithHeader.csv",
            "--response", "Survived", "--output", out,
        ])
        printed = json.loads(capsys.readouterr().out.strip())
        assert printed["kind"] == "BinaryClassification"

    def test_missing_response_errors(self, tmp_path):
        import pytest

        with pytest.raises(SystemExit):
            generate_project(
                "/root/reference/test-data/PassengerDataAllWithHeader.csv",
                response="NoSuchColumn",
                output_dir=str(tmp_path / "x"),
            )


class TestTextResponseGen:
    def test_gen_text_label_project(self, tmp_path):
        """A string-labeled response generates the PickList+index pattern."""
        import csv as _csv

        data = tmp_path / "flowers.csv"
        with open(data, "w", newline="") as f:
            w = _csv.writer(f)
            w.writerow(["a", "b", "species"])
            for i in range(30):
                w.writerow([i * 0.1, i * 0.2, ["setosa", "virginica", "versicolor"][i % 3]])
        out = str(tmp_path / "proj")
        info = generate_project(str(data), response="species", output_dir=out)
        assert info["kind"] == "MultiClassification"
        src = open(os.path.join(out, "main.py")).read()
        assert "FeatureBuilder.PickList" in src      # typed text response
        assert "string_indexed" in src
        compile(src, "main.py", "exec")


class TestGeneratedProjectRuns:
    @pytest.mark.slow
    def test_generated_titanic_project_trains(self, tmp_path):
        """The emitted typed-feature project must actually train end-to-end
        (the reference's generated projects are runnable sbt apps)."""
        out = str(tmp_path / "proj")
        generate_project(
            "/root/reference/test-data/PassengerDataAllWithHeader.csv",
            response="Survived",
            output_dir=out,
            id_field="PassengerId",
            project_name="TitanicGen",
        )
        src = open(os.path.join(out, "main.py")).read()
        assert "FeatureBuilder.RealNN('Survived')" in src.replace('"', "'")
        assert os.path.exists(os.path.join(out, "test_smoke.py"))
        import subprocess
        import sys as _sys

        proc = subprocess.run(
            [_sys.executable, "main.py", "Train", "--model-location",
             os.path.join(out, "model")],
            cwd=out, capture_output=True, text=True, timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "AuPR" in proc.stdout or "AuROC" in proc.stdout, proc.stdout


@_needs_titanic
class TestAvroSchemaSource:
    """CommandParser.scala:111 / SchemaSource.scala:85,158 — the generator
    accepts an Avro .avsc record schema as the typed-project source, with
    field types from the SCHEMA rather than CSV inference."""

    AVSC = "/root/reference/test-data/PassengerDataAll.avsc"
    CSV = "/root/reference/test-data/PassengerDataAllWithHeader.csv"

    def test_avro_schema_fields(self):
        from transmogrifai_tpu.cli import avro_schema_fields

        name, fields = avro_schema_fields(self.AVSC)
        assert name == "Passenger"
        assert fields["Survived"] == "Integral"
        assert fields["Age"] == "Real"
        assert fields["Sex"] == "Text"
        assert fields["Pclass"] == "Integral"

    def test_gen_from_avsc(self, tmp_path):
        out = str(tmp_path / "proj_avsc")
        info = generate_project(
            self.CSV, response="Survived", output_dir=out,
            id_field="PassengerId", project_name="TitanicAvro",
            schema_file=self.AVSC,
        )
        assert info["kind"] == "BinaryClassification"
        src = open(os.path.join(out, "main.py")).read().replace('"', "'")
        # schema-typed: Pclass is Integral per the .avsc (CSV inference
        # also says numeric, but Sex/Cabin stay Text by SCHEMA even though
        # inference would pivot low-cardinality strings as Categorical)
        assert "FeatureBuilder.Integral('Pclass')" in src
        assert "FeatureBuilder.Text('Sex')" in src
        assert "FeatureBuilder.RealNN('Survived')" in src
        compile(src, "main.py", "exec")

    def test_cli_main_with_schema(self, tmp_path, capsys):
        out = str(tmp_path / "proj_avsc2")
        main([
            "gen", "--input", self.CSV, "--schema", self.AVSC,
            "--response", "Survived", "--output", out,
        ])
        printed = json.loads(capsys.readouterr().out.strip())
        assert printed["kind"] == "BinaryClassification"

    def test_bad_schema_errors(self, tmp_path):
        bad = tmp_path / "bad.avsc"
        bad.write_text('{"type": "enum", "symbols": ["a"]}')
        with pytest.raises(SystemExit):
            generate_project(
                self.CSV, response="Survived",
                output_dir=str(tmp_path / "p"), schema_file=str(bad),
            )

    @pytest.mark.slow
    def test_avsc_generated_project_trains(self, tmp_path):
        out = str(tmp_path / "proj_avsc_train")
        generate_project(
            self.CSV, response="Survived", output_dir=out,
            id_field="PassengerId", project_name="TitanicAvro",
            schema_file=self.AVSC,
        )
        import subprocess
        import sys as _sys

        proc = subprocess.run(
            [_sys.executable, "main.py", "Train", "--model-location",
             os.path.join(out, "model")],
            cwd=out, capture_output=True, text=True, timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "AuPR" in proc.stdout or "AuROC" in proc.stdout, proc.stdout
