"""Evaluator completeness: multiclass ThresholdMetrics, regression
signed-percentage-error histogram, forecast SeasonalError/MASE.

Parity targets: OpMultiClassificationEvaluator.scala:153-238,
OpRegressionEvaluator.scala:63-190, OpForecastEvaluator.scala:83-121.
The multiclass test cross-checks the vectorized implementation against a
direct per-row transcription of the reference algorithm.
"""
import numpy as np
import pytest

from transmogrifai_tpu.evaluators.forecast import ForecastEvaluator
from transmogrifai_tpu.evaluators.multiclass import (
    MultiClassificationEvaluator,
    calculate_threshold_metrics,
)
from transmogrifai_tpu.evaluators.regression import (
    RegressionEvaluator,
    signed_percentage_error_histogram,
)


def _reference_threshold_metrics(prob, y, top_ns, thresholds):
    """Per-row transcription of calculateThresholdMetrics (Scala)."""
    n, c = prob.shape
    n_t = len(thresholds)
    correct = {t: np.zeros(n_t, dtype=int) for t in top_ns}
    incorrect = {t: np.zeros(n_t, dtype=int) for t in top_ns}
    for i in range(n):
        label = int(y[i])
        scores = prob[i]
        true_score = scores[label] if 0 <= label < c else 0.0
        order = sorted(range(c), key=lambda j: (-scores[j], j))
        top_score = scores[order[0]]
        t_cut = next(
            (j for j, th in enumerate(thresholds) if th > true_score), n_t
        )
        m_cut = next(
            (j for j, th in enumerate(thresholds) if th > top_score), n_t
        )
        for t in top_ns:
            in_top = label in order[: min(t, c)]
            if in_top:
                correct[t][0:t_cut] += 1
                incorrect[t][t_cut:m_cut] += 1
            else:
                incorrect[t][0:m_cut] += 1
    return correct, incorrect


def test_threshold_metrics_match_reference_algorithm():
    rng = np.random.default_rng(0)
    n, c = 200, 4
    logits = rng.normal(size=(n, c))
    prob = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
    y = rng.integers(0, c, n)
    thresholds = np.arange(0, 1.01, 0.05)
    out = calculate_threshold_metrics(prob, y, (1, 3), thresholds)
    ref_c, ref_i = _reference_threshold_metrics(prob, y, (1, 3), thresholds)
    for t in (1, 3):
        np.testing.assert_array_equal(out["correctCounts"][str(t)], ref_c[t])
        np.testing.assert_array_equal(out["incorrectCounts"][str(t)], ref_i[t])
        total = (
            np.array(out["correctCounts"][str(t)])
            + np.array(out["incorrectCounts"][str(t)])
            + np.array(out["noPredictionCounts"][str(t)])
        )
        # the three arrays always sum to N (the reference's invariant)
        np.testing.assert_array_equal(total, np.full(len(thresholds), n))


def test_threshold_metrics_unseen_label_scores_zero():
    prob = np.array([[0.7, 0.3]])
    y = np.array([5])  # unseen class -> true score 0.0
    out = calculate_threshold_metrics(prob, y, (1,), np.array([0.0, 0.5, 0.9]))
    assert out["correctCounts"]["1"] == [0, 0, 0]
    # top score .7 clears thresholds 0 and .5 but not .9
    assert out["incorrectCounts"]["1"] == [1, 1, 0]
    assert out["noPredictionCounts"]["1"] == [0, 0, 1]


def test_threshold_metrics_validation():
    prob = np.array([[0.5, 0.5]])
    y = np.array([0])
    with pytest.raises(ValueError):
        calculate_threshold_metrics(prob, y, (), None)
    with pytest.raises(ValueError):
        calculate_threshold_metrics(prob, y, (1,), np.array([-0.1, 0.5]))
    with pytest.raises(ValueError):
        calculate_threshold_metrics(prob, y, (0,), None)


def test_multiclass_evaluator_includes_threshold_metrics():
    rng = np.random.default_rng(1)
    prob = rng.dirichlet(np.ones(3), size=60)
    y = rng.integers(0, 3, 60).astype(float)
    pred = prob.argmax(axis=1).astype(float)
    m = MultiClassificationEvaluator().evaluate_arrays(y, pred, prob)
    tm = m["ThresholdMetrics"]
    assert tm["topNs"] == [1, 3]
    assert len(tm["thresholds"]) == 101
    assert set(tm["correctCounts"]) == {"1", "3"}


def test_signed_percentage_error_histogram():
    y = np.array([1.0, 2.0, 100.0, 0.0])
    pred = np.array([1.1, 1.0, 50.0, 5.0])
    h = signed_percentage_error_histogram(pred, y)
    assert len(h["counts"]) == len(h["bins"]) - 1
    assert sum(h["counts"]) == 4
    # errors: +10%, -50%, -50%, +500000% (cutoff 1e-3 -> huge, lands in +inf bin)
    assert h["counts"][-1] == 1
    bins = np.asarray(h["bins"])
    neg50 = int(np.searchsorted(bins, -50.0, side="right")) - 1
    assert h["counts"][neg50] == 2


def test_signed_percentage_error_smart_cutoff():
    y = np.zeros(10)
    pred = np.ones(10)
    h = signed_percentage_error_histogram(
        pred, y, smart_cutoff_ratio=0.1, scaled_error_cutoff=1e-3
    )
    # all-zero labels: smart cutoff falls back to scaledErrorCutoff
    assert h["scaledErrorCutoff"] == pytest.approx(1e-3)
    y2 = np.full(10, 10.0)
    h2 = signed_percentage_error_histogram(
        pred, y2, smart_cutoff_ratio=0.5, scaled_error_cutoff=1e-3
    )
    assert h2["scaledErrorCutoff"] == pytest.approx(5.0)


def test_regression_evaluator_has_histogram():
    y = np.array([1.0, 2.0, 3.0])
    pred = np.array([1.5, 2.5, 2.0])
    m = RegressionEvaluator().evaluate_arrays(y, pred, None)
    assert "SignedPercentageErrorHistogram" in m
    assert sum(m["SignedPercentageErrorHistogram"]["counts"]) == 3


def test_forecast_seasonal_error_and_mase():
    # y has period-2 seasonality; a one-step-behind forecast
    y = np.array([1.0, 5.0, 1.0, 5.0, 1.0, 5.0], dtype=float)
    pred = np.array([1.0, 1.0, 5.0, 1.0, 5.0, 1.0], dtype=float)
    ev = ForecastEvaluator(seasonal_window=2)
    m = ev.evaluate_arrays(y, pred, None)
    # seasonal error over first cnt-2 rows: |y_i - y_{i+2}| = 0
    assert m["SeasonalError"] == 0.0
    assert m["MASE"] == 0.0  # denominator 0 -> 0 per reference
    ev1 = ForecastEvaluator(seasonal_window=1)
    m1 = ev1.evaluate_arrays(y, pred, None)
    # window 1: |1-5| repeated over 5 gaps -> 4.0
    assert m1["SeasonalError"] == pytest.approx(4.0)
    abs_diff = np.abs(y - pred).sum()
    assert m1["MASE"] == pytest.approx(abs_diff / (4.0 * 6))
    assert 0.0 <= m1["SMAPE"] <= 2.0


def test_forecast_validation():
    with pytest.raises(ValueError):
        ForecastEvaluator(seasonal_window=0)
    with pytest.raises(ValueError):
        ForecastEvaluator(max_items=0)
