"""The sharded CV candidate sweep (parallel/sweep.py + workflow/cv.py).

Tier-1 legs run on any device count (degenerate 1x1 mesh); the mesh legs
need the forced 8-device CPU mesh the CI ``sweep`` job provides
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) and skip
elsewhere.
"""
import numpy as np
import pytest

import jax

import transmogrifai_tpu.types as T
from transmogrifai_tpu.compiler import bucketing
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.models.solvers import (
    fit_linear_batched,
    fit_logistic_binary_batched,
)
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.parallel.fit import sweep_parallel_fit
from transmogrifai_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    use_execution_mesh,
)
from transmogrifai_tpu.parallel.sweep import SweepLayout, mesh_lane_capacity
from transmogrifai_tpu.resilience.distributed import HostLostError
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.workflow import cv as cv_mod
from transmogrifai_tpu.workflow.cv import workflow_cv_results

EIGHT = len(jax.devices()) >= 8
needs_mesh = pytest.mark.skipif(
    not EIGHT, reason="needs the forced 8-device CPU mesh (sweep CI job)"
)


def _sweep_data(rows=48, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(rows, dim)).astype(np.float32)
    w = rng.normal(size=dim)
    y_lin = (x @ w + 0.1 * rng.normal(size=rows)).astype(np.float32)
    y_log = (x @ w > 0).astype(np.float32)
    return x, y_lin, y_log


def _lanes(k, rows, seed=1):
    rng = np.random.default_rng(seed)
    masks = (rng.random((k, rows)) > 0.25).astype(np.float32)
    # floor at 0.01: an unregularized logistic lane on separable labels
    # diverges, and divergence amplifies fp-ordering noise past any
    # bit-parity contract — parity is only meaningful on well-posed lanes
    regs = np.linspace(0.01, 0.3, k).astype(np.float32)
    ens = np.zeros(k, dtype=np.float32)
    return masks, regs, ens


# ==========================================================================
# bucketing: the mesh-aware lane bucket
# ==========================================================================
def test_mesh_lane_bucket_divisible_by_mesh():
    # plain pow2 ladder when the mesh axis divides it already
    assert bucketing.mesh_lane_bucket(5, 1) == bucketing.lane_bucket(5)
    assert bucketing.mesh_lane_bucket(5, 8) == 8
    assert bucketing.mesh_lane_bucket(9, 8) == 16
    assert bucketing.mesh_lane_bucket(64, 8) == 64
    # past the pow2 ladder the 32-multiples stay divisible by 8
    b = bucketing.mesh_lane_bucket(65, 8)
    assert b >= 65 and b % 8 == 0
    # the invariant that lets SweepLayout shard the lane axis evenly
    for k in range(1, 130):
        for m in (1, 2, 4, 8):
            b = bucketing.mesh_lane_bucket(k, m)
            assert b >= k and b % m == 0, (k, m, b)


def test_mesh_lane_bucket_when_bucketing_disabled(monkeypatch):
    monkeypatch.setenv("TPTPU_LANE_BUCKETS", "0")
    # degrades to ceil-to-multiple: no pow2 padding, still shardable
    assert bucketing.mesh_lane_bucket(5, 8) == 8
    assert bucketing.mesh_lane_bucket(9, 8) == 16
    assert bucketing.mesh_lane_bucket(7, 1) == 7


# ==========================================================================
# SweepLayout: the explicit per-axis PartitionSpecs
# ==========================================================================
def test_sweep_layout_partition_specs():
    from jax.sharding import PartitionSpec as P

    layout = SweepLayout()
    # plane/target: rows over the data axis, replicated over model
    assert layout.plane_spec() == P(DATA_AXIS, None)
    assert layout.target_spec() == P(DATA_AXIS)
    # lane tensors: candidate lanes over the model axis
    assert layout.lane_mask_spec() == P(MODEL_AXIS, DATA_AXIS)
    assert layout.lane_spec() == P(MODEL_AXIS)
    # fold outputs come back lane-sharded, gather-free
    assert layout.out_weights_spec() == P(MODEL_AXIS, None)
    assert layout.out_lane_spec() == P(MODEL_AXIS)


def test_mesh_lane_capacity():
    assert mesh_lane_capacity(None) == 1
    mesh = make_mesh(n_data=1, n_model=1)
    assert mesh_lane_capacity(mesh) == 1


# ==========================================================================
# sharded-vs-single parity (degenerate 1x1 mesh; any device count)
# ==========================================================================
def test_sweep_parallel_fit_parity_single_device():
    x, y_lin, y_log = _sweep_data()
    masks, regs, ens = _lanes(3, len(y_lin))
    mesh = make_mesh(n_data=1, n_model=1)
    statics = dict(num_iters=60, fit_intercept=True)

    out = sweep_parallel_fit(
        fit_linear_batched, "t_sweep_lin_1x1", mesh,
        x, y_lin, masks, regs, ens, **statics,
    )
    ref = fit_linear_batched(x, y_lin, masks, regs, ens, **statics)
    assert out.weights.shape == (3, x.shape[1])
    np.testing.assert_allclose(
        np.asarray(out.weights), np.asarray(ref.weights), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(out.intercept), np.asarray(ref.intercept), atol=1e-6
    )

    out = sweep_parallel_fit(
        fit_logistic_binary_batched, "t_sweep_log_1x1", mesh,
        x, y_log, masks, regs, ens, standardization=True, **statics,
    )
    ref = fit_logistic_binary_batched(
        x, y_log, masks, regs, ens, standardization=True, **statics
    )
    np.testing.assert_allclose(
        np.asarray(out.weights), np.asarray(ref.weights), atol=1e-6
    )


# ==========================================================================
# TPJ003: fold-level donation proven in the lowered sweep program
# ==========================================================================
def test_sweep_programs_pass_tpj_donation_gate():
    from transmogrifai_tpu.analysis.program import audit_programs

    rep = audit_programs(
        names=["sweep_linear_sharded", "sweep_logistic_binary_sharded"],
        include_ast=False,
    )
    findings = [f.render() for f in rep.findings]
    assert not any("TPJ003" in f for f in findings), findings


def test_sweep_lowering_carries_donation_aliasing():
    # the contract behind the TPJ003 gate, checked directly: the lowered
    # StableHLO of the sharded sweep marks input->output buffer aliases
    from transmogrifai_tpu.parallel.sweep import program_trace_specs

    for spec in program_trace_specs():
        args, statics = spec["build"](spec["buckets"][0])
        text = spec["fn"].lower(*args, **statics).as_text()
        assert (
            "tf.aliasing_output" in text or "jax.buffer_donor" in text
        ), f"{spec['name']}: no aliasing in lowered IR"


# ==========================================================================
# lane-granular failure isolation (satellite: no O(families x points)
# rebuild; surviving lanes keep their results)
# ==========================================================================
class _BoomModel:
    def predict_arrays(self, x):
        raise RuntimeError("boom lane")


class _OkModel:
    def __init__(self, v):
        self.v = v

    def predict_arrays(self, x):
        return np.full(len(x), self.v), None, None


class _Eval:
    is_larger_better = False

    def evaluate_arrays(self, y, pred, prob):
        return {"err": float(np.mean(np.abs(y - pred)))}

    def metric_of(self, m):
        return m["err"]


class _Est:
    def __init__(self, uid):
        self.uid = uid


def test_eval_lanes_isolates_one_bad_lane():
    est = _Est("estA")
    points = [{"p": i} for i in range(3)]
    models = [_OkModel(0.0), _BoomModel(), _OkModel(1.0)]
    per_candidate: dict = {}
    failed_lanes: set = set()
    xv = np.zeros((4, 2), np.float32)
    yv = np.zeros(4)
    cv_mod._eval_lanes(
        est, points, models, xv, yv, _Eval(), per_candidate, failed_lanes
    )
    # the bad lane lost ONLY its own entry; neighbors kept theirs
    assert ("estA", 1) not in per_candidate
    assert failed_lanes == {("estA", 1)}
    assert per_candidate[("estA", 0)].metric_values == [0.0]
    assert per_candidate[("estA", 2)].metric_values == [1.0]
    # later folds skip the poisoned lane instead of re-raising
    cv_mod._eval_lanes(
        est, points, models, xv, yv, _Eval(), per_candidate, failed_lanes
    )
    assert len(per_candidate[("estA", 0)].metric_values) == 2
    assert ("estA", 1) not in per_candidate


def test_drop_family_pops_only_its_own_lanes():
    from transmogrifai_tpu.selector.validators import CandidateResult

    per_candidate = {
        (uid, gi): CandidateResult(
            model_name="m", model_uid=uid, grid={}, metric_values=[0.1]
        )
        for uid in ("a", "b")
        for gi in range(4)
    }
    failed: set = set()
    cv_mod._drop_family(
        _Est("a"), [{}] * 4, RuntimeError("x"), per_candidate, failed,
        None, 0, 0.0, 10,
    )
    assert failed == {"a"}
    assert set(per_candidate) == {("b", gi) for gi in range(4)}


def test_validator_sweep_scores_nan_for_failed_lane():
    """validators._sweep_family: one lane's scoring failure is a NaN
    metric (filtered by ``best``), not a family exclusion."""
    from transmogrifai_tpu.selector.validators import CrossValidator

    class _FlakyPredictEst(LogisticRegression):
        # no batched hooks: force the per-model predict loop
        sweep_dispatch_masks = None
        fit_arrays_batched_masks = None
        fit_arrays_batched = None

        def fit_arrays(self, x, y, row_mask):
            model = super().fit_arrays(x, y, row_mask)
            if self.reg_param and self.reg_param > 0.2:
                model.predict_arrays = _BoomModel().predict_arrays
            return model

    x, _, y = _sweep_data(rows=64)
    v = CrossValidator(num_folds=2, seed=0)
    folds = v.split_masks(y.astype(np.float64))
    from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator

    results = v._sweep_family(
        _FlakyPredictEst(),
        [{"reg_param": 0.0}, {"reg_param": 0.3}],
        folds, x, y.astype(np.float64),
        BinaryClassificationEvaluator(),
    )
    assert len(results) == 2
    assert np.isfinite(results[0].metric_mean)
    assert np.isnan(results[1].metric_mean)  # poisoned lane, isolated
    best = v.best(results, BinaryClassificationEvaluator())
    assert best.grid == {"reg_param": 0.0}


# ==========================================================================
# fold-resume stash: < 1 fold of rework after a mid-sweep host loss
# ==========================================================================
def _mini_binary_graph(n=120, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    label = (x1 + 0.5 * x2 + 0.3 * rng.normal(size=n) > 0).astype(float)
    ds = Dataset.of({
        "label": column_from_values(T.RealNN, label),
        "x1": column_from_values(T.Real, x1),
        "x2": column_from_values(T.Real, x2),
    })
    resp, preds = from_dataset(ds, response="label")
    vec = transmogrify(list(preds))
    return ds, resp, vec


def _flaky_family(calls):
    class Flaky(LogisticRegression):
        # plain host-side family: fold-count bookkeeping stays exact
        sweep_dispatch_masks = None
        fit_arrays_batched_masks = None

        def fit_arrays_batched(self, x, y, row_mask, grid_points):
            calls["folds"] += 1
            if calls["folds"] == 2 and not calls["raised"]:
                calls["raised"] = True
                raise HostLostError(host=1, reason="injected mid-sweep")
            return [
                LogisticRegression(**{
                    **self.get_params(), **p
                }).fit_arrays(x, y, row_mask)
                for p in grid_points
            ]

    return Flaky


def test_host_loss_resumes_with_less_than_one_fold_rework():
    ds, resp, vec = _mini_binary_graph()
    calls = {"folds": 0, "raised": False}
    selector = BinaryClassificationModelSelector(
        models=[(_flaky_family(calls)(), {"reg_param": [0.0, 0.1]})],
        num_folds=2, seed=3,
    )
    selector.set_input(resp, vec)

    with pytest.raises(HostLostError):
        workflow_cv_results(selector, ds)
    assert calls["folds"] == 2  # fold 0 done, fold 1 died mid-sweep

    # the failover-loop re-entry (workflow/workflow.py): fold 0 is NOT
    # re-dispatched — the stash replays it, fold 1 alone re-runs
    results = workflow_cv_results(selector, ds)
    assert calls["folds"] == 3  # < 1 fold of rework
    assert len(results) == 2
    assert all(len(r.metric_values) == 2 for r in results)
    # normal completion drops the stash: a fresh run starts at fold 0
    assert not any(
        key[0] == selector.uid for key in cv_mod._RESUME
    )


def test_non_host_loss_clears_stash():
    ds, resp, vec = _mini_binary_graph(seed=1)
    calls = {"folds": 0}

    class Dies(LogisticRegression):
        sweep_dispatch_masks = None
        fit_arrays_batched_masks = None

        def fit_arrays_batched(self, x, y, row_mask, grid_points):
            calls["folds"] += 1
            if calls["folds"] == 2:
                # fold 0 is already stashed by now — a non-host-loss
                # unwind (BaseException included) must drop that stash
                raise KeyboardInterrupt
            return [
                LogisticRegression(**{
                    **self.get_params(), **p
                }).fit_arrays(x, y, row_mask)
                for p in grid_points
            ]

    selector = BinaryClassificationModelSelector(
        models=[(Dies(), {"reg_param": [0.0]})], num_folds=2, seed=3,
    )
    selector.set_input(resp, vec)
    with pytest.raises(KeyboardInterrupt):
        workflow_cv_results(selector, ds)
    assert calls["folds"] == 2
    assert not any(key[0] == selector.uid for key in cv_mod._RESUME)


# ==========================================================================
# mesh legs: the forced 8-device CPU mesh (sweep CI job)
# ==========================================================================
@needs_mesh
def test_sharded_parity_across_lane_bucket_boundary():
    """Bit-parity twins across the pow2 bucket edge: k=63 pads to the
    64-lane bucket, k=64 lands exact — both must match the single-device
    sweep (logistic bit-exact; linear within GEMM-tiling tolerance)."""
    x, y_lin, y_log = _sweep_data(rows=64, dim=5)
    mesh = make_mesh(n_data=1, n_model=8)
    statics = dict(num_iters=40, fit_intercept=True)
    for k in (63, 64):  # padded twin / unpadded twin
        masks, regs, ens = _lanes(k, len(y_lin), seed=k)
        sh = sweep_parallel_fit(
            fit_logistic_binary_batched, f"t_sweep_log_8_{k}", mesh,
            x, y_log, masks, regs, ens, standardization=True, **statics,
        )
        ref = fit_logistic_binary_batched(
            x, y_log, masks, regs, ens, standardization=True, **statics
        )
        assert np.asarray(sh.weights).shape == (k, 5)
        assert np.array_equal(
            np.asarray(sh.weights), np.asarray(ref.weights)
        ), f"logistic k={k}: sharded sweep not bit-identical"
        assert np.array_equal(
            np.asarray(sh.intercept), np.asarray(ref.intercept)
        )

        sh = sweep_parallel_fit(
            fit_linear_batched, f"t_sweep_lin_8_{k}", mesh,
            x, y_lin, masks, regs, ens, **statics,
        )
        ref = fit_linear_batched(x, y_lin, masks, regs, ens, **statics)
        np.testing.assert_allclose(
            np.asarray(sh.weights), np.asarray(ref.weights),
            atol=2e-6, rtol=1e-5,
        )


@needs_mesh
def test_estimator_sweep_sharded_vs_single_parity():
    """The full estimator path (sweep_dispatch_masks -> SweepLayout pjit)
    against the mesh-free path, via the A/B parity lever."""
    x, _, y = _sweep_data(rows=80, dim=4)
    masks = [
        np.ones(80, np.float32),
        (np.arange(80) % 2).astype(np.float32),
    ]
    pts = [{"reg_param": float(r)} for r in np.linspace(0.0, 0.2, 5)]
    mesh = make_mesh(n_data=1, n_model=8)
    with use_execution_mesh(mesh):
        sharded = LogisticRegression().fit_arrays_batched_masks(
            x, y.astype(np.float64), masks, pts
        )
    with use_execution_mesh(None):
        single = LogisticRegression().fit_arrays_batched_masks(
            x, y.astype(np.float64), masks, pts
        )
    for mi in range(2):
        for gi in range(5):
            assert np.array_equal(
                sharded[mi][gi].weights, single[mi][gi].weights
            ), f"mask {mi} point {gi} diverged"


@needs_mesh
def test_host_loss_mid_sharded_sweep_failover():
    """Seeded host loss during the SHARDED fold loop: the controller
    declares the host dead, the workflow-style failover loop re-enters,
    and the stash holds rework under one fold — with the collective
    tapes reconciling clean afterwards."""
    from transmogrifai_tpu.analysis import spmd as SP
    from transmogrifai_tpu.parallel import guarded as G
    from transmogrifai_tpu.resilience.distributed import (
        FailoverController,
        HeartbeatConfig,
        installed_controller,
    )

    ds, resp, vec = _mini_binary_graph(seed=2)
    calls = {"folds": 0, "raised": False}
    selector = BinaryClassificationModelSelector(
        models=[
            (_flaky_family(calls)(), {"reg_param": [0.0, 0.1]}),
            (LogisticRegression(), {"reg_param": [0.0, 0.05, 0.1]}),
        ],
        num_folds=2, seed=3,
    )
    selector.set_input(resp, vec)
    mesh = make_mesh(n_data=1, n_model=8)
    ctrl = FailoverController(
        n_hosts=4, config=HeartbeatConfig(clock=lambda: 0.0)
    ).bind(mesh)

    G.set_tracing(True)
    try:
        with installed_controller(ctrl), use_execution_mesh(mesh):
            results = None
            while results is None:
                try:
                    results = workflow_cv_results(selector, ds)
                except HostLostError as e:
                    ctrl.failover(e)
    finally:
        G.set_tracing(False)

    # < 1 fold of rework: fold 0 (2 dispatches incl. the killed fold-1
    # attempt) + ONLY fold 1 again on re-entry
    assert calls["folds"] == 3
    assert ctrl.counters["hostsLost"] == 1
    assert len(results) == 5
    # per-host collective tapes reconcile against the static seam census
    static = SP.audit_spmd()
    seams: dict = {}
    for rel, names in (static.data.get("spmdSeams") or {}).items():
        for name, linenos in names.items():
            seams.setdefault(name, []).extend(
                f"{rel}:{ln}" for ln in linenos
            )
    recon = SP.reconcile_collective_orders(G.collective_tapes(), seams)
    rec_data = recon.data["reconciliation"]
    assert rec_data["tapesAgree"] and rec_data["explained"], rec_data
