"""Static-analysis plane (analysis/) — pre-flight DAG validation and the
serving-plan auditor.

The seeded bad-DAG corpus maps every known defect class to its expected
TPA code; the good-DAG cases pin that legitimate flows (label-aware
stages, label-derived result features, shrunk variable-arity wirings)
stay clean. Marker: ``analysis`` (fast, pure graph walking — no fits
except the two end-to-end audit tests).
"""
import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu import Dataset
from transmogrifai_tpu.analysis import (
    CODES,
    Finding,
    PreflightError,
    Report,
    Severity,
    preflight,
)
from transmogrifai_tpu.features import FeatureBuilder, from_dataset
from transmogrifai_tpu.features.feature import Feature
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.ops.numeric import RealVectorizer
from transmogrifai_tpu.ops.text_stages import TextTokenizer
from transmogrifai_tpu.prep import SanityChecker
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.workflow.dag import compute_dag, validate_stages
from transmogrifai_tpu.workflow.workflow import Workflow

pytestmark = pytest.mark.analysis

LR_MODELS = [(LogisticRegression(), {"reg_param": [0.01]})]


# --------------------------------------------------------------- fixtures
def _dataset(n=120, seed=0):
    rng = np.random.default_rng(seed)
    return Dataset.of({
        "label": column_from_values(T.RealNN, rng.integers(0, 2, n).tolist()),
        "age": column_from_values(T.Real, rng.normal(40.0, 9.0, n).tolist()),
        "city": column_from_values(
            T.PickList, [["ankara", "bern", "cairo"][i % 3] for i in range(n)]
        ),
    })


def _flow(ds):
    """label/predictors + the standard transmogrify->check->select DAG."""
    label, predictors = from_dataset(ds, response="label")
    vec = transmogrify(predictors)
    checked = label.sanity_check(vec, remove_bad_features=True)
    pred = (
        BinaryClassificationModelSelector(seed=7, models=LR_MODELS)
        .set_input(label, checked)
        .get_output()
    )
    return label, predictors, pred


def _codes(report):
    return sorted({f.code for f in report.findings})


def _error_codes(report):
    return sorted({f.code for f in report.errors()})


# ------------------------------------------------------------ report core
def test_finding_requires_registered_code():
    with pytest.raises(ValueError, match="unregistered"):
        Finding("TPZ999", "nope")


def test_report_ordering_and_queries():
    r = Report()
    r.add("TPA001", "a", subject="s1")
    r.add("TPX004", "b", severity=Severity.INFO)
    r.add("TPL002", "c", severity=Severity.WARNING)
    assert len(r) == 3 and not r.ok
    assert [f.code for f in r.errors()] == ["TPA001"]
    assert [f.code for f in r.warnings()] == ["TPL002"]
    assert r.by_code("TPX004")[0].message == "b"
    js = r.to_json()
    assert js["errors"] == 1 and js["warnings"] == 1
    assert "TPA001" in r.summary_line()


def test_report_raise_if_errors_is_valueerror():
    r = Report()
    r.add("TPA009", "loop", subject="x")
    with pytest.raises(PreflightError) as ei:
        r.raise_if_errors()
    assert isinstance(ei.value, ValueError)
    assert "TPA009" in str(ei.value)
    # clean reports pass through
    assert Report().raise_if_errors().ok


def test_all_emittable_codes_are_catalogued():
    for code in CODES:
        # TPR: the cross-run regression sentinel (telemetry/runlog.py);
        # TPC: the concurrency analysis plane (analysis/concurrency.py);
        # TPJ: the compiled-program contract auditor (analysis/program.py);
        # TPS: the SPMD contract auditor (analysis/spmd.py)
        assert code[:3] in ("TPA", "TPX", "TPL", "TPR", "TPC", "TPJ", "TPS")
        assert CODES[code]


# -------------------------------------------------- good DAGs stay clean
def test_titanic_style_flow_validates_clean():
    ds = _dataset()
    _, _, pred = _flow(ds)
    report = Workflow().set_result_features(pred).validate()
    assert report.ok, report.pretty()
    # the sanctioned label crossings must not trip the leakage check
    assert not report.by_code("TPA003")


def test_label_derived_result_feature_is_not_leakage():
    # a result feature computed FROM the label is legitimate as long as it
    # never feeds a predictor's feature input (score_columns parity tests
    # rely on exactly this shape)
    ds = _dataset()
    label, predictors, pred = _flow(ds)
    derived = (label + 1.0).alias("labelPlusOne")
    report = Workflow().set_result_features(pred, derived).validate()
    assert report.ok, report.pretty()


def test_preflight_rejects_unknown_mode():
    with pytest.raises(ValueError, match="mode"):
        preflight([], mode="banana")


# ------------------------------------------------- seeded bad-DAG corpus
def test_corpus_type_clash_tpa001():
    age = FeatureBuilder.Real("age").as_predictor()
    stage = TextTokenizer()  # wants Text
    stage.input_features = (age,)  # bypass set_input's eager check
    bad = stage.get_output()
    report = preflight([bad])
    assert "TPA001" in _error_codes(report)
    f = report.by_code("TPA001")[0]
    assert "age" in f.message and "Text" in f.message


def test_corpus_arity_mismatch_tpa002():
    age = FeatureBuilder.Real("age").as_predictor()
    other = FeatureBuilder.Real("other").as_predictor()
    stage = RealVectorizer()
    stage.set_input(age, other)
    out = stage.get_output()
    checker = SanityChecker()  # wants exactly (label, vector)
    checker.input_features = (out,)  # wrong arity, bypassing set_input
    bad = checker.get_output()
    report = preflight([bad])
    assert "TPA002" in _error_codes(report)


def test_corpus_leakage_tpa003():
    ds = _dataset()
    label, predictors = from_dataset(ds, response="label")
    leaky = (label + predictors[0]).alias("leaky")
    vec = transmogrify(list(predictors) + [leaky])
    pred = (
        BinaryClassificationModelSelector(seed=7, models=LR_MODELS)
        .set_input(label, vec)
        .get_output()
    )
    report = preflight([pred])
    assert "TPA003" in _error_codes(report)
    f = report.by_code("TPA003")[0]
    assert "label" in str(f.detail.get("path"))
    # and train() refuses it before touching any data
    with pytest.raises(PreflightError, match="TPA003"):
        Workflow().set_result_features(pred).set_input_dataset(ds).train()


def test_corpus_duplicate_outputs_tpa004():
    a = FeatureBuilder.Real("a").as_predictor()
    b = FeatureBuilder.Real("b").as_predictor()
    out1 = (a + 1.0).alias("same")
    out2 = (b + 2.0).alias("same")
    report = preflight([out1, out2])
    assert "TPA004" in _error_codes(report)


def test_corpus_duplicate_raw_names_tpa005():
    a1 = FeatureBuilder.Real("dup").as_predictor()
    a2 = FeatureBuilder.Real("dup").as_predictor()
    report = preflight([(a1 + 1.0).alias("x"), (a2 + 2.0).alias("y")])
    assert "TPA005" in _error_codes(report)


def test_corpus_orphan_feature_tpa006():
    orphan = Feature(name="ghost", ftype=T.Real)  # no origin stage
    out = (orphan + 1.0).alias("derived")
    report = preflight([out])
    codes = [f.code for f in report.findings]
    assert "TPA006" in codes
    assert report.by_code("TPA006")[0].severity is Severity.WARNING


def test_corpus_unwired_stage_tpa007():
    stage = RealVectorizer()
    feat = Feature(
        name="dangling", ftype=T.OPVector, origin_stage=stage, parents=()
    )
    report = preflight([feat])
    assert "TPA007" in _error_codes(report)


def test_corpus_estimator_in_serving_plan_tpa008():
    ds = _dataset()
    label, predictors, pred = _flow(ds)
    report = preflight([pred], mode="serve", fitted={})
    assert "TPA008" in _error_codes(report)
    # with every estimator fitted (simulated via a transformer stand-in),
    # train mode accepts the same DAG
    assert "TPA008" not in _codes(preflight([pred], mode="train"))


def test_corpus_cycle_tpa009():
    a = FeatureBuilder.Real("a").as_predictor()
    f1 = (a + 1.0).alias("f1")
    f2 = (f1 + 1.0).alias("f2")
    # hand-wire the cycle: f1's stage now consumes f2
    f1.origin_stage.input_features = (f2,)
    report = preflight([f2])
    assert "TPA009" in _error_codes(report)
    # and it did NOT hang or blow the recursion limit getting there


def test_corpus_duplicate_uid_tpa011():
    a = FeatureBuilder.Real("a").as_predictor()
    b = FeatureBuilder.Real("b").as_predictor()
    s1 = RealVectorizer()
    s2 = RealVectorizer()
    s2.uid = s1.uid
    out1 = s1.set_input(a).get_output()
    out2 = s2.set_input(b).get_output()
    report = preflight([out1, out2])
    assert "TPA011" in _error_codes(report)


def test_corpus_multiple_selectors_tpa013():
    ds = _dataset()
    label, predictors = from_dataset(ds, response="label")
    vec = transmogrify(predictors)
    p1 = (
        BinaryClassificationModelSelector(seed=1, models=LR_MODELS)
        .set_input(label, vec).get_output()
    )
    p2 = (
        BinaryClassificationModelSelector(seed=2, models=LR_MODELS)
        .set_input(label, vec).get_output()
    )
    report = preflight([p1, p2])
    assert "TPA013" in _error_codes(report)
    assert "Only one ModelSelector" in report.by_code("TPA013")[0].message


# ------------------------------------------------ validate_stages satellite
def test_validate_stages_names_offending_stage():
    a = FeatureBuilder.Real("a").as_predictor()
    b = FeatureBuilder.Real("b").as_predictor()
    s1, s2 = RealVectorizer(), RealVectorizer()
    s2.uid = s1.uid
    out1 = s1.set_input(a).get_output()
    out2 = s2.set_input(b).get_output()
    layers = [[s1, s2]]
    with pytest.raises(ValueError) as ei:
        validate_stages(layers)
    msg = str(ei.value)
    assert "TPA011" in msg and s1.uid in msg


def test_validate_stages_rejects_duplicate_output_names():
    a = FeatureBuilder.Real("a").as_predictor()
    b = FeatureBuilder.Real("b").as_predictor()
    out1 = (a + 1.0).alias("same")
    out2 = (b + 2.0).alias("same")
    layers = compute_dag([out1, out2])
    with pytest.raises(ValueError) as ei:
        validate_stages(layers)
    assert "TPA004" in str(ei.value) and "same" in str(ei.value)


def test_validate_stages_accepts_good_dag():
    ds = _dataset()
    _, _, pred = _flow(ds)
    validate_stages(compute_dag([pred]))  # no raise


# ----------------------------------------------------- end-to-end + audit
@pytest.fixture(scope="module")
def trained():
    ds = _dataset(n=160)
    label, predictors, pred = _flow(ds)
    model = (
        Workflow().set_result_features(pred).set_input_dataset(ds).train()
    )
    return ds, model


def test_train_records_analysis_report(trained):
    _, model = trained
    js = model.summary_json()
    assert js["analysis"] is not None
    assert js["analysis"]["errors"] == 0


def test_summary_json_carries_concurrency_summary(trained):
    # the TPC static-concurrency summary rides beside the TPA/TPX
    # reports in summary_json()["analysis"] (lru-cached per process)
    _, model = trained
    conc = model.summary_json()["analysis"]["concurrency"]
    assert set(conc) == {"findings", "codes", "locks", "edges"}
    assert conc["locks"] > 0


def test_summary_pretty_reports_surviving_findings(trained):
    _, model = trained
    # a clean train prints no analysis line...
    assert "Static analysis:" not in model.summary_pretty()
    # ...but surviving warnings surface with their codes
    model.analysis = {
        "findings": [
            {"code": "TPA006", "severity": "warning", "message": "m",
             "subject": "ghost"},
        ],
        "errors": 0,
        "warnings": 1,
    }
    pretty = model.summary_pretty()
    assert "Static analysis: 0 error(s), 1 warning(s) (TPA006)" in pretty
    model.analysis = {"findings": [], "errors": 0, "warnings": 0}


def test_analysis_survives_save_load(trained, tmp_path):
    from transmogrifai_tpu.workflow.workflow import WorkflowModel

    _, model = trained
    path = str(tmp_path / "model")
    model.save(path)
    loaded = WorkflowModel.load(path)
    assert loaded.analysis == model.analysis
    assert loaded.summary_json()["analysis"]["errors"] == 0


def test_preflight_overhead_under_one_percent(trained):
    # acceptance criterion: the pre-flight walk must cost < 1% of a
    # flagship train. The flow above trains in seconds; 100 validate()
    # passes must land well under that even on this 2-vCPU container.
    import time

    ds, model = trained
    label, predictors, pred = _flow(_dataset())
    wf = Workflow().set_result_features(pred)
    wf.validate()  # warm the lazy imports
    t0 = time.perf_counter()
    for _ in range(100):
        wf.validate()
    per_pass = (time.perf_counter() - t0) / 100
    assert per_pass < 0.05, f"preflight too slow: {per_pass:.4f}s/pass"


def test_serving_audit_census_in_metadata(trained):
    from transmogrifai_tpu.local.scoring import score_function

    _, model = trained
    fn = score_function(model)
    fn.batch([{"age": 31.0, "city": "bern"}] * 4)
    md = fn.metadata()
    analysis = md["analysis"]
    assert analysis is not None
    census = analysis["transferCensus"]
    assert census["batchBucketed"] is True
    assert census["hostToDeviceTransfers"] == 1
    assert census["deviceToHostTransfers"] == 1
    fams = {e["family"] for e in census["stages"]}
    assert {"vectorizer", "combiner", "predictor"} <= fams
    # widths are learned after the first batch: every vectorizer proves
    # its [N, width] and the predictor's upload bytes follow from them
    vec_widths = [
        e["width"] for e in census["stages"] if e["family"] == "vectorizer"
    ]
    assert all(isinstance(w, int) and w > 0 for w in vec_widths)
    predictor = [
        e for e in census["stages"] if e["family"] == "predictor"
    ][0]
    if census.get("fusedProgram"):
        # the fused graph carries the whole segment in ONE dispatch: the
        # upload accounting moves from the predictor stage to the
        # program-level ingest (compiler/fused.py)
        assert predictor.get("fused") is True
        assert census["upBytesPerRow"] > 0
        assert analysis["fusedProgram"]["upBytesPerRow"] > 0
    else:
        assert predictor["upBytesPerRow"] and predictor["upBytesPerRow"] > 0
    # no TPX004 left once shapes are proven
    assert not [
        f for f in analysis["findings"] if f["code"] == "TPX004"
    ]


def test_audit_flags_unbucketed_plan(trained):
    from transmogrifai_tpu.analysis.plan_audit import audit_serving_plan
    from transmogrifai_tpu.stages.base import Estimator
    from transmogrifai_tpu.workflow.dag import compute_dag as cd

    _, model = trained
    plan = []
    for layer in cd(list(model.result_features)):
        for stage in layer:
            t = model.fitted.get(stage.uid, stage)
            assert not isinstance(t, Estimator)
            plan.append(t)
    report = audit_serving_plan(
        plan, list(model.raw_features),
        [f.name for f in model.result_features], bucketed=False,
    )
    assert "TPX001" in {f.code for f in report.findings}


def test_donation_misuse_detector():
    from transmogrifai_tpu.analysis.plan_audit import donation_misuse

    bad = (
        "def f(buf, k):\n"
        "    g = donating('p', kern, donate_argnums=(0,))\n"
        "    out = g(buf, k)\n"
        "    return out + buf\n"
    )
    report = donation_misuse(bad, "bad.py")
    assert [f.code for f in report.findings] == ["TPX003"]

    good = (
        "def f(buf, k):\n"
        "    g = donating('p', kern, donate_argnums=(0,))\n"
        "    out, buf = g(buf, k)\n"
        "    return out + buf\n"
    )
    assert not donation_misuse(good, "good.py").findings

    # the aot_call form used by the gbdt boost chunks: donated arg rides
    # the args tuple and is re-bound by the same statement
    aot = (
        "def f(binned, margin):\n"
        "    g = donating('boost', kern, donate_argnums=(1,))\n"
        "    trees, margin = aot_call('boost', g, (binned, margin), {})\n"
        "    return trees, margin\n"
    )
    assert not donation_misuse(aot, "aot.py").findings

    aot_bad = (
        "def f(binned, margin):\n"
        "    g = donating('boost', kern, donate_argnums=(1,))\n"
        "    trees = aot_call('boost', g, (binned, margin), {})\n"
        "    return trees, margin\n"
    )
    assert [f.code for f in donation_misuse(aot_bad, "x.py").findings] == [
        "TPX003"
    ]


def test_gbdt_module_passes_donation_audit():
    # the one real donating() call site in the repo must stay clean
    from transmogrifai_tpu.analysis.plan_audit import donation_misuse_module

    report = donation_misuse_module("transmogrifai_tpu.models.trees")
    assert not report.findings, report.pretty()
