"""Joined readers: many-to-many joins + post-join secondary aggregation.

Parity: readers/.../JoinedDataReader.scala:83-390 and the scenarios of
core's JoinedDataReaderDataGenerationTest (parent/child sales+calls data:
join, then aggregate child events per parent key under a time filter).
"""
import numpy as np
import pytest

from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.readers.catalog import SimpleReader
from transmogrifai_tpu.readers.joins import (
    JoinKeys,
    JoinType,
    JoinedReader,
    TimeBasedFilter,
    TimeColumn,
    join_datasets,
)


def _features():
    kf = FeatureBuilder.ID("key").extract(lambda r: r["k"]).as_predictor()
    name = FeatureBuilder.Text("name").extract(lambda r: r.get("name")).as_predictor()
    amount = FeatureBuilder.Real("amount").extract(lambda r: r.get("amount")).as_predictor()
    ts = FeatureBuilder.Integral("ts").extract(lambda r: r.get("ts")).as_predictor()
    cutoff = FeatureBuilder.Integral("cutoff").extract(lambda r: r.get("cutoff")).as_predictor()
    return kf, name, amount, ts, cutoff


def _readers():
    # parent table: one row per account; child table: many events per key
    left = SimpleReader(
        [
            {"k": "a", "name": "Acme", "cutoff": 100},
            {"k": "b", "name": "Bolt", "cutoff": 200},
            {"k": "d", "name": "Dorm", "cutoff": 50},
        ],
        key_fn=lambda r: r["k"],
    )
    right = SimpleReader(
        [
            {"k": "a", "amount": 1.0, "ts": 90},
            {"k": "a", "amount": 2.0, "ts": 95},
            {"k": "a", "amount": 100.0, "ts": 150},  # after cutoff: dropped
            {"k": "b", "amount": 10.0, "ts": 190},
            {"k": "b", "amount": 20.0, "ts": 10},    # too old for window 100
            {"k": "c", "amount": 7.0, "ts": 5},
        ],
        key_fn=lambda r: r["k"],
    )
    return left, right


def test_many_to_many_join():
    left, right = _readers()
    kf, name, amount, ts, cutoff = _features()
    lds = left.generate_dataset([kf, name, cutoff])
    rds = right.generate_dataset([kf, amount, ts])
    out = join_datasets(lds, rds, JoinType.LEFT_OUTER)
    # 'a' matches 3 child rows, 'b' 2, 'd' none -> 3+2+1 rows
    assert out["key"].to_list() == ["a", "a", "a", "b", "b", "d"]
    assert out["amount"].to_list() == [1.0, 2.0, 100.0, 10.0, 20.0, None]
    assert out["name"].to_list() == [
        "Acme", "Acme", "Acme", "Bolt", "Bolt", "Dorm"
    ]


def test_outer_join_emits_right_only_rows():
    left, right = _readers()
    kf, name, amount, ts, cutoff = _features()
    lds = left.generate_dataset([kf, name])
    rds = right.generate_dataset([kf, amount])
    out = join_datasets(lds, rds, JoinType.OUTER)
    assert out["key"].to_list().count("c") == 1
    c_row = out["key"].to_list().index("c")
    assert out["name"].to_list()[c_row] is None
    assert out["amount"].to_list()[c_row] == 7.0


def test_secondary_aggregation_with_time_filter():
    left, right = _readers()
    kf, name, amount, ts, cutoff = _features()
    reader = JoinedReader(
        left, right, JoinType.LEFT_OUTER, JoinKeys(),
        left_features=[kf, name, cutoff],
        right_features=[amount, ts],
    ).with_secondary_aggregation(
        TimeBasedFilter(
            condition=TimeColumn("cutoff", keep=False),
            primary=TimeColumn("ts", keep=False),
            time_window_ms=100,
        )
    )
    out = reader.generate_dataset([kf, name, cutoff, amount, ts])
    assert out["key"].to_list() == ["a", "b", "d"]
    # parent features keep one copy per key
    assert out["name"].to_list() == ["Acme", "Bolt", "Dorm"]
    # child amounts: only events with cutoff-100 < ts < cutoff merge
    # a: 1.0 + 2.0 (ts 150 after cutoff dropped); b: 10.0 (ts 10 too old)
    assert out["amount"].to_list() == [3.0, 10.0, None]
    # keep=False drops both time columns
    assert "ts" not in out and "cutoff" not in out


def test_secondary_aggregation_keeps_time_columns_when_asked():
    left, right = _readers()
    kf, name, amount, ts, cutoff = _features()
    reader = JoinedReader(
        left, right, JoinType.LEFT_OUTER, JoinKeys(),
        left_features=[kf, name, cutoff],
        right_features=[amount, ts],
    ).with_secondary_aggregation(
        TimeBasedFilter(
            condition=TimeColumn("cutoff", keep=True),
            primary=TimeColumn("ts", keep=False),
            time_window_ms=100,
        )
    )
    out = reader.generate_dataset([kf, name, cutoff, amount, ts])
    # cutoff is a parent feature: one copy per key survives
    assert out["cutoff"].to_list() == [100, 200, 50]
    assert "ts" not in out


def test_response_window_direction():
    """Responses aggregate FORWARD from the cutoff (reference
    JoinedConditionalAggregator.update:434-436)."""
    left = SimpleReader(
        [{"k": "a", "cutoff": 100}], key_fn=lambda r: r["k"]
    )
    right = SimpleReader(
        [
            {"k": "a", "label": 1.0, "ts": 150},   # in [100, 200)
            {"k": "a", "label": 1.0, "ts": 90},    # before cutoff: dropped
            {"k": "a", "label": 1.0, "ts": 250},   # beyond window: dropped
        ],
        key_fn=lambda r: r["k"],
    )
    kf = FeatureBuilder.ID("key").extract(lambda r: r["k"]).as_predictor()
    cutoff = FeatureBuilder.Integral("cutoff").extract(lambda r: r["cutoff"]).as_predictor()
    label = FeatureBuilder.Real("label").extract(lambda r: r.get("label")).as_response()
    ts = FeatureBuilder.Integral("ts").extract(lambda r: r.get("ts")).as_predictor()
    reader = JoinedReader(
        left, right, JoinType.LEFT_OUTER, JoinKeys(),
        left_features=[kf, cutoff],
        right_features=[label, ts],
    ).with_secondary_aggregation(
        TimeBasedFilter(
            condition=TimeColumn("cutoff", keep=False),
            primary=TimeColumn("ts", keep=False),
            time_window_ms=100,
        )
    )
    out = reader.generate_dataset([kf, cutoff, label, ts])
    assert out["label"].to_list() == [1.0]
