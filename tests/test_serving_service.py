"""Standing scoring service suite (transmogrifai_tpu/serving/): bounded
admission, dynamic micro-batching, deadline budgets, tiered load shedding
with hysteresis, chaos-proven graceful degradation, and the thread-safety
hardening of the shared sentinel/breaker/quarantine state.

Everything runs on injectable/virtual clocks — zero real sleeps; the
open-loop loadtest harness drives overload entirely in virtual time.
Markers: serving, faults.
"""
import threading

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.local.scoring import score_function
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.resilience import FaultPlan, installed
from transmogrifai_tpu.resilience.guards import ScoreGuard
from transmogrifai_tpu.resilience.sentinel import (
    BreakerConfig,
    CircuitBreaker,
    QuarantineLog,
    QuarantineRecord,
    SchemaSentinel,
)
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.serving import (
    AdmissionQueue,
    DeadlineBudget,
    DeadlineExceeded,
    LoadShedder,
    MicroBatcher,
    RejectedByAdmission,
    ScoringService,
    ServiceConfig,
    ShedConfig,
    run_loadtest,
)
from transmogrifai_tpu.serving import deadline as sdl
from transmogrifai_tpu.serving import shedding as sshed
from transmogrifai_tpu.serving.loadtest import LoadSchedule, VirtualClock
from transmogrifai_tpu.telemetry import events as tevents
from transmogrifai_tpu.telemetry import metrics as tm
from transmogrifai_tpu.telemetry import spans as tspans
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.utils import uid as uid_util
from transmogrifai_tpu.workflow.workflow import Workflow

pytestmark = [pytest.mark.serving, pytest.mark.faults]


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def _binary_ds(n=160, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    label = (x1 + 0.5 * x2 + 0.3 * rng.normal(size=n) > 0).astype(float)
    return Dataset.of({
        "label": column_from_values(T.RealNN, label),
        "x1": column_from_values(T.Real, x1),
        "x2": column_from_values(T.Real, x2),
    })


@pytest.fixture(scope="module")
def trained():
    uid_util.reset()
    ds = _binary_ds(n=120, seed=3)
    resp, preds = from_dataset(ds, response="label")
    vec = transmogrify(list(preds))
    selector = BinaryClassificationModelSelector(
        seed=7, models=[(LogisticRegression(), {"reg_param": [0.01]})],
        num_folds=2,
    )
    pred = selector.set_input(resp, vec).get_output()
    model = Workflow().set_result_features(pred).set_input_dataset(ds).train()
    return ds, model


@pytest.fixture(scope="module")
def trained_fused():
    """A flow whose transmogrify plane has MULTIPLE vectorizer members
    feeding one combiner — the shape on which fusion (and fit-static
    priming) engages."""
    uid_util.reset()
    rng = np.random.default_rng(5)
    n = 96
    x1 = rng.normal(size=n)
    city = [["a", "b", "c"][i % 3] for i in range(n)]
    label = (x1 > 0).astype(float)
    ds = Dataset.of({
        "label": column_from_values(T.RealNN, label),
        "x1": column_from_values(T.Real, x1),
        "city": column_from_values(T.PickList, city),
    })
    resp, preds = from_dataset(ds, response="label")
    vec = transmogrify(list(preds))
    selector = BinaryClassificationModelSelector(
        seed=7, models=[(LogisticRegression(), {"reg_param": [0.01]})],
        num_folds=2,
    )
    pred = selector.set_input(resp, vec).get_output()
    model = Workflow().set_result_features(pred).set_input_dataset(ds).train()
    return ds, model


@pytest.fixture()
def score_fn(trained):
    _, model = trained
    return score_function(model)


@pytest.fixture()
def rows():
    rng = np.random.default_rng(11)
    return [
        {"x1": float(a), "x2": float(b)}
        for a, b in zip(rng.normal(size=64), rng.normal(size=64))
    ]


def _mkreq(n_rows=1, budget=None, enq=0.0):
    """A minimal queue item: anything with .rows / .budget / .enqueued_at."""
    class R:
        pass

    r = R()
    r.rows = [{"x1": 0.0}] * n_rows
    r.budget = budget
    r.enqueued_at = enq
    return r


# ------------------------------------------------------------ admission queue
class TestAdmissionQueue:
    def test_bounded_in_rows_typed_rejection(self):
        q = AdmissionQueue(max_rows=4)
        q.offer(_mkreq(3))
        with pytest.raises(RejectedByAdmission) as ei:
            q.offer(_mkreq(2))
        assert ei.value.reason == "queue_full"
        q.offer(_mkreq(1))  # exactly at the bound fits
        assert q.depth_rows() == 4 and q.peak_rows == 4

    def test_fifo_pop_many_respects_row_budget(self):
        q = AdmissionQueue(max_rows=64)
        items = [_mkreq(3), _mkreq(3), _mkreq(3)]
        for it in items:
            q.offer(it)
        got = q.pop_many(max_rows=6)
        assert got == items[:2] and q.depth_rows() == 3

    def test_oversized_single_request_still_progresses(self):
        q = AdmissionQueue(max_rows=64)
        big = _mkreq(32)
        q.offer(big)
        assert q.pop_many(max_rows=8) == [big]

    def test_closed_refuses_with_stopped(self):
        q = AdmissionQueue(max_rows=8)
        q.offer(_mkreq(1))
        q.close()
        with pytest.raises(RejectedByAdmission) as ei:
            q.offer(_mkreq(1))
        assert ei.value.reason == "stopped"
        # queued items survive close for draining
        assert len(q.drain()) == 1 and q.depth_rows() == 0

    def test_unknown_reason_rejected(self):
        with pytest.raises(ValueError):
            RejectedByAdmission("nope")


# ------------------------------------------------------------ deadline budget
class TestDeadlineBudget:
    def setup_method(self):
        tm.REGISTRY.reset_metrics_for_tests()

    def test_remaining_on_injectable_clock(self):
        clk = FakeClock()
        b = DeadlineBudget(0.100, clock=clk)
        clk.now = 0.040
        assert b.remaining() == pytest.approx(0.060)
        assert not b.expired()
        clk.now = 0.120
        assert b.expired()

    def test_consume_burns_simulated_seconds(self):
        clk = FakeClock()
        b = DeadlineBudget(0.100, clock=clk)
        b.consume(0.075)
        assert b.remaining() == pytest.approx(0.025)
        b.consume(0.050)
        assert b.expired()

    def test_covers_uses_recorded_family_p95(self):
        clk = FakeClock()
        # seed a dispatch-family history of ~50 ms
        h = tm.REGISTRY.histogram(
            "tptpu_serve_seconds", labels={"stage": "dispatch"}
        )
        for _ in range(50):
            h.observe(0.050)
        assert sdl.family_p95("dispatch") > 0.030
        tight = DeadlineBudget(0.010, clock=clk)
        roomy = DeadlineBudget(1.0, clock=clk)
        assert not tight.covers()
        assert roomy.covers()

    def test_checkpoint_raises_typed_counts_and_emits(self):
        clk = FakeClock()
        h = tm.REGISTRY.histogram(
            "tptpu_serve_seconds", labels={"stage": "featurize"}
        )
        for _ in range(50):
            h.observe(0.080)
        b = DeadlineBudget(0.020, clock=clk)
        with sdl.active(b):
            sdl.checkpoint("sentinel")  # no sentinel history: 0 required
            with pytest.raises(DeadlineExceeded) as ei:
                sdl.checkpoint("featurize")
        assert ei.value.family == "featurize"
        assert ei.value.required > ei.value.remaining
        kinds = [e["kind"] for e in tevents.recent(5)]
        assert "deadline_exceeded" in kinds
        # the OUTCOME counter belongs to the service (one checkpoint trip
        # can shed several co-batched members) — a bare checkpoint must
        # not book it
        assert (
            tm.REGISTRY.counter("tptpu_serve_deadline_exceeded_total").value
            == 0
        )

    def test_no_history_only_spent_budget_rejects(self):
        clk = FakeClock()
        b = DeadlineBudget(0.010, clock=clk)
        with sdl.active(b):
            sdl.checkpoint("dispatch")  # 0 required, 10 ms left: passes
            clk.now = 0.020
            with pytest.raises(DeadlineExceeded):
                sdl.checkpoint("dispatch")

    def test_active_installs_thread_locally_and_restores(self):
        b1 = DeadlineBudget(1.0, clock=FakeClock())
        b2 = DeadlineBudget(2.0, clock=FakeClock())
        assert sdl.current() is None
        with sdl.active(b1):
            assert sdl.current() is b1
            with sdl.active(b2):
                assert sdl.current() is b2
            assert sdl.current() is b1
        assert sdl.current() is None
        seen = []

        def other():
            seen.append(sdl.current())

        with sdl.active(b1):
            th = threading.Thread(target=other)
            th.start()
            th.join()
        assert seen == [None]  # budgets never leak across threads


# ------------------------------------------------------------- micro batcher
class TestMicroBatcher:
    def test_assembles_up_to_max_rows(self):
        q = AdmissionQueue(max_rows=64)
        clk = FakeClock()
        mb = MicroBatcher(q, max_rows=4, clock=clk)
        for _ in range(3):
            q.offer(_mkreq(2))
        plan = mb.next_batch()
        assert len(plan.requests) == 2 and len(plan.rows) == 4
        assert mb.stats()["batchesAssembled"] == 1

    def test_expired_members_split_out(self):
        q = AdmissionQueue(max_rows=64)
        clk = FakeClock()
        mb = MicroBatcher(q, max_rows=16, clock=clk)
        dead = DeadlineBudget(0.010, clock=clk)
        live = DeadlineBudget(10.0, clock=clk)
        q.offer(_mkreq(1, budget=dead))
        q.offer(_mkreq(1, budget=live))
        clk.now = 0.020  # first budget expired while queued
        plan = mb.next_batch()
        assert len(plan.expired) == 1 and len(plan.requests) == 1
        assert plan.budget is live

    def test_tightest_member_budget_wins(self):
        q = AdmissionQueue(max_rows=64)
        clk = FakeClock()
        mb = MicroBatcher(q, max_rows=16, clock=clk)
        loose = DeadlineBudget(10.0, clock=clk)
        tight = DeadlineBudget(1.0, clock=clk)
        q.offer(_mkreq(1, budget=loose))
        q.offer(_mkreq(1, budget=tight))
        q.offer(_mkreq(1))  # no budget
        plan = mb.next_batch()
        assert plan.budget is tight and len(plan.rows) == 3


# -------------------------------------------------------------- load shedder
class TestLoadShedder:
    def setup_method(self):
        tm.REGISTRY.reset_metrics_for_tests()
        sshed.reset_process_flags_for_tests()

    def teardown_method(self):
        sshed.reset_process_flags_for_tests()

    def test_tiers_climb_in_order(self):
        sh = LoadShedder(ShedConfig(), capacity=100)
        assert sh.update(10, 0, 0.0) == 0
        assert sh.update(40, 0, 0.0) == 1   # explain_enter 0.35
        assert sh.update(55, 0, 0.0) == 2   # detail_enter 0.50
        assert sh.update(75, 0, 0.0) == 3   # drift_enter 0.70
        assert sh.update(95, 0, 0.0) == 4   # reject_enter 0.90
        assert sh.reject_admissions
        assert sh.stats()["tierEntries"] == {
            "shed_explain": 1, "shed_detail": 1, "shed_drift": 1,
            "reject": 1,
        }

    def test_hysteresis_no_flapping_at_the_boundary(self):
        sh = LoadShedder(ShedConfig(), capacity=100)
        sh.update(95, 0, 0.0)
        assert sh.tier == 4
        # load falls below ENTER but above EXIT (0.65): tier holds
        sh.update(80, 0, 0.0)
        assert sh.tier == 4
        transitions = sh.transitions
        # hovering there forever never flaps
        for _ in range(10):
            sh.update(80, 0, 0.0)
        assert sh.transitions == transitions
        # below reject_exit: drops to 3 (still above drift_exit 0.50)
        sh.update(60, 0, 0.0)
        assert sh.tier == 3
        sh.update(10, 0, 0.0)
        assert sh.tier == 0

    def test_side_effects_detail_spans_and_drift_flag(self):
        sh = LoadShedder(ShedConfig(), capacity=100)
        assert tspans.stage_detail(1000) and not sshed.drift_shed()
        assert not sshed.explain_shed()
        sh.update(40, 0, 0.0)
        assert sshed.explain_shed()           # tier 1 sheds explain FIRST
        assert tspans.stage_detail(1000)      # detail spans still on
        sh.update(55, 0, 0.0)
        assert not tspans.stage_detail(1000)  # tier 2 sheds detail spans
        assert not sshed.drift_shed()
        sh.update(75, 0, 0.0)
        assert sshed.drift_shed()             # tier 3 sheds drift observe
        sh.update(5, 0, 0.0)
        assert tspans.stage_detail(1000) and not sshed.drift_shed()
        assert not sshed.explain_shed()

    def test_open_breakers_add_load(self):
        sh = LoadShedder(ShedConfig(breaker_weight=0.5), capacity=100)
        # queue alone: below every tier; breakers half open: the load
        # signal crosses the explain AND detail enter points
        assert sh.update(30, 0, 0.0) == 0
        assert sh.update(30, 0, 0.5) == 2

    def test_transitions_emit_load_shed_events(self):
        sh = LoadShedder(ShedConfig(), capacity=100)
        sh.update(95, 0, 0.0)
        evts = [e for e in tevents.recent(10) if e["kind"] == "load_shed"]
        assert evts and evts[-1]["tier"] == "reject"
        assert (
            tm.REGISTRY.counter("tptpu_serve_shed_transitions_total").value
            >= 1
        )

    def test_invalid_config_rejected(self):
        with pytest.raises(ValueError):
            ShedConfig(detail_enter=0.3, detail_exit=0.5)

    def test_reset_restores_process_flags(self):
        sh = LoadShedder(ShedConfig(), capacity=100)
        sh.update(95, 0, 0.0)
        sh.reset()
        assert sh.tier == 0
        assert tspans.stage_detail(1000) and not sshed.drift_shed()


# ---------------------------------------------------------- service lifecycle
class TestServiceLifecycle:
    def test_pump_mode_scores_and_reconciles(self, score_fn, rows):
        clk = VirtualClock()
        svc = ScoringService(
            score_fn,
            ServiceConfig(workers=0, max_queue_rows=64, max_batch_rows=8),
            clock=clk,
        )
        svc.start()
        handles = [svc.submit(dict(r)) for r in rows[:20]]
        while svc.pump():
            pass
        svc.stop()
        s = svc.stats()
        assert s["admitted"] == 20 and s["completed"] == 20
        assert s["outstanding"] == 0 and s["queueDepthRows"] == 0
        out = handles[0].result(timeout=1)
        assert len(out) == 1 and isinstance(out[0], dict)
        assert handles[0].outcome == "completed"
        assert handles[0].latency() is not None

    def test_batch_results_map_back_to_requests(self, score_fn, rows):
        """Multi-row requests sliced out of the shared micro-batch match
        scoring the same rows alone."""
        clk = VirtualClock()
        svc = ScoringService(
            score_fn,
            ServiceConfig(workers=0, max_queue_rows=64, max_batch_rows=16),
            clock=clk,
        )
        svc.start()
        h2 = svc.submit([dict(rows[0]), dict(rows[1])])
        h1 = svc.submit(dict(rows[2]))
        while svc.pump():
            pass
        svc.stop()
        solo = score_fn.batch([dict(rows[0]), dict(rows[1]), dict(rows[2])])
        assert h2.result(timeout=1) == solo[:2]
        assert h1.result(timeout=1) == solo[2:]

    def test_worker_mode_completes_and_quiesces(self, score_fn, rows):
        svc = ScoringService(
            score_fn,
            ServiceConfig(workers=2, max_queue_rows=128, max_batch_rows=16),
        )
        svc.start()
        handles = [svc.submit(dict(rows[i % len(rows)])) for i in range(40)]
        for h in handles:
            h.result(timeout=60)
        svc.stop()
        s = svc.stats()
        assert s["admitted"] == 40 and s["completed"] == 40
        assert s["outstanding"] == 0
        assert not [
            t for t in threading.enumerate()
            if t.name.startswith("tptpu-serve-") and t.is_alive()
        ]

    def test_submit_after_stop_typed_rejection(self, score_fn, rows):
        svc = ScoringService(score_fn, ServiceConfig(workers=0))
        svc.start()
        svc.stop()
        with pytest.raises(RejectedByAdmission) as ei:
            svc.submit(dict(rows[0]))
        assert ei.value.reason == "stopped"
        assert svc.stats()["rejected"]["stopped"] == 1

    def test_stop_drains_queued_requests(self, score_fn, rows):
        clk = VirtualClock()
        svc = ScoringService(
            score_fn,
            ServiceConfig(workers=0, max_queue_rows=64, max_batch_rows=8),
            clock=clk,
        )
        svc.start()
        handles = [svc.submit(dict(r)) for r in rows[:10]]
        svc.stop(drain=True)  # never pumped: drain scores the backlog
        s = svc.stats()
        assert s["outstanding"] == 0 and s["queueDepthRows"] == 0
        assert s["completed"] == 10
        assert all(h.done() for h in handles)

    def test_stop_resets_gauges_in_exposition(self, score_fn, rows):
        """Quiesce → exposition: the queue-depth / in-flight gauges must
        read ZERO in the Prometheus text after stop(), not freeze at
        their last pre-quiesce value (a stopped service reporting queued
        rows would look like a live backlog to a scraper)."""
        from transmogrifai_tpu.telemetry import render_prometheus

        svc = ScoringService(
            score_fn,
            ServiceConfig(workers=0, max_queue_rows=64, max_batch_rows=8),
        )
        svc.start()
        for r in rows[:6]:
            svc.submit(dict(r))
        # queued, never pumped: the queue gauge holds a nonzero value now
        assert tm.REGISTRY.gauge("tptpu_serve_queue_depth").value > 0
        svc.stop(drain=True)
        text = render_prometheus()
        assert "tptpu_serve_queue_depth 0" in text
        assert "tptpu_serve_in_flight_rows 0" in text
        assert svc.stats()["outstanding"] == 0

    def test_context_manager(self, score_fn, rows):
        with ScoringService(score_fn, ServiceConfig(workers=1)) as svc:
            h = svc.submit(dict(rows[0]))
            h.result(timeout=30)
        assert svc.stats()["outstanding"] == 0

    def test_empty_request_rejected(self, score_fn):
        svc = ScoringService(score_fn, ServiceConfig(workers=0))
        svc.start()
        with pytest.raises(ValueError):
            svc.submit([])
        svc.stop()

    def test_start_primes_fusion_from_fit_static_widths(self, trained_fused):
        _, model = trained_fused
        fn = score_function(model)
        assert not fn.fusion.disabled and not fn.fusion.ready()
        svc = ScoringService(fn, ServiceConfig(workers=0))
        svc.start()
        # the fitted vectorizers' meta caches are populated at train time,
        # so the planner is ready before the first batch ever runs
        assert fn.fusion.ready()
        h = svc.submit({"x1": 0.1, "city": "a"})
        svc.pump()
        svc.stop()
        # primed-first-batch output matches an unprimed closure's
        fresh = score_function(model)
        assert h.result(timeout=1) == fresh.batch([{"x1": 0.1, "city": "a"}])

    def test_prime_is_safe_on_fusion_disabled_plans(self, score_fn):
        # the two-Real flow has no combiner: prime() must be a quiet no-op
        assert score_fn.fusion.disabled
        assert score_fn.fusion.prime() is False

    def test_unhealthy_batch_is_typed_error_not_crash(self, trained, rows):
        _, model = trained
        fn = score_function(model, isolation="raise", breaker=False)
        clk = VirtualClock()
        svc = ScoringService(
            fn, ServiceConfig(workers=0, max_batch_rows=8), clock=clk
        )
        svc.start()
        plan = FaultPlan(seed=1).fail_stage_transform(
            target="modelSelector", times=1
        )
        with installed(plan):
            h = svc.submit(dict(rows[0]))
            svc.pump()
        svc.stop()
        assert h.outcome == "error"
        with pytest.raises(Exception):
            h.result(timeout=1)
        s = svc.stats()
        assert s["errors"] == 1 and s["outstanding"] == 0


# ------------------------------------------------------------ deadline serving
class TestServiceDeadlines:
    def setup_method(self):
        tm.REGISTRY.reset_metrics_for_tests()

    def test_queued_expiry_is_shed_not_executed(self, score_fn, rows):
        clk = VirtualClock()
        svc = ScoringService(
            score_fn,
            ServiceConfig(
                workers=0, max_queue_rows=64, max_batch_rows=8,
                default_deadline=0.050,
            ),
            clock=clk,
        )
        svc.start()
        h = svc.submit(dict(rows[0]))
        clk.advance(0.100)  # budget expires while queued
        h2 = svc.submit(dict(rows[1]))
        while svc.pump():
            pass
        svc.stop()
        assert h.outcome == "deadline_exceeded"
        with pytest.raises(DeadlineExceeded):
            h.result(timeout=1)
        assert h2.outcome == "completed"
        s = svc.stats()
        assert s["shed"]["deadline_exceeded"] == 1
        assert s["admitted"] == s["completed"] + sum(s["shed"].values())

    def test_admission_rejects_budget_below_pipeline_p95(self, score_fn, rows):
        h = tm.REGISTRY.histogram(
            "tptpu_serve_seconds", labels={"stage": "dispatch"}
        )
        for _ in range(50):
            h.observe(0.200)
        clk = VirtualClock()
        svc = ScoringService(
            score_fn, ServiceConfig(workers=0, default_deadline=0.010),
            clock=clk,
        )
        svc.start()
        with pytest.raises(DeadlineExceeded):
            svc.submit(dict(rows[0]))
        svc.stop()
        assert svc.stats()["rejected"]["deadline"] == 1

    def test_slow_stage_chaos_burns_budget_mid_execution(self, score_fn, rows):
        """slow_stage simulated seconds consume the active budget, so the
        dispatch-family checkpoint rejects the batch DURING execution —
        without one real sleep."""
        clk = VirtualClock()
        svc = ScoringService(
            score_fn,
            ServiceConfig(
                workers=0, max_batch_rows=8, default_deadline=0.100,
            ),
            clock=clk,
        )
        svc.start()
        plan = FaultPlan(seed=2).slow_stage(delay=0.500)
        with installed(plan):
            hd = svc.submit(dict(rows[0]))
            svc.pump()
        svc.stop()
        assert hd.outcome == "deadline_exceeded"
        assert svc.stats()["shed"]["deadline_exceeded"] == 1
        assert ("slow", plan.fired[0][1]) in plan.fired

    def test_mid_execution_deadline_sheds_only_the_spent_member(
        self, score_fn, rows
    ):
        """Co-batched requests carry their OWN deadline outcomes: when the
        tightest member's budget trips a checkpoint mid-execution, members
        that never asked for a deadline still complete (re-executed
        without the tripped member)."""
        clk = VirtualClock()
        svc = ScoringService(
            score_fn, ServiceConfig(workers=0, max_batch_rows=8), clock=clk
        )
        svc.start()
        plan = FaultPlan(seed=7).slow_stage(delay=0.500)
        with installed(plan):
            tight = svc.submit(dict(rows[0]), deadline=0.100)
            loose = svc.submit(dict(rows[1]))  # no deadline at all
            svc.pump()
            while svc.pump():
                pass
        svc.stop()
        assert tight.outcome == "deadline_exceeded"
        assert loose.outcome == "completed"
        assert loose.result(timeout=1) is not None
        s = svc.stats()
        assert s["shed"]["deadline_exceeded"] == 1 and s["completed"] == 1


# ------------------------------------------------- backpressure and shedding
class TestServiceBackpressure:
    #: thresholds pushed above any reachable load so the queue bound, not
    #: the shed tiers, is the limit under test
    NO_SHED = ShedConfig(
        explain_enter=2.0, explain_exit=1.0,
        detail_enter=3.0, detail_exit=2.0, drift_enter=5.0, drift_exit=4.0,
        reject_enter=9.0, reject_exit=8.0,
    )

    def test_queue_full_typed_rejection(self, score_fn, rows):
        clk = VirtualClock()
        svc = ScoringService(
            score_fn,
            ServiceConfig(
                workers=0, max_queue_rows=4, max_batch_rows=4,
                shed=self.NO_SHED,
            ),
            clock=clk,
        )
        svc.start()
        for i in range(4):
            svc.submit(dict(rows[i]))
        with pytest.raises(RejectedByAdmission) as ei:
            svc.submit(dict(rows[4]))
        assert ei.value.reason == "queue_full"
        while svc.pump():
            pass
        svc.stop()
        s = svc.stats()
        assert s["rejected"]["queue_full"] == 1 and s["completed"] == 4

    def test_reject_tier_refuses_then_readmits(self, score_fn, rows):
        clk = VirtualClock()
        svc = ScoringService(
            score_fn,
            ServiceConfig(
                workers=0, max_queue_rows=10, max_batch_rows=4,
                shed=ShedConfig(
                    explain_enter=0.25, explain_exit=0.15,
                    detail_enter=0.30, detail_exit=0.20,
                    drift_enter=0.50, drift_exit=0.35,
                    reject_enter=0.85, reject_exit=0.50,
                ),
            ),
            clock=clk,
        )
        svc.start()
        for i in range(9):  # up to load 0.8 at the last admission check
            svc.submit(dict(rows[i]))
        with pytest.raises(RejectedByAdmission) as ei:
            svc.submit(dict(rows[9]))
        assert ei.value.reason == "shedding"
        assert svc.shedder.tier == 4
        # drain below reject_exit: admissions resume (hysteresis honored)
        while svc.pump():
            pass
        assert svc.shedder.tier == 0
        h = svc.submit(dict(rows[9]))
        while svc.pump():
            pass
        svc.stop()
        assert h.outcome == "completed"
        s = svc.stats()
        assert s["rejected"]["shedding"] == 1
        assert s["shedding"]["tierEntries"]["reject"] >= 1

    def test_drift_observation_shed_at_tier_three(self, trained, rows):
        _, model = trained
        fn = score_function(model)
        if not fn.drift.enabled:
            pytest.skip("model carries no serving profiles")
        before = fn.drift.rows_observed
        sh = LoadShedder(ShedConfig(), capacity=100)
        sh.update(75, 0, 0.0)  # tier 3: drift shed process-wide
        try:
            fn.batch([dict(rows[0])])
            assert fn.drift.rows_observed == before  # observation skipped
        finally:
            sh.reset()
        fn.batch([dict(rows[0])])
        assert fn.drift.rows_observed == before + 1  # restored


# ----------------------------------------------------------- open-loop chaos
class TestChaosLoadtest:
    def test_reports_are_seed_deterministic(self, score_fn, rows):
        kw = dict(
            rate=100.0, duration=1.0, seed=9,
            service_time=lambda n: 0.004,
            config=ServiceConfig(max_queue_rows=64, max_batch_rows=16),
        )
        a = run_loadtest(score_fn, rows, **kw)
        b = run_loadtest(score_fn, rows, **kw)
        assert a == b
        assert a["reconciled"] and a["completed"] > 0

    def test_burst_windows_multiply_arrivals(self):
        plan = FaultPlan(seed=0).burst_arrivals(
            start=0.5, duration=0.5, multiplier=4.0
        )
        flat = LoadSchedule(rate=100.0, duration=1.0, seed=0).arrivals()
        burst = LoadSchedule(rate=100.0, duration=1.0, seed=0).arrivals(plan)
        assert len(flat) == pytest.approx(100, abs=2)
        assert len(burst) == pytest.approx(250, abs=5)
        assert ("burst", "t=0.5") in plan.fired

    def test_overload_sheds_but_goodput_stays_positive(self, score_fn, rows):
        """Open-loop overload: the service costs more virtual time per
        batch than the arrival gaps provide, so queue pressure builds;
        healthy requests keep completing while the excess sheds with typed
        outcomes, and every counter reconciles."""
        rep = run_loadtest(
            score_fn, rows, rate=400.0, duration=1.5, seed=4,
            deadline=0.100, service_time=lambda n: 0.030,
            config=ServiceConfig(max_queue_rows=32, max_batch_rows=8),
        )
        assert rep["completed"] > 0 and rep["goodput_rows_per_s"] > 0
        assert rep["shed_total"] + rep["rejected_total"] > 0
        assert rep["shed_rate"] > 0
        assert rep["reconciled"]
        # typed taxonomy: everything shed/rejected has a named bucket
        assert sum(rep["shed"].values()) == rep["shed_total"]
        assert sum(rep["rejected"].values()) == rep["rejected_total"]

    def test_full_chaos_soak(self, score_fn, rows):
        """The acceptance-criteria soak: slow_stage + burst_arrivals +
        stage-failure storms against the standing service. Healthy goodput
        stays positive, p99 stays bounded by the deadline ceiling, every
        shed is typed, counters reconcile, and the service quiesces."""
        threads_before = {
            t.name for t in threading.enumerate() if t.is_alive()
        }
        plan = (
            FaultPlan(seed=13)
            .slow_stage(delay=0.020, times=40)
            .burst_arrivals(start=0.3, duration=0.4, multiplier=6.0)
            .fail_stage_transform(target="modelSelector", times=5)
        )
        with installed(plan):
            rep = run_loadtest(
                score_fn, rows, rate=150.0, duration=1.5, seed=13,
                deadline=0.250, service_time=lambda n: 0.010,
                config=ServiceConfig(max_queue_rows=48, max_batch_rows=8),
                plan=plan,
            )
        # graceful degradation, not collapse
        assert rep["completed"] > 0 and rep["goodput_rows_per_s"] > 0
        assert rep["reconciled"]
        # bounded p99: a completed request can never exceed its deadline
        # budget by more than one batch's service cost
        # a completed request's latency is capped at its deadline budget
        # plus one batch's worst cost (0.010 base + 4 slow-stage hits of
        # 0.020 simulated each) — beyond that the checkpoints shed it
        assert rep["latency_ms"]["p99"] is not None
        assert rep["latency_ms"]["p99"] <= 250.0 + 10.0 + 4 * 20.0 + 1.0
        # the storms actually fired
        fired_kinds = {k for k, _ in plan.fired}
        assert {"slow", "burst", "transform"} <= fired_kinds
        # chaos produced typed degradation somewhere (shed, rejection,
        # quarantine, or a contained error) — never an untyped loss
        degraded = (
            rep["shed_total"] + rep["rejected_total"]
            + rep["quarantined"] + rep["errors"]
        )
        assert degraded > 0
        # quiesced: no service threads leaked, queue drained
        leaked = {
            t.name for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("tptpu-serve-")
        } - threads_before
        assert not leaked
        assert rep["max_queue_depth_rows"] <= 48

    def test_soak_is_deterministic_with_the_same_plan_seed(
        self, score_fn, rows
    ):
        def once():
            plan = (
                FaultPlan(seed=21)
                .slow_stage(delay=0.015, times=20)
                .burst_arrivals(start=0.2, duration=0.3, multiplier=5.0)
                .fail_stage_transform(target="modelSelector", times=3)
            )
            with installed(plan):
                return run_loadtest(
                    score_fn, rows, rate=120.0, duration=1.0, seed=21,
                    deadline=0.200, service_time=lambda n: 0.008,
                    config=ServiceConfig(
                        max_queue_rows=32, max_batch_rows=8
                    ),
                    plan=plan,
                )

        assert once() == once()

    def test_loadtest_uses_no_real_sleeps(self, score_fn, rows):
        import time as _time

        t0 = _time.perf_counter()
        rep = run_loadtest(
            score_fn, rows, rate=200.0, duration=5.0, seed=3,
            service_time=lambda n: 0.004,
            config=ServiceConfig(max_queue_rows=64, max_batch_rows=32),
        )
        wall = _time.perf_counter() - t0
        assert rep["virtual_end_s"] >= 5.0
        # 5 virtual seconds of traffic must cost nowhere near 5 real ones
        # (scoring ~1000 rows on CPU dominates; sleeping would add 5 s+)
        assert wall < 4.0


# ------------------------------------------------- thread-safety hammer suite
class TestThreadSafetyHammers:
    N_THREADS = 8

    def _hammer(self, fn, per_thread=200):
        errs = []
        barrier = threading.Barrier(self.N_THREADS)

        def run():
            barrier.wait()
            try:
                for _ in range(per_thread):
                    fn()
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [
            threading.Thread(target=run) for _ in range(self.N_THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs

    def test_schema_sentinel_counters_exact_under_hammer(self):
        ds = _binary_ds(8)
        resp, preds = from_dataset(ds, response="label")
        s = SchemaSentinel([resp, *preds])
        self._hammer(lambda: s.check_row({"x1": "zzz", "x2": 1.0}))
        stats = s.stats()
        total = self.N_THREADS * 200
        assert stats["rowsSeen"] == total
        assert stats["violations"]["unparseable"] == total
        assert stats["byFeature"]["x1"] == total

    def test_quarantine_log_totals_exact_and_batches_thread_local(self):
        qlog = QuarantineLog(keep=50)
        counter = {"i": 0}
        lock = threading.Lock()

        def add():
            with lock:
                counter["i"] += 1
                i = counter["i"]
            qlog.start_batch()
            qlog.add(QuarantineRecord(i, "x1", "stage", "boom"))
            qlog.add(QuarantineRecord(i, "x2", "stage", "boom"))  # same row
            assert qlog.batch_rows() == {i}  # this thread's batch only
            assert len(qlog.last) == 2

        self._hammer(add, per_thread=100)
        stats = qlog.stats()
        total = self.N_THREADS * 100
        assert stats["quarantinedRows"] == total
        assert stats["records"] == 2 * total
        assert stats["byKind"]["stage"] == 2 * total
        assert len(qlog.records) == 50  # ring bound holds

    def test_score_guard_counts_exact_under_hammer(self, trained):
        _, model = trained
        guard = ScoreGuard()

        class Stage:
            output_name = "out"
            uid = "Stage_000000000001"

        stage = Stage()
        from transmogrifai_tpu.types.columns import NumericColumn

        # a PRESENT NaN (the codec masks NaNs out, so build it directly)
        col = NumericColumn(
            T.Real, np.array([np.nan, 1.0]), np.array([True, True])
        )
        self._hammer(
            lambda: guard.apply(stage, col, is_result=True, num_rows=2),
            per_thread=100,
        )
        assert guard.stats()["guardedRows"] == self.N_THREADS * 100

    def test_breaker_transitions_consistent_under_hammer(self):
        clk = FakeClock()
        br = CircuitBreaker(
            "s", BreakerConfig(failure_threshold=3, clock=clk)
        )

        def step():
            if br.allow():
                br.record_failure()

        self._hammer(step, per_thread=100)
        st = br.stats()
        assert st["state"] == "open"
        # every thread observed a consistent machine: exactly one
        # closed->open transition, no lost counts
        assert st["transitions"] == {"closed->open": 1}
        assert (
            st["shortCircuits"]
            == self.N_THREADS * 100 - st["consecutiveFailures"]
        )

    def test_half_open_admits_exactly_one_concurrent_probe(self):
        clk = FakeClock()
        br = CircuitBreaker(
            "s", BreakerConfig(failure_threshold=1, recovery_time=1.0,
                               clock=clk)
        )
        br.allow()
        br.record_failure()
        assert br.state == "open"
        clk.now = 2.0  # recovery window elapsed: next allow() half-opens
        results = []
        barrier = threading.Barrier(self.N_THREADS)

        def probe():
            barrier.wait()
            results.append(br.allow())

        threads = [
            threading.Thread(target=probe) for _ in range(self.N_THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results.count(True) == 1  # exactly one probe passes
        assert br.state == "half_open"
        # the losing racers were counted as short circuits
        assert br.short_circuits >= self.N_THREADS - 1
        # probe succeeds: breaker closes and normal traffic resumes
        br.record_success()
        assert br.state == "closed"
        assert all(br.allow() for _ in range(4))

    def test_release_probe_unwedges_an_abandoned_half_open_probe(self):
        """An exception that unwinds between allow() and the outcome
        record (deadline rejection, guard escalation) must release the
        probe slot — otherwise the breaker wedges half-open forever."""
        clk = FakeClock()
        br = CircuitBreaker(
            "s", BreakerConfig(failure_threshold=1, recovery_time=1.0,
                               clock=clk)
        )
        br.allow()
        br.record_failure()
        clk.now = 2.0
        assert br.allow()          # probe claimed
        assert not br.allow()      # slot taken
        br.release_probe()         # the claimant unwound exceptionally
        assert br.allow()          # next caller can probe again
        br.record_success()
        assert br.state == "closed"

    def test_failed_probe_reopens_and_next_window_reprobes(self):
        clk = FakeClock()
        br = CircuitBreaker(
            "s", BreakerConfig(failure_threshold=1, recovery_time=1.0,
                               clock=clk)
        )
        br.allow()
        br.record_failure()
        clk.now = 1.5
        assert br.allow()          # the probe
        assert not br.allow()      # concurrent caller: short circuit
        br.record_failure()        # probe failed: re-open
        assert br.state == "open"
        assert not br.allow()
        clk.now = 3.0              # a fresh window: probe again
        assert br.allow()
        br.record_success()
        assert br.state == "closed"

    def test_concurrent_scoring_through_one_closure(self, trained, rows):
        """The re-entrant seam: N threads score through ONE closure while
        another thread reads metadata(); counters stay exact and no read
        tears."""
        _, model = trained
        fn = score_function(model)
        self._hammer(lambda: fn.batch([dict(rows[0]), {"x1": "zzz"}]),
                     per_thread=25)
        stats = fn.quarantine.stats()
        total = self.N_THREADS * 25
        assert stats["quarantinedRows"] == total
        assert fn.sentinel.stats()["rowsSeen"] == 2 * total
        md = fn.metadata()
        assert md["quarantine"]["quarantinedRows"] == total

    def test_metadata_consistent_while_scoring_concurrently(
        self, trained, rows
    ):
        _, model = trained
        fn = score_function(model)
        stop = threading.Event()
        errs = []

        def reader():
            while not stop.is_set():
                md = fn.metadata()
                drift = md["drift"]
                if drift["enabled"]:
                    for f in drift["features"].values():
                        rows_ = f.get("rows")
                        if rows_ is not None and rows_ < 0:
                            errs.append("negative rows")

        th = threading.Thread(target=reader)
        th.start()
        try:
            self._hammer(lambda: fn.batch([dict(rows[0])]), per_thread=30)
        finally:
            stop.set()
            th.join()
        assert not errs


# ------------------------------------------------------------- observability
class TestServiceObservability:
    def test_service_source_in_prometheus_export(self, score_fn, rows):
        from transmogrifai_tpu.telemetry.export import render_prometheus

        clk = VirtualClock()
        svc = ScoringService(
            score_fn, ServiceConfig(workers=0, max_batch_rows=8), clock=clk
        )
        svc.start()
        for r in rows[:4]:
            svc.submit(dict(r))
        while svc.pump():
            pass
        text = render_prometheus()
        assert "tptpu_service_admitted" in text
        assert "tptpu_serve_queue_depth" in text
        svc.stop()

    def test_render_prometheus_never_deadlocks_against_submit(
        self, score_fn, rows
    ):
        # regression: the 'service' exposition source takes the service
        # lock (stats()) while submit() holds it around the queue-depth
        # gauge set (registry lock) — render_prometheus() must run its
        # sources OUTSIDE the registry lock or the two directions are an
        # ABBA deadlock. Daemon threads + join timeout = the alarm.
        from transmogrifai_tpu.telemetry.export import render_prometheus

        clk = VirtualClock()
        svc = ScoringService(
            score_fn,
            ServiceConfig(workers=0, max_queue_rows=100_000),
            clock=clk,
        )
        svc.start()
        barrier = threading.Barrier(2)
        errors: list[BaseException] = []

        def _submit():
            barrier.wait()
            for _ in range(300):
                try:
                    svc.submit(dict(rows[0]))
                except BaseException as e:  # pragma: no cover
                    errors.append(e)

        def _render():
            barrier.wait()
            for _ in range(300):
                render_prometheus()

        threads = [
            threading.Thread(target=_submit, daemon=True),
            threading.Thread(target=_render, daemon=True),
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30.0)
        hung = [th.name for th in threads if th.is_alive()]
        assert not hung, f"deadlock: {hung} never finished"
        assert not errors
        svc.stop()

    def test_serve_queue_span_recorded_per_batch(self, score_fn, rows):
        tspans.reset_for_tests()
        clk = VirtualClock()
        svc = ScoringService(
            score_fn, ServiceConfig(workers=0, max_batch_rows=8), clock=clk
        )
        svc.start()
        for r in rows[:4]:
            svc.submit(dict(r))
        clk.advance(0.005)
        while svc.pump():
            pass
        svc.stop()
        names = [e["name"] for e in tspans.snapshot_events()]
        assert "serve/queue" in names

    def test_shed_and_reject_counters_reconcile_with_events(
        self, score_fn, rows
    ):
        clk = VirtualClock()
        svc = ScoringService(
            score_fn,
            ServiceConfig(
                workers=0, max_queue_rows=4, max_batch_rows=4,
                default_deadline=0.050,
                shed=TestServiceBackpressure.NO_SHED,
            ),
            clock=clk,
        )
        svc.start()
        svc.submit(dict(rows[0]))
        clk.advance(0.100)  # expire it in queue
        for i in range(1, 4):
            svc.submit(dict(rows[i]))
        with pytest.raises(RejectedByAdmission):
            svc.submit(dict(rows[4]))  # queue_full
        while svc.pump():
            pass
        svc.stop()
        s = svc.stats()
        assert s["shed"]["deadline_exceeded"] == 1
        assert s["rejected"]["queue_full"] == 1
        assert s["admitted"] == (
            s["completed"] + s["quarantined"] + s["errors"]
            + sum(s["shed"].values())
        )
