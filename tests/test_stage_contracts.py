"""Stage contract specs — the generic per-stage test layer.

Parity: the reference ships OpTransformerSpec / OpEstimatorSpec /
OpPipelineStageSpec in MAIN source (features/.../test/OpTransformerSpec.
scala:1-184) so every stage gets uid / params-round-trip / row-vs-columnar
consistency / persistence contracts for free. This module applies the same
contracts to EVERY registered stage class that is constructible with
defaults, via the persistence registry.
"""
import inspect
import json

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu import testkit as tk
from transmogrifai_tpu.stages.base import Estimator, Model, PipelineStage, Transformer
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.utils import uid as uid_util
from transmogrifai_tpu.workflow.persistence import _registry, construct_stage


def _all_stage_classes() -> list[type]:
    out = []
    for name, cls in sorted(_registry().items()):
        if inspect.isabstract(cls):
            continue
        out.append(cls)
    return out


def _default_constructible(cls) -> PipelineStage | None:
    try:
        return cls()
    except Exception:
        return None


CONSTRUCTIBLE = [
    c for c in _all_stage_classes() if _default_constructible(c) is not None
]


def test_registry_covers_a_real_stage_surface():
    # the registry is the persistence surface: a shrink here means stages
    # silently fell out of the load path
    assert len(_all_stage_classes()) >= 100
    assert len(CONSTRUCTIBLE) >= 45


@pytest.mark.parametrize(
    "cls", CONSTRUCTIBLE, ids=lambda c: c.__name__
)
def test_uid_contract(cls):
    """Fresh instances get distinct uids carrying the class marker
    (OpPipelineStageSpec 'uid' contract)."""
    a, b = cls(), cls()
    assert a.uid != b.uid
    assert isinstance(a.uid, str) and len(a.uid) > 0


@pytest.mark.parametrize(
    "cls", CONSTRUCTIBLE, ids=lambda c: c.__name__
)
def test_params_json_round_trip(cls):
    """get_params must be JSON-serializable and reconstruct an equal stage
    through the persistence path (OpPipelineStageReaderWriter contract)."""
    stage = cls()
    params = stage.get_params()
    assert isinstance(params, dict)
    blob = json.dumps(params, default=str)
    params2 = json.loads(blob)
    try:
        rebuilt = construct_stage(cls.__name__, stage.get_params(), {})
    except Exception as e:
        pytest.skip(f"{cls.__name__} needs fitted arrays to rebuild: {e}")
    assert type(rebuilt) is cls
    # params survive the round trip (order-insensitive, str-normalized)
    p1 = json.loads(json.dumps(stage.get_params(), default=str))
    p2 = json.loads(json.dumps(rebuilt.get_params(), default=str))
    assert p1 == p2


# ---------------------------------------------------------------------------
# row-vs-columnar consistency: transform_row must equal transform_columns
# on every row (OpTransformerSpec's core contract). Exercised for every
# default-constructible Transformer whose declared input_types we can
# generate with the testkit.
# ---------------------------------------------------------------------------
def _generator_for(ftype: type):
    storage = getattr(ftype, "storage", None)
    name = ftype.__name__
    if name in ("Text", "TextArea", "PickList", "ComboBox", "ID", "Base64",
                "URL", "Email", "Phone", "State", "Country", "City",
                "PostalCode", "Street"):
        return tk.RandomText.strings(3, 12, ftype=ftype, seed=7).with_probability_of_empty(0.2)
    if name in ("Real", "RealNN", "Currency", "Percent"):
        g = tk.RandomReal.normal(0.0, 2.0, ftype=ftype, seed=7)
        return g if name == "RealNN" else g.with_probability_of_empty(0.2)
    if name in ("Integral", "Date", "DateTime"):
        return tk.RandomIntegral.integers(0, 10_000, ftype=ftype, seed=7).with_probability_of_empty(0.2)
    if name == "Binary":
        return tk.RandomBinary.of(0.5, seed=7).with_probability_of_empty(0.2)
    if name == "OPVector":
        return tk.RandomVector.dense(4, seed=7)
    if name in ("TextList", "DateList", "DateTimeList", "Geolocation"):
        return None  # list stages have dedicated tests
    if storage is not None and "Map" in name:
        return None  # map stages have dedicated tests
    return None


def _consistency_cases():
    cases = []
    for cls in CONSTRUCTIBLE:
        stage = _default_constructible(cls)
        if not isinstance(stage, Transformer) or isinstance(stage, Model):
            continue
        in_types = getattr(stage, "input_types", None)
        if not in_types:
            continue
        gens = [_generator_for(t) for t in in_types]
        if any(g is None for g in gens):
            continue
        cases.append((cls, tuple(in_types)))
    return cases


@pytest.mark.parametrize(
    "cls,in_types", _consistency_cases(), ids=lambda v: getattr(v, "__name__", "")
)
def test_row_vs_columnar_consistency(cls, in_types):
    uid_util.reset()
    stage = cls()
    n = 24
    cols = []
    for j, t in enumerate(in_types):
        gen = _generator_for(t).with_seed(100 + j)
        cols.append(gen.to_column(n))
    try:
        out_col = stage.transform_columns(*cols, num_rows=n)
    except Exception as e:
        pytest.skip(f"{cls.__name__} not applicable to generated data: {e}")
    col_vals = out_col.to_list()

    class _F:  # minimal feature stand-in for transform_row
        def __init__(self, name, ftype):
            self.name = name
            self.ftype = ftype

    stage.input_features = tuple(
        _F(f"in{j}", t) for j, t in enumerate(in_types)
    )
    for i in range(n):
        row = {
            f"in{j}": column_from_values(t, [cols[j].to_list()[i]])
            for j, t in enumerate(in_types)
        }
        row_val = stage.transform_row(row)
        cv = col_vals[i]
        if isinstance(cv, float) and isinstance(row_val, float):
            assert (np.isnan(cv) and np.isnan(row_val)) or cv == pytest.approx(row_val)
        elif isinstance(cv, np.ndarray):
            np.testing.assert_allclose(cv, np.asarray(row_val), rtol=1e-6)
        else:
            assert cv == row_val, f"{cls.__name__} row {i}: {cv!r} != {row_val!r}"
