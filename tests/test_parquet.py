"""Parquet/Arrow data plane (readers/.../DataReaders.scala:116 parquetCase;
RichDataset save/load round-trip, RichDataset.scala:201-330)."""
import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.readers import (
    DataReaders,
    dataset_from_arrow,
    infer_parquet_dataset,
    read_parquet,
    write_parquet,
)
from transmogrifai_tpu.types.columns import (
    MapColumn,
    NumericColumn,
    TextColumn,
    column_from_values,
)


def _sample_table():
    return pa.table(
        {
            "age": pa.array([22.0, None, 38.0], type=pa.float64()),
            "siblings": pa.array([1, 0, None], type=pa.int64()),
            "survived": pa.array([True, False, True], type=pa.bool_()),
            "name": pa.array(["Braund", None, "Heikkinen"], type=pa.string()),
            "joined": pa.array(
                [1_600_000_000_000, None, 1_600_000_500_000], type=pa.int64()
            ),
        }
    )


def test_arrow_schema_directed_typing():
    ds = dataset_from_arrow(
        _sample_table(), type_overrides={"joined": T.DateTime}
    )
    assert ds["age"].feature_type is T.Real
    assert ds["siblings"].feature_type is T.Integral
    assert ds["survived"].feature_type is T.Binary
    assert ds["name"].feature_type is T.Text
    assert ds["joined"].feature_type is T.DateTime
    assert isinstance(ds["age"], NumericColumn)
    np.testing.assert_array_equal(ds["age"].mask, [True, False, True])
    np.testing.assert_array_equal(ds["siblings"].mask, [True, True, False])
    assert ds["age"].values[0] == 22.0
    assert ds["name"].to_list() == ["Braund", None, "Heikkinen"]


def test_timestamp_and_date_normalize_to_epoch_millis():
    import datetime

    table = pa.table(
        {
            "ts": pa.array(
                [datetime.datetime(2020, 1, 1), None], type=pa.timestamp("us")
            ),
            "d": pa.array([datetime.date(2020, 1, 1), None], type=pa.date32()),
        }
    )
    ds = dataset_from_arrow(table)
    assert ds["ts"].feature_type is T.DateTime
    assert ds["d"].feature_type is T.Date
    expected_ms = 1_577_836_800_000  # 2020-01-01T00:00:00Z
    assert int(ds["ts"].values[0]) == expected_ms
    assert int(ds["d"].values[0]) == expected_ms
    assert not ds["ts"].mask[1] and not ds["d"].mask[1]


def test_parquet_round_trip_preserves_feature_types(tmp_path):
    cols = {
        "x": column_from_values(T.Currency, [1.5, None, 3.25]),
        "k": column_from_values(T.PickList, ["a", "b", None]),
        "m": MapColumn(T.RealMap, [{"u": 1.0}, {}, {"v": 2.0}]),
        "tags": column_from_values(T.TextList, [["a", "b"], [], ["c"]]),
    }
    ds = Dataset.of(cols)
    path = str(tmp_path / "ds.parquet")
    write_parquet(ds, path)
    back = read_parquet(path)
    # stamped feature types survive the round trip (not just arrow types)
    assert back["x"].feature_type is T.Currency
    assert back["k"].feature_type is T.PickList
    assert back["m"].feature_type is T.RealMap
    assert back["tags"].feature_type is T.TextList
    assert back["x"].to_list() == [1.5, None, 3.25]
    assert back["m"].to_list()[0] == {"u": 1.0}
    assert back["tags"].to_list()[0] == ["a", "b"]
    # empty containers survive as empty, not missing
    assert back["m"].to_list()[1] == {}
    assert back["tags"].to_list()[1] == []


def test_parquet_reader_feeds_workflow(tmp_path):
    import pyarrow.parquet as pq

    path = str(tmp_path / "t.parquet")
    pq.write_table(_sample_table(), path)
    ds = infer_parquet_dataset(path)
    resp, preds = from_dataset(ds, response="survived")
    assert resp.name == "survived"
    assert {p.name for p in preds} == {"age", "siblings", "name", "joined"}


def test_datareaders_catalog_names():
    # the reference's factory surface resolves
    assert DataReaders.Simple.csv and DataReaders.Simple.parquet
    assert DataReaders.Aggregate.records and DataReaders.Conditional.records
    r = DataReaders.Simple.records([{"a": 1}], key_fn=lambda r: "k")
    assert list(r.read_records()) == [{"a": 1}]


def test_parquet_record_reader(tmp_path):
    import pyarrow.parquet as pq

    path = str(tmp_path / "t.parquet")
    pq.write_table(_sample_table(), path)
    recs = list(DataReaders.Simple.parquet(path).read_records())
    assert recs[0]["name"] == "Braund"
    assert recs[1]["age"] is None
