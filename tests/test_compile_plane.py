"""Compile-plane suite (transmogrifai_tpu/compiler/ + utils/aot.py):
persistent executable cache (fresh-process hits, corruption fallback,
version invalidation), cross-candidate program dedup + lane buckets,
async warmup, donated dispatch twins, and the compileStats ledger
surfaced in selector summaries and scoring metadata.
"""
import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from transmogrifai_tpu.compiler import bucketing, dispatch
from transmogrifai_tpu.compiler import stats as cstats
from transmogrifai_tpu.compiler import warmup
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.utils import aot


# ------------------------------------------------------------------- ledger
class TestCompileStatsLedger:
    def test_record_and_delta(self):
        s = cstats.CompileStats()
        s.record_compile("prog_a")
        s.record_compile("prog_a")
        s.bump("cacheHitsDisk")
        s.record_sweep(lanes=6, padded=2)
        snap = s.snapshot()
        assert snap["programsCompiled"] == 2
        assert snap["programsCompiledByName"] == {"prog_a": 2}
        assert snap["dedupHits"] == 5
        assert snap["laneBucketPads"] == 2
        assert snap["bucketedSweeps"] == 1
        assert snap["compileCacheHitRate"] == pytest.approx(1 / 3, abs=1e-3)

    def test_global_delta_isolates_a_phase(self):
        before = cstats.snapshot()
        cstats.stats().record_compile("prog_delta_test")
        d = cstats.delta(before)
        assert d["programsCompiled"] == 1
        assert d["programsCompiledByName"] == {"prog_delta_test": 1}

    def test_warmup_overlap_accumulates(self):
        s = cstats.CompileStats()
        s.record_warmup(3, 0.5)
        s.record_warmup(1, 0.25)
        snap = s.snapshot()
        assert snap["warmupPrograms"] == 4
        assert snap["warmupOverlapSeconds"] == pytest.approx(0.75)


# ------------------------------------------------------------- lane buckets
class TestLaneBuckets:
    def test_bucket_values(self):
        assert bucketing.lane_bucket(1) == 1
        assert bucketing.lane_bucket(2) == 2
        assert bucketing.lane_bucket(3) == 4
        assert bucketing.lane_bucket(24) == 32
        assert bucketing.lane_bucket(64) == 64
        assert bucketing.lane_bucket(65) == 96  # multiples of 32 past 64
        assert bucketing.lane_bucket(97) == 128

    def test_env_disable(self, monkeypatch):
        monkeypatch.setenv("TPTPU_LANE_BUCKETS", "0")
        assert bucketing.lane_bucket(24) == 24

    def test_pad_replicates_lane_zero(self):
        a = np.arange(6, dtype=np.float32).reshape(3, 2)
        b = np.asarray([1.0, 2.0, 3.0], np.float32)
        pa, pb = bucketing.pad_lane_arrays(4, a, b)
        assert pa.shape == (4, 2) and pb.shape == (4,)
        np.testing.assert_array_equal(pa[3], a[0])
        assert pb[3] == b[0]
        # no-op when already at the bucket
        (same,) = bucketing.pad_lane_arrays(3, a)
        assert same is a


# ------------------------------------------------- dedup / padding parity
def _sweep_data(seed=0, n=97, d=11):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    return x, y


class TestCandidateDedup:
    def test_value_only_candidates_share_one_program(self):
        """Acceptance: >=4 value-only hyperparameter variants compile at
        most ONE program for the family, and the ledger records the shared
        lanes as dedup hits."""
        x, y = _sweep_data()
        est = LogisticRegression(max_iter=20)
        masks = [np.ones(len(y), np.float32)] * 2
        points = [{"reg_param": r} for r in (0.0, 0.01, 0.1, 0.3)]
        before = cstats.snapshot()
        models = est.fit_arrays_batched_masks(x, y, masks, points)
        d = cstats.delta(before)
        assert d["programsCompiledByName"].get(
            "logistic_binary_batched", 0
        ) <= 1
        assert d["dedupHits"] >= len(masks) * len(points) - 1
        assert models[0][0].weights.shape == (x.shape[1],)

    def test_dedup_is_bit_identical_across_lane_order(self):
        """Two value-only candidates share one executable; swapping their
        lane order reuses it (no new compile) and produces bit-identical
        fits — lanes are independent GEMM columns."""
        x, y = _sweep_data(seed=1)
        est = LogisticRegression(max_iter=20)
        mask = np.ones(len(y), np.float32)
        p1, p2 = {"reg_param": 0.01}, {"reg_param": 0.2}
        a = est.fit_arrays_batched_masks(x, y, [mask], [p1, p2])
        before = cstats.snapshot()
        b = est.fit_arrays_batched_masks(x, y, [mask], [p2, p1])
        d = cstats.delta(before)
        assert d["programsCompiled"] == 0  # shared executable
        assert d["cacheHitsMemory"] >= 1
        np.testing.assert_array_equal(a[0][0].weights, b[0][1].weights)
        np.testing.assert_array_equal(a[0][1].weights, b[0][0].weights)

    def test_padded_bucket_matches_unpadded(self, monkeypatch):
        """3 candidates pad onto the 4-lane bucket; the padded program's
        real lanes match the unpadded (TPTPU_LANE_BUCKETS=0) fits."""
        x, y = _sweep_data(seed=2)
        est = LogisticRegression(max_iter=20)
        mask = np.ones(len(y), np.float32)
        points = [{"reg_param": r} for r in (0.0, 0.05, 0.5)]
        before = cstats.snapshot()
        padded = est.fit_arrays_batched_masks(x, y, [mask], points)
        assert cstats.delta(before)["laneBucketPads"] == 1
        monkeypatch.setenv("TPTPU_LANE_BUCKETS", "0")
        plain = est.fit_arrays_batched_masks(x, y, [mask], points)
        for i in range(len(points)):
            np.testing.assert_allclose(
                padded[0][i].weights, plain[0][i].weights,
                rtol=1e-6, atol=1e-7,
            )
            np.testing.assert_allclose(
                padded[0][i].intercept, plain[0][i].intercept,
                rtol=1e-6, atol=1e-7,
            )

    def test_deduped_matches_sequential_fit(self):
        """The shared-program fit agrees with the undeduped sequential
        fit_arrays path (same solver, K=1 lane) to solver tolerance."""
        x, y = _sweep_data(seed=3)
        est = LogisticRegression(max_iter=40)
        mask = (np.random.default_rng(4).random(len(y)) > 0.2).astype(
            np.float32
        )
        points = [{"reg_param": 0.01}, {"reg_param": 0.1}]
        batched = est.fit_arrays_batched_masks(x, y, [mask], points)
        for i, p in enumerate(points):
            seq = est.with_params(**p).fit_arrays(x, y, mask)
            pb = x @ batched[0][i].weights + batched[0][i].intercept
            ps = x @ seq.weights + seq.intercept
            np.testing.assert_allclose(pb, ps, atol=1e-3)


# ------------------------------------------------------- persistent cache
@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("TPTPU_COMPILE_CACHE", str(tmp_path))
    return tmp_path


def _drain_saves():
    for th in list(aot._THREADS):
        th.join(timeout=30)


class TestPersistentCache:
    def test_blob_roundtrip_and_disk_hit(self, cache_dir):
        fn = jax.jit(lambda a: a * 3.0)
        args = (np.arange(5, dtype=np.float32),)
        before = cstats.snapshot()
        out = aot.aot_call("plane_rt_test", fn, args, {})
        np.testing.assert_allclose(np.asarray(out), args[0] * 3.0)
        _drain_saves()
        key = aot._key("plane_rt_test", args, {})
        path = aot._blob_path("plane_rt_test", key)
        assert os.path.exists(path)
        # evict the in-memory entry: the next call must load from disk
        with aot._LOCK:
            aot._MEM.pop(key, None)
        out2 = aot.aot_call("plane_rt_test", fn, args, {})
        np.testing.assert_allclose(np.asarray(out2), args[0] * 3.0)
        d = cstats.delta(before)
        assert d["programsCompiled"] >= 1
        assert d["cacheHitsDisk"] >= 1

    def test_garbage_blob_recompiles_and_counts(self, cache_dir):
        fn = jax.jit(lambda a: a + 1.0)
        args = (np.arange(4, dtype=np.float32),)
        key = aot._key("plane_corrupt_test", args, {})
        path = aot._blob_path("plane_corrupt_test", key)
        with open(path, "wb") as fh:
            fh.write(b"\x00garbage-not-a-pickle")
        before = cstats.snapshot()
        out = aot.aot_call("plane_corrupt_test", fn, args, {})
        np.testing.assert_allclose(np.asarray(out), args[0] + 1.0)
        d = cstats.delta(before)
        assert d["corruptBlobsDropped"] == 1
        assert d["programsCompiled"] == 1  # recompiled transparently

    def test_valid_pickle_wrong_payload_recompiles(self, cache_dir):
        fn = jax.jit(lambda a: a - 2.0)
        args = (np.arange(4, dtype=np.float32),)
        key = aot._key("plane_payload_test", args, {})
        path = aot._blob_path("plane_payload_test", key)
        with open(path, "wb") as fh:
            fh.write(pickle.dumps({"not": "an executable"}))
        before = cstats.snapshot()
        out = aot.aot_call("plane_payload_test", fn, args, {})
        np.testing.assert_allclose(np.asarray(out), args[0] - 2.0)
        assert cstats.delta(before)["corruptBlobsDropped"] == 1
        assert not os.path.exists(path) or os.path.getsize(path) > 100

    def test_version_mismatch_invalidation(self, cache_dir):
        """Blobs from another source version (different salt) are deleted
        on sight by prewarm and counted as invalidations."""
        d = aot._exec_dir()
        stale = os.path.join(d, f"{'0' * 16}-somename-{'1' * 24}.jaxexec")
        with open(stale, "wb") as fh:
            fh.write(b"stale-version-blob")
        legacy = os.path.join(d, "not-a-blob.jaxexec")  # unknown layout
        with open(legacy, "wb") as fh:
            fh.write(b"legacy")
        before = cstats.snapshot()
        aot.prewarm()
        assert not os.path.exists(stale)
        assert not os.path.exists(legacy)
        assert cstats.delta(before)["versionInvalidations"] == 2

    def test_prewarm_name_filter(self, cache_dir):
        """prewarm(names=...) loads only the named programs and leaves the
        rest banked on disk."""
        fn = jax.jit(lambda a: a * 5.0)
        args = (np.arange(3, dtype=np.float32),)
        aot.aot_call("plane_filter_keep", fn, args, {})
        fn2 = jax.jit(lambda a: a * 7.0)
        aot.aot_call("plane_filter_other", fn2, args, {})
        _drain_saves()
        k1 = aot._key("plane_filter_keep", args, {})
        k2 = aot._key("plane_filter_other", args, {})
        assert os.path.exists(aot._blob_path("plane_filter_keep", k1))
        assert os.path.exists(aot._blob_path("plane_filter_other", k2))
        with aot._LOCK:
            aot._MEM.pop(k1, None)
            aot._MEM.pop(k2, None)
        loaded = aot.prewarm(names={"plane_filter_keep"})
        assert loaded == 1
        with aot._LOCK:
            assert k1 in aot._MEM and k2 not in aot._MEM
        assert os.path.exists(aot._blob_path("plane_filter_other", k2))


# ------------------------------------------------------------------ warmup
class TestWarmup:
    def test_train_programs_maps_selector_families(self):
        from transmogrifai_tpu.selector import (
            BinaryClassificationModelSelector,
        )

        sel = BinaryClassificationModelSelector(seed=0)
        names = warmup.train_programs([sel])
        assert "logistic_binary_batched" in names
        assert "boost_chunk" in names       # XGB default candidate
        assert "forest_scan" in names       # RF default candidate
        assert "predict_boosted" in names   # winner's scoring program

    def test_unknown_family_warms_everything(self):
        class Weird:
            pass

        from transmogrifai_tpu.selector.model_selector import ModelSelector

        sel = ModelSelector.__new__(ModelSelector)
        sel.models = [(Weird(), {})]
        assert warmup.train_programs([sel]) is None

    def test_start_warmup_runs_once_per_scope(self, cache_dir):
        warmup.reset_for_tests()
        th = warmup.start_warmup(names=set(), scope="plane-test")
        assert th is not None
        th.join(timeout=30)
        assert warmup.start_warmup(names=set(), scope="plane-test") is None
        warmup.reset_for_tests()


# ---------------------------------------------------------------- dispatch
class TestDispatch:
    def test_prefetch_roundtrip(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        dispatch.prefetch_f32(arr)
        buf = dispatch.device_f32(arr)
        buf2 = dispatch.device_f32(arr)
        assert buf is buf2  # the prefetched buffer, not a fresh upload
        np.testing.assert_array_equal(np.asarray(buf), arr)

    def test_device_f32_fallback_without_prefetch(self):
        arr = np.arange(4, dtype=np.float64)
        out = dispatch.device_f32(arr)
        assert out.dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(out), arr)

    def test_donating_twin_matches_plain(self):
        def f(a, b, n):
            return a * n + b

        plain = jax.jit(f, static_argnames=("n",))
        twin = dispatch.donating(
            "plane_donate_test", plain, donate_argnums=(0,),
            static_argnames=("n",),
        )
        a = jnp.arange(4, dtype=jnp.float32)
        b = jnp.ones(4, dtype=jnp.float32)
        expect = np.asarray(plain(jnp.array(a), b, n=2))
        got = np.asarray(twin(a, b, n=2))
        np.testing.assert_array_equal(got, expect)

    def test_donation_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("TPTPU_DONATE", "0")
        plain = jax.jit(lambda a: a)
        assert dispatch.donating("plane_kill_test", plain, (0,)) is plain

    def test_boost_donation_changes_no_results(self, monkeypatch):
        """The donated boost-chunk twin fits bit-identical trees to the
        undonated path (donation is an aliasing property, not math)."""
        from transmogrifai_tpu.models import trees as TR

        rng = np.random.default_rng(0)
        x = rng.normal(size=(80, 5)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        thr = TR.quantile_thresholds(x, max_bins=8)
        binned = TR.bin_data(jnp.asarray(x), jnp.asarray(thr))
        mask = jnp.ones((1, len(y)), dtype=jnp.float32)

        def run():
            trees, margin = TR.fit_boosted_batched(
                binned, jnp.asarray(y), mask, num_rounds=3, max_depth=3,
                num_bins=8, eta=0.3, objective="binary:logistic",
            )
            return np.asarray(margin)

        donated = run()
        monkeypatch.setenv("TPTPU_DONATE", "0")
        # TPTPU_AOT=0 too: without it the second run would hit the first
        # run's in-memory program and never execute the undonated twin
        monkeypatch.setenv("TPTPU_AOT", "0")
        monkeypatch.setattr(dispatch, "_DONATED", {})
        plain = run()
        np.testing.assert_array_equal(donated, plain)


# ----------------------------------------------- fresh-process cache reuse
_CHILD_TRAIN = """
import json
import numpy as np
import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.workflow.workflow import Workflow

rng = np.random.default_rng(0)
n = 80
x1 = rng.normal(size=n)
x2 = rng.normal(size=n)
label = (x1 + 0.5 * x2 > 0).astype(float)
ds = Dataset.of({
    "label": column_from_values(T.RealNN, label),
    "x1": column_from_values(T.Real, x1),
    "x2": column_from_values(T.Real, x2),
})
resp, preds = from_dataset(ds, response="label")
vec = transmogrify(list(preds))
sel = BinaryClassificationModelSelector(
    seed=3, num_folds=2,
    models=[(LogisticRegression(), {"reg_param": [0.0, 0.01, 0.1, 0.3]})],
)
pred = sel.set_input(resp, vec).get_output()
model = Workflow().set_result_features(pred).set_input_dataset(ds).train()
print(json.dumps(model.summary_json()["modelSelectorSummary"]["compileStats"]))
"""


class TestFreshProcessCache:
    def test_second_fresh_process_compiles_strictly_fewer(self, tmp_path):
        """Acceptance: two fresh processes train against one shared
        persistent cache dir; the second deserializes banked executables
        (cache hits > 0) and compiles strictly fewer programs."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["TPTPU_COMPILE_CACHE"] = str(tmp_path)
        env.pop("XLA_FLAGS", None)  # single device: keep the sweep batched

        def run():
            p = subprocess.run(
                [sys.executable, "-c", _CHILD_TRAIN],
                capture_output=True, text=True, timeout=420, env=env,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )
            assert p.returncode == 0, p.stderr[-2000:]
            return json.loads(p.stdout.strip().splitlines()[-1])

        first = run()
        second = run()
        assert first["programsCompiled"] >= 1
        assert second["programsCompiled"] < first["programsCompiled"]
        hits = (
            second["cacheHitsDisk"] + second["cacheHitsMemory"]
            + second["warmupPrograms"]
        )
        assert hits > 0
        assert second["compileCacheHitRate"] == pytest.approx(1.0)


# --------------------------------------------------------- summary surface
class TestCompileStatsSurface:
    @pytest.fixture(scope="class")
    def trained(self):
        import transmogrifai_tpu.types as T
        from transmogrifai_tpu.dataset import Dataset
        from transmogrifai_tpu.features import from_dataset
        from transmogrifai_tpu.ops import transmogrify
        from transmogrifai_tpu.selector import (
            BinaryClassificationModelSelector,
        )
        from transmogrifai_tpu.types.columns import column_from_values
        from transmogrifai_tpu.utils import uid as uid_util
        from transmogrifai_tpu.workflow.workflow import Workflow

        uid_util.reset()
        rng = np.random.default_rng(5)
        n = 90
        x1 = rng.normal(size=n)
        label = (x1 > 0).astype(float)
        ds = Dataset.of({
            "label": column_from_values(T.RealNN, label),
            "x1": column_from_values(T.Real, x1),
            "x2": column_from_values(T.Real, rng.normal(size=n)),
        })
        resp, preds = from_dataset(ds, response="label")
        vec = transmogrify(list(preds))
        sel = BinaryClassificationModelSelector(
            seed=9, num_folds=2,
            models=[(LogisticRegression(), {"reg_param": [0.0, 0.1]})],
        )
        pred = sel.set_input(resp, vec).get_output()
        model = (
            Workflow().set_result_features(pred).set_input_dataset(ds).train()
        )
        return ds, pred, model

    def test_selector_summary_carries_compile_stats(self, trained):
        _ds, _pred, model = trained
        cs = model.summary_json()["modelSelectorSummary"]["compileStats"]
        assert "programsCompiled" in cs and "dedupHits" in cs
        assert cs["dedupHits"] >= 1  # 2 points x (2 folds + refit) lanes

    def test_summary_pretty_renders_compile_line(self, trained):
        _ds, _pred, model = trained
        assert "Compile plane:" in model.summary_pretty()

    def test_score_metadata_carries_compile_stats(self, trained):
        from transmogrifai_tpu.local.scoring import score_function

        ds, _pred, model = trained
        fn = score_function(model)
        fn(ds.rows()[0])
        md = fn.metadata()
        assert "compileStats" in md
        assert "programsCompiled" in md["compileStats"]
