"""POS tagger + NP chunker fixtures (OpenNLP pos-maxent/chunker
replacement — nlp/pos.py). Accuracy is measured over an authored gold
corpus and the floor pinned; tools/nlp_agreement.py reports the number."""
from transmogrifai_tpu.nlp.pos import chunk_noun_phrases, pos_tag

import importlib.util
import os

_TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "nlp_agreement.py",
)
_spec = importlib.util.spec_from_file_location("nlp_agreement_pos", _TOOL)
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)
measured_accuracy = _mod.eval_pos
GOLD = _mod.POS_GOLD


def test_pos_accuracy_floor():
    # PARITY.md reports the measured number; this floor must stay within
    # rounding of it so the claim cannot silently go stale
    acc = measured_accuracy()
    assert acc >= 0.9, f"POS accuracy regressed: {acc:.1%}"


def test_closed_class_words():
    assert pos_tag(["the"]) == ["DT"]
    assert pos_tag(["between"]) == ["IN"]
    assert pos_tag(["would"]) == ["MD"]


def test_shape_rules():
    tags = pos_tag(["He", "sadly", "watched", "the", "sinking", "ship"])
    assert tags[1] == "RB" and tags[2] == "VBD" and tags[4] in ("VBG", "JJ")


def test_contextual_patches():
    # verb-shaped noun after a determiner
    assert pos_tag(["the", "building"])[-1] == "NN"
    # base verb after 'to' and after a modal
    assert pos_tag(["to", "work"])[-1] == "VB"
    assert pos_tag(["they", "must", "report"])[-1] == "VB"


def test_np_chunker():
    toks = "The old house had a beautiful garden".split()
    nps = chunk_noun_phrases(toks)
    assert "The old house" in nps
    assert any(np.endswith("garden") for np in nps)


def test_np_chunker_proper_nouns():
    toks = "Mary Johnson visited the London office".split()
    nps = chunk_noun_phrases(toks)
    assert any("Mary Johnson" in np for np in nps)
    assert any("office" in np for np in nps)


def test_punctuation_tags():
    assert pos_tag(["Stop", "!"])[-1] == "."


# ---------------------------------------------------------------------
# the six non-English reference POS languages (OpenNLP binaries for
# da/de/es/nl/pt/sv — models/README.md): accuracy floors on the authored
# gold corpora + per-language chunking
# ---------------------------------------------------------------------
import json
import os

_GOLD_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "pos_gold.json"
)


def _gold():
    with open(_GOLD_PATH) as f:
        return json.load(f)


def test_pos_gold_floors_all_languages():
    gold = _gold()
    assert sorted(gold) == ["da", "de", "es", "nl", "pt", "sv"]
    for lang, sents in gold.items():
        hits = total = 0
        for toks, gt in sents:
            tags = pos_tag(toks, language=lang)
            assert len(tags) == len(gt)
            hits += sum(a == b for a, b in zip(tags, gt))
            total += len(gt)
        assert hits / total >= 0.9, f"{lang}: {hits}/{total}"


def test_pos_unknown_language_falls_back_to_english():
    assert pos_tag(["the", "dog"], language="zz") == ["DT", "NN"]


def test_chunker_german():
    nps = chunk_noun_phrases(
        "Die Lehrerin las eine interessante Geschichte .".split(),
        language="de",
    )
    assert "Die Lehrerin" in nps
    assert "eine interessante Geschichte" in nps


def test_chunker_spanish_postnominal():
    nps = chunk_noun_phrases(
        "Ella compró una casa nueva en la ciudad .".split(), language="es"
    )
    assert "una casa nueva" in nps  # postnominal adjective joins the NP
    assert "la ciudad" in nps


def test_chunker_swedish():
    nps = chunk_noun_phrases(
        "Hon köpte ett stort hus i staden .".split(), language="sv"
    )
    assert "ett stort hus" in nps
