"""Fused native tokenize+hash+scatter parity with the Python tokenizer path
(native/tptpu_native.cpp tp_tokenize_hash_scatter vs utils/text.tokenize +
murmur3_scatter). Unicode rows must route through the exact Python fallback.
"""
import numpy as np
import pytest

from transmogrifai_tpu import native as N
from transmogrifai_tpu.ops.text import hash_block

CASES = [
    ["Hello world", None, "a b c_d e", "x1 Y2  z3", "", "ONE one OnE"],
    ["naïve café", "ASCII then ünïcode", "日本語 text", None],
    ["under_score_s", "1_000 2_000", "trailing space ", "  lead"],
]


@pytest.mark.parametrize("values", CASES)
@pytest.mark.parametrize("shared", [False, True])
@pytest.mark.parametrize("binary", [False, True])
def test_hash_block_native_matches_python(values, shared, binary):
    kw = dict(
        num_features=32, feature_slot=2, shared=shared, binary_freq=binary,
        to_lowercase=True, min_token_length=1, seed=42, track_nulls=True,
    )
    out_native = hash_block(values, **kw)
    try:
        N._TRIED, N._LIB = True, None  # force the Python fallback
        out_py = hash_block(values, **kw)
    finally:
        N._TRIED = False
    np.testing.assert_array_equal(out_native, out_py)


def test_min_token_length_and_case():
    vals = ["ab a ABC x", "a  b"]
    kw = dict(
        num_features=16, feature_slot=0, shared=False, binary_freq=False,
        to_lowercase=False, min_token_length=2, seed=7, track_nulls=False,
    )
    out_native = hash_block(vals, **kw)
    try:
        N._TRIED, N._LIB = True, None
        out_py = hash_block(vals, **kw)
    finally:
        N._TRIED = False
    np.testing.assert_array_equal(out_native, out_py)
    # min length 2 keeps "ab"/"ABC" only in row 0 and nothing in row 1
    assert out_native[0].sum() == 2.0 and out_native[1].sum() == 0.0


def test_coo_binary_dedups_across_same_row_strings():
    """Two strings mapped to ONE row must share a dedup scope in binary
    mode: a bucket emitted by the first string must not re-emit from the
    second (add-combine would otherwise yield 2.0 where dense binary
    yields 1.0)."""
    from transmogrifai_tpu import native

    out = native.tokenize_hash_coo(
        ["alpha beta", "beta gamma"], np.array([5, 5]),
        num_buckets=64, binary=True,
    )
    if out is None:
        pytest.skip("native library unavailable")
    rows, cols = out
    pairs = list(zip(rows.tolist(), cols.tolist()))
    assert len(pairs) == len(set(pairs)), f"duplicate pairs: {pairs}"
    # distinct rows still dedup independently (beta appears in both)
    out2 = native.tokenize_hash_coo(
        ["alpha beta", "beta gamma"], np.array([0, 1]),
        num_buckets=64, binary=True,
    )
    rows2, cols2 = out2
    assert len(rows2) == 4  # 2 tokens per row, no cross-row suppression
