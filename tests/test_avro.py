"""Pure-Python Avro container reader (utils/avro.py) + reader catalog hookup.

Parity: readers/.../CSVAutoReaders.scala (schema-driven ingestion),
utils/.../io/avro/AvroInOut.scala. Round-trips through our own writer and
checks decoding of every supported datum type, deflate codec, and the
infer_avro_dataset entry point.
"""
import io
import json
import struct
import zlib

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.readers.parquet import AvroReader, infer_avro_dataset
from transmogrifai_tpu.utils.avro import (
    AvroError,
    read_avro,
    read_container,
    write_avro,
)

SCHEMA = {
    "type": "record",
    "name": "Passenger",
    "fields": [
        {"name": "id", "type": "long"},
        {"name": "name", "type": ["null", "string"]},
        {"name": "age", "type": ["null", "double"]},
        {"name": "survived", "type": "boolean"},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "scores", "type": {"type": "map", "values": "double"}},
        {
            "name": "klass",
            "type": {"type": "enum", "name": "K", "symbols": ["a", "b"]},
        },
    ],
}

RECORDS = [
    {
        "id": 1, "name": "Miss Maia", "age": 30.5, "survived": True,
        "tags": ["x", "y"], "scores": {"s": 0.5}, "klass": "a",
    },
    {
        "id": 2, "name": None, "age": None, "survived": False,
        "tags": [], "scores": {}, "klass": "b",
    },
    {
        "id": -3, "name": "Mr Zed", "age": 0.0, "survived": False,
        "tags": ["z"], "scores": {"s": -1.5, "t": 2.0}, "klass": "a",
    },
]


def test_round_trip(tmp_path):
    path = str(tmp_path / "p.avro")
    write_avro(path, SCHEMA, RECORDS)
    assert read_avro(path) == RECORDS


def test_deflate_codec(tmp_path):
    # hand-build a deflate container (the writer only emits null codec)
    buf = io.BytesIO()
    schema = {"type": "record", "name": "R",
              "fields": [{"name": "v", "type": "long"}]}

    def wlong(out, v):
        v = (v << 1) ^ (v >> 63)
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.write(bytes([b | 0x80]))
            else:
                out.write(bytes([b]))
                return

    def wbytes(out, data):
        wlong(out, len(data))
        out.write(data)

    buf.write(b"Obj\x01")
    wlong(buf, 2)
    wbytes(buf, b"avro.schema")
    wbytes(buf, json.dumps(schema).encode())
    wbytes(buf, b"avro.codec")
    wbytes(buf, b"deflate")
    wlong(buf, 0)
    sync = b"0123456789abcdef"
    buf.write(sync)
    raw = io.BytesIO()
    for v in (7, -9, 1 << 40):
        wlong(raw, v)
    comp = zlib.compress(raw.getvalue())[2:-4]  # raw deflate (no zlib header)
    wlong(buf, 3)
    wlong(buf, len(comp))
    buf.write(comp)
    buf.write(sync)
    buf.seek(0)
    assert list(read_container(buf)) == [{"v": 7}, {"v": -9}, {"v": 1 << 40}]


def test_bad_magic():
    with pytest.raises(AvroError):
        list(read_container(io.BytesIO(b"nope")))


def test_sync_marker_mismatch(tmp_path):
    path = str(tmp_path / "p.avro")
    write_avro(path, SCHEMA, RECORDS)
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0xFF  # corrupt the trailing sync marker
    with pytest.raises(AvroError):
        list(read_container(io.BytesIO(bytes(data))))


def test_float_and_fixed():
    schema = {
        "type": "record", "name": "R",
        "fields": [
            {"name": "f", "type": "float"},
            {"name": "x", "type": {"type": "fixed", "name": "F", "size": 3}},
        ],
    }
    raw = io.BytesIO()
    raw.write(struct.pack("<f", 1.5))
    raw.write(b"abc")
    raw.seek(0)
    from transmogrifai_tpu.utils.avro import _read_datum

    assert _read_datum(raw, schema) == {"f": 1.5, "x": b"abc"}


def test_infer_avro_dataset_types(tmp_path):
    path = str(tmp_path / "p.avro")
    write_avro(path, SCHEMA, RECORDS)
    ds = infer_avro_dataset(path)
    assert ds.num_rows == 3
    assert ds.columns["id"].feature_type is T.Integral
    assert ds.columns["age"].feature_type is T.Real
    assert ds.columns["survived"].feature_type is T.Binary
    assert ds.columns["name"].feature_type is T.Text
    assert ds.columns["scores"].feature_type is T.RealMap
    age = ds.columns["age"]
    assert not age.mask[1]  # null age stays missing
    np.testing.assert_allclose(age.values[0], 30.5)


def test_avro_reader_in_catalog(tmp_path):
    path = str(tmp_path / "p.avro")
    write_avro(path, SCHEMA, RECORDS)
    records = list(AvroReader(path).read_records())
    assert records == RECORDS


def test_union_branch_matches_value_type(tmp_path):
    """ADVICE r2: the writer must pick the union branch by the VALUE's
    type, not the first non-null branch."""
    from transmogrifai_tpu.utils.avro import read_avro, write_avro

    schema = {
        "type": "record", "name": "R",
        "fields": [{"name": "v", "type": ["null", "int", "string"]}],
    }
    path = str(tmp_path / "u.avro")
    records = [{"v": 3}, {"v": "three"}, {"v": None}]
    write_avro(path, schema, records)
    assert [r["v"] for r in read_avro(path)] == [3, "three", None]


def test_fixed_truncation_raises(tmp_path):
    """A truncated 'fixed' value must raise AvroError, not silently return
    a short value."""
    import io

    import pytest as _pytest

    from transmogrifai_tpu.utils.avro import AvroError, _read_datum

    fh = io.BytesIO(b"ab")
    with _pytest.raises(AvroError):
        _read_datum(fh, {"type": "fixed", "name": "F", "size": 4})


def test_union_accepts_numpy_scalars(tmp_path):
    import numpy as np

    from transmogrifai_tpu.utils.avro import read_avro, write_avro

    schema = {
        "type": "record", "name": "R",
        "fields": [
            {"name": "d", "type": ["null", "double"]},
            {"name": "l", "type": ["null", "long"]},
            {"name": "b", "type": ["null", "boolean", "int"]},
        ],
    }
    path = str(tmp_path / "np.avro")
    write_avro(path, schema, [
        {"d": np.float32(1.5), "l": np.int64(7), "b": True},
        {"d": np.int32(2), "l": np.int32(9), "b": np.bool_(False)},
    ])
    rows = read_avro(path)
    assert rows[0]["d"] == 1.5 and rows[0]["l"] == 7 and rows[0]["b"] is True
    assert rows[1]["d"] == 2.0 and rows[1]["l"] == 9 and rows[1]["b"] is False
