"""Property-based tests (hypothesis) over the testkit generators and core
invariants — the ScalaCheck layer of the reference's test strategy
(SURVEY.md §4: RandomData generators feed property specs).

Each property states an invariant that must hold for ALL generated inputs,
not just hand-picked cases: generator typing/determinism, column codec
round-trips, monoid laws for the aggregators, murmur3 stability, and
evaluator bounds.
"""
import numpy as np
import pytest

# hypothesis is an optional test dependency (installed in CI): skip this
# module instead of failing collection when it is absent — the
# StreamingHistogram invariants also have deterministic seeded twins in
# test_serving_sentinel.py that always run
try:
    from hypothesis import given, settings, strategies as st
except ModuleNotFoundError:  # pragma: no cover
    pytest.skip("hypothesis not installed", allow_module_level=True)

import transmogrifai_tpu.types as T
from transmogrifai_tpu import testkit as tk
from transmogrifai_tpu.features.aggregators import aggregator_of
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.utils.text import clean_string, murmur3_32, tokenize

SETTINGS = settings(max_examples=60, deadline=None)


@SETTINGS
@given(seed=st.integers(0, 2**31 - 1), n=st.integers(1, 60))
def test_generators_are_deterministic_per_seed(seed, n):
    g1 = tk.RandomReal.normal(0.0, 2.0, seed=seed)
    g2 = tk.RandomReal.normal(0.0, 2.0, seed=seed)
    c1, c2 = g1.to_column(n), g2.to_column(n)
    np.testing.assert_array_equal(c1.values, c2.values)
    np.testing.assert_array_equal(c1.mask, c2.mask)


@SETTINGS
@given(
    seed=st.integers(0, 2**31 - 1),
    p_empty=st.floats(0.0, 1.0),
    n=st.integers(1, 80),
)
def test_probability_of_empty_bounds(seed, p_empty, n):
    g = tk.RandomReal.uniform(seed=seed).with_probability_of_empty(p_empty)
    col = g.to_column(n)
    # masked-out entries are exactly the empties; all values remain finite
    assert col.mask.dtype == bool
    assert np.isfinite(col.values[col.mask]).all()
    if p_empty == 0.0:
        assert col.mask.all()
    if p_empty == 1.0:
        assert not col.mask.any()


@SETTINGS
@given(
    values=st.lists(
        st.one_of(st.none(), st.floats(-1e6, 1e6, allow_nan=False)),
        min_size=1, max_size=50,
    )
)
def test_numeric_column_round_trip(values):
    col = column_from_values(T.Real, values)
    back = col.to_list()
    assert len(back) == len(values)
    for orig, got in zip(values, back):
        if orig is None:
            assert got is None
        else:
            assert got is not None and abs(got - orig) <= 1e-6 * max(1, abs(orig))


@SETTINGS
@given(
    values=st.lists(st.one_of(st.none(), st.text(max_size=20)),
                    min_size=1, max_size=50)
)
def test_text_column_round_trip(values):
    col = column_from_values(T.Text, values)
    # "" normalizes to None (missing) — the reader/codec convention
    assert col.to_list() == [v if v else None for v in values]


@SETTINGS
@given(
    a=st.lists(st.floats(-1e6, 1e6, allow_nan=False), max_size=10),
    b=st.lists(st.floats(-1e6, 1e6, allow_nan=False), max_size=10),
    c=st.lists(st.floats(-1e6, 1e6, allow_nan=False), max_size=10),
)
def test_real_aggregator_monoid_laws(a, b, c):
    """associativity + zero identity for the Real monoid (Algebird laws)."""
    agg = aggregator_of(T.Real)

    def fold(vals):
        acc = agg.zero
        for v in vals:
            acc = agg.plus(acc, agg.prepare(v))
        return acc

    left = agg.plus(agg.plus(fold(a), fold(b)), fold(c))
    right = agg.plus(fold(a), agg.plus(fold(b), fold(c)))
    if left is None or right is None:
        assert left == right
    else:
        np.testing.assert_allclose(left, right, rtol=1e-9)
    # zero identity
    x = fold(a)
    assert agg.plus(agg.zero, x) == agg.plus(x, agg.zero)


@SETTINGS
@given(s=st.text(max_size=60))
def test_murmur3_matches_itself_and_is_stable(s):
    h1 = murmur3_32(s)
    h2 = murmur3_32(s.encode("utf-8"))
    assert h1 == h2
    assert 0 <= h1 < 2**32


@SETTINGS
@given(s=st.text(max_size=60))
def test_tokenize_tokens_are_clean(s):
    for t in tokenize(s):
        assert t == t.lower()
        assert len(t) >= 1
        # tokens never contain separators or underscores (_TOKEN_RE)
        assert not any(ch.isspace() or ch == "_" for ch in t)


@SETTINGS
@given(s=st.text(max_size=60))
def test_clean_string_idempotent_shape(s):
    cleaned = clean_string(s)
    # cleaning twice changes nothing except case normalization of the
    # already-cleaned form (capitalize is stable on CamelCase words)
    assert clean_string(cleaned) == clean_string(clean_string(cleaned))
    assert " " not in cleaned


@SETTINGS
@given(
    y=st.lists(st.integers(0, 1), min_size=4, max_size=60),
    seed=st.integers(0, 1000),
)
def test_binary_evaluator_metric_bounds(y, seed):
    from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator

    y = np.asarray(y, dtype=np.float64)
    if y.sum() == 0 or y.sum() == len(y):
        y[0] = 1.0 - y[0]  # ensure both classes present
    rng = np.random.default_rng(seed)
    prob1 = rng.random(len(y))
    prob = np.stack([1 - prob1, prob1], axis=1)
    pred = (prob1 > 0.5).astype(np.float64)
    m = BinaryClassificationEvaluator().evaluate_arrays(y, pred, prob)
    for key in ("AuROC", "AuPR", "Precision", "Recall", "F1"):
        assert 0.0 <= m[key] <= 1.0, (key, m[key])


# ------------------------------------------------------- streaming histogram
# the serving drift sentinel (resilience/sentinel.py) depends on these
# invariants: JS divergence is computed off merged window sketches, so a
# merge that loses mass or a non-monotone quantile would silently skew the
# drift verdicts

_hist_values = st.lists(
    st.floats(-1e6, 1e6, allow_nan=False, allow_infinity=False),
    min_size=1, max_size=80,
)


def _hist_of(values, max_bins):
    from transmogrifai_tpu.utils.streaming_histogram import StreamingHistogram

    h = StreamingHistogram(max_bins)
    for v in values:
        h.update(float(v))
    return h


@SETTINGS
@given(a=_hist_values, b=_hist_values, bins=st.integers(2, 16))
def test_histogram_merge_preserves_total_count(a, b, bins):
    ha, hb = _hist_of(a, bins), _hist_of(b, bins)
    merged = ha.merge(hb)
    assert merged.total_count == pytest.approx(len(a) + len(b), rel=1e-9)


@SETTINGS
@given(values=_hist_values, bins=st.integers(2, 16))
def test_histogram_quantiles_monotone_in_q(values, bins):
    h = _hist_of(values, bins)
    qs = [h.quantile(q) for q in np.linspace(0.0, 1.0, 11)]
    assert all(q2 >= q1 - 1e-6 for q1, q2 in zip(qs, qs[1:]))


@SETTINGS
@given(values=_hist_values, bins=st.integers(2, 8))
def test_histogram_shrink_never_drops_mass(values, bins):
    """_shrink fires on every update past capacity; total mass must be
    conserved at every step and the bin count bounded."""
    from transmogrifai_tpu.utils.streaming_histogram import StreamingHistogram

    h = StreamingHistogram(bins)
    for i, v in enumerate(values, start=1):
        h.update(float(v))
        assert h.total_count == pytest.approx(i, rel=1e-9)
        assert len(h.bins) <= bins
