"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's local[2] Spark strategy (utils/.../test/
TestSparkContext.scala:50): all algorithms are shard-order-invariant, so a
small local mesh exercises the same code paths as real hardware.
"""
import os

# force CPU even if the session env points at the real chip — EXCEPT when
# explicitly running the on-device suites (TPTPU_TPU_TESTS=1)
_ON_DEVICE = os.environ.get("TPTPU_TPU_TESTS", "") == "1"
if not _ON_DEVICE:
    os.environ["JAX_PLATFORMS"] = "cpu"
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not _ON_DEVICE:
    # the axon PJRT plugin (registered by sitecustomize) latches the
    # platform even when JAX_PLATFORMS=cpu is in the env; the config
    # update wins.
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from transmogrifai_tpu.utils import uid as uid_util  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_uids():
    uid_util.reset()
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def fault_plan():
    """An installed, empty FaultPlan — tests script faults onto it and the
    fixture guarantees uninstall (resilience.faults is process-global)."""
    from transmogrifai_tpu.resilience import faults

    plan = faults.FaultPlan()
    with faults.installed(plan):
        yield plan


TITANIC_CSV = "/root/reference/test-data/PassengerDataAllWithHeader.csv"


@pytest.fixture(scope="session")
def titanic_path():
    if not os.path.exists(TITANIC_CSV):
        pytest.skip("Titanic test data not available")
    return TITANIC_CSV
