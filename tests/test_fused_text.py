"""Device-side text hashing suite (compiler/fused.py
``hashed_text_member`` + ops/text.py ``SmartTextModel.fused_member_spec``):
a high-cardinality HASH text flow must serve FUSED — host tokenize +
murmur3 to int32 codes, device scatter — with scores matching the staged
path and ZERO ``unfuseable`` hits in the fallback-reason map; the
``TPTPU_TEXT_FUSED_TOKENS`` per-row token cap must degrade through the
COUNTED fallback seam (correct scores via the staged loop, fallback
recorded); the pure-Python hashing fallback (``TPTPU_DISABLE_NATIVE=1``)
must produce identical planes; and all-PIVOT text flows keep riding the
one-hot member. Markers: ``residency`` + ``fused``.
"""
import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.local.scoring import score_function
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.utils import uid as uid_util
from transmogrifai_tpu.workflow.workflow import Workflow

pytestmark = [pytest.mark.residency, pytest.mark.fused]

_WORDS = [
    "alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
    "hotel", "india", "juliet", "kilo", "lima", "mike", "november",
]


def _text_rows(n=160, seed=11, max_tokens=4):
    """Unique multi-token strings: cardinality n >> max_cardinality, so
    SmartTextVectorizer decides HASH for the column."""
    rng = np.random.default_rng(seed)
    texts = []
    for i in range(n):
        k = 1 + int(rng.integers(0, max_tokens))
        toks = [str(_WORDS[int(j)]) for j in rng.integers(0, len(_WORDS), k)]
        texts.append(" ".join(toks) + f" id{i}")
    return texts


def _train_text_flow(n=160, seed=11, max_tokens=4):
    uid_util.reset()
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    texts = _text_rows(n, seed, max_tokens)
    label = (x1 + 0.2 * rng.normal(size=n) > 0).astype(float)
    ds = Dataset.of({
        "label": column_from_values(T.RealNN, label),
        "x1": column_from_values(T.Real, x1),
        "desc": column_from_values(T.Text, texts),
    })
    resp, preds = from_dataset(ds, response="label")
    vec = transmogrify(list(preds))
    sel = BinaryClassificationModelSelector(
        seed=7, num_folds=2,
        models=[(LogisticRegression(), {"reg_param": [0.01]})],
    )
    pred = sel.set_input(resp, vec).get_output()
    model = (
        Workflow().set_result_features(pred).set_input_dataset(ds).train()
    )
    rows = [
        {"x1": float(a), "desc": t} for a, t in zip(x1, texts)
    ]
    # serving traffic includes nulls and unseen tokens
    rows[3] = {"x1": 0.1, "desc": None}
    rows[5] = {"x1": -0.4, "desc": "zulu yankee xray"}
    return model, rows


def _probs(out):
    return np.array(
        [next(iter(r.values()))["probability_1"] for r in out]
    )


@pytest.fixture
def no_host_predict(monkeypatch):
    monkeypatch.setenv("TPTPU_HOST_PREDICT_MAX", "0")


class TestHashedTextFusion:
    def test_hash_flow_serves_fused_zero_unfuseable(
        self, no_host_predict, monkeypatch,
    ):
        model, rows = _train_text_flow()
        # staged reference
        monkeypatch.setenv("TPTPU_FUSED", "0")
        staged = _probs(score_function(model).batch(rows))
        monkeypatch.delenv("TPTPU_FUSED")
        fn = score_function(model)
        fn.prime_fused()
        md = fn.metadata()["fused"]
        # the tentpole claim: text flows no longer raise Unfuseable
        assert md["active"], md["reason"]
        fused = _probs(fn.batch(rows))
        np.testing.assert_allclose(fused, staged, atol=1e-5)
        md = fn.metadata()["fused"]
        assert md["dispatches"] >= 1
        assert "unfuseable" not in md["fallbackReasons"]
        assert md["fallbacks"] == 0

    def test_token_cap_degrades_through_counted_seam(
        self, no_host_predict, monkeypatch,
    ):
        # cap the per-row distinct-token budget below the corpus: the
        # batch must still score CORRECTLY (staged loop), and the miss
        # must be a counted fallback, not an exception
        monkeypatch.setenv("TPTPU_FUSED", "0")
        model, rows = _train_text_flow()
        staged = _probs(score_function(model).batch(rows))
        monkeypatch.delenv("TPTPU_FUSED")
        monkeypatch.setenv("TPTPU_TEXT_FUSED_TOKENS", "1")
        fn = score_function(model)
        fn.prime_fused()
        out = _probs(fn.batch(rows))
        np.testing.assert_allclose(out, staged, atol=1e-5)
        md = fn.metadata()["fused"]
        assert md["fallbacks"] >= 1
        assert sum(md["fallbackReasons"].values()) >= 1

    def test_python_hash_fallback_parity(
        self, no_host_predict, monkeypatch,
    ):
        # same model, native tokenize/murmur kernels disabled: the pure
        # Python host encode must produce the identical fused plane
        model, rows = _train_text_flow()
        fn = score_function(model)
        fn.prime_fused()
        with_native = _probs(fn.batch(rows))
        monkeypatch.setenv("TPTPU_DISABLE_NATIVE", "1")
        fn2 = score_function(model)
        fn2.prime_fused()
        without = _probs(fn2.batch(rows))
        md = fn2.metadata()["fused"]
        assert md["active"] and md["fallbacks"] == 0
        np.testing.assert_array_equal(with_native, without)

    def test_hash_flow_quantized_narrows_codes(
        self, no_host_predict, monkeypatch,
    ):
        # the hashed-code member advertises its code range; quantization
        # narrows the int32 wire format and must keep score parity
        model, rows = _train_text_flow()
        base = score_function(model)
        base.prime_fused()
        p0 = _probs(base.batch(rows))
        up0 = base.audit().to_json()["transferCensus"]["upBytesPerRow"]
        quant = score_function(model, quantized=True)
        quant.prime_fused()
        p1 = _probs(quant.batch(rows))
        up1 = quant.audit().to_json()["transferCensus"]["upBytesPerRow"]
        md = quant.metadata()["fused"]
        assert md["quantized"] is True and md["fallbacks"] == 0
        # affine dequant on the GLM's numeric member moves probabilities
        # by at most the advertised scale/2 epilogue error — small, not
        # zero (the AUPR-budget test lives in test_quantize.py)
        np.testing.assert_allclose(p1, p0, atol=2e-2)
        assert up1 < up0

    def test_pivot_flow_still_fuses_onehot(
        self, no_host_predict,
    ):
        # low-cardinality text decides PIVOT for every slot and keeps the
        # one-hot member (no hashing plane involved)
        uid_util.reset()
        rng = np.random.default_rng(23)
        n = 160
        x1 = rng.normal(size=n)
        cats = [["red", "green", "blue"][i % 3] for i in range(n)]
        label = (x1 > 0).astype(float)
        ds = Dataset.of({
            "label": column_from_values(T.RealNN, label),
            "x1": column_from_values(T.Real, x1),
            "color": column_from_values(T.Text, cats),
        })
        resp, preds = from_dataset(ds, response="label")
        vec = transmogrify(list(preds))
        sel = BinaryClassificationModelSelector(
            seed=7, num_folds=2,
            models=[(LogisticRegression(), {"reg_param": [0.01]})],
        )
        pred = sel.set_input(resp, vec).get_output()
        model = (
            Workflow().set_result_features(pred).set_input_dataset(ds)
            .train()
        )
        rows = [
            {"x1": float(a), "color": c} for a, c in zip(x1[:32], cats[:32])
        ]
        fn = score_function(model)
        fn.prime_fused()
        assert fn.metadata()["fused"]["active"]
        fn.batch(rows)
        assert fn.metadata()["fused"]["fallbacks"] == 0
