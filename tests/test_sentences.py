"""Sentence splitter fixtures (OpenNLP SentenceDetector replacement —
nlp/sentences.py; NameEntityRecognizer runs per sentence)."""
from transmogrifai_tpu.nlp.sentences import split_sentences


def test_basic_split():
    s = split_sentences("The cat sat. The dog barked! Did it rain? Yes.")
    assert s == ["The cat sat.", "The dog barked!", "Did it rain?", "Yes."]


def test_abbreviations_do_not_split():
    s = split_sentences("Mr. Smith met Dr. Jones at 5 p.m. yesterday. "
                        "They talked.")
    assert len(s) == 2
    assert s[0].startswith("Mr. Smith") and s[1] == "They talked."


def test_initials_do_not_split():
    s = split_sentences("J. K. Rowling wrote it. I read it.")
    assert s == ["J. K. Rowling wrote it.", "I read it."]


def test_decimals_and_numbers():
    s = split_sentences("Pi is 3.14 roughly. The price rose 2.5 percent.")
    assert len(s) == 2


def test_dotted_acronyms():
    s = split_sentences("She moved to the U.S. in May. He stayed.")
    assert s == ["She moved to the U.S. in May.", "He stayed."]


def test_quotes_and_closers():
    s = split_sentences('He said "stop." Then he left.')
    assert s == ['He said "stop."', "Then he left."]


def test_german_abbrevs_and_ordinals():
    s = split_sentences(
        "Das Treffen ist am 3. Oktober. Dr. Meier kommt z.B. später. Gut.",
        language="de",
    )
    assert len(s) == 3
    assert s[0] == "Das Treffen ist am 3. Oktober."


def test_spanish_abbrevs():
    s = split_sentences(
        "El Sr. García llegó tarde. La Dra. López no vino.", language="es"
    )
    assert s == ["El Sr. García llegó tarde.", "La Dra. López no vino."]


def test_empty_and_single():
    assert split_sentences("") == []
    assert split_sentences("   ") == []
    assert split_sentences("One sentence without a period") == [
        "One sentence without a period"
    ]


def test_ellipsis_kept_with_sentence():
    s = split_sentences("Well… Maybe so. It happened.")
    assert s[-1] == "It happened."


def test_ner_sentence_opener_discounted():
    """'The' opening a sentence is not an entity; real names still are."""
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.ops.text_stages import NameEntityRecognizer
    from transmogrifai_tpu.types import Text
    from transmogrifai_tpu.types.columns import column_from_values

    f = FeatureBuilder.Text("t").as_predictor()
    ner = NameEntityRecognizer().set_input(f)
    col = column_from_values(Text, [
        "The weather was bad. John Smith stayed home. Nothing happened.",
    ])
    out = ner.transform_columns(col, num_rows=1).to_list()[0]
    persons = out.get("Person", frozenset())
    assert "john" in persons and "smith" in persons
    all_toks = set().union(*out.values()) if out else set()
    assert "the" not in all_toks and "nothing" not in all_toks


def test_decimal_at_sentence_end_splits():
    s = split_sentences("The price was 3.5. Next day it fell.")
    assert s == ["The price was 3.5.", "Next day it fell."]


def test_ner_sentence_final_and_quoted_openers():
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.ops.text_stages import NameEntityRecognizer
    from transmogrifai_tpu.types import Text
    from transmogrifai_tpu.types.columns import column_from_values

    f = FeatureBuilder.Text("t").as_predictor()
    ner = NameEntityRecognizer().set_input(f)
    col = column_from_values(Text, [
        "He met John.",                      # entity abuts the final period
        '"The dog barked." Mary left.',      # quoted opener still discounted
        "North is cold. It snowed.",         # LOC-hint opener survives
    ])
    rows = ner.transform_columns(col, num_rows=3).to_list()
    assert "john" in rows[0].get("Person", frozenset()), rows[0]
    assert "mary" in rows[1].get("Person", frozenset()), rows[1]
    assert "the" not in set().union(*rows[1].values()), rows[1]
    assert "north" in rows[2].get("Location", frozenset()), rows[2]
