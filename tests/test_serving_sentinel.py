"""Serving-path hardening suite (resilience/sentinel.py + local/scoring.py):
schema sentinel, per-row quarantine, train/serve drift detection, and the
scoring circuit breaker — all driven through deterministic fault plans and
injectable clocks (zero real sleeps; markers: serving, faults).
"""
import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.local.scoring import score_function
from transmogrifai_tpu.models.logistic import LogisticRegression
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.resilience import (
    BreakerConfig,
    DriftConfig,
    FaultPlan,
    SchemaSentinel,
    SchemaViolationError,
    SentinelPolicy,
    installed,
)
from transmogrifai_tpu.resilience.sentinel import (
    DriftSentinel,
    histogram_js_divergence,
)
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.utils import uid as uid_util
from transmogrifai_tpu.utils.streaming_histogram import (
    StreamingHistogram,
    histogram_from_values,
)
from transmogrifai_tpu.workflow.workflow import Workflow, WorkflowModel

pytestmark = [pytest.mark.serving, pytest.mark.faults]


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def _binary_ds(n=160, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    label = (x1 + 0.5 * x2 + 0.3 * rng.normal(size=n) > 0).astype(float)
    return Dataset.of({
        "label": column_from_values(T.RealNN, label),
        "x1": column_from_values(T.Real, x1),
        "x2": column_from_values(T.Real, x2),
    })


@pytest.fixture(scope="module")
def trained():
    uid_util.reset()
    ds = _binary_ds(n=160, seed=3)
    resp, preds = from_dataset(ds, response="label")
    vec = transmogrify(list(preds))
    selector = BinaryClassificationModelSelector(
        seed=7, models=[(LogisticRegression(), {"reg_param": [0.01]})],
        num_folds=2,
    )
    pred = selector.set_input(resp, vec).get_output()
    model = Workflow().set_result_features(pred).set_input_dataset(ds).train()
    return ds, pred, model


# ------------------------------------------------------------ schema sentinel
class TestSchemaSentinel:
    def _features(self, ds):
        resp, preds = from_dataset(ds, response="label")
        return [resp, *preds]

    def test_default_policy_coerces_and_quarantines(self):
        ds = _binary_ds(8)
        s = SchemaSentinel(self._features(ds))
        clean, q = s.check_row({"x1": "3.5", "x2": 1.0})
        assert q == [] and clean["x1"] == 3.5
        assert s.counts["wrong_type"] == 1
        clean, q = s.check_row({"x1": "zzz", "x2": 1.0})
        assert len(q) == 1 and q[0][0] == "x1" and q[0][1] == "unparseable"
        clean, q = s.check_row({"x1": float("nan"), "x2": float("inf")})
        assert q == [] and clean["x1"] is None and clean["x2"] is None
        assert s.counts["non_finite"] == 2

    def test_missing_key_is_normal_sparsity_not_a_violation(self):
        """An absent optional field under the default policy is ordinary
        sparse data: scored as missing, NOT counted — real violations must
        not drown in fill-rate noise (that's the drift sentinel's job)."""
        ds = _binary_ds(8)
        s = SchemaSentinel(self._features(ds))
        clean, q = s.check_row({"x1": 1.0})
        assert q == [] and clean.get("x2") is None
        assert not s.counts and not s.by_feature

    def test_response_features_never_validated(self):
        ds = _binary_ds(8)
        s = SchemaSentinel(self._features(ds))
        clean, q = s.check_row({"x1": 1.0, "x2": 2.0, "label": "garbage"})
        assert q == [] and clean["label"] == "garbage"
        assert s.counts["unparseable"] == 0

    def test_raise_policy_escalates(self):
        ds = _binary_ds(8)
        s = SchemaSentinel(
            self._features(ds),
            policy=SentinelPolicy(unparseable="raise"),
        )
        with pytest.raises(SchemaViolationError, match="x1"):
            s.check_row({"x1": "zzz", "x2": 1.0})

    def test_per_feature_policy_override(self):
        ds = _binary_ds(8)
        s = SchemaSentinel(
            self._features(ds),
            per_feature={"x1": SentinelPolicy(missing="quarantine")},
        )
        _, q = s.check_row({"x2": 1.0})  # x1 missing -> quarantine
        assert len(q) == 1 and q[0][0] == "x1"
        _, q = s.check_row({"x1": 1.0})  # x2 missing -> default policy
        assert q == []

    def test_off_policy_allows_everything(self):
        ds = _binary_ds(8)
        s = SchemaSentinel(self._features(ds), policy=SentinelPolicy.off())
        clean, q = s.check_row({"x1": "zzz"})
        assert q == [] and clean["x1"] == "zzz" and not s.counts

    def test_copy_on_write(self):
        ds = _binary_ds(8)
        s = SchemaSentinel(self._features(ds))
        row = {"x1": 1.0, "x2": 2.0}
        clean, _ = s.check_row(row)
        assert clean is row  # untouched rows are not copied

    def test_numpy_scalars_are_valid(self):
        """np.float64/np.int64/np.bool_ rows (pandas to_dict output) must
        pass validation untouched — they scored fine pre-sentinel."""
        ds = _binary_ds(8)
        s = SchemaSentinel(self._features(ds))
        row = {"x1": np.float64(1.5), "x2": np.int64(3)}
        clean, q = s.check_row(row)
        assert q == [] and clean is row and not s.counts
        clean, q = s.check_row({"x1": np.bool_(True), "x2": np.float32(2.0)})
        assert q == [] and not s.counts

    def test_binary_garbage_strings_do_not_coerce_to_false(self):
        from transmogrifai_tpu.resilience.sentinel import _inspect_value

        assert _inspect_value(T.Binary, "yes") == ("wrong_type", True)
        assert _inspect_value(T.Binary, "false") == ("wrong_type", False)
        assert _inspect_value(T.Binary, np.bool_(True)) == (None, True)
        kind, coerced = _inspect_value(T.Binary, "N/A")
        assert kind == "unparseable"  # garbage must not score as False


# --------------------------------------------------------- per-row quarantine
class TestQuarantine:
    def test_k_malformed_rows_quarantine_exactly_k(self, trained):
        """Acceptance: a batch with k malformed rows returns n scored rows
        with exactly k quarantine records, counters matching exactly."""
        ds, pred, model = trained
        rows = ds.rows()[:10]
        fn = score_function(model)
        plan = (
            FaultPlan()
            .malform_row("x1", rows=(2,), value="##bad##")
            .malform_row("x2", rows=(7,), value=object())
        )
        with installed(plan):
            out = fn.batch(rows)
        assert len(out) == 10  # every row came back scored
        recs = fn.quarantine.last
        assert sorted(r.index for r in recs) == [2, 7]
        assert len(recs) == 2
        # quarantined rows got the default prediction, not a crash
        for i in (2, 7):
            assert out[i][pred.name]["prediction"] is not None
        # others scored normally
        clean_out = fn.batch(rows)  # no faults
        for i in (0, 1, 3, 4, 5, 6, 8, 9):
            assert out[i][pred.name] == clean_out[i][pred.name]
        md = fn.metadata()
        assert md["quarantine"]["quarantinedRows"] == 2
        assert md["sentinel"]["violations"]["unparseable"] == 1
        assert md["sentinel"]["violations"]["wrong_type"] >= 1

    def test_unparseable_value_no_longer_kills_the_batch(self, trained):
        ds, pred, model = trained
        rows = ds.rows()[:4]
        bad = dict(rows[1])
        bad["x1"] = "not-a-number"
        fn = score_function(model)
        out = fn.batch([rows[0], bad, rows[2], rows[3]])
        assert len(out) == 4
        assert [r.index for r in fn.quarantine.last] == [1]

    def test_stage_poison_isolates_per_row(self, trained):
        """A row that poisons a stage is quarantined; the other rows keep
        their REAL scores (recovered by per-row isolation)."""
        ds, pred, model = trained
        rows = ds.rows()[:6]
        fn = score_function(model)
        clean_out = fn.batch(rows)
        plan = FaultPlan().fail_stage_transform(
            pred.name, rows=(3,), times=None
        )
        with installed(plan):
            out = fn.batch(rows)
        recs = fn.quarantine.last
        assert [r.index for r in recs] == [3] and recs[0].kind == "stage"
        for i in (0, 1, 2, 4, 5):
            assert out[i][pred.name] == clean_out[i][pred.name]

    def test_empty_batch(self, trained):
        _, _, model = trained
        assert score_function(model).batch([]) == []

    def test_multi_violation_row_counts_as_one_row(self, trained):
        ds, pred, model = trained
        rows = ds.rows()[:4]
        bad = dict(rows[1])
        bad["x1"] = "zzz"
        bad["x2"] = "www"  # two violating features, ONE quarantined row
        fn = score_function(model)
        fn.batch([rows[0], bad, rows[2], rows[3]])
        md = fn.metadata()["quarantine"]
        assert md["quarantinedRows"] == 1 and md["records"] == 2

    def test_quarantined_rows_never_reach_the_plan(self, trained):
        """A quarantined row must not be scored as an all-missing
        placeholder — it could poison a stage and feed the breaker. The
        fault targeting the quarantined row's index must never fire."""
        ds, pred, model = trained
        rows = ds.rows()[:6]
        fn = score_function(model)
        plan = (
            FaultPlan()
            .malform_row("x1", rows=(2,), value="##bad##")
            .fail_stage_transform(pred.name, rows=(2,), times=None)
        )
        with installed(plan):
            out = fn.batch(rows)
        assert len(out) == 6
        # row 2 was quarantined at validation; the stage fault keyed to
        # row 2 never fired because the row never entered the plan
        assert [r.index for r in fn.quarantine.last] == [2]
        assert fn.quarantine.last[0].kind == "unparseable"
        assert ("transform", pred.name) not in plan.fired

    def test_deterministic_total_failure_is_budget_bounded(self, trained):
        """A stage failing for EVERY row must not turn one batch into
        O(n) plan re-runs: the isolation budget caps the re-runs and the
        remaining rows are quarantined wholesale."""
        ds, pred, model = trained
        rows = ds.rows()[:64]
        fn = score_function(model)
        plan = FaultPlan().fail_stage_transform(pred.name, times=None)
        with installed(plan):
            out = fn.batch(rows)
        assert len(out) == 64
        assert sorted(r.index for r in fn.quarantine.last) == list(range(64))
        # the fault's internal count = number of plan executions; the
        # budget keeps it well under the unbounded 2n-1 = 127 re-runs
        # (1 primary + ~44 budgeted + exhausted siblings' single runs)
        executions = plan._transform_faults[0]["count"]
        assert executions <= 70

    def test_bisection_isolates_multiple_poisoned_rows(self, trained):
        ds, pred, model = trained
        rows = ds.rows()[:9]
        fn = score_function(model)
        clean_out = fn.batch(rows)
        plan = FaultPlan().fail_stage_transform(
            pred.name, rows=(0, 5, 8), times=None
        )
        with installed(plan):
            out = fn.batch(rows)
        assert sorted(r.index for r in fn.quarantine.last) == [0, 5, 8]
        for i in (1, 2, 3, 4, 6, 7):
            assert out[i][pred.name] == clean_out[i][pred.name]

    def test_open_breaker_plus_fresh_failure_does_not_kill_batch(self, trained):
        """An open breaker on stage A must stay skipped during the per-row
        isolation triggered by a DIFFERENT stage's failure — A's persistent
        failure must not quarantine the whole batch."""
        ds, pred, model = trained
        rows = ds.rows()[:6]
        clk = FakeClock()
        fn = score_function(
            model,
            breaker=BreakerConfig(
                failure_threshold=1, recovery_time=1000.0, clock=clk
            ),
        )
        # open the breaker on the terminal stage (stage A)
        with installed(FaultPlan().fail_stage_transform(pred.name, times=1)):
            fn(rows[0])
        assert fn.breakers[pred.name].state == "open"
        # now a different (upstream) stage fails freshly on row 2: the
        # isolation re-runs must keep skipping A instead of executing it
        vec_stage = next(
            t for t in model.fitted.values() if t.output_name != pred.name
        )
        plan = FaultPlan().fail_stage_transform(
            vec_stage.output_name, rows=(2,), times=None
        )
        with installed(plan):
            out = fn.batch(rows)
        assert len(out) == 6
        # only the genuinely poisoning row is quarantined
        assert [r.index for r in fn.quarantine.last] == [2]
        # breaker untouched by the observe-mode re-runs
        br = fn.breakers[pred.name]
        assert br.state == "open"
        assert br.stats()["transitions"] == {"closed->open": 1}

    def test_score_columns_stage_poison_isolates_per_row(self, trained):
        ds, pred, model = trained
        sub = ds.take(np.arange(6))
        fn = score_function(model)
        clean = fn.columns(sub.drop(["label"]))[pred.name]
        plan = FaultPlan().fail_stage_transform(
            pred.name, rows=(2,), times=None
        )
        fn2 = score_function(model)
        with installed(plan):
            out = fn2.columns(sub.drop(["label"]))[pred.name]
        assert len(out) == 6
        recs = fn2.quarantine.last
        assert [r.index for r in recs] == [2]
        clean_pred = np.asarray(clean.prediction)
        got_pred = np.asarray(out.prediction)
        keep = [0, 1, 3, 4, 5]
        np.testing.assert_allclose(got_pred[keep], clean_pred[keep])


# ------------------------------------------------- score_one / batch parity
class TestScoreOneParity:
    def test_parity_under_malformed_input(self, trained):
        ds, pred, model = trained
        row = ds.rows()[0]
        plan = FaultPlan().malform_row("x1", rows=(0,), value="##bad##")
        fn_one = score_function(model)
        with installed(plan):
            one = fn_one(row)
        plan2 = FaultPlan().malform_row("x1", rows=(0,), value="##bad##")
        fn_batch = score_function(model)
        with installed(plan2):
            batch = fn_batch.batch([row])
        assert one == batch[0]
        assert (
            [(r.feature, r.kind) for r in fn_one.quarantine.last]
            == [(r.feature, r.kind) for r in fn_batch.quarantine.last]
        )

    def test_parity_under_nan_fault(self, trained):
        ds, pred, model = trained
        row = ds.rows()[0]
        fn_one = score_function(model)
        with installed(FaultPlan().nan_output(pred.name, rows=(0,))):
            one = fn_one(row)
        fn_batch = score_function(model)
        with installed(FaultPlan().nan_output(pred.name, rows=(0,))):
            batch = fn_batch.batch([row])
        assert one == batch[0]
        assert fn_one.guard.counts == fn_batch.guard.counts

    def test_parity_clean(self, trained):
        ds, pred, model = trained
        row = ds.rows()[5]
        fn = score_function(model)
        assert fn(row) == fn.batch([row])[0]


# ------------------------------------------------------------ circuit breaker
class TestCircuitBreaker:
    def test_opens_after_k_failures_and_recovers_via_half_open(self, trained):
        """Acceptance: breaker opens after K injected stage failures and
        recovers via half-open probe (injected clock, no sleeps)."""
        ds, pred, model = trained
        rows = ds.rows()
        clk = FakeClock()
        fn = score_function(
            model,
            breaker=BreakerConfig(
                failure_threshold=3, recovery_time=10.0, clock=clk
            ),
        )
        plan = FaultPlan().fail_stage_transform(pred.name, times=3)
        with installed(plan):
            for i in range(3):
                out = fn(rows[i])  # each fails once; defaults returned
                assert out[pred.name]["prediction"] is not None
            br = fn.breakers[pred.name]
            assert br.state == "open"
            assert br.stats()["transitions"] == {"closed->open": 1}
            # open: short-circuits without executing the stage
            out = fn(rows[3])
            assert br.stats()["shortCircuits"] == 1
            # not yet recovered
            clk.now = 5.0
            fn(rows[4])
            assert br.state == "open"
            # past recovery_time: half-open probe runs the stage for real
            clk.now = 11.0
            out = fn(rows[5])
            assert br.state == "closed"
            assert br.stats()["transitions"]["open->half_open"] == 1
            assert br.stats()["transitions"]["half_open->closed"] == 1
            assert np.isfinite(out[pred.name]["prediction"])
        assert len(plan.fired) == 1  # one fired entry per configured fault

    def test_failed_probe_reopens(self, trained):
        ds, pred, model = trained
        rows = ds.rows()
        clk = FakeClock()
        fn = score_function(
            model,
            breaker=BreakerConfig(
                failure_threshold=2, recovery_time=10.0, clock=clk
            ),
        )
        plan = FaultPlan().fail_stage_transform(pred.name, times=3)
        with installed(plan):
            fn(rows[0])
            fn(rows[1])
            br = fn.breakers[pred.name]
            assert br.state == "open"
            clk.now = 11.0
            fn(rows[2])  # probe consumes the third injected failure
            assert br.state == "open"
            assert br.stats()["transitions"]["half_open->open"] == 1
            clk.now = 22.0
            out = fn(rows[3])  # next probe succeeds
            assert br.state == "closed"
            assert np.isfinite(out[pred.name]["prediction"])

    def test_short_circuit_degrades_not_crashes(self, trained):
        ds, pred, model = trained
        rows = ds.rows()
        clk = FakeClock()
        fn = score_function(
            model,
            breaker=BreakerConfig(
                failure_threshold=1, recovery_time=100.0, clock=clk
            ),
        )
        with installed(FaultPlan().fail_stage_transform(pred.name, times=1)):
            fn(rows[0])
        # breaker open, no faults installed: batch still degrades to
        # defaults (the stage is skipped entirely)
        out = fn.batch(rows[:4])
        assert len(out) == 4
        br = fn.breakers[pred.name]
        assert br.stats()["shortCircuits"] == 1
        assert all(r[pred.name]["prediction"] is not None for r in out)

    def test_deadline_overruns_count_as_failures(self, trained):
        ds, pred, model = trained
        rows = ds.rows()

        class TickClock:
            """Each clock() call advances 1s: every stage 'takes' 1s."""

            def __init__(self):
                self.now = 0.0

            def __call__(self):
                self.now += 1.0
                return self.now

        fn = score_function(
            model,
            breaker=BreakerConfig(
                failure_threshold=100, recovery_time=1.0,
                deadline=0.5, clock=TickClock(),
            ),
        )
        fn(rows[0])
        stats = fn.metadata()["breakers"]
        assert all(s["deadlineOverruns"] >= 1 for s in stats.values())
        assert all(s["consecutiveFailures"] >= 1 for s in stats.values())

    def test_breaker_disabled(self, trained):
        ds, pred, model = trained
        fn = score_function(model, breaker=False)
        fn(ds.rows()[0])
        assert fn.breakers == {} and fn.metadata()["breakers"] == {}


# -------------------------------------------------------------- drift sentinel
class TestDriftSentinel:
    def test_profiles_captured_and_persisted(self, trained, tmp_path):
        ds, pred, model = trained
        profs = model.serving_profiles
        assert set(profs) == {"x1", "x2"}  # response never profiled
        assert profs["x1"]["count"] > 0
        assert profs["x1"]["histogram"] is not None
        model.save(str(tmp_path / "m"))
        m2 = WorkflowModel.load(str(tmp_path / "m"))
        assert m2.serving_profiles == profs

    def test_in_distribution_stream_stays_quiet(self, trained):
        ds, pred, model = trained
        fn = score_function(
            model, drift=DriftConfig(min_rows=30, js_threshold=0.35)
        )
        for r in ds.rows()[:80]:
            fn(r)
        rep = fn.metadata()["drift"]
        assert rep["enabled"] and rep["alerts"] == []
        assert rep["driftAlertsTotal"] == 0
        assert rep["features"]["x1"]["status"] == "ok"
        assert rep["features"]["x1"]["jsDivergence"] < 0.35

    def test_shifted_stream_trips_js_alert(self, trained):
        """Acceptance: a serve stream drawn from a shifted distribution
        trips the drift sentinel while an in-distribution stream does not
        (previous test)."""
        ds, pred, model = trained
        fn = score_function(
            model, drift=DriftConfig(min_rows=30, js_threshold=0.35)
        )
        plan = FaultPlan().shift_feature("x1", offset=25.0)
        with installed(plan):
            for r in ds.rows()[:80]:
                fn(r)
        rep = fn.metadata()["drift"]
        assert rep["alerts"] == ["x1"]
        assert rep["driftAlertsTotal"] == 1
        assert rep["features"]["x1"]["jsDivergence"] > 0.35
        assert rep["features"]["x2"]["status"] == "ok"
        assert plan.fired == [("drift", "x1")]
        # alert counter counts TRANSITIONS, not reports
        assert fn.metadata()["drift"]["driftAlertsTotal"] == 1

    def test_fill_rate_collapse_trips_alert(self, trained):
        ds, pred, model = trained
        fn = score_function(
            model,
            drift=DriftConfig(min_rows=30, fill_ratio_threshold=5.0),
        )
        for r in ds.rows()[:60]:
            r = dict(r)
            r.pop("x2", None)  # feature vanished from the serve stream
            fn(r)
        rep = fn.metadata()["drift"]
        assert "x2" in rep["alerts"]
        # an infinite ratio reports null so the metadata stays strict-JSON
        assert rep["features"]["x2"]["fillRatio"] is None
        import json

        json.dumps(rep, allow_nan=False)  # whole report is serializable

    def test_sliding_window_forgets_old_drift(self, trained):
        ds, pred, model = trained
        cfg = DriftConfig(window=40, chunks=4, min_rows=20, js_threshold=0.35)
        fn = score_function(model, drift=cfg)
        plan = FaultPlan().shift_feature("x1", offset=25.0, times=40)
        with installed(plan):
            for r in ds.rows()[:40]:
                fn(r)
        assert fn.metadata()["drift"]["alerts"] == ["x1"]
        # stream recovers: the shifted chunks age out of the window
        for r in ds.rows()[40:120]:
            fn(r)
        rep = fn.metadata()["drift"]
        assert rep["alerts"] == []
        assert rep["driftAlertsTotal"] == 1  # the historical alert remains

    def test_torn_profile_disables_feature_not_scoring(self, trained):
        """Acceptance: torn profiles degrade monitoring, never scoring."""
        ds, pred, model = trained
        plan = FaultPlan().tear_profile("x1")
        with installed(plan):
            fn = score_function(model)
            out = fn(ds.rows()[0])
        assert np.isfinite(out[pred.name]["prediction"])
        rep = fn.metadata()["drift"]
        assert rep["tornProfiles"] == ["x1"]
        assert "x1" not in rep["features"] and plan.fired == [("profile", "x1")]

    def test_corrupt_profile_json_is_torn_not_fatal(self):
        sent = DriftSentinel({"x1": {"count": "??", "nulls": None}})
        assert sent.torn == ["x1"] and sent.profiles == {}

    def test_model_without_profiles_is_inert(self, trained):
        ds, pred, model = trained
        stripped = WorkflowModel(
            result_features=model.result_features,
            raw_features=model.raw_features,
            fitted=model.fitted,
            selector_info=model.selector_info,
        )
        fn = score_function(stripped)
        fn(ds.rows()[0])
        rep = fn.metadata()["drift"]
        assert rep["enabled"] is False and rep["features"] == {}

    def test_testkit_drifted_stream_trips_sentinel(self, trained):
        """testkit.drifted() builds the covariate-shifted serve stream
        without a FaultPlan — same generator, same seed, offset values."""
        from transmogrifai_tpu import testkit as tk
        from transmogrifai_tpu.dataset import Dataset

        ds, pred, model = trained
        base = tk.RandomReal.normal(0.0, 1.0, seed=9)
        shifted = tk.drifted(base, offset=30.0)
        n = 80
        serve = Dataset.of({
            "x1": shifted.to_column(n),
            "x2": tk.RandomReal.normal(0.0, 1.0, seed=10).to_column(n),
        })
        fn = score_function(
            model, drift=DriftConfig(min_rows=30, js_threshold=0.35)
        )
        fn.columns(serve)
        rep = fn.metadata()["drift"]
        assert rep["alerts"] == ["x1"]
        # the un-shifted twin draws the same sequence minus the offset
        vals = np.asarray(shifted.to_column(n).values)
        np.testing.assert_allclose(
            vals - 30.0, np.asarray(base.to_column(n).values)
        )

    def test_isolation_reruns_do_not_inflate_guard_counters(self, trained):
        """Bisection re-runs sanitize NaN outputs but never count them:
        guard counters reflect the PRIMARY pass only, so a failure that
        triggers O(log n) re-runs cannot multiply the degradation stats."""
        ds, pred, model = trained
        rows = ds.rows()[:8]
        fn = score_function(model)
        vec_stage = next(
            t for t in model.fitted.values() if t.output_name != pred.name
        )
        plan = (
            FaultPlan()
            .nan_output(pred.name, rows=(1,), times=10)
            .fail_stage_transform(vec_stage.output_name, rows=(5,), times=None)
        )
        with installed(plan):
            out = fn.batch(rows)
        assert len(out) == 8
        assert [r.index for r in fn.quarantine.last] == [5]
        # pred only ran inside the re-runs (its input stage failed in the
        # primary pass): outputs are still sanitized, counters untouched
        assert fn.metadata()["scoreGuard"]["guardedRows"] == 0
        for r in out:
            assert np.isfinite(r[pred.name]["prediction"])

    def test_columns_path_observes_drift(self, trained):
        ds, pred, model = trained
        fn = score_function(
            model, drift=DriftConfig(min_rows=30, js_threshold=0.35)
        )
        fn.columns(ds.drop(["label"]))
        rep = fn.metadata()["drift"]
        assert rep["rowsObserved"] == ds.num_rows
        assert rep["features"]["x1"]["status"] == "ok"


# -------------------------------------------------------- histogram invariants
class TestStreamingHistogramInvariants:
    """Deterministic invariant sweeps (the hypothesis @given twins live in
    test_property_based.py and run where hypothesis is installed); the
    drift sentinel's JS math depends on all three."""

    @pytest.mark.parametrize("seed", range(8))
    def test_merge_preserves_total_count(self, seed):
        rng = np.random.default_rng(seed)
        a = StreamingHistogram(16)
        b = StreamingHistogram(16)
        for v in rng.normal(size=50):
            a.update(float(v))
        for v in rng.exponential(size=37):
            b.update(float(v))
        merged = a.merge(b)
        assert merged.total_count == pytest.approx(
            a.total_count + b.total_count
        )

    @pytest.mark.parametrize("seed", range(8))
    def test_quantiles_monotone_in_q(self, seed):
        rng = np.random.default_rng(seed + 100)
        h = StreamingHistogram(12)
        for v in rng.normal(size=80):
            h.update(float(v))
        qs = [h.quantile(q) for q in np.linspace(0.0, 1.0, 21)]
        assert all(q2 >= q1 - 1e-9 for q1, q2 in zip(qs, qs[1:]))

    @pytest.mark.parametrize("seed", range(8))
    def test_shrink_never_drops_mass(self, seed):
        rng = np.random.default_rng(seed + 200)
        h = StreamingHistogram(4)  # tiny capacity: every update shrinks
        total = 0.0
        for v in rng.uniform(-5, 5, size=60):
            h.update(float(v))
            total += 1.0
            assert h.total_count == pytest.approx(total)
        assert len(h.bins) <= 4

    def test_bulk_builder_matches_incremental_when_exact(self):
        vals = [1.0, 2.0, 2.0, 5.0, 9.0]
        bulk = histogram_from_values(vals, max_bins=16)
        inc = StreamingHistogram(16)
        for v in vals:
            inc.update(v)
        assert bulk.bins == inc.bins

    def test_bulk_builder_preserves_mass_when_approximate(self):
        rng = np.random.default_rng(7)
        vals = rng.normal(size=5000)
        h = histogram_from_values(vals, max_bins=32)
        assert h.total_count == pytest.approx(5000)
        assert len(h.bins) <= 32

    def test_js_divergence_bounds(self):
        rng = np.random.default_rng(1)
        a = histogram_from_values(rng.normal(size=500), max_bins=32)
        b = histogram_from_values(rng.normal(size=500) + 0.01, max_bins=32)
        far = histogram_from_values(rng.normal(size=500) + 100.0, max_bins=32)
        near_js = histogram_js_divergence(a, b)
        far_js = histogram_js_divergence(a, far)
        assert 0.0 <= near_js < 0.2
        assert far_js > 0.9  # disjoint supports approach the log2 bound 1.0
        assert histogram_js_divergence(a, a) == pytest.approx(0.0, abs=1e-9)


# -------------------------------------------------------------------- metadata
class TestMetadataAndSummary:
    def test_counters_match_injected_counts_exactly(self, trained):
        """Acceptance: metadata() counters match injected counts exactly."""
        ds, pred, model = trained
        rows = ds.rows()[:12]
        fn = score_function(model)
        plan = (
            FaultPlan()
            .malform_row("x1", rows=(1, 4, 9), value="##bad##")
            .nan_output(pred.name, rows=(0,))
        )
        with installed(plan):
            out = fn.batch(rows)
        assert len(out) == 12
        md = fn.metadata()
        assert md["quarantine"]["quarantinedRows"] == 3
        assert md["quarantine"]["byKind"] == {"unparseable": 3}
        assert md["sentinel"]["violations"]["unparseable"] == 3
        assert md["scoreGuard"]["guardedRows"] == 1
        # quarantined rows never reach the plan, so the drift window holds
        # the 9 surviving rows only
        assert md["drift"]["rowsObserved"] == 9
        assert len([f for f in plan.fired if f[0] == "malform"]) == 3

    def test_summary_pretty_reports_serving_counters(self, trained):
        ds, pred, model = trained
        fn = score_function(model)
        bad = dict(ds.rows()[0])
        bad["x1"] = "not-a-number"
        fn.batch([bad, ds.rows()[1]])
        text = model.summary_pretty()
        assert "Serving resilience:" in text
        assert "quarantined row(s)" in text

    def test_true_flags_mean_defaults(self, trained):
        ds, pred, model = trained
        fn = score_function(model, sentinel=True, breaker=True, drift=True)
        out = fn(ds.rows()[0])
        assert np.isfinite(out[pred.name]["prediction"])
        assert fn.sentinel is not None and fn.metadata()["drift"]["enabled"]

    def test_isolation_raise_restores_fail_fast(self, trained):
        from transmogrifai_tpu.resilience import TransientError

        ds, pred, model = trained
        fn = score_function(model, isolation="raise")
        plan = FaultPlan().fail_stage_transform(pred.name, times=1)
        with installed(plan):
            with pytest.raises(TransientError, match="injected"):
                fn.batch(ds.rows()[:4])
        # the breaker still recorded the failure before propagating
        assert fn.breakers[pred.name].stats()["consecutiveFailures"] == 1
        with pytest.raises(ValueError, match="isolation"):
            score_function(model, isolation="nope")

    def test_default_values_do_not_alias_between_rows(self, trained):
        ds, pred, model = trained
        rows = ds.rows()[:4]
        bad1, bad2 = dict(rows[0]), dict(rows[1])
        bad1["x1"] = "zzz"
        bad2["x1"] = "www"
        fn = score_function(model)
        out = fn.batch([bad1, bad2])
        out[0][pred.name]["prediction"] = 99.0
        assert out[1][pred.name]["prediction"] != 99.0

    def test_guard_still_escalates_in_raise_mode(self, trained):
        """PR-1 semantics preserved: ScoreGuard(raise) is an explicit
        escalation and must NOT be swallowed by stage isolation."""
        from transmogrifai_tpu.resilience import ScoreGuard, ScoreGuardError

        ds, pred, model = trained
        fn = score_function(model, guard=ScoreGuard(fallback="raise"))
        with installed(FaultPlan().nan_output(pred.name, rows=(0,))):
            with pytest.raises(ScoreGuardError, match="non-finite"):
                fn.batch(ds.rows()[:2])


# --------------------------------------- all-null response: entry-point parity
class TestAllNullResponseParity:
    """A PRESENT but all-null response column must score through
    ``fn.columns`` exactly like ``fn.batch`` scores the same unlabeled
    rows: both entry points substitute the score-time null-label fill
    (``column_from_values(ftype, [0]*b)``), so label-observing machinery
    (the drift sentinel's fill-rate window, label-consuming stages) sees
    identical raw columns."""

    def _null_label_data(self, trained, n=64):
        from transmogrifai_tpu.types.columns import empty_like

        ds, pred, model = trained
        label_f = next(f for f in model.raw_features if f.is_response)
        sub = ds.take(np.arange(n))
        null_ds = sub.with_column(
            label_f.name, empty_like(label_f.ftype, n)
        )
        rows = null_ds.rows()
        assert all(r[label_f.name] is None for r in rows)
        return null_ds, rows, label_f, pred, model

    def test_predictions_agree(self, trained):
        null_ds, rows, _label_f, pred, model = self._null_label_data(trained)
        fn = score_function(model)
        out_rows = fn.batch(rows)
        out_cols = fn.columns(null_ds)[pred.name].to_list()
        for i, row_out in enumerate(out_rows):
            assert row_out[pred.name] == out_cols[i]

    def test_label_consuming_stage_sees_the_fill_on_both_paths(self):
        """The distinguishing assertion: a result feature DERIVED from the
        response (label - 1.0) must see the score-time 0-fill on BOTH
        entry points — without the columnar-path substitution, fn.columns
        hands the stage an all-null label and the derived column nulls
        out while fn.batch reports -1.0."""
        import transmogrifai_tpu.dsl  # noqa: F401  (Feature arithmetic)

        uid_util.reset()
        ds = _binary_ds(n=160, seed=23)
        resp, preds = from_dataset(ds, response="label")
        vec = transmogrify(list(preds))
        selector = BinaryClassificationModelSelector(
            seed=7, models=[(LogisticRegression(), {"reg_param": [0.01]})],
            num_folds=2,
        )
        pred = selector.set_input(resp, vec).get_output()
        shifted = resp - 1.0
        model = (
            Workflow()
            .set_result_features(pred, shifted)
            .set_input_dataset(ds)
            .train()
        )
        from transmogrifai_tpu.types.columns import empty_like

        n = 8
        null_ds = ds.take(np.arange(n)).with_column(
            "label", empty_like(T.RealNN, n)
        )
        rows = null_ds.rows()
        fn = score_function(model)
        out_rows = fn.batch(rows)
        out_cols = fn.columns(null_ds)[shifted.name].to_list()
        for i in range(n):
            assert out_rows[i][shifted.name] == -1.0
            assert out_cols[i] == out_rows[i][shifted.name]
