"""Model save/load round-trip + local scoring tests (parity:
OpWorkflowModelReaderWriterTest, OpWorkflowModelLocalTest)."""
import numpy as np
import pytest

from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.local import score_function
from transmogrifai_tpu.models import LogisticRegression, XGBoostClassifier
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.prep import SanityChecker
from transmogrifai_tpu.readers import infer_csv_dataset
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.workflow.workflow import Workflow, WorkflowModel

LR_MODELS = [(LogisticRegression(), {"reg_param": [0.01, 0.1]})]

TITANIC_CSV = "/root/reference/test-data/PassengerDataAllWithHeader.csv"


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    import os

    if not os.path.exists(TITANIC_CSV):
        pytest.skip("Titanic fixture data not available")
    ds = infer_csv_dataset(TITANIC_CSV)
    resp, preds = from_dataset(ds, response="Survived")
    preds = [p for p in preds if p.name != "PassengerId"]
    vector = transmogrify(preds)
    checked = resp.transform_with(SanityChecker(remove_bad_features=True), vector)
    sel = BinaryClassificationModelSelector(seed=5, models=LR_MODELS)
    pred = sel.set_input(resp, checked).get_output()
    model = Workflow().set_result_features(pred).set_input_dataset(ds).train()
    return ds, pred, model


def test_save_load_scores_identically(trained, tmp_path):
    ds, pred, model = trained
    path = str(tmp_path / "model")
    model.save(path)
    loaded = WorkflowModel.load(path)
    s1 = model.score(dataset=ds)
    s2 = loaded.score(dataset=ds)
    np.testing.assert_allclose(
        np.asarray(s1[pred.name].probability),
        np.asarray(s2[pred.name].probability),
        atol=1e-7,
    )
    np.testing.assert_array_equal(
        s1[pred.name].prediction, s2[pred.name].prediction
    )


def test_loaded_model_summary_and_evaluate(trained, tmp_path):
    ds, pred, model = trained
    path = str(tmp_path / "model2")
    model.save(path)
    loaded = WorkflowModel.load(path)
    s = loaded.summary_json()
    assert s["modelSelectorSummary"]["problemKind"] == "BinaryClassification"
    assert s["trainRows"] == model.train_rows
    metrics = loaded.evaluate(ds)
    assert metrics["AuROC"] > 0.7
    assert "LogisticRegression" in loaded.summary_pretty()


@pytest.mark.slow
def test_save_load_tree_model(tmp_path, rng):
    import transmogrifai_tpu.types as T
    from transmogrifai_tpu.dataset import Dataset
    from transmogrifai_tpu.types.columns import NumericColumn, column_from_values

    n = 400
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    y = ((x0**2 + x1**2) < 1.0).astype(float)
    ds = Dataset.of({
        "label": column_from_values(T.Integral, y.astype(int)),
        "a": column_from_values(T.Real, x0),
        "b": column_from_values(T.Real, x1),
    })
    resp, preds = from_dataset(ds, response="label")
    vector = transmogrify(preds)
    sel = BinaryClassificationModelSelector(
        seed=2, models=[(XGBoostClassifier(num_round=10, max_depth=3), {})]
    )
    pred = sel.set_input(resp, vector).get_output()
    model = Workflow().set_result_features(pred).set_input_dataset(ds).train()
    path = str(tmp_path / "treemodel")
    model.save(path)
    loaded = WorkflowModel.load(path)
    np.testing.assert_allclose(
        np.asarray(model.score(dataset=ds)[pred.name].probability),
        np.asarray(loaded.score(dataset=ds)[pred.name].probability),
        atol=1e-7,
    )


def test_local_score_function(trained):
    ds, pred, model = trained
    fn = score_function(model)
    row = ds.rows()[0]
    out = fn(row)
    assert pred.name in out
    pmap = out[pred.name]
    assert "prediction" in pmap and "probability_1" in pmap
    # matches batch scoring
    batch_probs = np.asarray(model.score(dataset=ds)[pred.name].probability)
    assert pmap["probability_1"] == pytest.approx(batch_probs[0, 1], abs=1e-9)


def test_local_score_function_batch(trained):
    ds, pred, model = trained
    fn = score_function(model)
    rows = ds.rows()[:10]
    outs = fn.batch(rows)
    assert len(outs) == 10
    assert all(pred.name in o for o in outs)


def test_local_score_function_columns(trained):
    """Columnar scoring (fn.columns) matches the row-dict batch path and
    tolerates a dataset with the response column absent."""
    ds, pred, model = trained
    fn = score_function(model)
    out = fn.columns(ds)
    assert pred.name in out
    rows = ds.rows()
    dict_outs = fn.batch(rows)
    col_rendered = out[pred.name].to_list()
    assert len(col_rendered) == len(rows)
    for i in (0, 1, len(rows) - 1):
        assert col_rendered[i]["probability_1"] == pytest.approx(
            dict_outs[i][pred.name]["probability_1"], abs=1e-9
        )
    # response column absent -> scored with null labels
    out2 = fn.columns(ds.drop(["Survived"]))
    assert np.allclose(
        np.asarray(out[pred.name].prediction),
        np.asarray(out2[pred.name].prediction),
    )
    # absent predictor column -> all-null, same tolerance as the row path
    some_pred = next(
        f.name for f in model.raw_features
        if not f.is_response and f.name in ds
    )
    out3 = fn.columns(ds.drop([some_pred]))
    rows_missing = [
        {k: v for k, v in r.items() if k != some_pred} for r in rows
    ]
    dict3 = fn.batch(rows_missing)
    col3 = out3[pred.name].to_list()
    for i in (0, len(rows) - 1):
        assert col3[i]["probability_1"] == pytest.approx(
            dict3[i][pred.name]["probability_1"], abs=1e-9
        )


def test_local_score_missing_label(trained):
    ds, pred, model = trained
    fn = score_function(model)
    row = {k: v for k, v in ds.rows()[3].items() if k != "Survived"}
    out = fn(row)
    assert 0.0 <= out[pred.name]["probability_1"] <= 1.0


def test_local_score_batch_above_bucket_cap(trained):
    """Batches above _BUCKET_CAP pad to the next multiple of the cap
    instead of the next power of two (bounded program count, <=2x pad);
    outputs must match the plain batch path row-for-row."""
    from transmogrifai_tpu.local import scoring as S

    ds, pred, model = trained
    fn = score_function(model)
    rows = ds.rows()
    # replicate the dataset past the 8192 cap (8910 rows -> 16384 pad)
    big = (rows * 11)[: S._BUCKET_CAP + 718]
    assert len(big) > S._BUCKET_CAP
    outs = fn.batch(big)
    assert len(outs) == len(big)
    small = fn.batch(rows[:5])
    for i in range(5):
        assert outs[i][pred.name]["probability_1"] == pytest.approx(
            small[i][pred.name]["probability_1"], abs=1e-9
        )
    # wrap-around replica must score identically to its original row
    j = len(rows)  # first repeated row == rows[0]
    assert outs[j][pred.name]["probability_1"] == pytest.approx(
        outs[0][pred.name]["probability_1"], abs=1e-9
    )
