"""SanityChecker tests — mini BadFeatureZoo (parity: core/.../preparators/
BadFeatureZooTest.scala approach: construct known-leaky/known-junk features
and assert they are caught)."""
import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.prep import SanityChecker
from transmogrifai_tpu.stages.metadata import (
    NULL_STRING,
    OTHER_STRING,
    ColumnMeta,
    VectorMetadata,
)
from transmogrifai_tpu.types.columns import NumericColumn, VectorColumn
from transmogrifai_tpu.utils import stats as S


def _vec_ds(x, metas, y, name="vec", label="label"):
    meta = VectorMetadata(name, tuple(
        ColumnMeta(**{**m, "index": i}) for i, m in enumerate(metas)
    ))
    return Dataset.of({
        label: NumericColumn(T.RealNN, np.asarray(y, dtype=np.float64),
                             np.ones(len(y), dtype=bool)),
        name: VectorColumn(T.OPVector, np.asarray(x, dtype=np.float32), meta),
    })


def _checker_inputs(name="vec", label="label"):
    lbl = FeatureBuilder.RealNN(label).as_response()
    vec = FeatureBuilder.OPVector(name).as_predictor()
    return lbl, vec


def _col(parent, **kw):
    return {"parent_names": (parent,), "parent_type": "Real", **kw}


# ------------------------------ stats plane ---------------------------------
def test_correlation_matrix_basic():
    rng = np.random.default_rng(0)
    a = rng.normal(size=500)
    b = 2 * a + 0.001 * rng.normal(size=500)
    c = rng.normal(size=500)
    corr = S.correlation_matrix(np.stack([a, b, c], axis=1))
    assert corr[0, 1] > 0.999
    assert abs(corr[0, 2]) < 0.2
    np.testing.assert_allclose(np.diag(corr), 1.0)


def test_correlation_zero_variance_is_zero():
    x = np.stack([np.ones(10), np.arange(10.0)], axis=1)
    corr = S.correlation_matrix(x)
    assert corr[0, 1] == 0.0


def test_cramers_v_perfect_and_independent():
    perfect = np.array([[50.0, 0.0], [0.0, 50.0]])
    assert S.cramers_v(perfect) == pytest.approx(1.0)
    indep = np.array([[25.0, 25.0], [25.0, 25.0]])
    assert S.cramers_v(indep) == pytest.approx(0.0)


def test_spearman_monotonic():
    x = np.arange(100.0)
    y = np.exp(x / 10.0)  # monotonic, nonlinear
    corr = S.spearman_correlation_matrix(x[:, None], y)
    assert corr[0, 1] == pytest.approx(1.0)


def test_association_rule_confidence():
    cont = np.array([[30.0, 0.0], [10.0, 10.0]])
    conf, support = S.association_rule_confidence(cont)
    assert conf[0] == pytest.approx(1.0)
    assert support[0] == pytest.approx(0.6)


# --------------------------- sanity checker zoo -----------------------------
def test_leaky_label_copy_dropped(rng):
    y = rng.integers(0, 2, 400).astype(float)
    good = rng.normal(size=400)
    x = np.stack([y, good], axis=1)  # col 0 IS the label
    ds = _vec_ds(x, [_col("leak"), _col("good")], y)
    lbl, vec = _checker_inputs()
    est = SanityChecker(remove_bad_features=True).set_input(lbl, vec)
    model = est.fit(ds)
    assert model.indices_to_keep == [1]
    summary = est.metadata["sanityCheckerSummary"]
    assert summary["numDropped"] == 1
    dropped = [c for c in summary["columns"] if c["dropped"]][0]
    assert any("corrLabel" in r for r in dropped["reasons"])


def test_constant_column_dropped(rng):
    y = rng.integers(0, 2, 300).astype(float)
    x = np.stack([np.full(300, 7.0), rng.normal(size=300)], axis=1)
    ds = _vec_ds(x, [_col("const"), _col("ok")], y)
    lbl, vec = _checker_inputs()
    model = SanityChecker(remove_bad_features=True).set_input(lbl, vec).fit(ds)
    assert model.indices_to_keep == [1]


def test_duplicate_feature_drops_later(rng):
    y = rng.integers(0, 2, 300).astype(float)
    a = rng.normal(size=300)
    x = np.stack([a, a.copy(), rng.normal(size=300)], axis=1)
    ds = _vec_ds(x, [_col("a"), _col("a2"), _col("b")], y)
    lbl, vec = _checker_inputs()
    model = SanityChecker(remove_bad_features=True).set_input(lbl, vec).fit(ds)
    assert model.indices_to_keep == [0, 2]


def test_categorical_leak_drops_whole_group(rng):
    n = 400
    y = rng.integers(0, 2, n).astype(float)
    # pivot group "cat" perfectly encodes the label
    cat_a = (y == 0).astype(float)
    cat_b = (y == 1).astype(float)
    other = np.zeros(n)
    good = rng.normal(size=n)
    x = np.stack([cat_a, cat_b, other, good], axis=1)
    metas = [
        _col("cat", grouping="cat", indicator_value="A", parent_type="PickList"),
        _col("cat", grouping="cat", indicator_value="B", parent_type="PickList"),
        _col("cat", grouping="cat", indicator_value=OTHER_STRING, parent_type="PickList"),
        _col("good"),
    ]
    ds = _vec_ds(x, metas, y)
    lbl, vec = _checker_inputs()
    est = SanityChecker(remove_bad_features=True).set_input(lbl, vec)
    model = est.fit(ds)
    assert model.indices_to_keep == [3]  # whole group removed


def test_good_features_kept(rng):
    n = 500
    y = rng.integers(0, 2, n).astype(float)
    x = np.stack([
        y * 0.4 + rng.normal(size=n),  # informative, not leaky
        rng.normal(size=n),
    ], axis=1)
    ds = _vec_ds(x, [_col("f1"), _col("f2")], y)
    lbl, vec = _checker_inputs()
    model = SanityChecker(remove_bad_features=True).set_input(lbl, vec).fit(ds)
    assert model.indices_to_keep == [0, 1]


def test_remove_bad_features_false_keeps_all(rng):
    y = rng.integers(0, 2, 200).astype(float)
    x = np.stack([y, rng.normal(size=200)], axis=1)
    ds = _vec_ds(x, [_col("leak"), _col("good")], y)
    lbl, vec = _checker_inputs()
    est = SanityChecker(remove_bad_features=False).set_input(lbl, vec)
    model = est.fit(ds)
    out = model.transform(ds)[est.output_name]
    assert out.values.shape[1] == 2  # reported but not removed
    assert est.metadata["sanityCheckerSummary"]["numDropped"] == 1


def test_transform_removes_and_subsets_metadata(rng):
    y = rng.integers(0, 2, 200).astype(float)
    x = np.stack([y, rng.normal(size=200)], axis=1)
    ds = _vec_ds(x, [_col("leak"), _col("good")], y)
    lbl, vec = _checker_inputs()
    est = SanityChecker(remove_bad_features=True).set_input(lbl, vec)
    out = est.fit(ds).transform(ds)[est.output_name]
    assert out.values.shape == (200, 1)
    assert [c.parent_names for c in out.metadata.columns] == [("good",)]
    assert out.metadata.columns[0].index == 0


def test_titanic_transmogrify_plus_sanity_check(titanic_path):
    from transmogrifai_tpu.features import from_dataset
    from transmogrifai_tpu.ops import transmogrify
    from transmogrifai_tpu.readers import infer_csv_dataset
    from transmogrifai_tpu.readers.core import DatasetReader
    from transmogrifai_tpu.workflow.dag import raw_features_of
    from transmogrifai_tpu.workflow.fit import fit_and_transform_dag

    ds = infer_csv_dataset(titanic_path)
    resp, preds = from_dataset(ds, response="Survived")
    preds = [p for p in preds if p.name != "PassengerId"]
    vector = transmogrify(preds)
    checker = SanityChecker(remove_bad_features=True)
    checked = resp.transform_with(checker, vector)
    raw = DatasetReader(ds).generate_dataset(raw_features_of([checked]))
    data, fitted = fit_and_transform_dag(raw, [checked])
    out = data[checked.name]
    before = data[vector.name].values.shape[1]
    after = out.values.shape[1]
    assert 0 < after <= before
    assert np.isfinite(np.asarray(out.values)).all()
    summary = checker.metadata["sanityCheckerSummary"]
    assert summary["numColumns"] == before


# ---------------- sampling caps (SanityChecker.scala:356-361,562-564) -------
def test_sample_fraction_clamps():
    est = SanityChecker()
    # small data: lower limit forces full fraction
    assert est._sample_fraction(500) == 1.0
    # above the upper limit: fraction caps the checked rows at the limit
    assert est._sample_fraction(4_000_000) == pytest.approx(0.25)
    # check_sample below the lower-limit floor gets raised to it
    est2 = SanityChecker(check_sample=0.0001)
    assert est2._sample_fraction(100_000) == pytest.approx(0.01)
    # explicit fraction honored when inside the clamp window
    est3 = SanityChecker(check_sample=0.5)
    assert est3._sample_fraction(100_000) == pytest.approx(0.5)


def test_sampled_check_is_deterministic_and_bounded(rng):
    n = 5000
    y = rng.integers(0, 2, n).astype(float)
    leak = y + rng.normal(scale=1e-4, size=n)
    good = rng.normal(size=n)
    x = np.stack([leak, good], axis=1)
    metas = [_col("leak"), _col("good")]
    ds = _vec_ds(x, metas, y)
    lbl, vec = _checker_inputs()
    est = SanityChecker(
        remove_bad_features=True, check_sample=0.1,
        sample_lower_limit=100, sample_upper_limit=1000,
    ).set_input(lbl, vec)
    model = est.fit(ds)
    summary = est.metadata["sanityCheckerSummary"]
    assert summary["numRows"] == 500  # 0.1 * 5000, inside [100, 1000]
    assert model.indices_to_keep == [1]  # leak caught on the sample
    # same seed -> same sample -> same decisions
    est2 = SanityChecker(
        remove_bad_features=True, check_sample=0.1,
        sample_lower_limit=100, sample_upper_limit=1000,
    ).set_input(lbl, vec)
    assert est2.fit(ds).indices_to_keep == model.indices_to_keep


# --------- text shared-hash protection (DerivedFeatureFilterUtils) ----------
def _hash_block_with_leaky_pivot(rng, n=400):
    y = rng.integers(0, 2, n).astype(float)
    pivot_a = (y == 0).astype(float)  # leaky indicator, parent "desc"
    hash_0 = rng.normal(size=n)       # shared-hash block, same parent
    hash_1 = rng.normal(size=n)
    good = rng.normal(size=n)
    x = np.stack([pivot_a, hash_0, hash_1, good], axis=1)
    metas = [
        _col("desc", grouping="desc", indicator_value="A", parent_type="Text"),
        _col("desc", parent_type="Text", descriptor_value="hash_0"),
        _col("desc", parent_type="Text", descriptor_value="hash_1"),
        _col("good"),
    ]
    return _vec_ds(x, metas, y), y


def test_leaky_pivot_takes_sibling_hash_block_by_default(rng):
    ds, _ = _hash_block_with_leaky_pivot(rng)
    lbl, vec = _checker_inputs()
    est = SanityChecker(remove_bad_features=True).set_input(lbl, vec)
    model = est.fit(ds)
    # reference default (protectTextSharedHash=false): parent-level removal
    # takes the hash block down with the leaky pivot
    assert model.indices_to_keep == [3]


def test_protect_text_shared_hash_keeps_hash_block(rng):
    ds, _ = _hash_block_with_leaky_pivot(rng)
    lbl, vec = _checker_inputs()
    est = SanityChecker(
        remove_bad_features=True, protect_text_shared_hash=True
    ).set_input(lbl, vec)
    model = est.fit(ds)
    # hashes survive; the leaky pivot still goes
    assert model.indices_to_keep == [1, 2, 3]


def test_correlation_exclusion_hashed_text(rng):
    n = 400
    y = rng.integers(0, 2, n).astype(float)
    leaky_hash = y + rng.normal(scale=1e-4, size=n)  # a hash col that leaks
    good = rng.normal(size=n)
    x = np.stack([leaky_hash, good], axis=1)
    metas = [
        _col("desc", parent_type="Text", descriptor_value="hash_0"),
        _col("good"),
    ]
    ds = _vec_ds(x, metas, y)
    lbl, vec = _checker_inputs()
    # excluded from correlation checks -> survives despite the leak
    est = SanityChecker(
        remove_bad_features=True, correlation_exclusion="HashedText"
    ).set_input(lbl, vec)
    assert est.fit(ds).indices_to_keep == [0, 1]
    # default NoExclusion catches it
    est2 = SanityChecker(remove_bad_features=True).set_input(lbl, vec)
    assert est2.fit(ds).indices_to_keep == [1]
