"""RawFeatureFilter tests (parity: RawFeatureFilterTest.scala, 1,065 LoC —
known-bad features must be excluded, good ones kept)."""
import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import from_dataset
from transmogrifai_tpu.models import LogisticRegression
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.prep.raw_feature_filter import (
    RawFeatureFilter,
    compute_distribution,
)
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.types.columns import column_from_values
from transmogrifai_tpu.workflow.workflow import Workflow


def _ds(n, rng, **extra):
    cols = {
        "label": column_from_values(T.Integral, rng.integers(0, 2, n).tolist()),
        "good": column_from_values(T.Real, rng.normal(size=n).tolist()),
    }
    cols.update(extra)
    return Dataset.of(cols)


def test_distribution_fill_rate_and_js(rng):
    a = column_from_values(T.Real, [1.0, 2.0, None, 4.0])
    d = compute_distribution("a", a, bins=10)
    assert d.fill_rate == 0.75
    same = compute_distribution("a", a, bins=10)
    assert d.js_divergence(same) == pytest.approx(0.0, abs=1e-12)
    shifted = column_from_values(T.Real, [100.0, 200.0, 300.0, 400.0])
    d2 = compute_distribution(
        "a", shifted, bins=10, numeric_range=(d.summary["min"], d.summary["max"])
    )
    # out-of-range values clip into the edge bin, which train also occupies,
    # so divergence is high but not maximal
    assert d.js_divergence(d2) > 0.4


def test_low_fill_feature_excluded(rng):
    n = 1000
    mostly_null = [None] * (n - 1) + [1.0]
    ds = _ds(n, rng, sparse=column_from_values(T.Real, mostly_null))
    resp, preds = from_dataset(ds, response="label")
    rff = RawFeatureFilter(min_fill=0.01)
    excl = rff.compute_exclusions(ds, preds, label_name="label")
    assert "sparse" in excl and "good" not in excl
    reasons = rff.results.excluded["sparse"]
    assert any("fillRate" in r for r in reasons)


def test_train_score_drift_excluded(rng):
    n = 1000
    train = _ds(n, rng, drifty=column_from_values(T.Real, rng.normal(0, 1, n).tolist()))
    score = Dataset.of({
        "good": train["good"],
        "drifty": column_from_values(T.Real, rng.normal(100, 1, n).tolist()),
    })
    resp, preds = from_dataset(train, response="label")
    rff = RawFeatureFilter(max_js_divergence=0.5)
    excl = rff.compute_exclusions(train, preds, score=score, label_name="label")
    assert "drifty" in excl and "good" not in excl


def test_null_label_leakage_excluded(rng):
    n = 600
    y = rng.integers(0, 2, n)
    leaky = [None if yi == 1 else 1.0 for yi in y]  # missingness == label
    ds = Dataset.of({
        "label": column_from_values(T.Integral, y.tolist()),
        "good": column_from_values(T.Real, rng.normal(size=n).tolist()),
        "leaky_nulls": column_from_values(T.Real, leaky),
    })
    resp, preds = from_dataset(ds, response="label")
    rff = RawFeatureFilter()
    excl = rff.compute_exclusions(ds, preds, label_name="label")
    assert "leaky_nulls" in excl


def test_workflow_with_rff_rewrites_dag(rng):
    n = 800
    y = rng.integers(0, 2, n)
    x = rng.normal(size=n) + y  # informative
    sparse = [None] * (n - 2) + [1.0, 2.0]
    ds = Dataset.of({
        "label": column_from_values(T.Integral, y.tolist()),
        "good": column_from_values(T.Real, x.tolist()),
        "sparse": column_from_values(T.Real, sparse),
    })
    resp, preds = from_dataset(ds, response="label")
    vector = transmogrify(preds)
    sel = BinaryClassificationModelSelector(
        seed=1, models=[(LogisticRegression(), {"reg_param": [0.01]})]
    )
    pred = sel.set_input(resp, vector).get_output()
    model = (
        Workflow()
        .set_result_features(pred)
        .set_input_dataset(ds)
        .with_raw_feature_filter(min_fill=0.01)
        .train()
    )
    s = model.summary_json()
    assert "sparse" in s["blocklistedFeatures"]
    assert s["rawFeatureFilterResults"]["exclusionReasons"]["sparse"]
    # the fitted vectorizer no longer references the dropped feature
    scores = model.score(dataset=ds.drop(["sparse"]))
    assert scores.num_rows == n
